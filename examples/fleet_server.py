"""Server-side fleet reconciliation: the TPU-native path.

A sync server holds many documents; each round, clients send update
payloads; the whole fleet merges in batched XLA launches (docs axis
sharded over the device mesh).  Run on CPU:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fleet_server.py
"""
import os, sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import os
import random
import time

import jax

# default to the (virtual) CPU mesh: the ambient environment may pin
# JAX_PLATFORMS to a TPU plugin; opt onto real chips with FLEET_ON_TPU=1
if not os.environ.get("FLEET_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import loro_tpu as lt
from loro_tpu.parallel.fleet import DeviceDocBatch, Fleet
from loro_tpu.parallel.mesh import make_mesh


def main() -> None:
    rng = random.Random(0)
    n_docs = 24
    mesh = make_mesh()
    print(f"mesh: {mesh}")

    # client replicas (host engine) — the server only sees their payloads
    docs = [lt.LoroDoc(peer=i + 1) for i in range(n_docs)]
    cid = docs[0].get_text("doc").id
    batch = DeviceDocBatch(n_docs=n_docs, capacity=4096, mesh=mesh)
    marks = [d.oplog_vv() for d in docs]

    for round_no in range(4):
        # clients edit offline...
        for d in docs:
            t = d.get_text("doc")
            for _ in range(rng.randint(1, 20)):
                if len(t) and rng.random() < 0.3:
                    pos = rng.randint(0, len(t) - 1)
                    t.delete(pos, min(2, len(t) - pos))
                else:
                    t.insert(rng.randint(0, len(t)), rng.choice(["go ", "tpu ", "crdt "]))
            d.commit()
        # ...and sync: the server ingests every doc's delta in one batch
        updates = []
        for i, d in enumerate(docs):
            updates.append(d.oplog.changes_between(marks[i], d.oplog_vv()))
            marks[i] = d.oplog_vv()
        t0 = time.perf_counter()
        batch.append_changes(updates, cid)
        texts = batch.texts()
        dt = time.perf_counter() - t0
        ok = texts == [d.get_text("doc").to_string() for d in docs]
        print(f"round {round_no}: merged {n_docs} docs in {dt*1000:.0f} ms "
              f"({'consistent' if ok else 'DIVERGED'}) e.g. {texts[0][:30]!r}")
    # each round above placed only the DELTA rows (host ShadowOrder,
    # O(delta)) and materialized with one multi-key device sort — no
    # per-round re-rank of the standing table
    print(f"order renumbers across all rounds: "
          f"{sum(b.renumbers for b in batch.order)}")

    # very large imports can also shard the OP axis (sp) over a 2D mesh:
    # per-shard scatter-max partials combine with pmax collectives
    from loro_tpu.ops.columnar import extract_map_ops

    fleet2d = Fleet(make_mesh(op_parallel=2))
    for d in docs:
        m = d.get_map("meta")
        for k in "abc":
            m.set(k, f"{d.peer}:{k}")
        d.commit()
    extracts = [extract_map_ops(d.oplog.changes_in_causal_order()) for d in docs]
    wins = fleet2d.merge_map_docs_sharded(extracts)
    ok = all(wins[i] == d.get_map("meta").get_value() for i, d in enumerate(docs))
    print(f"sharded (docs x ops) LWW merge of {n_docs} docs: "
          f"{'consistent' if ok else 'DIVERGED'}")

    # long-lived server lifecycle: auto_grow repacks past the initial
    # capacity bucket, and once every client has acked an ingest epoch
    # the server reclaims causally-stable tombstones in place
    batch.auto_grow = True
    stable = batch.epoch  # every round above was fully synced
    reclaimed = batch.compact([stable] * batch.d)
    ok = batch.texts() == [d.get_text("doc").to_string() for d in docs]
    print(f"compaction: reclaimed {reclaimed} tombstone rows "
          f"({'consistent' if ok else 'DIVERGED'})")

    # server restart: the resident state checkpoints through the LTKV
    # store and the restored batch keeps serving appends + rich reads
    blob = batch.export_state()
    restored = DeviceDocBatch.import_state(blob, mesh=mesh)
    for d in docs:
        d.get_text("doc").insert(0, "post-restart ")
        d.commit()
    updates = []
    for i, d in enumerate(docs):
        updates.append(d.oplog.changes_between(marks[i], d.oplog_vv()))
        marks[i] = d.oplog_vv()
    restored.append_changes(updates, cid)
    ok = restored.texts() == [d.get_text("doc").to_string() for d in docs]
    print(f"checkpoint/restore: {len(blob)} bytes LTKV; restored server "
          f"{'consistent' if ok else 'DIVERGED'} after new appends")


if __name__ == "__main__":
    main()
