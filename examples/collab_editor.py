"""Collaborative rich-text editor session (host path walkthrough).

Run: python examples/collab_editor.py
"""
import os, sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import loro_tpu as lt
from loro_tpu.undo import UndoManager
from loro_tpu.cursor import get_cursor, get_cursor_pos


def main() -> None:
    alice, bob = lt.LoroDoc(peer=1), lt.LoroDoc(peer=2)
    alice.config.text_style_config["link"] = "none"

    doc = alice.get_text("article")
    doc.insert(0, "CRDTs merge without conflicts.")
    doc.mark(0, 5, "bold", True)
    alice.commit()

    # bob joins from a snapshot
    bob.import_(alice.export_snapshot())

    # concurrent edits + a cursor that survives them
    cursor = get_cursor(alice, doc, 6)  # before "merge"
    undo = UndoManager(alice)
    doc.insert(6, "always ")
    alice.commit()
    bob.get_text("article").insert(0, "[draft] ")
    bob.commit()

    # two-round sync
    alice.import_(bob.export_updates(alice.oplog_vv()))
    bob.import_(alice.export_updates(bob.oplog_vv()))
    assert alice.get_deep_value() == bob.get_deep_value()

    print("merged:", alice.get_text("article").to_string())
    print("cursor now at:", get_cursor_pos(alice, cursor).pos)
    print("rich segments:", alice.get_text("article").get_richtext_value()[:2])

    undo.undo()  # undoes only alice's "always ", keeps bob's prefix
    print("after undo:", alice.get_text("article").to_string())

    # time travel
    f = alice.oplog_frontiers()
    alice.checkout(lt.Frontiers())
    print("at genesis:", alice.get_value())
    alice.checkout_to_latest()
    print("back to latest:", alice.get_text("article").to_string())


if __name__ == "__main__":
    main()
