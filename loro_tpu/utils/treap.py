"""Order-statistic treap over sequence elements.

Host-side replacement for the reference's `generic-btree` rope
(crates/generic-btree): O(log n) insert-after, rank and k-th-visible
queries over elements that each carry a total width (1) and a visible
width (0 when tombstoned / zero-width anchor).

Nodes are intrusive: any object with the `TreapNode` slots mixed in can
live in the tree (SeqElem uses this).  Priorities come from a
deterministic xorshift of an insertion tick so behavior reproduces
across runs.
"""
from __future__ import annotations

from typing import Iterator, Optional


class TreapNode:
    __slots__ = ("tl", "tr", "tp", "tpri", "tcount", "tvis", "vis_w")

    def init_treap(self, vis_w: int) -> None:
        self.tl: Optional[TreapNode] = None
        self.tr: Optional[TreapNode] = None
        self.tp: Optional[TreapNode] = None
        self.tpri: int = 0
        self.vis_w: int = vis_w  # this node's own visible width
        self.tcount: int = 1  # subtree node count
        self.tvis: int = vis_w  # subtree visible width


def _cnt(n: Optional[TreapNode]) -> int:
    return n.tcount if n is not None else 0


def _vis(n: Optional[TreapNode]) -> int:
    return n.tvis if n is not None else 0


class Treap:
    """Sequence of TreapNodes in insertion order with rank/select."""

    __slots__ = ("root", "_tick")

    def __init__(self) -> None:
        self.root: Optional[TreapNode] = None
        self._tick = 0x9E3779B97F4A7C15

    # deterministic pseudo-random priority (splitmix64)
    def _next_pri(self) -> int:
        self._tick = (self._tick + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = self._tick
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return z ^ (z >> 31)

    # -- internal maintenance ----------------------------------------
    @staticmethod
    def _pull(n: TreapNode) -> None:
        # hot path: inlined child reads (called ~20x per insert)
        l = n.tl
        r = n.tr
        if l is not None:
            if r is not None:
                n.tcount = 1 + l.tcount + r.tcount
                n.tvis = n.vis_w + l.tvis + r.tvis
            else:
                n.tcount = 1 + l.tcount
                n.tvis = n.vis_w + l.tvis
        elif r is not None:
            n.tcount = 1 + r.tcount
            n.tvis = n.vis_w + r.tvis
        else:
            n.tcount = 1
            n.tvis = n.vis_w

    def _rot_up(self, n: TreapNode) -> None:
        """Rotate n above its parent."""
        p = n.tp
        g = p.tp
        if p.tl is n:
            p.tl = n.tr
            if n.tr is not None:
                n.tr.tp = p
            n.tr = p
        else:
            p.tr = n.tl
            if n.tl is not None:
                n.tl.tp = p
            n.tl = p
        p.tp = n
        n.tp = g
        if g is None:
            self.root = n
        elif g.tl is p:
            g.tl = n
        else:
            g.tr = n
        self._pull(p)
        self._pull(n)

    def _bubble(self, n: TreapNode) -> None:
        while n.tp is not None and n.tp.tpri < n.tpri:
            self._rot_up(n)
        # fix sizes up the remaining path
        p = n.tp
        while p is not None:
            self._pull(p)
            p = p.tp

    # -- public api ---------------------------------------------------
    def insert_after(self, after: Optional[TreapNode], n: TreapNode) -> None:
        """Insert n immediately after `after` (None = at the beginning)."""
        n.tpri = self._next_pri()
        n.tl = n.tr = None
        if self.root is None:
            n.tp = None
            self.root = n
            self._pull(n)
            return
        if after is None:
            cur = self.root
            while cur.tl is not None:
                cur = cur.tl
            cur.tl = n
            n.tp = cur
        elif after.tr is None:
            after.tr = n
            n.tp = after
        else:
            cur = after.tr
            while cur.tl is not None:
                cur = cur.tl
            cur.tl = n
            n.tp = cur
        self._pull(n)
        self._bubble(n)

    def set_visible(self, n: TreapNode, vis_w: int) -> None:
        if n.vis_w == vis_w:
            return
        n.vis_w = vis_w
        cur: Optional[TreapNode] = n
        while cur is not None:
            self._pull(cur)
            cur = cur.tp

    def visible_rank(self, n: TreapNode) -> int:
        """Number of visible width units strictly before n."""
        r = _vis(n.tl)
        cur = n
        while cur.tp is not None:
            p = cur.tp
            if p.tr is cur:
                r += _vis(p.tl) + p.vis_w
            cur = p
        return r

    def total_rank(self, n: TreapNode) -> int:
        r = _cnt(n.tl)
        cur = n
        while cur.tp is not None:
            p = cur.tp
            if p.tr is cur:
                r += _cnt(p.tl) + 1
            cur = p
        return r

    def find_visible(self, k: int) -> Optional[TreapNode]:
        """The visible node covering visible index k (0-based)."""
        cur = self.root
        if cur is None or k < 0 or k >= cur.tvis:
            return None
        while True:
            lv = _vis(cur.tl)
            if k < lv:
                cur = cur.tl
            elif k < lv + cur.vis_w:
                return cur
            else:
                k -= lv + cur.vis_w
                cur = cur.tr

    @property
    def visible_len(self) -> int:
        return _vis(self.root)

    @property
    def total_len(self) -> int:
        return _cnt(self.root)

    @staticmethod
    def successor(n: TreapNode) -> Optional[TreapNode]:
        if n.tr is not None:
            cur = n.tr
            while cur.tl is not None:
                cur = cur.tl
            return cur
        cur = n
        while cur.tp is not None and cur.tp.tr is cur:
            cur = cur.tp
        return cur.tp

    @staticmethod
    def predecessor(n: TreapNode) -> Optional[TreapNode]:
        if n.tl is not None:
            cur = n.tl
            while cur.tr is not None:
                cur = cur.tr
            return cur
        cur = n
        while cur.tp is not None and cur.tp.tl is cur:
            cur = cur.tp
        return cur.tp

    def first(self) -> Optional[TreapNode]:
        cur = self.root
        while cur is not None and cur.tl is not None:
            cur = cur.tl
        return cur

    def __iter__(self) -> Iterator[TreapNode]:
        n = self.first()
        while n is not None:
            yield n
            n = self.successor(n)
