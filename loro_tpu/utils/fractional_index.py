"""Byte-string fractional indexes for tree sibling ordering.

reference: crates/fractional_index (FractionalIndex over Vec<u8>,
TERMINATOR=128).  Keys sort lexicographically as bytes; `key_between`
produces a key strictly between its arguments (None = ±infinity) by
base-256 midpointing, growing one byte only when digits are adjacent.
"""
from __future__ import annotations

from typing import List, Optional

DEFAULT = bytes([128])


def key_between(a: Optional[bytes], b: Optional[bytes]) -> bytes:
    """A key x with a < x < b (lexicographic bytes; None = ±inf)."""
    if a is not None and b is not None:
        assert a < b, f"key_between requires a < b, got {a.hex()} >= {b.hex()}"
    av = a or b""
    out = bytearray()
    i = 0
    binf = b is None
    while True:
        da = av[i] if i < len(av) else 0
        db = 256 if binf else (b[i] if i < len(b) else 256)  # type: ignore[index]
        if db - da > 1:
            out.append((da + db) // 2)
            return bytes(out)
        out.append(da)
        if db == da + 1:
            binf = True  # b-side exhausted at this digit; remaining bound is +inf
        i += 1


def keys_between(a: Optional[bytes], b: Optional[bytes], n: int) -> List[bytes]:
    """n evenly-generated keys strictly between a and b, in order."""
    out: List[bytes] = []
    lo = a
    for _ in range(n):
        k = key_between(lo, b)
        out.append(k)
        lo = k
    return out
