"""Lightweight tracing: spans -> Chrome trace JSON.

reference: the `tracing` spans on loro's hot paths + dev-utils
(crates/dev-utils/src/lib.rs:9-31 writes ./log/trace-*.json for
chrome://tracing when DEBUG is set).  Same contract here: zero overhead
unless enabled (env LORO_TPU_TRACE=1 or enable()); `span(name)` context
managers on import/merge/export paths; dump() writes the trace file.

Span observers (obs bridge): loro_tpu.obs.enable_span_metrics()
registers a callback that receives every span's (name, duration_s) so
ONE instrumentation point feeds both the chrome trace and the metrics
histograms.  With no observers and tracing disabled, span() keeps its
zero-overhead contract.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

_enabled = os.environ.get("LORO_TPU_TRACE", "") not in ("", "0")
_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_t0 = time.perf_counter()
_span_observers: List[Callable[[str, float], None]] = []


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def add_span_observer(fn: Callable[[str, float], None]) -> None:
    """Register a (name, duration_seconds) callback fired at every span
    exit, independent of chrome-trace collection (the obs bridge)."""
    if fn not in _span_observers:
        _span_observers.append(fn)


def remove_span_observer(fn: Callable[[str, float], None]) -> None:
    try:
        _span_observers.remove(fn)
    except ValueError:
        pass


@contextmanager
def span(name: str, **args):
    """Trace span; ~zero cost when tracing is off and no observer is
    registered."""
    if not _enabled and not _span_observers:
        yield
        return
    start = (time.perf_counter() - _t0) * 1e6
    try:
        yield
    finally:
        end = (time.perf_counter() - _t0) * 1e6
        if _enabled:
            with _lock:
                _events.append(
                    {
                        "name": name,
                        "ph": "X",
                        "ts": start,
                        "dur": end - start,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() % 0xFFFF,
                        "args": {k: _safe(v) for k, v in args.items()} if args else {},
                    }
                )
        for fn in _span_observers:
            fn(name, (end - start) * 1e-6)


def instant(name: str, **args) -> None:
    if not _enabled:
        return
    with _lock:
        _events.append(
            {
                "name": name,
                "ph": "i",
                "ts": (time.perf_counter() - _t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 0xFFFF,
                "s": "t",
                "args": {k: _safe(v) for k, v in args.items()} if args else {},
            }
        )


def _safe(v):
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)


def events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def clear() -> None:
    with _lock:
        _events.clear()


def dump(path: Optional[str] = None) -> str:
    """Write chrome://tracing JSON; returns the path."""
    if path is None:
        os.makedirs("log", exist_ok=True)
        path = os.path.join("log", f"trace-{int(time.time())}.json")  # tpulint: disable=LT-TIME(artifact filename stamp; wall time is the point)
    with _lock:
        data = {"traceEvents": list(_events)}
    with open(path, "w") as f:
        json.dump(data, f)
    return path
