"""Lightweight tracing: spans -> Chrome trace JSON, plus trace contexts.

reference: the `tracing` spans on loro's hot paths + dev-utils
(crates/dev-utils/src/lib.rs:9-31 writes ./log/trace-*.json for
chrome://tracing when DEBUG is set).  Same contract here: zero overhead
unless enabled (env LORO_TPU_TRACE=1 or enable()); `span(name)` context
managers on import/merge/export paths; dump() writes the trace file.

Span observers (obs bridge): loro_tpu.obs.enable_span_metrics()
registers a callback that receives every span's (name, duration_s) so
ONE instrumentation point feeds both the chrome trace and the metrics
histograms.  ``instant()`` events fire observers too (duration 0.0), so
the bridge sees point events as well as spans.  With no observers and
tracing disabled, span() keeps its zero-overhead contract.

The observer list is COPY-ON-WRITE: ``span()`` iterates an immutable
tuple snapshot while add/remove rebuild it under the module lock, so a
concurrent (un)register can never skip or double-fire an observer
mid-iteration (the ISSUE 14 race: list.append/remove raced the
unsynchronized iteration in span()).

Trace contexts (docs/OBSERVABILITY.md "Request tracing"): a trace id is
a process-unique opaque string minted at a request entry point
(``new_trace_id()``) and carried end-to-end — push tickets, pipeline
rounds, WAL round stamps, follower applies.  ``set_current()`` /
``current()`` keep a per-thread ambient id so deep layers (the WAL
append inside a pipelined commit) can stamp the request that caused
them without threading an argument through every signature.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

_enabled = os.environ.get("LORO_TPU_TRACE", "") not in ("", "0")
_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_t0 = time.perf_counter()
# COW snapshot: readers iterate whatever tuple they loaded; writers
# replace the whole tuple under _lock (never mutate in place)
_span_observers: Tuple[Callable[[str, float], None], ...] = ()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def add_span_observer(fn: Callable[[str, float], None]) -> None:
    """Register a (name, duration_seconds) callback fired at every span
    exit and instant event, independent of chrome-trace collection (the
    obs bridge).  Copy-on-write under the module lock: a span iterating
    the old snapshot is unaffected."""
    global _span_observers
    with _lock:
        if fn not in _span_observers:
            _span_observers = _span_observers + (fn,)


def remove_span_observer(fn: Callable[[str, float], None]) -> None:
    global _span_observers
    with _lock:
        if fn in _span_observers:
            _span_observers = tuple(f for f in _span_observers if f is not fn)


# -- trace contexts ----------------------------------------------------
# process-unique request ids: pid + monotonic counter (deterministic,
# no wall clock / randomness — chaos replays stay byte-stable where it
# matters and the id still tells you which process minted it)
_trace_counter = itertools.count(1)
_ambient = threading.local()


def new_trace_id(prefix: str = "t") -> str:
    """Mint a process-unique trace id (cheap: one counter bump)."""
    return f"{prefix}{os.getpid():x}-{next(_trace_counter):x}"


def set_current(trace_id: Optional[str]) -> None:
    """Install the ambient trace id for this thread (None clears it).
    Deep layers read it via ``current()`` to stamp work they perform on
    behalf of a request (e.g. the WAL append inside a commit)."""
    _ambient.trace = trace_id


def current() -> Optional[str]:
    """The ambient trace id of this thread, or None."""
    return getattr(_ambient, "trace", None)


@contextmanager
def ambient(trace_id: Optional[str]):
    """Scope an ambient trace id (restores the previous one)."""
    prev = current()
    set_current(trace_id)
    try:
        yield
    finally:
        set_current(prev)


@contextmanager
def span(name: str, **args):
    """Trace span; ~zero cost when tracing is off and no observer is
    registered."""
    obs = _span_observers  # COW snapshot: stable for this span
    if not _enabled and not obs:
        yield
        return
    start = (time.perf_counter() - _t0) * 1e6
    try:
        yield
    finally:
        end = (time.perf_counter() - _t0) * 1e6
        if _enabled:
            with _lock:
                _events.append(
                    {
                        "name": name,
                        "ph": "X",
                        "ts": start,
                        "dur": end - start,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() % 0xFFFF,
                        "args": {k: _safe(v) for k, v in args.items()} if args else {},
                    }
                )
        for fn in obs:
            fn(name, (end - start) * 1e-6)


def instant(name: str, **args) -> None:
    obs = _span_observers
    if not _enabled and not obs:
        return
    if _enabled:
        with _lock:
            _events.append(
                {
                    "name": name,
                    "ph": "i",
                    "ts": (time.perf_counter() - _t0) * 1e6,
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 0xFFFF,
                    "s": "t",
                    "args": {k: _safe(v) for k, v in args.items()} if args else {},
                }
            )
    # point events reach the obs bridge too (duration 0.0): counters of
    # named occurrences, not timings
    for fn in obs:
        fn(name, 0.0)


def _safe(v):
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)


def events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def clear() -> None:
    with _lock:
        _events.clear()


# dump() collision guard: two dumps in the same wall-second used to
# overwrite each other (the ISSUE 14 satellite) — the default filename
# now carries pid + a monotonic per-process counter
_dump_counter = itertools.count(1)


def dump(path: Optional[str] = None) -> str:
    """Write chrome://tracing JSON; returns the path.  The default
    path is collision-free across processes and across same-second
    dumps (timestamp + pid + per-process counter)."""
    if path is None:
        os.makedirs("log", exist_ok=True)
        path = os.path.join(
            "log",
            f"trace-{int(time.time())}-{os.getpid()}-{next(_dump_counter)}.json",  # tpulint: disable=LT-TIME(artifact filename stamp; wall time is the point)
        )
    with _lock:
        data = {"traceEvents": list(_events)}
    with open(path, "w") as f:
        json.dump(data, f)
    return path
