"""JSONPath queries over live documents + subscriptions.

reference: crates/loro-internal/src/jsonpath/ (pest grammar + evaluator
+ subscribe_jsonpath re-evaluating on events).  Supported syntax:
  $                     root
  .key  ['key']         member access
  [0]  [-1]             index access (negative from end)
  [s:e]  [s:e:st]       slices
  .*  [*]               wildcard
  ..key  ..*            recursive descent
  [?(@.k op lit)]       filters (==, !=, <, <=, >, >=)
Results are deep values (container contents resolve recursively).
"""
from __future__ import annotations

import re
from typing import Any, Callable, List, Tuple

from .doc import LoroDoc, LoroError


class JsonPathError(LoroError):
    pass


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<root>\$)
  | (?P<recursive>\.\.(?:(?P<rkey>[A-Za-z_][\w]*)|(?P<rstar>\*)|(?P<rbracket>(?=\[)))?)
  | (?P<member>\.(?P<key>[A-Za-z_][\w]*))
  | (?P<wildcard>\.\*)
  | (?P<bracket>\[(?P<body>[^\]]*)\])
    """,
    re.VERBOSE,
)


def parse(path: str) -> List[Tuple]:
    """Parse into a list of step tuples."""
    steps: List[Tuple] = []
    i = 0
    if not path:
        raise JsonPathError("empty path")
    while i < len(path):
        m = _TOKEN_RE.match(path, i)
        if m is None:
            raise JsonPathError(f"bad jsonpath at {i}: {path[i:]!r}")
        if m.group("root"):
            steps.append(("root",))
        elif m.group("recursive") is not None:
            if m.group("rkey"):
                steps.append(("recursive", m.group("rkey")))
            elif m.group("rstar"):
                steps.append(("recursive", None))
            else:
                steps.append(("recursive_pending",))  # ..[...] handled next
        elif m.group("member"):
            steps.append(("key", m.group("key")))
        elif m.group("wildcard"):
            steps.append(("wild",))
        elif m.group("bracket") is not None:
            steps.append(_parse_bracket(m.group("body")))
        i = m.end()
    # fold recursive_pending + following step
    out: List[Tuple] = []
    i = 0
    while i < len(steps):
        if steps[i][0] == "recursive_pending":
            if i + 1 >= len(steps):
                raise JsonPathError("dangling '..'")
            out.append(("recursive_step", steps[i + 1]))
            i += 2
        else:
            out.append(steps[i])
            i += 1
    return out


_FILTER_RE = re.compile(
    r"^\?\(\s*@\.(?P<key>[\w]+)\s*(?P<op>==|!=|<=|>=|<|>)\s*(?P<lit>.+?)\s*\)$"
)


def _parse_bracket(body: str) -> Tuple:
    body = body.strip()
    if body == "*":
        return ("wild",)
    quoted = (body.startswith("'") and body.endswith("'")) or (
        body.startswith('"') and body.endswith('"')
    )
    if quoted and "," not in body:
        return ("key", body[1:-1])
    fm = _FILTER_RE.match(body)
    if fm:
        lit = fm.group("lit")
        if lit.startswith(("'", '"')):
            val: Any = lit[1:-1]
        elif lit in ("true", "false"):
            val = lit == "true"
        elif lit == "null":
            val = None
        else:
            try:
                val = int(lit)
            except ValueError:
                try:
                    val = float(lit)
                except ValueError:
                    raise JsonPathError(f"bad filter literal {lit!r}")
        return ("filter", fm.group("key"), fm.group("op"), val)
    if ":" in body:
        parts = body.split(":")
        if len(parts) not in (2, 3):
            raise JsonPathError(f"bad slice {body!r}")
        try:
            nums = [int(p) if p.strip() else None for p in parts]
        except ValueError:
            raise JsonPathError(f"bad slice {body!r}")
        while len(nums) < 3:
            nums.append(None)
        if nums[2] == 0:
            raise JsonPathError("slice step cannot be 0")
        return ("slice", nums[0], nums[1], nums[2])
    if "," in body:
        keys = []
        for part in body.split(","):
            part = part.strip()
            if part.startswith(("'", '"')):
                keys.append(part[1:-1])
            else:
                keys.append(int(part))
        return ("union", tuple(keys))
    try:
        return ("index", int(body))
    except ValueError:
        raise JsonPathError(f"bad bracket body {body!r}")


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def _children(v: Any) -> List[Any]:
    if isinstance(v, dict):
        return list(v.values())
    if isinstance(v, list):
        return list(v)
    return []


def _descendants(v: Any) -> List[Any]:
    out = [v]
    stack = [v]
    while stack:
        cur = stack.pop()
        for c in _children(cur):
            out.append(c)
            stack.append(c)
    return out


_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: _cmp_ok(a, b) and a < b,
    "<=": lambda a, b: _cmp_ok(a, b) and a <= b,
    ">": lambda a, b: _cmp_ok(a, b) and a > b,
    ">=": lambda a, b: _cmp_ok(a, b) and a >= b,
}


def _cmp_ok(a: Any, b: Any) -> bool:
    return isinstance(a, (int, float)) and isinstance(b, (int, float)) or (
        isinstance(a, str) and isinstance(b, str)
    )


def _apply_step(nodes: List[Any], step: Tuple) -> List[Any]:
    kind = step[0]
    out: List[Any] = []
    if kind == "root":
        return nodes
    for v in nodes:
        if kind == "key":
            if isinstance(v, dict) and step[1] in v:
                out.append(v[step[1]])
        elif kind == "index":
            if isinstance(v, list):
                i = step[1]
                if -len(v) <= i < len(v):
                    out.append(v[i])
        elif kind == "slice":
            if isinstance(v, list):
                out.extend(v[step[1] : step[2] : step[3]])
        elif kind == "wild":
            out.extend(_children(v))
        elif kind == "union":
            for k in step[1]:
                if isinstance(k, str) and isinstance(v, dict) and k in v:
                    out.append(v[k])
                elif isinstance(k, int) and isinstance(v, list) and -len(v) <= k < len(v):
                    out.append(v[k])
        elif kind == "recursive":
            key = step[1]
            for d in _descendants(v):
                if key is None:
                    out.extend(_children(d))
                elif isinstance(d, dict) and key in d:
                    out.append(d[key])
        elif kind == "recursive_step":
            inner = step[1]
            for d in _descendants(v):
                out.extend(_apply_step([d], inner))
        elif kind == "filter":
            _, key, op, lit = step
            for c in _children(v):
                if isinstance(c, dict) and key in c and _OPS[op](c[key], lit):
                    out.append(c)
        else:  # pragma: no cover
            raise JsonPathError(f"unknown step {step}")
    return out


def query(doc: LoroDoc, path: str) -> List[Any]:
    """Evaluate a JSONPath against the doc's deep value.
    reference API: loro.rs jsonpath / loro/src/lib.rs:1358."""
    steps = parse(path)
    nodes: List[Any] = [doc.get_deep_value()]
    for step in steps:
        nodes = _apply_step(nodes, step)
    return nodes


def subscribe_jsonpath(
    doc: LoroDoc, path: str, cb: Callable[[List[Any]], None]
) -> Callable[[], None]:
    """Re-evaluate on every doc event; callback fires when the result
    set changes (reference: jsonpath/subscription.rs)."""
    steps = parse(path)  # validate early
    last: List[Any] = query(doc, path)

    def on_event(_ev) -> None:
        nonlocal last
        cur = query(doc, path)
        if cur != last:
            last = cur
            cb(cur)

    return doc.subscribe_root(on_event)
