"""JSONPath queries over live documents + subscriptions.

reference: crates/loro-internal/src/jsonpath/ (jsonpath.pest grammar,
parser.rs, jsonpath_impl.rs evaluator, subscription.rs).  The full
grammar is supported (recursive-descent parser mirroring the pest
rules, not a translation):

  $                         root
  .key  ['key']  ["key"]    member access (string escapes incl. \\uXXXX)
  [0]  [-1]                 index access (negative from end)
  [s:e]  [s:e:st]           slices (negative step supported)
  .*  [*]                   wildcard
  ..key  ..*  ..[...]       recursive descent
  [sel, sel, ...]           unions of ANY selectors (names, indexes,
                            slices, wildcards, filters)
  [? expr]  [?(expr)]       filters: comparisons (==, !=, <, <=, >, >=,
                            contains, in), existence tests (?@.k),
                            logical && || !, parentheses, literals
                            (numbers, strings, true/false/null, arrays),
                            nested queries from @ or $, and the
                            standard functions length(), count(),
                            value(), match(), search()
Results are deep values (container contents resolve recursively).
"""
from __future__ import annotations

import re as _re
from typing import Any, Callable, List, Optional, Tuple

from .doc import LoroDoc, LoroError


class JsonPathError(LoroError):
    pass


_NOTHING = object()  # absent value (RFC 9535 "Nothing")

_NAME_FIRST = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_NAME_CHARS = _NAME_FIRST | set("0123456789")


# ---------------------------------------------------------------------------
# parsing (recursive descent over the pest grammar's shape)
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    # -- low-level ----------------------------------------------------
    def err(self, msg: str) -> JsonPathError:
        return JsonPathError(f"{msg} at {self.i}: {self.s[self.i : self.i + 20]!r}")

    def peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def starts(self, tok: str) -> bool:
        return self.s.startswith(tok, self.i)

    def eat(self, tok: str) -> bool:
        if self.starts(tok):
            self.i += len(tok)
            return True
        return False

    def expect(self, tok: str) -> None:
        if not self.eat(tok):
            raise self.err(f"expected {tok!r}")

    def ws(self) -> None:
        while self.peek() and self.peek() in " \t\n\r":
            self.i += 1

    # -- names / literals ---------------------------------------------
    def member_name(self) -> str:
        start = self.i
        c = self.peek()
        if c not in _NAME_FIRST and not (c and ord(c) >= 0x80):
            raise self.err("expected member name")
        self.i += 1
        while True:
            c = self.peek()
            if c in _NAME_CHARS or (c and ord(c) >= 0x80):
                self.i += 1
            else:
                break
        return self.s[start : self.i]

    def string_literal(self) -> str:
        quote = self.peek()
        assert quote in "'\""
        self.i += 1
        out: List[str] = []
        while True:
            c = self.peek()
            if not c:
                raise self.err("unterminated string")
            if c == quote:
                self.i += 1
                return "".join(out)
            if c == "\\":
                self.i += 1
                e = self.peek()
                self.i += 1
                mapped = {
                    "b": "\b", "f": "\f", "n": "\n", "r": "\r", "t": "\t",
                    "/": "/", "\\": "\\", "'": "'", '"': '"',
                }.get(e)
                if mapped is not None:
                    out.append(mapped)
                elif e == "u":
                    hex4 = self.s[self.i : self.i + 4]
                    if len(hex4) != 4 or any(h not in "0123456789abcdefABCDEF" for h in hex4):
                        raise self.err("bad \\u escape")
                    self.i += 4
                    cp = int(hex4, 16)
                    if 0xD800 <= cp <= 0xDBFF and self.s.startswith("\\u", self.i):
                        lo4 = self.s[self.i + 2 : self.i + 6]
                        if len(lo4) == 4:
                            lo = int(lo4, 16)
                            if 0xDC00 <= lo <= 0xDFFF:
                                self.i += 6
                                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00)
                    out.append(chr(cp))
                else:
                    raise self.err(f"bad escape \\{e}")
            else:
                out.append(c)
                self.i += 1

    def int_literal(self) -> Optional[int]:
        start = self.i
        self.eat("-")
        if not self.peek().isdigit():
            self.i = start
            return None
        while self.peek().isdigit():
            self.i += 1
        return int(self.s[start : self.i])

    def number_literal(self) -> Any:
        start = self.i
        if self.int_literal() is None:
            raise self.err("expected number")
        is_float = False
        if self.peek() == "." and self.s[self.i + 1 : self.i + 2].isdigit():
            is_float = True
            self.i += 1
            while self.peek().isdigit():
                self.i += 1
        if self.peek() in "eE":
            is_float = True
            self.i += 1
            if self.peek() in "+-":
                self.i += 1
            if not self.peek().isdigit():
                raise self.err("bad exponent")
            while self.peek().isdigit():
                self.i += 1
        text = self.s[start : self.i]
        return float(text) if is_float else int(text)

    # -- path ---------------------------------------------------------
    def parse_path(self) -> List[Tuple]:
        self.ws()
        self.expect("$")
        steps = self.parse_segments()
        self.ws()
        if self.i != len(self.s):
            raise self.err("trailing input")
        return steps

    def parse_segments(self) -> List[Tuple]:
        """Segments until something that isn't a segment start."""
        steps: List[Tuple] = []
        while True:
            self.ws()
            if self.starts(".."):
                self.i += 2
                if self.peek() == "[":
                    steps.append(("recursive_step", self.bracketed()))
                elif self.eat("*"):
                    steps.append(("recursive_step", ("select", (("wild",),))))
                else:
                    steps.append(("recursive_step", ("select", (("key", self.member_name()),))))
            elif self.peek() == ".":
                self.i += 1
                if self.eat("*"):
                    steps.append(("select", (("wild",),)))
                else:
                    steps.append(("select", (("key", self.member_name()),)))
            elif self.peek() == "[":
                steps.append(self.bracketed())
            else:
                return steps

    def bracketed(self) -> Tuple:
        self.expect("[")
        sels = [self.selector()]
        self.ws()
        while self.eat(","):
            self.ws()
            sels.append(self.selector())
            self.ws()
        self.expect("]")
        return ("select", tuple(sels))

    def selector(self) -> Tuple:
        self.ws()
        c = self.peek()
        if c == "*":
            self.i += 1
            return ("wild",)
        if c and c in "'\"":
            return ("key", self.string_literal())
        if c == "?":
            self.i += 1
            self.ws()
            return ("filter", self.logical_or())
        # slice or index
        start = self.int_literal()
        self.ws()
        if self.peek() == ":":
            self.i += 1
            self.ws()
            stop = self.int_literal()
            self.ws()
            step = None
            if self.eat(":"):
                self.ws()
                step = self.int_literal()
            if step == 0:
                raise self.err("slice step cannot be 0")
            return ("slice", start, stop, step)
        if start is None:
            raise self.err("expected selector")
        return ("index", start)

    # -- filter expressions -------------------------------------------
    def logical_or(self) -> Tuple:
        terms = [self.logical_and()]
        while True:
            self.ws()
            if not self.eat("||"):
                break
            terms.append(self.logical_and())
        return terms[0] if len(terms) == 1 else ("or", tuple(terms))

    def logical_and(self) -> Tuple:
        terms = [self.basic_expr()]
        while True:
            self.ws()
            if not self.eat("&&"):
                break
            terms.append(self.basic_expr())
        return terms[0] if len(terms) == 1 else ("and", tuple(terms))

    def basic_expr(self) -> Tuple:
        self.ws()
        neg = False
        while self.eat("!"):
            neg = not neg
            self.ws()
        if self.eat("("):
            inner = self.logical_or()
            self.ws()
            self.expect(")")
            expr = inner
            # a paren group may still be the left side of a comparison?
            # grammar says no (paren_expr is a basic_expr) — keep as-is
        else:
            expr = self.comparison_or_test()
        return ("not", expr) if neg else expr

    def comparison_or_test(self) -> Tuple:
        left = self.comparable()
        self.ws()
        for op in ("==", "!=", "<=", ">=", "<", ">", "contains", "in"):
            if self.starts(op):
                # word ops need a boundary so keys like "interest" are safe
                end = self.i + len(op)
                if op.isalpha() and end < len(self.s) and self.s[end] in _NAME_CHARS:
                    continue
                self.i = end
                self.ws()
                right = self.comparable()
                return ("cmp", op, left, right)
        # bare test: must be a query or function, not a literal
        if left[0] not in ("query", "func"):
            raise self.err("literal is not a valid filter test")
        return ("test", left)

    def comparable(self) -> Tuple:
        self.ws()
        c = self.peek()
        if c and c in "'\"":
            return ("lit", self.string_literal())
        if c == "@" or c == "$":
            self.i += 1
            kind = "rel" if c == "@" else "abs"
            return ("query", kind, tuple(self.parse_segments()))
        if c == "[":  # array literal
            self.i += 1
            items: List[Any] = []
            self.ws()
            if not self.eat("]"):
                while True:
                    lit = self.comparable()
                    if lit[0] != "lit":
                        raise self.err("array literals hold literals only")
                    items.append(lit[1])
                    self.ws()
                    if self.eat("]"):
                        break
                    self.expect(",")
                    self.ws()
            return ("lit", items)
        if self.starts("true") :
            self.i += 4
            return ("lit", True)
        if self.starts("false"):
            self.i += 5
            return ("lit", False)
        if self.starts("null"):
            self.i += 4
            return ("lit", None)
        if c.isdigit() or c == "-":
            return ("lit", self.number_literal())
        if c in _NAME_FIRST:
            save = self.i
            name = self.member_name()
            self.ws()
            if self.eat("("):
                args: List[Tuple] = []
                self.ws()
                if not self.eat(")"):
                    while True:
                        args.append(self.comparable())
                        self.ws()
                        if self.eat(")"):
                            break
                        self.expect(",")
                return ("func", name, tuple(args))
            self.i = save
            raise self.err(f"bare name {name!r} is not a comparable")
        raise self.err("expected comparable")


def parse(path: str) -> List[Tuple]:
    """Parse into a list of step tuples (raises JsonPathError)."""
    if not path:
        raise JsonPathError("empty path")
    return _Parser(path).parse_path()


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def _children(v: Any) -> List[Any]:
    if isinstance(v, dict):
        return list(v.values())
    if isinstance(v, list):
        return list(v)
    return []


def _descendants(v: Any) -> List[Any]:
    out = [v]
    stack = [v]
    while stack:
        cur = stack.pop()
        for c in _children(cur):
            out.append(c)
            stack.append(c)
    return out


def _cmp_ok(a: Any, b: Any) -> bool:
    num = isinstance(a, (int, float)) and not isinstance(a, bool)
    numb = isinstance(b, (int, float)) and not isinstance(b, bool)
    return (num and numb) or (isinstance(a, str) and isinstance(b, str))


def _strict_eq(a: Any, b: Any) -> bool:
    """JSON-typed equality: bools never equal numbers (Python's
    True == 1 would diverge from the reference's serde_json values)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_strict_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(_strict_eq(v, b[k]) for k, v in a.items())
    return a == b


def _eval_cmp(op: str, a: Any, b: Any) -> bool:
    if op == "==":
        if a is _NOTHING or b is _NOTHING:
            return a is b
        return _strict_eq(a, b)
    if op == "!=":
        return not _eval_cmp("==", a, b)
    if a is _NOTHING or b is _NOTHING:
        return False
    if op == "contains":
        if isinstance(a, list):
            return any(_strict_eq(x, b) for x in a)
        if isinstance(a, str) and isinstance(b, str):
            return b in a
        return False
    if op == "in":
        return _eval_cmp("contains", b, a)
    if not _cmp_ok(a, b):
        return False
    return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b}[op]


def _apply_selector(sel: Tuple, v: Any, root: Any) -> List[Any]:
    kind = sel[0]
    if kind == "key":
        if isinstance(v, dict) and sel[1] in v:
            return [v[sel[1]]]
        return []
    if kind == "index":
        if isinstance(v, list) and -len(v) <= sel[1] < len(v):
            return [v[sel[1]]]
        return []
    if kind == "slice":
        if isinstance(v, list):
            return v[sel[1] : sel[2] : sel[3]]
        return []
    if kind == "wild":
        return _children(v)
    if kind == "filter":
        return [c for c in _children(v) if _truthy(_eval_expr(sel[1], c, root))]
    raise JsonPathError(f"unknown selector {sel}")  # pragma: no cover


def _apply_step(nodes: List[Any], step: Tuple, root: Any) -> List[Any]:
    out: List[Any] = []
    if step[0] == "select":
        for v in nodes:
            for sel in step[1]:
                out.extend(_apply_selector(sel, v, root))
        return out
    if step[0] == "recursive_step":
        inner = step[1]
        for v in nodes:
            for d in _descendants(v):
                out.extend(_apply_step([d], inner, root))
        return out
    raise JsonPathError(f"unknown step {step}")  # pragma: no cover


def _eval_query(q: Tuple, current: Any, root: Any) -> List[Any]:
    _, kind, segments = q
    nodes = [current if kind == "rel" else root]
    for step in segments:
        nodes = _apply_step(nodes, step, root)
    return nodes


def _singular(v: Tuple, current: Any, root: Any) -> Any:
    """Comparable -> value or _NOTHING."""
    if v[0] == "lit":
        return v[1]
    if v[0] == "query":
        nodes = _eval_query(v, current, root)
        return nodes[0] if len(nodes) == 1 else _NOTHING
    if v[0] == "func":
        return _eval_func(v, current, root)
    raise JsonPathError(f"bad comparable {v}")  # pragma: no cover


def _eval_func(f: Tuple, current: Any, root: Any) -> Any:
    _, name, args = f

    def arg_value(i: int) -> Any:
        return _singular(args[i], current, root)

    if name == "length" and len(args) == 1:
        v = arg_value(0)
        if isinstance(v, (str, list, dict)):
            return len(v)
        return _NOTHING
    if name == "count" and len(args) == 1 and args[0][0] == "query":
        return len(_eval_query(args[0], current, root))
    if name == "value" and len(args) == 1 and args[0][0] == "query":
        nodes = _eval_query(args[0], current, root)
        return nodes[0] if len(nodes) == 1 else _NOTHING
    if name in ("match", "search") and len(args) == 2:
        s = arg_value(0)
        pat = arg_value(1)
        if not isinstance(s, str) or not isinstance(pat, str):
            return False
        try:
            rx = _re.compile(pat)
        except _re.error:
            raise JsonPathError(f"bad regex {pat!r}")
        return bool(rx.fullmatch(s) if name == "match" else rx.search(s))
    raise JsonPathError(f"unknown function {name}/{len(args)}")


def _truthy(v: Any) -> bool:
    if v is _NOTHING:
        return False
    return bool(v)


def _eval_expr(e: Tuple, current: Any, root: Any) -> Any:
    kind = e[0]
    if kind == "or":
        return any(_truthy(_eval_expr(t, current, root)) for t in e[1])
    if kind == "and":
        return all(_truthy(_eval_expr(t, current, root)) for t in e[1])
    if kind == "not":
        return not _truthy(_eval_expr(e[1], current, root))
    if kind == "cmp":
        # existential comparison over query nodelists (reference
        # jsonpath_impl.rs compare_expr: any node pair may satisfy;
        # empty nodelists never do — even for ==)
        _, op, left, right = e

        def operand(v):
            if v[0] == "query":
                return "nodes", _eval_query(v, current, root)
            return "val", _singular(v, current, root)

        lk, lv = operand(left)
        rk, rv = operand(right)
        if lk == "nodes" and rk == "nodes":
            return any(_eval_cmp(op, a, b) for a in lv for b in rv)
        if lk == "nodes":
            return any(_eval_cmp(op, a, rv) for a in lv)
        if rk == "nodes":
            return any(_eval_cmp(op, lv, b) for b in rv)
        return _eval_cmp(op, lv, rv)
    if kind == "test":
        inner = e[1]
        if inner[0] == "query":
            return bool(_eval_query(inner, current, root))
        return _truthy(_eval_func(inner, current, root))
    raise JsonPathError(f"unknown expr {e}")  # pragma: no cover


def _eval_steps(doc: LoroDoc, steps: List[Tuple]) -> List[Any]:
    root: Any = doc.get_deep_value()
    nodes: List[Any] = [root]
    for step in steps:
        nodes = _apply_step(nodes, step, root)
    return nodes


def query(doc: LoroDoc, path: str) -> List[Any]:
    """Evaluate a JSONPath against the doc's deep value.
    reference API: loro.rs jsonpath / loro/src/lib.rs:1358."""
    return _eval_steps(doc, parse(path))


def subscribe_jsonpath(
    doc: LoroDoc, path: str, cb: Callable[[List[Any]], None]
) -> Callable[[], None]:
    """Re-evaluate on every doc event; callback fires when the result
    set changes (reference: jsonpath/subscription.rs)."""
    steps = parse(path)  # parse ONCE; events re-evaluate, not re-parse
    last: List[Any] = _eval_steps(doc, steps)

    def on_event(_ev) -> None:
        nonlocal last
        cur = _eval_steps(doc, steps)
        if cur != last:
            last = cur
            cb(cur)

    return doc.subscribe_root(on_event)
