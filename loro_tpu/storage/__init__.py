"""Reusable storage layer: the general ordered-KV store + SSTable
format (reference: crates/kv-store — MemKvStore over prefix-compressed
SSTable blocks, lib.rs:1-143)."""
from .kv import CompressionType, MemKvStore

__all__ = ["MemKvStore", "CompressionType"]
