"""General ordered key-value store with an SSTable wire format.

Reference parity: crates/kv-store (MemKvStore, lib.rs:1-143;
mem_store.rs:39-298 get/set/compare_and_swap/remove/scan/export_all/
import_all; block.rs prefix-compressed blocks; sstable.rs block metas
with lazy hydration).  Re-designed for this codebase, not translated:

  * our own wire layout (magic "LTKV"), zlib for block compression
    (the image has no LZ4) and crc32 per block (no xxhash32) — the
    same envelope/checksum family as codec/binary.py;
  * imported SSTables hydrate per block on first touch, the same lazy
    pattern as oplog/change_store.py cold blocks and snapshot v4 state
    segments;
  * one memtable (dict + sorted-key cache) over at most one imported
    table — the store is a document-scale component, not an LSM tree;
    deletes write tombstones that shadow imported entries.

Wire layout:

  "LTKV" | u8 version | u8 compression | blocks... | meta | u32 meta_off

  normal block (compressed then checksummed):
      payload = count:varint, then per pair:
          prefix_len:varint  suffix:bytes_  value:bytes_
      block bytes = compress(payload) + crc32(compressed):u32le
  large block: payload = key:bytes_ value:bytes (rest) — one pair whose
      value exceeds the block size, never split across blocks.
  meta: count:varint, then per block:
      offset:varint  length:varint  flags:u8(1=large)  first_key:bytes_
      last_key:bytes_ (omitted for large blocks — first==last)
"""
from __future__ import annotations

import bisect
import struct
import zlib
from enum import IntEnum
from typing import Dict, Iterator, List, Optional, Tuple

from ..codec.binary import Reader, Writer
from ..errors import DecodeError

MAGIC = b"LTKV"
VERSION = 1
DEFAULT_BLOCK_SIZE = 4096


class CompressionType(IntEnum):
    NONE = 0
    ZLIB = 1


_TOMBSTONE = None  # memtable value for deletes shadowing imported keys


class _Block:
    """One SSTable block: raw bytes + lazily-decoded pairs."""

    __slots__ = ("raw", "large", "first_key", "last_key", "compression", "_pairs")

    def __init__(self, raw, large, first_key, last_key, compression):
        self.raw = raw
        self.large = large
        self.first_key = first_key
        self.last_key = last_key
        self.compression = compression
        self._pairs: Optional[List[Tuple[bytes, bytes]]] = None

    def pairs(self) -> List[Tuple[bytes, bytes]]:
        if self._pairs is None:
            self._pairs = self._decode()
        return self._pairs

    def _decode(self) -> List[Tuple[bytes, bytes]]:
        if len(self.raw) < 4:
            raise DecodeError("kv block truncated")
        body, crc = self.raw[:-4], struct.unpack("<I", self.raw[-4:])[0]
        if zlib.crc32(body) != crc:
            raise DecodeError("kv block checksum mismatch")
        if self.compression == CompressionType.ZLIB:
            try:
                body = zlib.decompress(body)
            except zlib.error as e:
                raise DecodeError(f"kv block decompress failed: {e}") from None
        r = Reader(bytes(body))
        try:
            if self.large:
                key = r.bytes_()
                return [(key, bytes(r.buf[r.i :]))]
            out: List[Tuple[bytes, bytes]] = []
            prev = b""
            for _ in range(r.varint()):
                plen = r.varint()
                if plen > len(prev):
                    raise DecodeError("kv block prefix overrun")
                key = prev[:plen] + r.bytes_()
                out.append((key, r.bytes_()))
                prev = key
            return out
        except (IndexError, ValueError) as e:
            raise DecodeError(f"kv block malformed: {e}") from None


class MemKvStore:
    """Ordered byte-key/byte-value store.  All keys/values are bytes;
    iteration is lexicographic.  `export_all` emits the SSTable bytes;
    `import_all` replaces the store's imported table (lazy blocks) and
    clears the memtable."""

    def __init__(
        self,
        block_size: int = DEFAULT_BLOCK_SIZE,
        compression: CompressionType = CompressionType.ZLIB,
    ):
        self.block_size = block_size
        self.compression = CompressionType(compression)
        self._mem: Dict[bytes, Optional[bytes]] = {}
        self._mem_keys: Optional[List[bytes]] = []  # sorted; None = dirty
        self._blocks: List[_Block] = []
        self._block_first: List[bytes] = []  # bisect index over blocks

    # -- point ops -----------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        key = bytes(key)
        if key in self._mem:
            return self._mem[key]
        return self._sstable_get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(key, (bytes, bytearray)) or not isinstance(
            value, (bytes, bytearray)
        ):
            raise TypeError("MemKvStore keys and values are bytes")
        key = bytes(key)
        if key not in self._mem:
            self._mem_keys = None
        self._mem[key] = bytes(value)

    def compare_and_swap(
        self, key: bytes, old: Optional[bytes], new: bytes
    ) -> bool:
        if self.get(key) != old:
            return False
        self.set(key, new)
        return True

    def remove(self, key: bytes) -> None:
        key = bytes(key)
        if self._sstable_get(key) is not None:
            if key not in self._mem:
                self._mem_keys = None
            self._mem[key] = _TOMBSTONE  # shadow the imported pair
        else:
            if key in self._mem:
                self._mem_keys = None
            self._mem.pop(key, None)

    def contains_key(self, key: bytes) -> bool:
        return self.get(key) is not None

    # -- iteration -----------------------------------------------------
    def scan(
        self,
        start: Optional[bytes] = None,
        end: Optional[bytes] = None,
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        """Yield (key, value) with start <= key < end, ordered (or
        reversed) lexicographically, memtable entries shadowing
        imported ones."""
        mem_iter = self._mem_range(start, end, reverse)
        sst_iter = self._sstable_range(start, end, reverse)
        a = next(mem_iter, None)
        b = next(sst_iter, None)
        while a is not None or b is not None:
            if b is None:
                pick_mem = True
            elif a is None:
                pick_mem = False
            elif a[0] == b[0]:
                b = next(sst_iter, None)  # memtable shadows
                continue
            else:
                pick_mem = (a[0] < b[0]) != reverse
            if pick_mem:
                if a[1] is not _TOMBSTONE:
                    yield a
                a = next(mem_iter, None)
            else:
                yield b
                b = next(sst_iter, None)

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        return self.scan()

    def __len__(self) -> int:
        n = sum(1 for _ in self.scan())
        return n

    def len(self) -> int:
        return len(self)

    def is_empty(self) -> bool:
        return next(self.scan(), None) is None

    def size(self) -> int:
        """Approximate byte size of live pairs."""
        return sum(len(k) + len(v) for k, v in self.scan())

    # -- export / import ----------------------------------------------
    def export_all(self) -> bytes:
        w = Writer()
        w.buf += MAGIC
        w.u8(VERSION)
        w.u8(int(self.compression))
        metas: List[Tuple[int, int, bool, bytes, bytes]] = []

        def flush(pairs: List[Tuple[bytes, bytes]]) -> None:
            if not pairs:
                return
            body = Writer()
            body.varint(len(pairs))
            prev = b""
            for k, v in pairs:
                p = _common_prefix_len(prev, k)
                body.varint(p)
                body.bytes_(k[p:])
                body.bytes_(v)
                prev = k
            raw = self._compress(bytes(body.buf))
            metas.append((len(w.buf), len(raw) + 4, False, pairs[0][0], pairs[-1][0]))
            w.buf += raw
            w.u32le(zlib.crc32(raw))

        pending: List[Tuple[bytes, bytes]] = []
        pending_sz = 0
        for k, v in self.scan():
            if len(v) > self.block_size:
                flush(pending)
                pending, pending_sz = [], 0
                body = Writer()
                body.bytes_(k)
                body.buf += v
                raw = self._compress(bytes(body.buf))
                metas.append((len(w.buf), len(raw) + 4, True, k, k))
                w.buf += raw
                w.u32le(zlib.crc32(raw))
                continue
            pending.append((k, v))
            pending_sz += len(k) + len(v) + 4
            if pending_sz >= self.block_size:
                flush(pending)
                pending, pending_sz = [], 0
        flush(pending)

        meta_off = len(w.buf)
        if meta_off >= 2**32:
            # the v1 trailer is a fixed u32le; block metas are varints,
            # so only the trailer caps the format at 4 GiB of blocks
            raise ValueError(
                f"LTKV v1 store exceeds the 4 GiB trailer limit "
                f"(blocks span {meta_off} bytes); split the store"
            )
        w.varint(len(metas))
        for off, ln, large, first, last in metas:
            w.varint(off)
            w.varint(ln)
            w.u8(1 if large else 0)
            w.bytes_(first)
            if not large:
                w.bytes_(last)
        w.u32le(meta_off)
        return bytes(w.buf)

    def import_all(self, data: bytes) -> None:
        """Replace store contents with the SSTable (blocks stay encoded
        until first touch; metas and checking are eager)."""
        if len(data) < 10 or data[:4] != MAGIC:
            raise DecodeError("not an LTKV store")
        version = data[4]
        if version > VERSION:
            raise DecodeError(f"LTKV v{version} newer than supported v{VERSION}")
        try:
            compression = CompressionType(data[5])
        except ValueError:
            raise DecodeError(f"unknown LTKV compression {data[5]}") from None
        (meta_off,) = struct.unpack("<I", data[-4:])
        if not 6 <= meta_off <= len(data) - 4:
            raise DecodeError("LTKV meta offset out of range")
        r = Reader(data[meta_off : len(data) - 4])
        blocks: List[_Block] = []
        try:
            n = r.varint()
            for _ in range(n):
                off = r.varint()
                ln = r.varint()
                large = r.u8() == 1
                first = r.bytes_()
                last = first if large else r.bytes_()
                if not 6 <= off <= off + ln <= meta_off:
                    raise DecodeError("LTKV block span out of range")
                blocks.append(_Block(data[off : off + ln], large, first, last, compression))
            if not r.eof():
                raise DecodeError("LTKV trailing meta bytes")
        except (IndexError, ValueError) as e:
            raise DecodeError(f"LTKV meta malformed: {e}") from None
        for a, b in zip(blocks, blocks[1:]):
            if not a.last_key <= b.first_key:
                raise DecodeError("LTKV blocks out of order")
        self._mem.clear()
        self._mem_keys = []
        self._blocks = blocks
        self._block_first = [b.first_key for b in blocks]

    # -- internals -----------------------------------------------------
    def _compress(self, body: bytes) -> bytes:
        if self.compression == CompressionType.ZLIB:
            return zlib.compress(body, 6)
        return body

    def _mem_sorted(self) -> List[bytes]:
        if self._mem_keys is None:
            self._mem_keys = sorted(self._mem)
        return self._mem_keys

    def _mem_range(self, start, end, reverse) -> Iterator[Tuple[bytes, Optional[bytes]]]:
        ks = self._mem_sorted()
        lo = bisect.bisect_left(ks, start) if start is not None else 0
        hi = bisect.bisect_left(ks, end) if end is not None else len(ks)
        rng = range(hi - 1, lo - 1, -1) if reverse else range(lo, hi)
        for i in rng:
            yield ks[i], self._mem[ks[i]]

    def _block_idx_for(self, key: bytes) -> int:
        """Index of the block that may contain key, or -1."""
        i = bisect.bisect_right(self._block_first, key) - 1
        if i < 0 or key > self._blocks[i].last_key:
            return -1
        return i

    def _sstable_get(self, key: bytes) -> Optional[bytes]:
        i = self._block_idx_for(key)
        if i < 0:
            return None
        pairs = self._blocks[i].pairs()
        j = bisect.bisect_left(pairs, (key, b""))
        if j < len(pairs) and pairs[j][0] == key:
            return pairs[j][1]
        return None

    def _sstable_range(self, start, end, reverse) -> Iterator[Tuple[bytes, bytes]]:
        if not self._blocks:
            return
        lo_b = 0
        if start is not None:
            lo_b = max(0, bisect.bisect_right(self._block_first, start) - 1)
            if start > self._blocks[lo_b].last_key:
                lo_b += 1
        hi_b = len(self._blocks)
        if end is not None:
            hi_b = bisect.bisect_right(self._block_first, end)
        rng = range(hi_b - 1, lo_b - 1, -1) if reverse else range(lo_b, hi_b)
        for bi in rng:
            pairs = self._blocks[bi].pairs()
            it = reversed(pairs) if reverse else iter(pairs)
            for k, v in it:
                if start is not None and k < start:
                    continue
                if end is not None and k >= end:
                    continue
                yield k, v

    # test/diagnostic hook: how many imported blocks were ever decoded
    @property
    def decoded_blocks(self) -> int:
        return sum(1 for b in self._blocks if b._pairs is not None)

    @property
    def n_blocks(self) -> int:
        return len(self._blocks)


def _common_prefix_len(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i
