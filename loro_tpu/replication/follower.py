"""Hot-standby followers: a rolling ``recover_server`` over shipped WAL.

A follower owns a full durable-directory REPLICA of its leader —
checkpoint rungs plus visibility-gated WAL segment bytes, streamed by
``shipper.WalShipper`` — and keeps a live ResidentServer continuously
applying the shipped rounds (the exact ``_replay_journal_tail``
machinery crash recovery uses, run incrementally instead of once).
The follower therefore has everything recovery would need at every
instant: device batch state, the in-memory journal tail, mirror
anchors folded at every shipped checkpoint marker, and a WAL copy
whose torn tails truncate exactly like a reopen.

Lifecycle:

- ``Follower(source_dir, follower_dir, leader=...)`` bootstraps:
  ship rungs + visible segments, ``persist.recover_server`` the copy,
  then DETACH the copy's append handle — while following, the ship
  path owns the files and the resident refuses ``ingest()`` typed
  (a follower is read-only; pushes get ``NotLeader`` at the sync
  front).
- ``catch_up()`` ships new bytes, applies complete frames past the
  acked offsets (round records through the replay path; checkpoint
  markers fold the anchor and trim the journal via
  ``resident.checkpoint()``; prune markers above the applied epoch
  raise typed ``StaleFollower``), feeds the read-only sync front, and
  acks the applied epoch into the leader's ``replication.json`` (the
  WAL retention pin).
- ``promote()`` fences the old leader (token bump — checked at its
  every WAL append), drains the remaining tail with dead-leader
  visibility, reopens the WAL copy for append and flips the follower
  writable.  Loses nothing at or below the leader's acked watermark.

``ShardedFollower`` runs one Follower per ``shard-NN/`` stream and
tracks ``sharding.json`` (snapshot BEFORE each ship pass, so placement
never gets ahead of applied rounds — a mid-stream migration becomes
visible exactly when its round has applied).

Fault sites: ``repl_ship`` (shipper reads), ``repl_apply`` (before
each applied round), ``repl_promote`` (promotion entry).
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..analysis.lockwitness import named_rlock
from ..obs import flight
from ..errors import (
    FencedLeader,
    ReplicationError,
    ResilienceError,
    StaleFollower,
)
from ..obs import metrics as obs
from ..persist.wal import (
    R_CKPT,
    R_META,
    R_PRUNE,
    R_ROUND,
    _scan_segment,
    _seg_index,
    _seg_name,
)
from ..resilience import faultinject

faultinject.register_site(
    "repl_apply", "Follower apply loop: fires before each shipped "
    "round applies to the follower batch")
faultinject.register_site(
    "repl_promote", "Follower.promote entry: fires before the fencing "
    "token bump (a retried promote starts clean)")
from .manifest import DEFAULT_STALE_AFTER_S, ReplicationManifest
from .shipper import WalShipper


def _install_fence(srv, man: ReplicationManifest, token: int) -> None:
    """Arm the WAL append fence: any append after a newer token exists
    fail-stops typed ``FencedLeader`` before a byte lands."""

    def fence():
        cur, holder = man.leader()
        if cur > token:
            obs.counter(
                "repl.fenced_appends_total",
                "WAL appends refused on a fenced (deposed) leader",
            ).inc()
            raise FencedLeader(
                f"leader token {token} superseded by {cur} (held by "
                f"{holder!r}) — this leader is fenced and must fail-stop"
            )

    srv._durable.wal.fence = fence


def enable(leader, leader_id: str = "leader",
           stale_after: float = DEFAULT_STALE_AFTER_S, clock=None):
    """Make a durable leader replicable: claim the leader token in its
    ``replication.json``, install the append fence, publish the fsync
    visibility marker (cross-process followers), and pin WAL segment
    pruning at the registered followers' acked epochs.  A sharded
    leader enables every shard (per-shard manifests); returns the
    manifest (or the per-shard list)."""
    shards = getattr(leader, "shards", None)
    if shards is not None:
        return [enable(s, leader_id=leader_id, stale_after=stale_after,
                       clock=clock) for s in shards]
    log = leader._durable
    if log is None:
        raise ReplicationError(
            "replication needs a durable leader (durable_dir=) — the "
            "WAL is the shipped stream"
        )
    man = ReplicationManifest(log.dir, clock=clock, stale_after=stale_after)
    token = man.claim_leader(leader_id)
    _install_fence(leader, man, token)
    log.wal.retention_floor = man.pinned_floor
    log.wal.publish_visibility = True
    log.wal._publish_visibility()
    return man


class Follower:
    """One leader-directory → follower-directory replication stream
    with a live, read-only ResidentServer applying it.

    ``leader=`` the live leader ResidentServer when in-process (exact
    durable-watermark visibility); omit for a leader in another
    process (the ``.visible`` marker gates the tail).  ``sync_server=``
    attaches a ``ReadOnlySyncServer`` (pull/poll/presence; push raises
    ``NotLeader``) fed from the applied rounds, created as soon as the
    served container id is known."""

    def __init__(self, source_dir: str, follower_dir: str,
                 follower_id: str = "follower", leader=None, mesh=None,
                 sync_server: bool = True, clock=None,
                 stale_after: float = DEFAULT_STALE_AFTER_S, **sync_kw):
        self._lock = named_rlock("repl.follower")
        self.source_dir = source_dir
        self.follower_dir = follower_dir
        self.follower_id = follower_id
        self._mesh = mesh
        self._clock = time.time if clock is None else clock
        self.shipper = WalShipper(source_dir, leader=leader)
        self._src_manifest = ReplicationManifest(
            source_dir, clock=clock, stale_after=stale_after
        )
        self._stale_after = stale_after
        self.wal_dir = os.path.join(follower_dir, "wal")
        self.ckpt_dir = os.path.join(follower_dir, "ckpt")
        os.makedirs(self.wal_dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self._want_sync = sync_server
        self._sync_kw = dict(sync_kw)
        self.sync = None
        self.promoted = False
        self.rounds_applied = 0
        self.torn_tails = 0
        self.ckpts_applied = 0
        self.catch_ups = 0
        self.leader_epoch_seen = 0
        # replication-lag attribution (docs/OBSERVABILITY.md): shipped
        # round records carry the leader's wall-clock stamp + trace id;
        # each apply measures leader-commit -> follower-apply lag and
        # keeps a bounded sample window (``lag_samples()``)
        self._lag_samples: deque = deque(maxlen=256)
        # segment indexes whose full SEALED extent we hold (sealed at
        # source = rotation fsync'd it closed, and we shipped to its
        # size).  The continuity check below needs it: a source segment
        # that vanishes (pruned after the staleness cutoff dropped our
        # retention pin) while our copy was still partial is LOST
        # history — resuming over the hole must fail typed, never
        # fabricate a truncated replay.
        self._complete_segs: set = set()
        # bootstrap: ship, recover the copy, detach its append handle
        self._ship_files()
        from ..persist import recover_server

        self.resident = recover_server(follower_dir, mesh=mesh, fsync=False)
        # a tiered leader's tier map rides its rungs, so the recovered
        # copy can hold cold docs — whose every exit (read, oracle
        # seeding, the shipped-checkpoint rehydrate) needs the durable
        # log this follower is about to detach.  Flatten them warm
        # while the log is still attached; nothing re-demotes until
        # promotion re-attaches it.
        batch = getattr(self.resident, "batch", None)
        if hasattr(batch, "flatten_cold"):
            batch.flatten_cold()
        log = self.resident._durable
        self.resident._durable = None
        # while following, the ship path owns the WAL files and writes
        # land ONLY via promotion — ingest on the follower raises typed
        self.resident._durable_closed = True
        log.close()
        self._applied_off: Dict[int, int] = self._local_offsets()
        self.applied_epoch = self.resident.epoch
        self.leader_epoch_seen = self.applied_epoch
        self._ensure_sync()
        self._ack()

    # -- shipping ------------------------------------------------------
    def _local_offsets(self) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for name in os.listdir(self.wal_dir):
            if name.startswith("seg-") and name.endswith(".log"):
                out[_seg_index(name)] = os.path.getsize(
                    os.path.join(self.wal_dir, name)
                )
        return out

    def _ship_files(self) -> int:
        """Stream new rung files and visible segment bytes into the
        follower directory; mirror leader-side segment pruning for
        fully-applied local segments.  Returns bytes shipped."""
        shipped = 0
        for name, path in self.shipper.ckpt_files():
            dst = os.path.join(self.ckpt_dir, name)
            if os.path.exists(dst):
                continue
            try:
                data = self.shipper.read(path, 0, os.path.getsize(path))
            except OSError:
                continue  # rung pruned mid-listing: the next pass settles
            tmp = dst + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, dst)
            shipped += len(data)
        extent = self.shipper.extent()
        max_idx = max((i for i, _p, _v in extent), default=None)
        for idx, spath, vis in extent:
            dst = os.path.join(self.wal_dir, _seg_name(idx))
            have = os.path.getsize(dst) if os.path.exists(dst) else 0
            if vis > have:
                try:
                    data = self.shipper.read(spath, have, vis - have)
                except OSError:
                    continue  # segment pruned mid-pass: next pass (or
                    #           the continuity scan) settles it
                with open(dst, "ab") as f:
                    f.write(data)
                shipped += len(data)
                # advance by what the read actually RETURNED — a short
                # read (source torn/truncated, a mangle fault) must not
                # mark a partial copy complete below
                have += len(data)
            if have >= vis and (idx != max_idx or self.shipper.final):
                # sealed at source (or dead-leader drain: whole files
                # are the truth): our copy is complete
                self._complete_segs.add(idx)
        self._check_continuity(extent)
        # local copies of segments the leader pruned: drop the ones the
        # apply loop has fully consumed (bounded follower disk)
        src_idx = {i for i, _p, _v in extent}
        applied_off = getattr(self, "_applied_off", None)
        if src_idx:
            newest = max(src_idx)
            for name in list(os.listdir(self.wal_dir)):
                if not (name.startswith("seg-") and name.endswith(".log")):
                    continue
                idx = _seg_index(name)
                path = os.path.join(self.wal_dir, name)
                if idx in src_idx or idx >= newest:
                    continue
                if applied_off is None:
                    # bootstrap: applied offsets are not built yet
                    # (recovery is rung-based) and the unguarded pop
                    # below would AttributeError __init__ into a
                    # permanent crash loop.  Settle only the 0-byte
                    # artifact of a crashed pass (segment file created
                    # but never written — nothing to lose, and the
                    # recover_server magic check would refuse it);
                    # segments with content wait for real offsets
                    if os.path.getsize(path) == 0:
                        os.unlink(path)
                    continue
                if applied_off.get(idx, 0) >= os.path.getsize(path):
                    os.unlink(path)
                    applied_off.pop(idx, None)
        for name, path in self.shipper.extra_files():
            try:
                data = self.shipper.read(path, 0, os.path.getsize(path))
            except OSError:
                continue
            tmp = os.path.join(self.follower_dir, name + ".tmp")
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(self.follower_dir, name))
        return shipped

    def _check_continuity(self, extent) -> None:
        """The ship-scan staleness gate: every segment index between
        our oldest local copy and the source's oldest surviving segment
        must be held complete OR fully applied locally.  A hole means
        the leader pruned history we never finished shipping (our
        retention pin was dropped by the staleness cutoff) — fail typed
        ``StaleFollower`` before a single round past the hole can
        apply; re-bootstrap from a fresh directory instead (the shipped
        checkpoint rung covers the pruned rounds there).

        This scan is the EARLY, legible half of a two-part gate; the
        exact backstop is the ``R_PRUNE`` marker every segment re-emits
        (``_apply_new`` raises typed when the prune floor is above our
        applied epoch).  Hence two accepting cases besides a complete
        copy: bootstrap (``_applied_off`` not built yet — recovery is
        rung-based, and the shipped rung covers everything the source
        ever pruned), and a local copy every byte of which has applied
        (anything pruned past it sits under the source's prune floor,
        which the apply-time gate checks against our applied epoch) —
        a restarted follower or one whose caught-up active segment was
        sealed-and-pruned in one leader checkpoint must not be forced
        into a needless re-bootstrap."""
        if not extent:
            return
        applied = getattr(self, "_applied_off", None)
        if applied is None:
            return  # bootstrap: recover_server + shipped rungs decide
        lo = min(i for i, _p, _v in extent)
        have = self._local_offsets()
        for i in range(min(have, default=lo), lo):
            if i in have and (
                i in self._complete_segs
                or applied.get(i, 0) >= have[i]
            ):
                continue
            obs.counter(
                "repl.stale_resumes_total",
                "followers that resumed past pruned WAL history "
                "(typed StaleFollower at the ship scan)",
            ).inc()
            raise StaleFollower(
                f"{self.follower_id}: WAL segment {i} was pruned at the "
                f"source before this follower finished shipping it "
                f"(oldest surviving source segment is {lo}) — the "
                "retention pin was dropped by the staleness cutoff; "
                "re-bootstrap from a fresh directory"
            )

    def _scan_new(self) -> List[Tuple[int, int, object]]:
        """Complete frames past the applied offsets across local
        segments, in order: ``(seg_index, frame_end_offset, record)``.
        A torn frame truncates the local copy back to the last good
        boundary (the WAL reopen contract) — the next ship pass
        re-streams clean bytes from the source."""
        out: List[Tuple[int, int, object]] = []
        names = sorted(
            n for n in os.listdir(self.wal_dir)
            if n.startswith("seg-") and n.endswith(".log")
        )
        for name in names:
            idx = _seg_index(name)
            path = os.path.join(self.wal_dir, name)
            floor = max(self._applied_off.get(idx, 5), 5)
            if os.path.getsize(path) <= floor:
                continue
            recs: List[Tuple[int, object]] = []
            info = _scan_segment(path, idx,
                                 lambda off, r: recs.append((off, r)))
            if info.torn:
                with open(path, "r+b") as f:
                    f.truncate(info.good_bytes)
                self.torn_tails += 1
                obs.counter(
                    "repl.torn_shipped_tails_total",
                    "torn shipped tails truncated at the follower "
                    "(the WAL reopen contract)",
                ).inc()
            ends = [off for off, _r in recs[1:]] + [info.good_bytes]
            for (off, rec), end in zip(recs, ends):
                if off >= floor:
                    out.append((idx, end, rec))
        return out

    # -- applying ------------------------------------------------------
    def _apply_new(self) -> int:
        """Apply every newly complete shipped record in order; returns
        rounds applied.  Caller holds the follower lock."""
        applied = 0
        srv = self.resident
        for idx, end, rec in self._scan_new():
            if rec.rtype == R_ROUND:
                if rec.epoch > self.applied_epoch:
                    faultinject.check("repl_apply", rtype="round")
                    srv._replay_journal_tail(
                        [(rec.epoch, rec.cid, list(rec.updates))]
                    )
                    self.applied_epoch = srv.epoch
                    applied += 1
                    self.rounds_applied += 1
                    if rec.stamp_us:
                        # measured leader-commit -> follower-apply lag:
                        # the shipped wall stamp against our clock (same
                        # machine or NTP-close hosts; negative skew
                        # clamps to 0 — lag is never negative)
                        lag_s = max(
                            0.0, self._clock() - rec.stamp_us * 1e-6
                        )
                        self._lag_samples.append(
                            (rec.epoch, rec.trace, lag_s * 1e3)
                        )
                        obs.histogram(
                            "repl.apply_lag_seconds",
                            "leader WAL-stamp -> follower apply "
                            "(measured replication lag attribution)",
                        ).observe(lag_s, follower=self.follower_id,
                                  exemplar=rec.trace)
                        flight.record(
                            "repl.apply", epoch=rec.epoch,
                            trace=rec.trace,
                            lag_ms=round(lag_s * 1e3, 3),
                        )
                    if self.sync is not None:
                        self.sync._apply_replicated(
                            self.applied_epoch, rec.cid, rec.updates
                        )
            elif rec.rtype == R_CKPT:
                self._on_ckpt(rec)
            elif rec.rtype == R_PRUNE:
                if rec.epoch > self.applied_epoch:
                    obs.counter(
                        "repl.stale_resumes_total",
                        "followers that resumed past pruned WAL "
                        "history (typed StaleFollower at the ship "
                        "scan)",
                    ).inc()
                    raise StaleFollower(
                        f"{self.follower_id}: leader pruned WAL history "
                        f"below epoch {rec.epoch} but this follower only "
                        f"applied epoch {self.applied_epoch} — the "
                        "retention pin was dropped (staleness cutoff); "
                        "re-bootstrap from a fresh directory"
                    )
            elif rec.rtype == R_META:
                pass
            self._applied_off[idx] = max(
                self._applied_off.get(idx, 5), end
            )
        if applied:
            obs.counter(
                "repl.applied_rounds_total",
                "shipped WAL rounds applied by followers",
            ).inc(applied)
        obs.gauge(
            "repl.applied_epoch", "newest epoch the follower applied"
        ).set(self.applied_epoch, follower=self.follower_id)
        return applied

    def _on_ckpt(self, rec) -> None:
        """Replicate the leader's checkpoint boundary: fold the mirror
        anchor, trim the journal tail, re-seed the bounded-recover base
        — ``resident.checkpoint()`` with no durable log attached does
        exactly that (the rung FILE itself arrives via shipping)."""
        srv = self.resident
        try:
            srv.checkpoint()
        except ResilienceError:
            # degraded follower: the anchor fold needs device state;
            # keep applying on the mirror, checkpoint again post-recover
            return
        self.ckpts_applied += 1
        obs.counter(
            "repl.ckpts_applied_total",
            "leader checkpoint boundaries replicated on followers",
        ).inc()

    def _ensure_sync(self) -> None:
        if not self._want_sync or self.sync is not None:
            return
        srv = self.resident
        if srv.family not in ("map", "counter") and srv._cid is None:
            return  # no round shipped yet: the cid is not known
        from .readonly import ReadOnlySyncServer

        self.sync = ReadOnlySyncServer.over(
            srv, leader_id=self._leader_id_hint(), **self._sync_kw
        )

    def _leader_id_hint(self) -> Optional[str]:
        try:
            return self._src_manifest.leader()[1]
        except ReplicationError:
            return None

    def _ack(self) -> None:
        try:
            self._src_manifest.ack_follower(
                self.follower_id, self.applied_epoch
            )
        except OSError:
            pass  # source gone (dead leader): nothing left to pin

    # -- public surface ------------------------------------------------
    def catch_up(self) -> dict:
        """One ship+apply pass; returns the report dict.  Safe to call
        from a polling loop at any cadence."""
        with self._lock:
            if self.promoted:
                return self.report()
            shipped = self._ship_files()
            applied = self._apply_new()
            self._ensure_sync()
            self.catch_ups += 1
            lead = self.shipper.leader
            if lead is not None:
                self.leader_epoch_seen = max(
                    self.leader_epoch_seen, lead.durable_epoch
                )
            self.leader_epoch_seen = max(
                self.leader_epoch_seen, self.applied_epoch
            )
            self._ack()
            obs.gauge(
                "repl.lag_epochs",
                "epochs the follower trails the leader's durable "
                "watermark",
            ).set(self.lag_epochs, follower=self.follower_id)
            return dict(self.report(), shipped_bytes=shipped,
                        rounds=applied)

    @property
    def lag_epochs(self) -> int:
        return max(0, self.leader_epoch_seen - self.applied_epoch)

    def warm_read_plane(self, max_window: Optional[int] = None,
                        max_peers: int = 4) -> int:
        """Pre-compile the read-only sync front's selection shapes
        (``SyncServer.warm_read_plane``); 0 when no front is attached
        yet."""
        with self._lock:
            if self.sync is None:
                return 0
            return self.sync.warm_read_plane(max_window, max_peers)

    def lag_samples(self) -> List[Tuple[int, Optional[str], float]]:
        """Recent ``(epoch, trace_id, lag_ms)`` apply-lag attributions
        (bounded window; empty before the first stamped round applies
        — e.g. a leader that predates round stamping).  Snapshotted
        under the follower lock: catch_up() appends concurrently (the
        lock is reentrant, so catch_up's own report() call is fine)."""
        with self._lock:
            return list(self._lag_samples)

    def report(self) -> dict:
        out = {
            "follower_id": self.follower_id,
            "applied_epoch": self.applied_epoch,
            "leader_epoch_seen": self.leader_epoch_seen,
            "lag_epochs": self.lag_epochs,
            "rounds_applied": self.rounds_applied,
            "ckpts_applied": self.ckpts_applied,
            "torn_tails": self.torn_tails,
            "catch_ups": self.catch_ups,
            "promoted": self.promoted,
        }
        lags = sorted(ms for _e, _t, ms in self.lag_samples())
        if lags:
            out["apply_lag_ms_p50"] = round(lags[len(lags) // 2], 3)
            out["apply_lag_ms_max"] = round(lags[-1], 3)
        return out

    def promote(self, leader_id: Optional[str] = None,
                fsync=True) -> "object":
        """Fail the leader over to this follower: bump the leader token
        (fencing every older holder at its next append), drain the
        shipped tail with dead-leader visibility (torn tail truncated,
        the reopen contract), reopen the WAL copy for append, and flip
        the follower writable.  Returns the now-writable
        ResidentServer.  Idempotent once promoted."""
        with self._lock:
            if self.promoted:
                return self.resident
            faultinject.check("repl_promote")
            leader_id = leader_id or self.follower_id
            token = self._src_manifest.bump_token(leader_id)
            self.shipper.final = True
            self.shipper.leader = None
            self._ship_files()
            self._apply_new()
            from ..persist import DurableLog

            log = DurableLog(self.follower_dir, fsync=fsync)
            srv = self.resident
            srv.attach_durable(log)
            own = ReplicationManifest(
                self.follower_dir, clock=self._clock,
                stale_after=self._stale_after,
            )
            own.claim_leader(leader_id, token=token)
            _install_fence(srv, own, token)
            log.wal.retention_floor = own.pinned_floor
            log.wal.publish_visibility = True
            log.wal._publish_visibility()
            if self.sync is not None:
                self.sync._promote_writable()
            self.promoted = True
            obs.counter(
                "repl.followers_promoted_total",
                "followers flipped writable by promote()",
            ).inc()
            return srv

    def close(self) -> None:
        with self._lock:
            if self.sync is not None:
                self.sync.close()
            self.resident.close()

    def __enter__(self) -> "Follower":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ShardedFollower:
    """Follower fleet for a ``ShardedResidentServer`` durable dir: one
    per-shard WAL stream (``shard-NN/``), placement tracked from
    ``sharding.json`` — snapshotted BEFORE each ship pass so reads
    never route through a placement whose migration round has not
    applied yet.  ``durable_epoch``-style watermarks aggregate min-
    over-shards; lag is max-over-shards."""

    def __init__(self, source_dir: str, follower_dir: str,
                 follower_id: str = "follower", leader=None, mesh=None,
                 clock=None, stale_after: float = DEFAULT_STALE_AFTER_S):
        from ..parallel.mesh import make_mesh, shard_meshes
        from ..parallel.placement import ShardPlacement
        from ..parallel.sharded import load_manifest

        manifest = load_manifest(source_dir)
        if manifest is None:
            raise ReplicationError(
                f"{source_dir}: no sharding.json — use Follower for "
                "single-server dirs"
            )
        self.source_dir = source_dir
        self.follower_dir = follower_dir
        self.follower_id = follower_id
        os.makedirs(follower_dir, exist_ok=True)
        self.manifest = manifest
        self.n_shards = int(manifest["shards"])
        self.n_docs = int(manifest["n_docs"])
        self.family = manifest["family"]
        self.mesh = mesh if mesh is not None else make_mesh()
        self.meshes = shard_meshes(self.mesh, self.n_shards)
        self.placement = ShardPlacement.from_manifest(manifest)
        leader_shards = getattr(leader, "shards", None)
        self.shards: List[Follower] = []
        for s in range(self.n_shards):
            self.shards.append(Follower(
                os.path.join(source_dir, f"shard-{s:02d}"),
                os.path.join(follower_dir, f"shard-{s:02d}"),
                follower_id=follower_id,
                leader=leader_shards[s] if leader_shards else None,
                mesh=self.meshes[s], sync_server=False, clock=clock,
                stale_after=stale_after,
            ))
        self.promoted = False
        self._write_local_manifest()

    def _write_local_manifest(self) -> None:
        path = os.path.join(self.follower_dir, "sharding.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.manifest, f)
        os.replace(tmp, path)

    def catch_up(self) -> dict:
        from ..parallel.placement import ShardPlacement
        from ..parallel.sharded import load_manifest

        # snapshot FIRST: every placement this manifest names had its
        # migration round made durable before the manifest write, so
        # the ship pass below always applies at least that far
        man = load_manifest(self.source_dir)
        reports = [f.catch_up() for f in self.shards]
        if man is not None:
            self.manifest = man
            self.placement = ShardPlacement.from_manifest(man)
            self._write_local_manifest()
        return {
            "applied_epoch": self.applied_epoch,
            "lag_epochs": self.lag_epochs,
            "shards": reports,
        }

    def _emap(self, s: int):
        from ..parallel.placement import _EpochMap

        emaps = self.manifest.get("emaps") or [[[0, 0]]] * self.n_shards
        return _EpochMap.decode(emaps[s] if s < len(emaps) else [[0, 0]])

    @property
    def applied_epoch(self) -> int:
        """Fleet-global applied watermark: min over shards of the
        shard-local applied epoch translated through the manifest's
        epoch maps."""
        return min(
            self._emap(s).to_global(f.applied_epoch)
            for s, f in enumerate(self.shards)
        )

    @property
    def lag_epochs(self) -> int:
        g = int(self.manifest.get("global_epoch", 0))
        return max(0, g - self.applied_epoch)

    # -- reads (placement-merged, same shape as the sharded server) ----
    def _read(self, name: str, *args):
        outs = [getattr(f.resident, name)(*args) for f in self.shards]
        merged = [None] * self.n_docs
        for g in range(self.n_docs):
            s, l = self.placement.place(g)
            merged[g] = outs[s][l]
        return merged

    def texts(self):
        return self._read("texts")

    def richtexts(self):
        return self._read("richtexts")

    def values(self):
        return self._read("values")

    def value_maps(self):
        return self._read("value_maps")

    def root_value_maps(self, name: str):
        return self._read("root_value_maps", name)

    def parent_maps(self):
        return self._read("parent_maps")

    def children_maps(self):
        return self._read("children_maps")

    def value_lists(self):
        return self._read("value_lists")

    def report(self) -> dict:
        return {
            "follower_id": self.follower_id,
            "applied_epoch": self.applied_epoch,
            "lag_epochs": self.lag_epochs,
            "promoted": self.promoted,
            "shards": [f.report() for f in self.shards],
        }

    def promote(self, leader_id: Optional[str] = None, fsync=True):
        """Promote every shard, then reassemble the writable fleet
        through the recovered-manifest path
        (``ShardedResidentServer._assemble``).  Returns the writable
        ShardedResidentServer."""
        from ..parallel.sharded import ShardedResidentServer

        leader_id = leader_id or self.follower_id
        for f in self.shards:
            f.promote(leader_id=leader_id, fsync=fsync)
        fleet = ShardedResidentServer._assemble(
            self.manifest, [f.resident for f in self.shards],
            self.mesh, self.meshes, durable_dir=self.follower_dir,
        )
        fleet._write_manifest()
        self.promoted = True
        return fleet

    def close(self) -> None:
        for f in self.shards:
            f.close()

    def __enter__(self) -> "ShardedFollower":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
