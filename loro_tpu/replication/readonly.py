"""Read-only SyncServer over a follower resident.

The whole session surface — ``pull`` (batched device read plane
included), ``poll``, presence, frontiers, first-sync snapshots, TTL
expiry — works unchanged over a follower; the ONE difference is that a
``push()`` raises typed ``errors.NotLeader`` carrying the leader's
identity so clients redirect instead of guessing.  ``promote()`` flips
the server writable in place: the same sessions keep their frontiers
and start pushing.

The follower feeds committed rounds through ``_apply_replicated``
(the leader-side ``_commit_batch`` oracle/read-plane/fan-out tail,
minus the fan-in that never runs here): oracle import, change-span
index feed, committed-epoch bump, dirty marks and poll wakeups — so a
follower pull is byte-identical to the leader's at the same epoch (the
differential gate in tests/test_replication.py) and ``poll()``ers wake
on replicated commits exactly like on local ones.

Read-your-writes across the fleet: ``Session.pull(min_epoch=ticket_
epoch)`` blocks until the replica has applied that epoch (typed
``ReplicaLag`` on timeout) — push to the leader, read your write from
any follower.
"""
from __future__ import annotations

import struct
from typing import Optional

from ..errors import NotLeader
from ..obs import metrics as obs
from ..sync.server import SyncServer

_DATA_ERRORS = (ValueError, TypeError, KeyError, IndexError, struct.error)


class ReadOnlySyncServer(SyncServer):
    """``ReadOnlySyncServer.over(follower_resident, leader_id=...)`` —
    always construct via ``over`` (a follower resident already knows
    its family/cid).  ``pipeline`` is forced off: there is no write
    path to pipeline until promotion."""

    def __init__(self, *args, leader_id: Optional[str] = None, **kw):
        kw["pipeline"] = False
        super().__init__(*args, **kw)
        self.leader_id = leader_id
        self._writable = False

    # -- the read-only contract ----------------------------------------
    def _push(self, session, di: int, data: bytes):
        if not self._writable:
            obs.counter(
                "repl.not_leader_pushes_total",
                "pushes refused typed by read-only followers",
            ).inc(family=self.family)
            raise NotLeader(
                f"doc {di}: this server is a read-only follower — "
                "push to the leader", leader=self.leader_id,
            )
        return super()._push(session, di, data)

    def _promote_writable(self) -> None:
        """Called by ``Follower.promote()`` once the resident is
        durable-attached and writable: pushes start landing through the
        coalesced-ingest path (no pipeline is attached — attach one via
        ``resident.pipeline()`` before promoting if wanted)."""
        with self._lock:
            self._writable = True
            self.leader_id = None

    # -- replicated-round feed (Follower._apply_new) -------------------
    def _apply_replicated(self, epoch: int, cid, updates) -> None:
        """Apply one shipped round's frozen wire bytes to the serving
        planes: per-doc oracle import + change-span index feed (before
        the epoch bump — the window-snapshot contract), then the
        committed-epoch bump, dirty marks and poll wakeups."""
        from ..codec.binary import decode_changes

        if cid is not None and self.cid is None:
            self.cid = cid
        dirty = {}
        with self._lock:
            for di, u in enumerate(updates):
                if u is None:
                    continue
                try:
                    chs = decode_changes(bytes(u))
                except _DATA_ERRORS:
                    # shipped bytes applied once on the leader already;
                    # a decode failure here means damage on our side —
                    # isolate the doc, never the stream
                    obs.counter(
                        "repl.apply_decode_errors_total",
                        "shipped round entries the follower oracle "
                        "could not decode",
                    ).inc(family=self.family)
                    continue
                for ch in chs:
                    for op in ch.ops:
                        self._oracle._seen_cids[di].setdefault(op.container)
                self._oracle.docs[di]._import_changes(
                    list(chs), origin="repl"
                )
                self._head_vv.pop(di, None)
                if self._readbatch is not None:
                    self._readbatch.plane.note_changes(di, chs)
                dirty[di] = epoch
            if epoch > self._committed_epoch:
                self._committed_epoch = epoch
            self._oracle.epoch = self._committed_epoch
            if not dirty:
                # empty rounds still advance the epoch: wake min_epoch
                # gates waiting on it
                self._wakeup.notify_all()
        if dirty:
            self._fan_out_deltas(dirty)
