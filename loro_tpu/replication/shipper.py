"""Leader-side WAL shipper: visibility-gated byte streams per segment.

The WAL is already a total order of ingest rounds; shipping it is a
pure file-level protocol — sealed segments stream whole, the open
segment streams up to the **durable watermark** (the fsync'd byte
offset), so a follower can never apply a round the leader has not made
durable (docs/REPLICATION.md "tail protocol").  Three visibility
sources, strongest first:

- a live leader object (``leader=``): ``WriteAheadLog.visible_extent``
  — exact, in-process;
- the ``.visible`` marker the leader publishes after each fsync
  (``replication.enable()`` turns it on): cross-process followers of a
  leader in another process.  Sealed segments (index below the
  marker's) are fully visible — rotation fsyncs them closed;
- ``final=True`` (the promotion drain, leader dead): whole files —
  every complete frame on disk is fair game, torn tails are the
  follower's truncate-on-apply problem, exactly the WAL reopen
  contract.

Checkpoint rungs ship as whole files (their writes are atomic
renames).  Fault site ``repl_ship``: ``check`` fires before every
read (raise/delay = a mid-ship crash; the follower resumes from its
acked offset), ``mangle`` corrupts the streamed bytes (truncate /
bitflip = a genuinely torn shipped tail at the follower).
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from ..obs import metrics as obs
from ..persist.wal import _seg_index
from ..resilience import faultinject

faultinject.register_site(
    "repl_ship", "WalShipper.read: every shipped byte crosses it — "
    "raise/delay = a mid-ship crash; truncate/bitflip = a torn shipped "
    "tail the follower truncates like a WAL reopen")


class WalShipper:
    """Byte-stream source over one durable directory.

    ``leader=`` is the live durable ResidentServer when shipping
    in-process (exact visibility); None uses the ``.visible`` marker,
    or — with ``final=True`` — whole files (dead-leader drain)."""

    def __init__(self, source_dir: str, leader=None):
        self.source_dir = source_dir
        self.wal_dir = os.path.join(source_dir, "wal")
        self.ckpt_dir = os.path.join(source_dir, "ckpt")
        self.leader = leader
        self.final = False  # promotion drain: whole-file visibility

    # -- visibility ----------------------------------------------------
    def _source_segments(self) -> List[Tuple[int, str]]:
        if not os.path.isdir(self.wal_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.wal_dir)):
            if name.startswith("seg-") and name.endswith(".log"):
                out.append((_seg_index(name),
                            os.path.join(self.wal_dir, name)))
        return out

    def extent(self) -> List[Tuple[int, str, int]]:
        """``(index, path, visible_bytes)`` per source segment."""
        lead = self.leader
        log = getattr(lead, "_durable", None) if lead is not None else None
        if not self.final and log is not None:
            return log.wal.visible_extent()
        segs = self._source_segments()
        if self.final:
            return [(i, p, os.path.getsize(p)) for i, p in segs]
        marker = self._read_marker()
        out: List[Tuple[int, str, int]] = []
        max_idx = segs[-1][0] if segs else 0
        for i, p in segs:
            if i < max_idx:
                vis = os.path.getsize(p)  # sealed: rotation fsync'd it
            elif marker is not None and marker.get("seg") == i:
                vis = int(marker.get("off", 0))
            else:
                # active segment with no (or stale) marker: nothing of
                # it is provably durable — ship none of it yet
                vis = 0
            out.append((i, p, vis))
        return out

    def _read_marker(self) -> Optional[dict]:
        path = os.path.join(self.wal_dir, ".visible")
        try:
            with open(path, "r") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- byte reads ----------------------------------------------------
    def read(self, path: str, offset: int, length: int) -> bytes:
        """``length`` bytes of ``path`` from ``offset`` — the one choke
        point every shipped byte crosses (the ``repl_ship`` site)."""
        faultinject.check("repl_ship", rtype="segment")
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
        data = faultinject.mangle("repl_ship", data)
        obs.counter(
            "repl.shipped_bytes_total", "WAL bytes streamed to followers"
        ).inc(len(data))
        return data

    def ckpt_files(self) -> List[Tuple[str, str]]:
        """``(name, path)`` of every checkpoint rung currently on the
        source ladder (atomic-rename files: whole-file visibility)."""
        if not os.path.isdir(self.ckpt_dir):
            return []
        return [
            (n, os.path.join(self.ckpt_dir, n))
            for n in sorted(os.listdir(self.ckpt_dir))
            if n.endswith(".ltck")
        ]

    def extra_files(self) -> List[Tuple[str, str]]:
        """Sidecar manifests worth mirroring (``residency.json`` for
        tiered leaders) — best-effort, whole-file."""
        out = []
        for n in ("residency.json",):
            p = os.path.join(self.source_dir, n)
            if os.path.isfile(p):
                out.append((n, p))
        return out
