"""loro_tpu.replication: WAL-shipping hot standby, follower reads and
fault-injected failover (docs/REPLICATION.md).

The segmented WAL (loro_tpu/persist/) is a durable total order of
ingest rounds with an acked fsync watermark; this package streams it:

- ``enable(leader)``       — claim the leader token, install the
  append fence, publish the fsync-visibility marker, pin WAL pruning
  at follower acks (``manifest.ReplicationManifest``);
- ``WalShipper``           — visibility-gated per-segment byte streams
  (sealed segments whole, the open segment up to the durable
  watermark — the tail protocol);
- ``Follower``             — a rolling ``recover_server``: a live
  ResidentServer continuously applying shipped rounds, reporting
  ``applied_epoch``/``lag_epochs``, serving read-only sessions;
- ``ShardedFollower``      — one stream per shard, placement tracked
  from ``sharding.json`` (mid-stream migrations included);
- ``ReadOnlySyncServer``   — the full session surface over a follower;
  ``push()`` raises typed ``NotLeader``; ``pull(min_epoch=)`` is the
  read-your-writes gate (typed ``ReplicaLag`` on timeout);
- ``Follower.promote()``   — fence the old leader (token bump checked
  at its every WAL append → typed ``FencedLeader``), drain the shipped
  tail, reopen the WAL copy for append and flip writable.

Fault sites (``LORO_FAULT``/faultinject): ``repl_ship``,
``repl_apply``, ``repl_promote``.  Metrics: ``repl.*``
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

from .follower import Follower, ShardedFollower, enable
from .manifest import ReplicationManifest, load_replication
from .readonly import ReadOnlySyncServer
from .shipper import WalShipper

__all__ = [
    "Follower",
    "ReadOnlySyncServer",
    "ReplicationManifest",
    "ShardedFollower",
    "WalShipper",
    "enable",
    "load_replication",
]
