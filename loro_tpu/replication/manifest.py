"""``replication.json``: the durable control record of one replicated
directory (next to ``sharding.json`` / ``residency.json``).

Two things live here, both tiny and both load-bearing:

- the **leader token** — a monotone integer stamped with the holder's
  identity.  ``promote()`` bumps it; the (possibly zombie) old leader
  checks it at every WAL append through the installed fence hook and
  fail-stops typed ``FencedLeader`` when a newer token exists.  The
  highest token wins promotion races: whichever follower bumps last
  fences every earlier holder at its next append.
- the **follower ack table** — per registered follower, the newest
  applied epoch and a wall-clock last-seen stamp.  The minimum acked
  epoch over FRESH followers is the retention pin the WAL prune path
  honors (``WriteAheadLog.retention_floor``); followers staler than
  the cutoff stop pinning (counted) so a dead follower can never pin
  the log forever — when such a follower later resumes past pruned
  history it fails typed ``StaleFollower`` at the ship scan instead.

Writes are atomic (tmp + ``os.replace`` + directory fsync, the
``sharding.json`` idiom); reads are mtime/size-cached so the fence
check on the WAL append hot path costs one ``os.stat`` per append.
The clock is injectable (``clock=``) and defaults to wall time —
last-seen stamps must compare across processes.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

from ..errors import NotLeader, ReplicationError
from ..obs import metrics as obs
from ..persist.wal import fsync_dir

MANIFEST_NAME = "replication.json"
MANIFEST_VERSION = 1

# a follower silent for this long stops pinning WAL retention (the
# typed staleness cutoff; override per-manifest with stale_after=)
DEFAULT_STALE_AFTER_S = 600.0


class ReplicationManifest:
    """One ``replication.json`` under ``dir`` (a durable server
    directory, or a ``shard-NN/`` sub-directory of a sharded fleet)."""

    def __init__(self, dir: str, clock=None,
                 stale_after: float = DEFAULT_STALE_AFTER_S):
        self.dir = dir
        self.path = os.path.join(dir, MANIFEST_NAME)
        self._clock = time.time if clock is None else clock
        self.stale_after = float(stale_after)
        self._cache: Optional[dict] = None
        self._cache_stat: Optional[Tuple[int, float]] = None

    # -- raw I/O -------------------------------------------------------
    def read(self) -> dict:
        """Current manifest (mtime/size-cached; fresh skeleton when the
        file does not exist yet)."""
        try:
            st = os.stat(self.path)
            key = (st.st_size, st.st_mtime_ns)
        except OSError:
            self._cache, self._cache_stat = None, None
            return {"version": MANIFEST_VERSION, "leader_token": 0,
                    "leader_id": None, "followers": {}}
        if self._cache is not None and self._cache_stat == key:
            return self._cache
        with open(self.path, "r") as f:
            data = json.load(f)
        if data.get("version", 0) > MANIFEST_VERSION:
            raise ReplicationError(
                f"{self.path}: replication manifest v{data.get('version')} "
                "newer than supported"
            )
        self._cache, self._cache_stat = data, key
        return data

    def _write(self, data: dict) -> None:
        data["version"] = MANIFEST_VERSION
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        fsync_dir(self.dir)
        self._cache = None  # next read restats (mtime granularity)

    # -- leader token --------------------------------------------------
    def leader(self) -> Tuple[int, Optional[str]]:
        """``(token, holder_id)`` — the fence hook's view (one stat on
        the cached path)."""
        d = self.read()
        return int(d.get("leader_token", 0)), d.get("leader_id")

    def claim_leader(self, leader_id: str,
                     token: Optional[int] = None) -> int:
        """Record ``leader_id`` as the token holder and return the
        token.  A fresh directory starts at token 1; re-claiming a
        token this id already holds is idempotent; claiming over a
        DIFFERENT holder without an explicit (promotion-granted)
        ``token=`` raises typed ``NotLeader`` — enable() must never
        silently steal leadership."""
        d = self.read()
        cur, holder = int(d.get("leader_token", 0)), d.get("leader_id")
        if token is not None:
            new = max(cur, int(token))
        elif cur == 0 or holder == leader_id:
            new = max(cur, 1)
        else:
            raise NotLeader(
                f"{self.dir}: leader token {cur} is held by "
                f"{holder!r} — promote() a follower to take over",
                leader=holder,
            )
        d["leader_token"] = new
        d["leader_id"] = leader_id
        self._write(d)
        return new

    def bump_token(self, new_leader_id: str) -> int:
        """Fence the current holder: token+1 stamped with the new
        leader's identity.  Returns the granted token.

        Two promoters may race from SEPARATE processes (the designed
        deployment), so the read-modify-write is not enough: both
        would mint EQUAL tokens and neither would fence the other
        (the fence only fires on ``cur > token``) — split brain.  The
        token grant is therefore a filesystem CAS: each candidate
        token is claimed by ``O_EXCL``-creating ``.token-N.claim``
        (exactly one process can win each N), so racing promoters
        always hold DISTINCT tokens and the highest fences every
        lower holder, exactly the documented race semantic.  The
        manifest write then converges to the max over claimants
        (re-read after write; rewrite while a smaller token overwrote
        ours) — the token record can lag but never move backward."""
        d = self.read()
        new = int(d.get("leader_token", 0)) + 1
        while True:
            claim = os.path.join(self.dir, f".token-{new}.claim")
            try:
                fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                new += 1  # lost this token to a racing promoter
                continue
            try:
                os.write(fd, new_leader_id.encode())
                os.fsync(fd)
            finally:
                os.close(fd)
            fsync_dir(self.dir)
            break
        while True:
            d = self.read()
            cur = int(d.get("leader_token", 0))
            if cur >= new:
                break  # ours landed, or a higher claimant won — done
            d["leader_token"] = new
            d["leader_id"] = new_leader_id
            self._write(d)
        # retired claims (<= the recorded token) can never be granted
        # again — every future bump starts above it
        for name in os.listdir(self.dir):
            if name.startswith(".token-") and name.endswith(".claim"):
                try:
                    if int(name[len(".token-"):-len(".claim")]) < new:
                        os.unlink(os.path.join(self.dir, name))
                except (ValueError, OSError):
                    pass
        obs.counter(
            "repl.promotions_total", "leader-token bumps (promotions)"
        ).inc()
        return new

    # -- follower acks / retention pin ---------------------------------
    def ack_follower(self, fid: str, applied_epoch: int) -> None:
        """Record a follower's applied watermark (monotone) + freshness
        stamp.  The ack is what pins WAL retention."""
        d = self.read()
        f = d.setdefault("followers", {}).setdefault(fid, {})
        f["acked_epoch"] = max(int(f.get("acked_epoch", 0)),
                               int(applied_epoch))
        f["last_seen"] = self._clock()
        self._write(d)

    def drop_follower(self, fid: str) -> None:
        d = self.read()
        if fid in d.get("followers", {}):
            del d["followers"][fid]
            self._write(d)

    def followers(self) -> Dict[str, dict]:
        return dict(self.read().get("followers", {}))

    def pinned_floor(self) -> Optional[int]:
        """The retention pin: min acked epoch over FRESH followers
        (None = no fresh follower, nothing pinned).  Stale followers
        are skipped and counted — the typed cutoff that keeps a dead
        follower from pinning the WAL forever (it fails
        ``StaleFollower`` on resume instead)."""
        now = self._clock()
        floors = []
        for fid, f in self.read().get("followers", {}).items():
            if now - float(f.get("last_seen", 0.0)) > self.stale_after:
                obs.counter(
                    "repl.stale_followers_dropped_total",
                    "follower retention pins skipped by the staleness "
                    "cutoff",
                ).inc()
                continue
            floors.append(int(f.get("acked_epoch", 0)))
        return min(floors) if floors else None


def load_replication(dir: str) -> Optional[dict]:
    """The raw ``replication.json`` of a durable dir, or None (the
    jax-free read ``persist.inspect`` uses)."""
    path = os.path.join(dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None
    with open(path, "r") as f:
        return json.load(f)
