"""LoroDoc: the document runtime.

reference: crates/loro-internal/src/loro.rs (import/export dispatch,
checkout, fork) + crates/loro/src/lib.rs (public API).  A doc owns an
OpLog (history), a DocState (materialized state), an Observer, and the
single active transaction slot (reference lib.rs:142-172).
"""
from __future__ import annotations

import random
import zlib
from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from .codec import json_schema as jcodec
from .config import Configure
from .core.change import Change
from .core.ids import ContainerID, ContainerType, ID, IdSpan, PeerID
from .core.version import Frontiers, VersionRange, VersionVector
from .event import (
    ContainerDiff,
    DocDiff,
    EventTriggerKind,
    MapDiff,
    Observer,
)
from .models.handlers import (
    CounterHandler,
    Handler,
    ListHandler,
    MapHandler,
    MovableListHandler,
    TextHandler,
    TreeHandler,
    make_handler,
)
from .obs import metrics as obs
from .oplog.oplog import OpLog
from .state import DocState, compose_many
from .txn import Transaction
from .utils import tracing

MAGIC = b"LTPU"
# v2: snapshot history section became BlockStore blocks; snapshot state
# sections zlib-compressed (change_store.py).  Update payloads are
# layout-identical across v1/v2, so update blobs are stamped with the
# lowest version that can read them (mixed-version interop).
FORMAT_VERSION = 2
ENVELOPE_LEN = 10  # MAGIC + version + mode + crc32


def _min_version_for_mode(mode: "EncodeMode") -> int:
    if mode in (
        EncodeMode.FastSnapshot,
        EncodeMode.ShallowSnapshot,
        EncodeMode.StateOnly,
    ):
        return 2
    return 1


class EncodeMode(Enum):
    JsonUpdates = 1
    JsonSnapshot = 2
    ColumnarUpdates = 3
    ColumnarSnapshot = 4
    ShallowSnapshot = 5
    FastSnapshot = 6
    StateOnly = 7


def frame_columnar_updates(changes) -> bytes:
    """Frame an export-ordered change list as the columnar-updates wire
    envelope — the exact bytes ``export(ExportMode.Updates)`` ships.
    Module-level so the sync read plane (``sync/readbatch.py``) frames
    device-selected changes through the SAME code path the per-doc
    oracle uses: byte-identity by construction, not by parallel
    implementation."""
    from .codec import binary as bcodec

    payload = bcodec.encode_changes(changes)
    crc = zlib.crc32(payload)
    mode = EncodeMode.ColumnarUpdates
    return (
        MAGIC
        + bytes([_min_version_for_mode(mode), mode.value])
        + crc.to_bytes(4, "little")
        + payload
    )


class ExportMode:
    """reference: encoding.rs ExportMode."""

    class Snapshot:
        pass

    @dataclass
    class Updates:
        from_vv: Optional[VersionVector] = None

    @dataclass
    class UpdatesInRange:
        from_vv: VersionVector
        to_vv: VersionVector

    @dataclass
    class ShallowSnapshot:
        frontiers: Frontiers

    @dataclass
    class SnapshotAt:
        frontiers: Frontiers

    class StateOnly:
        pass


@dataclass
class ImportStatus:
    """reference: encoding.rs:227 ImportStatus."""

    success: VersionRange
    pending: Optional[VersionRange]


from .errors import DecodeError, LoroError  # noqa: E402  (re-export; defined in errors.py to avoid import cycles)


class LoroDoc:
    def __init__(self, peer: Optional[PeerID] = None):
        self.peer: PeerID = peer if peer is not None else random.getrandbits(63)
        self.oplog = OpLog()
        self.state = DocState()
        self.observer = Observer()
        self.config = Configure()
        self.oplog.config = self.config
        self._txn: Optional[Transaction] = None
        self._detached = False
        # (state bytes, vv, frontiers) of the frozen shallow-history root
        # (reference: GcStore, container_store.rs:58) — replay floor for
        # checkout/diff on shallow docs
        self._shallow_base: Optional[Tuple[bytes, VersionVector, Frontiers]] = None
        from .history_cache import StateCheckpointCache

        self._state_cache = StateCheckpointCache()
        self._local_update_subs: List[Callable[[bytes], None]] = []
        self._peer_id_change_subs: List[Callable[[PeerID], None]] = []
        self._pre_commit_subs: List[Callable[["Transaction"], None]] = []
        self._first_commit_from_peer_subs: List[Callable[[PeerID], None]] = []
        self._seen_peers: set = set()

    # ------------------------------------------------------------------
    # identity & mode
    # ------------------------------------------------------------------
    def set_peer_id(self, peer: PeerID) -> None:
        if self._txn is not None and not self._txn.is_empty():
            raise LoroError("cannot change peer id with uncommitted ops")
        self.peer = peer
        for cb in self._peer_id_change_subs:
            cb(peer)

    @property
    def peer_id(self) -> PeerID:
        """reference: LoroDoc::peer_id."""
        return self.peer

    def is_detached(self) -> bool:
        return self._detached

    def set_detached_editing(self, enable: bool) -> None:
        """Allow edits while detached: commits extend the checked-out
        branch instead of raising (reference:
        LoroDoc::set_detached_editing; new branch gets a fresh peer id
        in the reference — here the peer id is kept, which is safe
        because counters continue from the branch head)."""
        self.config.editable_detached_mode = enable

    def is_detached_editing_enabled(self) -> bool:
        return self.config.editable_detached_mode

    def detach(self) -> None:
        self._barrier()
        self._detached = True

    def attach(self) -> None:
        self.checkout_to_latest()

    # ------------------------------------------------------------------
    # handlers
    # ------------------------------------------------------------------
    def get_text(self, name: str) -> TextHandler:
        return TextHandler(self, ContainerID.root(name, ContainerType.Text))

    def get_list(self, name: str) -> ListHandler:
        return ListHandler(self, ContainerID.root(name, ContainerType.List))

    def get_map(self, name: str) -> MapHandler:
        return MapHandler(self, ContainerID.root(name, ContainerType.Map))

    def get_movable_list(self, name: str) -> MovableListHandler:
        return MovableListHandler(self, ContainerID.root(name, ContainerType.MovableList))

    def get_tree(self, name: str) -> TreeHandler:
        return TreeHandler(self, ContainerID.root(name, ContainerType.Tree))

    def get_counter(self, name: str) -> CounterHandler:
        return CounterHandler(self, ContainerID.root(name, ContainerType.Counter))

    def get_container(self, cid: Union[ContainerID, str]) -> Handler:
        if isinstance(cid, str):
            cid = ContainerID.parse(cid)
        return make_handler(self, cid)

    def _try_get(self, name: str, ctype: ContainerType) -> Optional[Handler]:
        """Handler for an EXISTING container of the right type, else
        None (reference: LoroDoc::try_get_text & co — the safe variants
        that neither create roots nor assert the type)."""
        cid = ContainerID.root(name, ctype) if isinstance(name, str) else name
        if cid.ctype != ctype or cid not in self.state.states:
            return None
        return make_handler(self, cid)

    def try_get_text(self, name: str) -> Optional[TextHandler]:
        return self._try_get(name, ContainerType.Text)  # type: ignore[return-value]

    def try_get_list(self, name: str) -> Optional[ListHandler]:
        return self._try_get(name, ContainerType.List)  # type: ignore[return-value]

    def try_get_map(self, name: str) -> Optional[MapHandler]:
        return self._try_get(name, ContainerType.Map)  # type: ignore[return-value]

    def try_get_movable_list(self, name: str) -> Optional[MovableListHandler]:
        return self._try_get(name, ContainerType.MovableList)  # type: ignore[return-value]

    def try_get_tree(self, name: str) -> Optional[TreeHandler]:
        return self._try_get(name, ContainerType.Tree)  # type: ignore[return-value]

    def try_get_counter(self, name: str) -> Optional[CounterHandler]:
        return self._try_get(name, ContainerType.Counter)  # type: ignore[return-value]

    # ------------------------------------------------------------------
    # transactions
    # ------------------------------------------------------------------
    def _txn_apply(self, cid: ContainerID, content) -> int:
        if self._detached and not self.config.editable_detached_mode:
            raise LoroError("doc is detached; checkout_to_latest() or enable editable_detached_mode")
        if self._txn is None:
            self._txn = Transaction(self)
        return self._txn.apply(cid, content)

    def _barrier(self) -> None:
        """Implicit commit (reference: with_barrier): finalize pending
        work, but an EMPTY implicit commit preserves next-commit options
        for the next real commit — unlike an explicit empty commit(),
        which swallows them."""
        txn = self._txn
        if txn is None or txn.is_empty():
            self._txn = None
            return
        self.commit()

    def commit(self, origin: str = "", message: Optional[str] = None) -> None:
        """Commit the implicit transaction (reference: txn.rs:426).
        An explicit empty commit swallows pending next-commit options
        (reference: explicit_empty_commit_swallow_options)."""
        txn = self._txn
        if txn is None or txn.is_empty():
            self._txn = None
            self.clear_next_commit_options()
            return
        pend_msg = getattr(self, "_next_commit_message", None)
        pend_origin = getattr(self, "_next_commit_origin", None)
        pend_ts = getattr(self, "_next_commit_timestamp", None)
        self._next_commit_message = None
        self._next_commit_origin = None
        self._next_commit_timestamp = None
        if message is not None:
            txn.message = message
        elif pend_msg is not None and txn.message is None:
            txn.message = pend_msg
        if not origin and pend_origin:
            origin = pend_origin
        if pend_ts is not None and txn.timestamp_override is None:
            txn.timestamp_override = pend_ts
        for cb in self._pre_commit_subs:
            cb(txn)
        change = txn.build_change()
        assert change is not None
        self._txn = None
        self.oplog.import_local_change(change)
        self.state.vv.extend_to_include(change.id_span())
        if self._detached:
            # stay on the branch: state head is this change, not the
            # merged oplog frontiers
            self.state.frontiers = Frontiers([change.last_id()])
        else:
            self.state.frontiers = self.oplog.frontiers
        if change.peer not in self._seen_peers:
            self._seen_peers.add(change.peer)
            for cb in self._first_commit_from_peer_subs:
                cb(change.peer)
        # events
        if self.observer.has_subscribers() and txn.diffs:
            self._emit(txn.diffs, origin or txn.origin, EventTriggerKind.Local, txn.start_frontiers)
        # local update push (reference: txn.rs:78-90 subscribe_local_update)
        if self._local_update_subs:
            payload = self._encode_changes([change], EncodeMode.ColumnarUpdates)
            for cb in self._local_update_subs:
                cb(payload)

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------
    def _emit(
        self,
        diffs: Dict[ContainerID, List],
        origin: str,
        by: EventTriggerKind,
        from_frontiers: Frontiers,
    ) -> None:
        cds: List[ContainerDiff] = []
        for cid, lst in diffs.items():
            if not lst:
                continue
            d = compose_many(lst)
            if hasattr(d, "is_empty") and d.is_empty():
                continue
            cds.append(ContainerDiff(cid, self.state.path_of(cid), d))
        if not cds:
            return
        cds.sort(key=lambda cd: (self.state.depth_of(cd.id), cd.path))
        self.observer.emit(DocDiff(origin, by, from_frontiers, self.state.frontiers, cds))

    def subscribe(self, cid: ContainerID, cb) -> Callable[[], None]:
        return self.observer.subscribe(cid, cb)

    def subscribe_root(self, cb) -> Callable[[], None]:
        return self.observer.subscribe_root(cb)

    def subscribe_local_update(self, cb: Callable[[bytes], None]) -> Callable[[], None]:
        self._local_update_subs.append(cb)
        return lambda: self._local_update_subs.remove(cb)

    def subscribe_peer_id_change(self, cb: Callable[[PeerID], None]) -> Callable[[], None]:
        self._peer_id_change_subs.append(cb)
        return lambda: self._peer_id_change_subs.remove(cb)

    def subscribe_pre_commit(self, cb) -> Callable[[], None]:
        self._pre_commit_subs.append(cb)
        return lambda: self._pre_commit_subs.remove(cb)

    def subscribe_first_commit_from_peer(self, cb) -> Callable[[], None]:
        self._first_commit_from_peer_subs.append(cb)
        return lambda: self._first_commit_from_peer_subs.remove(cb)

    # ------------------------------------------------------------------
    # import / export
    # ------------------------------------------------------------------
    def export(self, mode=None) -> bytes:
        """Export per ExportMode (reference: loro.rs:2096 dispatch)."""
        mode_name = (
            getattr(mode, "__name__", None)
            or (type(mode).__name__ if mode is not None else "Snapshot")
        )
        tracing.instant("doc.export", mode=mode_name)
        data = self._export_dispatch(mode)
        obs.counter("doc.export_calls_total").inc(mode=mode_name)
        obs.counter("doc.export_bytes_total").inc(len(data), mode=mode_name)
        return data

    def _export_dispatch(self, mode) -> bytes:
        self._barrier()
        if mode is None or isinstance(mode, ExportMode.Snapshot) or mode is ExportMode.Snapshot:
            return self._export_fast_snapshot()
        if isinstance(mode, ExportMode.Updates):
            vv = mode.from_vv or VersionVector()
            return self._encode_changes(
                self.oplog.changes_since(vv), EncodeMode.ColumnarUpdates, vv
            )
        if isinstance(mode, ExportMode.UpdatesInRange):
            chs = self.oplog.changes_between(mode.from_vv, mode.to_vv)
            return self._encode_changes(chs, EncodeMode.ColumnarUpdates, mode.from_vv)
        if isinstance(mode, ExportMode.SnapshotAt):
            if self._shallow_base is not None:
                # history below the root is gone: ship base + ops <= f
                return self._export_shallow(
                    self.oplog.dag.shallow_since_frontiers, with_updates=True, to_f=mode.frontiers
                )
            to_vv = self.oplog.dag.frontiers_to_vv(mode.frontiers)
            chs = self.oplog.changes_between(self.oplog.dag.shallow_since_vv, to_vv)
            return self._encode_changes(chs, EncodeMode.ColumnarSnapshot)
        if isinstance(mode, ExportMode.ShallowSnapshot):
            return self._export_shallow(mode.frontiers, with_updates=True)
        if mode is ExportMode.StateOnly or isinstance(mode, ExportMode.StateOnly):
            return self._export_shallow(self.oplog.frontiers, with_updates=False)
        raise LoroError(f"unsupported export mode {mode}")

    def _export_fast_snapshot(self) -> bytes:
        """[varint oplog_len][oplog changes][varint state_len][doc state]
        (reference layout: fast_snapshot.rs:1-15).  The encoded state is
        always the state at the oplog head — detached docs materialize
        it by replay from the floor (so shallow bases are never lost)."""
        import json as _json

        from .codec import binary as bcodec
        from .codec import snapshot as scodec
        from .codec.binary import Writer

        if self.state.frontiers == self.oplog.frontiers:
            head_state = self.state
        else:
            head_state = self._state_at(self.oplog.frontiers)
        w = Writer()
        # history ships as compressed change blocks (clean cold peers
        # pass through without decode or re-encode — change_store.py)
        oplog_bytes = self.oplog.export_block_store().encode()
        state_bytes = zlib.compress(
            scodec.encode_doc_state(head_state, head_state.parents), 6
        )
        w.bytes_(oplog_bytes)
        w.bytes_(state_bytes)
        # shallow-root carry-over so a fast snapshot of a shallow doc
        # keeps its replay floor
        if self._shallow_base is not None:
            base_bytes, base_vv, base_f = self._shallow_base
            w.u8(1)
            w.bytes_(base_bytes)
            w.str_(_json.dumps(base_vv.to_json()))
            w.str_(_json.dumps(base_f.to_json()))
        else:
            w.u8(0)
        payload = bytes(w.buf)
        crc = zlib.crc32(payload)
        return (
            MAGIC
            + bytes([_min_version_for_mode(EncodeMode.FastSnapshot), EncodeMode.FastSnapshot.value])
            + crc.to_bytes(4, "little")
            + payload
        )

    def _export_shallow(
        self, frontiers: Frontiers, with_updates: bool, to_f: Optional[Frontiers] = None
    ) -> bytes:
        """Frozen state at `frontiers` + (optionally) the ops after it,
        up to `to_f` (default: everything).
        reference: shallow_snapshot.rs:22."""
        import json as _json

        from .codec import binary as bcodec
        from .codec import snapshot as scodec
        from .codec.binary import Writer

        base_vv = self.oplog.dag.frontiers_to_vv(frontiers)
        if not (self.oplog.dag.shallow_since_vv <= base_vv):
            raise LoroError("shallow snapshot frontiers below this doc's shallow root")
        if frontiers == self.state.frontiers:
            base_state = self.state  # export() committed; live state reusable
        else:
            base_state = self._state_at(frontiers)
        state_bytes = zlib.compress(
            scodec.encode_doc_state(base_state, base_state.parents), 6
        )
        w = Writer()
        w.bytes_(state_bytes)
        w.str_(_json.dumps(base_vv.to_json()))
        w.str_(_json.dumps(frontiers.to_json()))
        if with_updates:
            to_vv = self.oplog.vv if to_f is None else self.oplog.dag.frontiers_to_vv(to_f)
            chs = self.oplog.changes_between(base_vv, to_vv)
            w.bytes_(bcodec.encode_changes(chs))
        else:
            w.bytes_(b"")
        payload = bytes(w.buf)
        crc = zlib.crc32(payload)
        mode = EncodeMode.ShallowSnapshot if with_updates else EncodeMode.StateOnly
        return MAGIC + bytes([_min_version_for_mode(mode), mode.value]) + crc.to_bytes(4, "little") + payload

    def export_snapshot(self) -> bytes:
        return self.export(ExportMode.Snapshot)

    def export_updates(self, from_vv: Optional[VersionVector] = None) -> bytes:
        return self.export(ExportMode.Updates(from_vv))

    def _encode_changes(
        self, changes: List[Change], mode: EncodeMode, start_vv: Optional[VersionVector] = None
    ) -> bytes:
        if mode is EncodeMode.ColumnarUpdates:
            return frame_columnar_updates(changes)
        if mode is EncodeMode.ColumnarSnapshot:
            from .codec import binary as bcodec

            payload = bcodec.encode_changes(changes)
        else:
            payload = jcodec.dumps(
                jcodec.export_json_updates(
                    changes, start_vv or VersionVector(), self.oplog.vv.copy()
                )
            )
        crc = zlib.crc32(payload)
        header = MAGIC + bytes([_min_version_for_mode(mode), mode.value]) + crc.to_bytes(4, "little")
        return header + payload

    @classmethod
    def from_snapshot(cls, data: bytes) -> "LoroDoc":
        """Construct a fresh doc from snapshot bytes (reference:
        LoroDoc::from_snapshot)."""
        doc = cls()
        doc.import_(data, origin="from_snapshot")
        return doc

    def import_with(self, data: bytes, origin: str = "import") -> ImportStatus:
        """reference: LoroDoc::import_with (origin-tagged import)."""
        return self.import_(data, origin)

    def import_(self, data: bytes, origin: str = "import") -> ImportStatus:
        """reference: loro.rs:568 LoroDoc::import (header parse + mode
        dispatch, loro.rs:584-649)."""
        obs.counter("doc.import_calls_total").inc()
        obs.counter("doc.import_bytes_total").inc(len(data))
        with tracing.span("doc.import", bytes=len(data)):
            self._barrier()
            mode, payload = self._parse_envelope(data)
            if mode == EncodeMode.FastSnapshot:
                return self._import_fast_snapshot(payload, origin)
            if mode in (EncodeMode.ShallowSnapshot, EncodeMode.StateOnly):
                return self._import_shallow(payload, origin)
            with tracing.span("decode", mode=mode.name):
                changes = self._decode_changes(mode, payload)
            return self._import_changes(changes, origin)

    import_bytes = import_

    def import_batch(self, blobs: Sequence[bytes], origin: str = "import") -> ImportStatus:
        """Import several update blobs atomically-ish (reference:
        loro.rs import_batch): decode everything first, then apply as
        one causally-sorted set so cross-blob dependencies resolve in
        one pass."""
        self._barrier()
        all_changes: List[Change] = []
        snapshots: List[bytes] = []
        for blob in blobs:
            mode, payload = self._parse_envelope(blob)
            if mode in (
                EncodeMode.FastSnapshot,
                EncodeMode.ShallowSnapshot,
                EncodeMode.StateOnly,
            ):
                snapshots.append(blob)
            else:
                all_changes.extend(self._decode_changes(mode, payload))
        success = VersionRange()
        pending: Optional[VersionRange] = None

        def fold(st: ImportStatus) -> None:
            nonlocal pending
            for p, (s, e) in st.success.items():
                success.extend_to_include(IdSpan(p, s, e))
            if st.pending is not None:
                if pending is None:
                    pending = VersionRange()
                for p, (s, e) in st.pending.items():
                    pending.extend_to_include(IdSpan(p, s, e))

        for blob in snapshots:
            fold(self.import_(blob, origin))
        if all_changes or (not snapshots):
            fold(self._import_changes(all_changes, origin))
        return ImportStatus(success, pending)

    def _parse_envelope(self, data: bytes) -> Tuple[EncodeMode, bytes]:
        _version, mode, payload = parse_envelope_header(data)
        return mode, payload

    def _decode_changes(self, mode: EncodeMode, payload: bytes) -> List[Change]:
        if mode in (EncodeMode.JsonUpdates, EncodeMode.JsonSnapshot):
            try:
                return jcodec.import_json_updates(jcodec.loads(payload))
            except (KeyError, ValueError, TypeError) as e:
                raise DecodeError(f"malformed payload: {e}") from e
        if mode in (EncodeMode.ColumnarUpdates, EncodeMode.ColumnarSnapshot):
            from .codec import binary as bcodec

            try:
                return bcodec.decode_changes(payload)
            except Exception as e:
                raise DecodeError(f"malformed columnar payload: {e}") from e
        raise DecodeError(f"unsupported mode {mode}")

    def _import_fast_snapshot(self, payload: bytes, origin: str) -> ImportStatus:
        """Empty doc: install oplog + state bytes directly (no replay —
        the point of the fast format, fast_snapshot.rs:27).  Non-empty
        doc: fall back to importing the embedded changes."""
        from .codec import binary as bcodec
        from .codec import snapshot as scodec
        from .codec.binary import Reader

        import json as _json

        from .oplog.change_store import BlockStore

        try:
            r = Reader(payload)
            oplog_bytes = r.bytes_()
            state_bytes = zlib.decompress(r.bytes_())
            has_base = bool(r.u8())
            base = None
            if has_base:
                bb = r.bytes_()
                bvv = VersionVector.from_json(_json.loads(r.str_()))
                bf = Frontiers.from_json(_json.loads(r.str_()))
                base = (bb, bvv, bf)
            store = BlockStore.decode(oplog_bytes)
        except DecodeError:
            raise
        except Exception as e:
            raise DecodeError(f"malformed fast snapshot: {e}") from e
        if not self.oplog.is_empty() or self.state.states:
            if base is not None:
                # retained changes alone are useless without the base
                raise LoroError(
                    "snapshot carries a shallow base; import it into an empty doc"
                )
            changes = [
                ch for p in store.peers() for ch in store.changes_for_peer(p)
            ]
            return self._import_changes(changes, origin)
        try:
            if base is not None:
                self._install_shallow_base(*base)
            try:
                states, parents = scodec.decode_doc_state(state_bytes)
            except Exception as e:
                raise DecodeError(f"malformed snapshot state: {e}") from e
            # lazy attach: dag/vv come from block metas; op payloads
            # decode per peer only when replay/diff/export needs them
            self.oplog.attach_cold_store(store)
        except DecodeError:
            self._reset_to_empty()
            raise
        self.state.states = states
        self.state.parents.update(parents)
        self.state.vv = self.oplog.vv.copy()
        self.state.frontiers = self.oplog.frontiers
        self._emit_state_install_event(origin)
        status = VersionRange()
        for peer in self.oplog.vv:
            lo = 0
            if base is not None:
                lo = base[1].get(peer)
            hi = self.oplog.vv.get(peer)
            if hi > lo:
                status.extend_to_include(IdSpan(peer, lo, hi))
        return ImportStatus(status, None)

    def _validate_planned(self, inserts: List[Change]) -> None:
        """Semantic integrity gate between decode and commit: every
        sequence/movable op reference must resolve against the known
        element ids (state tables keep tombstones, so an attached
        state's by_id is the full element history) or ids created
        earlier in this batch; delete spans must be sane.  A corrupt
        payload whose deps lie fails HERE, typed, with nothing mutated
        (reference: the random_import fuzz contract + oplog rollback)."""
        from .core.change import (
            CounterIncr,
            MapSet,
            MovableMove,
            MovableSet,
            SeqDelete,
            SeqInsert,
            StyleAnchor,
            TreeMove,
        )

        allowed_kinds = {
            ContainerType.Map: (MapSet,),
            ContainerType.Text: (SeqInsert, SeqDelete),
            ContainerType.List: (SeqInsert, SeqDelete),
            ContainerType.MovableList: (SeqInsert, SeqDelete, MovableSet, MovableMove),
            ContainerType.Tree: (TreeMove,),
            ContainerType.Counter: (CounterIncr,),
        }
        attached = not self._detached
        # ids created by THIS batch, per container (small); existing ids
        # are probed directly against the live state dicts — no O(doc)
        # set materialization on the import hot path
        batch_ids: Dict[ContainerID, set] = {}
        detached_extra: Dict[ContainerID, set] = {}

        def known_ids(cid: ContainerID) -> set:
            s_ = batch_ids.get(cid)
            if s_ is None:
                s_ = batch_ids[cid] = set()
            return s_

        def detached_ids(cid: ContainerID) -> set:
            """Element ids for `cid` over the FULL history — only built
            when the doc is detached (state lags the oplog) and a probe
            missed; cached per import."""
            s_ = detached_extra.get(cid)
            if s_ is None:
                s_ = set()
                for ch in self.oplog.changes_in_causal_order():
                    for op in ch.ops:
                        if op.container != cid:
                            continue
                        c = op.content
                        if isinstance(c, SeqInsert):
                            n_b = 1 if isinstance(c.content, StyleAnchor) else len(c.content)
                            for j in range(n_b):
                                s_.add((ch.peer, op.counter + j))
                        elif isinstance(c, MovableMove):
                            s_.add((ch.peer, op.counter))
                detached_extra[cid] = s_
            return s_

        def resolvable(cid: ContainerID, key: Tuple[int, int]) -> bool:
            if key in known_ids(cid):
                return True
            st = self.state.states.get(cid)
            if st is not None:
                seq = getattr(st, "seq", None)
                if seq is not None and key in seq.by_id:
                    return True
                elems = getattr(st, "elems", None)
                if elems is not None and ID(key[0], key[1]) in elems:
                    return True
            if not attached:
                # detached state lags the oplog: check the history
                # itself, per container (precise; built lazily)
                return key in detached_ids(cid)
            return False

        total_atoms = self.oplog.total_ops() + sum(ch.atom_len() for ch in inserts)
        for ch in inserts:
            for op in ch.ops:
                c = op.content
                ok_kinds = allowed_kinds.get(op.container.ctype)
                if ok_kinds is not None and not isinstance(c, ok_kinds):
                    # UnknownContent is only legal on Unknown containers
                    raise DecodeError(
                        f"op kind {type(c).__name__} not valid for "
                        f"{op.container.ctype.name} container (corrupt payload?)"
                    )
                if (
                    isinstance(c, SeqInsert)
                    and isinstance(c.content, StyleAnchor)
                    and op.container.ctype != ContainerType.Text
                ):
                    raise DecodeError(
                        "style anchor outside a Text container (corrupt payload?)"
                    )
                if isinstance(c, SeqInsert):
                    self._check_placement(op.container, ch.peer, op.counter, c.parent, c.side, resolvable)
                    if op.container.ctype == ContainerType.Text:
                        body_ok = isinstance(c.content, StyleAnchor) or (
                            isinstance(c.content, str)
                        )
                        if not body_ok:
                            raise DecodeError(
                                "non-text content in a Text container "
                                "(corrupt payload?)"
                            )
                    n_body = 1 if isinstance(c.content, StyleAnchor) else len(c.content)
                    ids = known_ids(op.container)
                    for j in range(n_body):
                        ids.add((ch.peer, op.counter + j))
                elif isinstance(c, SeqDelete):
                    for sp in c.spans:
                        if sp.end - sp.start > total_atoms or sp.end < sp.start:
                            raise DecodeError(
                                f"delete span of {sp.end - sp.start} atoms exceeds "
                                f"total history ({total_atoms}) — corrupt payload?"
                            )
                elif isinstance(c, (MovableSet, MovableMove)):
                    e = c.elem
                    if not resolvable(op.container, (e.peer, e.counter)):
                        raise DecodeError(
                            f"movable op references unknown element {e} "
                            "(corrupt payload?)"
                        )
                    if isinstance(c, MovableMove):
                        # a move creates a new position slot placed like
                        # an insert: validate its Fugue parent too
                        self._check_placement(
                            op.container, ch.peer, op.counter, c.parent, c.side, resolvable
                        )
                        known_ids(op.container).add((ch.peer, op.counter))

    @staticmethod
    def _check_placement(cid, peer, counter, parent, side, resolvable) -> None:
        from .core.change import Side
        from .oplog.oplog import _RunCont

        if isinstance(parent, _RunCont):
            if not resolvable(cid, (peer, counter - 1)):
                raise DecodeError(
                    f"run continuation at {peer}:{counter} has no preceding "
                    "element (corrupt payload?)"
                )
        elif parent is not None:
            if not resolvable(cid, (parent.peer, parent.counter)):
                raise DecodeError(
                    f"placement parent {parent} not a known element "
                    "(corrupt payload?)"
                )
        elif side == Side.Left:
            raise DecodeError("root placement must be right-side (corrupt payload?)")

    def _emit_state_install_event(self, origin: str) -> None:
        """Subscribers registered before a snapshot import still need to
        see the content: emit empty->state diffs for every container."""
        if not self.observer.has_subscribers():
            return
        diffs = {}
        for cid, st in self.state.states.items():
            d = st.to_diff()
            if not (hasattr(d, "is_empty") and d.is_empty()):
                diffs[cid] = [d]
        if diffs:
            self._emit(diffs, origin, EventTriggerKind.Import, Frontiers())

    def _import_shallow(self, payload: bytes, origin: str) -> ImportStatus:
        """Install a frozen base state + retained ops into an empty doc.
        reference: shallow snapshot import semantics."""
        import json as _json

        from .codec import binary as bcodec
        from .codec import snapshot as scodec
        from .codec.binary import Reader

        try:
            r = Reader(payload)
            state_bytes = r.bytes_()
            base_vv = VersionVector.from_json(_json.loads(r.str_()))
            base_f = Frontiers.from_json(_json.loads(r.str_()))
            updates = r.bytes_()
            changes = bcodec.decode_changes(updates) if updates else []
        except Exception as e:
            raise DecodeError(f"malformed shallow snapshot: {e}") from e
        if not self.oplog.is_empty() or self.state.states:
            # non-empty doc: usable iff our history already covers the
            # frozen base — then the retained ops import as plain
            # updates and the base is redundant (reference:
            # should_import_snapshot_before_shallow semantics)
            if base_vv <= self.oplog.vv:
                return self._import_changes(changes, origin)
            raise LoroError(
                "shallow snapshot into a non-empty doc requires the doc "
                "to already contain the history below the shallow root"
            )
        try:
            self._install_shallow_base(state_bytes, base_vv, base_f)
            try:
                states, parents = _decode_state_z(state_bytes)
            except Exception as e:
                raise DecodeError(f"malformed snapshot state: {e}") from e
            self.state.states = states
            self.state.parents.update(parents)
            self.state.vv = base_vv.copy()
            self.state.frontiers = base_f
            if changes:
                # validate BEFORE announcing anything to subscribers so
                # a corrupt retained-changes section leaves no trace
                plan = self.oplog.plan_import(changes)
                self._validate_planned(plan.inserts)
        except DecodeError:
            self._reset_to_empty()
            raise
        self._emit_state_install_event(origin)
        if changes:
            return self._import_changes(changes, origin)
        return ImportStatus(VersionRange(), None)

    def _reset_to_empty(self) -> None:
        """Roll a failed snapshot install back to the pristine empty
        doc (the import paths that install state require emptiness, so
        a full reset IS the rollback)."""
        self.oplog = OpLog()
        self.oplog.config = self.config
        self.state = DocState()
        self._shallow_base = None
        self._detached = False
        self._state_cache.clear()

    def _install_shallow_base(self, state_bytes: bytes, vv: VersionVector, f: Frontiers) -> None:
        self._shallow_base = (state_bytes, vv.copy(), f)
        self.oplog.dag.set_shallow_root(vv, f)

    def _import_changes(self, changes: List[Change], origin: str) -> ImportStatus:
        backfill = (
            self.oplog.plan_backfill(changes) if self._shallow_base is not None else None
        )
        with tracing.span("oplog.import", n_changes=len(changes)):
            plan = self.oplog.plan_import(changes)
            self._validate_planned(plan.inserts)
            # everything validated: commit the shallow upgrade first
            # (pre-floor splice), then the regular inserts — a failure
            # above leaves oplog, dag, and shallow root untouched
            if backfill is not None:
                self.oplog.commit_backfill(backfill)
                self._shallow_base = None
            applied, pending = self.oplog.commit_import(plan)
        obs.counter("oplog.changes_applied_total").inc(len(applied))
        obs.counter("oplog.ops_applied_total").inc(
            sum(len(ch.ops) for ch in applied)
        )
        # gauge, not counter: the parked backlog is cumulative state
        # carried across imports — a counter would re-add the whole
        # backlog every round and grow without any new parks
        obs.gauge("oplog.changes_pending").set(
            sum(len(v) for v in self.oplog.pending.by_missing.values())
        )
        success = VersionRange()
        for ch in applied:
            success.extend_to_include(ch.id_span())
        if applied and not self._detached:
            record = self.observer.has_subscribers()
            from_f = self.state.frontiers
            with tracing.span("state.apply", n_changes=len(applied)):
                diffs = self.state.apply_changes(applied, record=record)
            self.state.frontiers = self.oplog.frontiers
            if record and diffs:
                self._emit(diffs, origin, EventTriggerKind.Import, from_f)
            else:
                self.state.frontiers = self.oplog.frontiers
        return ImportStatus(success, pending if not pending.is_empty() else None)

    def import_json_updates(self, json_obj) -> ImportStatus:
        """reference: loro.rs:873 import_json_updates."""
        if isinstance(json_obj, (str, bytes)):
            import json as _json

            json_obj = _json.loads(json_obj)
        return self._import_changes(jcodec.import_json_updates(json_obj), "import")

    def export_json_updates(
        self, start_vv: Optional[VersionVector] = None, end_vv: Optional[VersionVector] = None
    ):
        self._barrier()
        start_vv = start_vv or VersionVector()
        end_vv = end_vv or self.oplog.vv.copy()
        chs = self.oplog.changes_between(start_vv, end_vv)
        return jcodec.export_json_updates(chs, start_vv, end_vv)

    def export_json_updates_without_peer_compression(
        self, start_vv: Optional[VersionVector] = None, end_vv: Optional[VersionVector] = None
    ):
        """reference: loro.rs export_json_updates_without_peer_compression.
        This JSON codec never peer-compresses (ids are spelled out per
        change), so both exports coincide."""
        return self.export_json_updates(start_vv, end_vv)

    # ------------------------------------------------------------------
    # versions
    # ------------------------------------------------------------------
    def oplog_vv(self) -> VersionVector:
        return self.oplog.vv.copy()

    def oplog_frontiers(self) -> Frontiers:
        return self.oplog.frontiers

    def state_vv(self) -> VersionVector:
        return self.state.vv.copy()

    def state_frontiers(self) -> Frontiers:
        return self.state.frontiers

    def vv_to_frontiers(self, vv: VersionVector) -> Frontiers:
        return self.oplog.dag.vv_to_frontiers(vv)

    def frontiers_to_vv(self, f: Frontiers) -> VersionVector:
        return self.oplog.dag.frontiers_to_vv(f)

    # ------------------------------------------------------------------
    # time travel
    # ------------------------------------------------------------------
    def checkout_to_latest(self) -> None:
        self._barrier()
        if not self._detached and self.state.frontiers == self.oplog.frontiers:
            return  # already attached at head (reference loro.rs:1543
            # early-returns and must not renew the peer id)
        self.checkout(self.oplog.frontiers)
        self._detached = False

    def checkout(self, frontiers: Frontiers) -> None:
        """reference: loro.rs:1625.  Sets detached mode unless the target
        is the latest version."""
        self._barrier()
        try:
            target_vv = self.oplog.dag.frontiers_to_vv(frontiers)
        except KeyError as e:
            raise LoroError(f"checkout target not in history (shallow/trimmed?): {e}") from e
        if self._shallow_base is not None and not (self.oplog.dag.shallow_since_vv <= target_vv):
            raise LoroError("cannot checkout below the shallow root")
        cur_vv = self.state.vv.copy()
        record = self.observer.has_subscribers()
        old_values = self._container_values() if record else None
        from_f = self.state.frontiers
        pre_state = self.state
        if cur_vv <= target_vv:
            chs = self.oplog.changes_between(cur_vv, target_vv)
            self.state.apply_changes(chs, record=False)
        else:
            # retreat: rebuild from the replay floor (empty or the
            # frozen shallow base) up to target_vv
            self.state = self._state_at(frontiers)
        self.state.vv = target_vv.copy()
        self.state.frontiers = frontiers
        # checkout always detaches (reference loro.rs:1625); only
        # checkout_to_latest re-attaches
        self._detached = True
        if self.config.editable_detached_mode:
            # a peer's ops must stay a counter prefix (VersionVector
            # representability); branch edits therefore need a fresh
            # peer id — same behavior as the reference's editable
            # detached mode
            self.set_peer_id(random.getrandbits(63))
        if record:
            diffs = self._value_level_diffs(old_values)
            for cid, d in self._seq_diff_batch(cur_vv, target_vv, (self.state, pre_state)).items():
                diffs[cid] = [d]
            if diffs:
                self._emit(diffs, "checkout", EventTriggerKind.Checkout, from_f)

    def _container_values(self) -> Dict[ContainerID, Any]:
        return {cid: st.get_value() for cid, st in self.state.states.items()}

    def _value_level_diffs(
        self, old_values: Dict[ContainerID, Any]
    ) -> Dict[ContainerID, List]:
        """Value-level diffs for map/counter; identity-bearing
        containers (sequences + tree) are handled by _seq_diff_batch."""
        new_values = self._container_values()
        batch = _diff_values(old_values, new_values, self.state)
        return {cid: [d] for cid, d in batch.items()}

    # ------------------------------------------------------------------
    # version diff / apply (reference: loro.rs:1244 diff, loro.rs:1302
    # apply_diff, loro.rs:1232 revert_to)
    # ------------------------------------------------------------------
    def _state_at(self, frontiers: Frontiers) -> DocState:
        return self._state_at_vv(self.oplog.dag.frontiers_to_vv(frontiers), frontiers)

    def _state_at_vv(self, vv: VersionVector, frontiers: Optional[Frontiers] = None) -> DocState:
        """Materialize a throwaway DocState at an arbitrary version by
        causal replay from the nearest floor: a cached checkpoint
        (history_cache.py — the reference's history_cache.rs analog),
        the frozen shallow base, or empty.  Shallow docs never replay
        below the base."""
        if self._shallow_base is not None:
            base_vv = self._shallow_base[1]
            if not (base_vv <= vv):
                raise LoroError("cannot materialize a version below the shallow root")
        cached = self._state_cache.best_floor(vv)
        if cached is not None:
            st, from_vv, _f = cached
        else:
            st = DocState()
            from_vv = VersionVector()
            if self._shallow_base is not None:
                base_bytes, base_vv, _ = self._shallow_base
                states, parents = _decode_state_z(base_bytes)
                st.states = states
                st.parents.update(parents)
                from_vv = base_vv
        chs = self.oplog.changes_between(from_vv, vv)
        m = len(chs)
        if m > 32:
            # long cold replay: drop a checkpoint ladder at halving gaps
            # approaching the target, so *receding* time travel (undo's
            # access pattern walks backwards step by step) always finds
            # a nearby floor on the next call
            marks = sorted({m - (m >> i) for i in range(1, 6) if (m >> i) >= 8})
            cur_vv = from_vv.copy()
            done = 0
            for mk in marks:
                st.apply_changes(chs[done:mk], record=False)
                for ch in chs[done:mk]:
                    if ch.ctr_end > cur_vv.get(ch.peer):
                        cur_vv.set_end(ch.peer, ch.ctr_end)
                done = mk
                self._state_cache.put(
                    cur_vv, self.oplog.dag.vv_to_frontiers(cur_vv), st
                )
            st.apply_changes(chs[done:], record=False)
        else:
            st.apply_changes(chs, record=False)
        st.vv = vv.copy()
        st.frontiers = frontiers if frontiers is not None else self.oplog.dag.vv_to_frontiers(vv)
        self._state_cache.put(st.vv, st.frontiers, st)
        return st

    def diff(self, a: Frontiers, b: Frontiers) -> Dict[ContainerID, Any]:
        """DiffBatch turning state(a) into state(b).  Sequence containers
        get EXACT deltas via element-identity visibility at each version
        (per-element deletion records); other containers diff by value.
        Endpoints equal to the live state reuse it instead of replaying
        the full history."""
        self._barrier()  # uncommitted ops would desync state vs frontiers
        va = self.oplog.dag.frontiers_to_vv(a)
        vb = self.oplog.dag.frontiers_to_vv(b)
        sa = self.state if a == self.state.frontiers else self._state_at(a)
        sb = self.state if b == self.state.frontiers else self._state_at(b)
        batch = _state_diff(sa, sb)
        batch.update(self._seq_diff_batch(va, vb, (self.state, sb, sa)))
        return batch

    def _seq_diff_batch(
        self, va: VersionVector, vb: VersionVector, candidates
    ) -> Dict[ContainerID, Any]:
        """Exact element-identity deltas for every sequence container,
        computed on whichever candidate state covers both versions (a
        union replay as the last resort).  Scans ALL sequence containers
        — identity changes with equal values still produce deltas."""
        union = va.join(vb)
        u_state = next((s for s in candidates if s is not None and union <= s.vv), None)
        if u_state is None:
            u_state = self._state_at_vv(union)
        out: Dict[ContainerID, Any] = {}
        for cid, st in u_state.states.items():
            if cid.ctype == ContainerType.Tree:
                d = st.delta_between(va, vb)
            elif cid.ctype == ContainerType.MovableList:
                d = st.delta_between(va, vb)
            elif cid.ctype == ContainerType.Text:
                # style-aware when the container ever carried anchors
                if getattr(st, "n_anchors", 0):
                    d = st.styled_delta_between(va, vb)
                else:
                    d = st.seq.delta_between(va, vb, as_text=True, vc=u_state.vv)
            elif cid.ctype == ContainerType.List:
                d = st.seq.delta_between(va, vb, as_text=False, vc=u_state.vv)
            else:
                continue
            if not d.is_empty():
                out[cid] = d
        return out

    def apply_diff(self, batch: Dict[ContainerID, Any], origin: str = "apply_diff") -> None:
        """Apply a DiffBatch as new local ops."""
        from .core.change import TreeMove
        from .event import CounterDiff as _CD
        from .event import Delta as _Delta
        from .event import MapDiff as _MD
        from .event import TreeDiff as _TD
        from .event import TreeDiffAction as _TDA
        from .event import Insert as _Ins
        from .event import Retain as _Ret

        for cid, d in batch.items():
            h = self.get_container(cid)
            if isinstance(d, _MD):
                for k, v in d.updated.items():
                    h.set(k, v)  # type: ignore[attr-defined]
                for k in d.deleted:
                    h.delete(k)  # type: ignore[attr-defined]
            elif isinstance(d, _CD):
                if d.delta:
                    h.increment(d.delta)  # type: ignore[attr-defined]
            elif isinstance(d, _Delta):
                pos = 0
                for it in d.items:
                    if isinstance(it, _Ret):
                        if it.attributes and hasattr(h, "mark"):
                            for k, v in it.attributes.items():
                                if v is None:
                                    h.unmark(pos, pos + it.n, k)
                                else:
                                    h.mark(pos, pos + it.n, k, v)
                        pos += it.n
                    elif isinstance(it, _Ins):
                        if isinstance(it.value, str):
                            h.insert(pos, it.value)  # type: ignore[call-arg]
                        else:
                            h.insert(pos, *it.value)  # type: ignore[call-arg]
                        if hasattr(h, "mark"):
                            # the diff's attributes are authoritative for
                            # the new text: neutralize styles inherited
                            # from surrounding live anchor pairs too
                            st = h._state
                            elem = st.seq.elem_at(pos)
                            inherited = (
                                st._styles_at_elem(elem)
                                if (st.n_anchors and elem is not None)
                                else {}
                            )
                            target = {
                                k: v
                                for k, v in (it.attributes or {}).items()
                                if v is not None
                            }
                            end = pos + len(it.value)
                            for k in set(inherited) | set(target):
                                tv = target.get(k)
                                if tv is None:
                                    h.unmark(pos, end, k)
                                elif inherited.get(k) != tv:
                                    h.mark(pos, end, k, tv)
                        pos += len(it.value)
                    else:
                        h.delete(pos, it.n)  # type: ignore[attr-defined]
            elif isinstance(d, _TD):
                for item in d.items:
                    try:
                        if item.action == _TDA.Delete:
                            h.delete(item.target)  # type: ignore[attr-defined]
                        elif item.action == _TDA.Create:
                            if not h.contains(item.target):  # type: ignore[attr-defined]
                                # re-creating a node keeps its identity: a
                                # move op revives it under the same TreeID
                                self._txn_apply(
                                    cid, TreeMove(item.target, item.parent, item.position)
                                )
                        else:
                            h.move(item.target, item.parent, item.index)  # type: ignore[attr-defined]
                    except (ValueError, LoroError):
                        continue  # target vanished concurrently
        # commit only what this batch produced: an empty batch must not
        # swallow pending next-commit options (it is an internal commit)
        if self._txn is not None and not self._txn.is_empty():
            self.commit(origin=origin)

    def revert_to(self, frontiers: Frontiers) -> None:
        """Generate new ops returning the doc to `frontiers`' state."""
        self._barrier()
        batch = self.diff(self.oplog.frontiers, frontiers)
        self.apply_diff(batch, origin="revert")

    # ------------------------------------------------------------------
    # fork
    # ------------------------------------------------------------------
    def fork(self) -> "LoroDoc":
        """Deep copy at the CURRENT version: a detached doc forks its
        checked-out state, not the latest history (reference: fork.rs +
        test_fork_when_detached)."""
        if self._detached:
            return self.fork_at(self.state_frontiers())
        new = LoroDoc()
        new.import_(self.export(ExportMode.Snapshot), origin="fork")
        return new

    def fork_at(self, frontiers: Frontiers) -> "LoroDoc":
        # typed validation: vv membership is not enough on shallow docs
        # (ids below the floor are in the vv but have no dag node); the
        # floor frontiers themselves are the one representable exception
        if frontiers != self.oplog.dag.shallow_since_frontiers:
            if self.is_shallow() and frontiers.is_empty():
                raise LoroError("fork_at below the shallow root")
            for id_ in frontiers:
                if self.oplog.dag.node_at(id_) is None:
                    raise LoroError(f"fork_at frontiers not in history: {id_}")
        new = LoroDoc()
        new.import_(self.export(ExportMode.SnapshotAt(frontiers)), origin="fork")
        return new

    # ------------------------------------------------------------------
    # values
    # ------------------------------------------------------------------
    def _hide_empty_filter(self, v: Dict[str, Any]) -> Dict[str, Any]:
        """Per-container-type emptiness, matching the reference
        (state.rs visible_container_value_is_empty): hide only empty
        Text/Map/List/MovableList/Tree roots; Counter and Unknown roots
        are never hidden regardless of value."""
        from loro_tpu.core.ids import ContainerType

        hideable = {
            ContainerType.Text: "",
            ContainerType.Map: {},
            ContainerType.List: [],
            ContainerType.MovableList: [],
            ContainerType.Tree: [],
        }
        empty_by_name: Dict[str, Any] = {}
        for cid in self.state.states:
            if cid.is_root and cid.ctype in hideable:
                empty_by_name[cid.name] = hideable[cid.ctype]
        return {
            k: x
            for k, x in v.items()
            if not (k in empty_by_name and x == empty_by_name[k])
        }

    def get_value(self) -> Dict[str, Any]:
        v = self.state.get_value()
        if self.config.hide_empty_root_containers:
            v = self._hide_empty_filter(v)
        return v

    def get_deep_value(self) -> Dict[str, Any]:
        v = self.state.get_deep_value()
        if self.config.hide_empty_root_containers:
            v = self._hide_empty_filter(v)
        return v

    def get_deep_value_with_id(self) -> Dict[str, Any]:
        """Like get_deep_value, but every container value is wrapped as
        {"cid": str, "value": ...} (reference:
        LoroDoc::get_deep_value_with_id)."""

        def deep(x):
            if isinstance(x, ContainerID):
                return wrap(x)
            if isinstance(x, dict):
                return {k: deep(v) for k, v in x.items()}
            if isinstance(x, list):
                return [deep(v) for v in x]
            return x

        def wrap(cid: ContainerID):
            st = self.state.states.get(cid)
            if st is None:
                return {"cid": str(cid), "value": None}
            return {"cid": str(cid), "value": deep(st.get_value())}

        from .core.ids import is_internal_root_name

        out: Dict[str, Any] = {}
        for cid in list(self.state.states):
            st = self.state.states.get(cid)
            if (
                cid.is_root
                and not is_internal_root_name(cid.name)
                and st is not None
                and st.materialized
            ):
                out[cid.name] = wrap(cid)
        return out

    def get_by_str_path(self, path: str):
        """Navigate "container/key/index/..." to a handler or value
        (reference: loro.rs get_by_str_path)."""
        parts = [p for p in path.split("/") if p]
        return self._navigate_parts(parts)

    def _navigate_parts(self, parts) -> Any:
        """Segment-by-segment navigation shared by get_by_str_path and
        get_by_path — lazy (touches only the containers on the path)
        and returns live handlers for sub-containers."""
        if not parts:
            raise LoroError("empty path")
        cur: Any = None
        for i, part in enumerate(parts):
            if i == 0:
                candidates = [
                    cid for cid in self.state.states if cid.is_root and cid.name == part
                ]
                if not candidates:
                    return None
                cur = self.get_container(candidates[0])
                continue
            from .models.handlers import ListHandler, MovableListHandler

            if isinstance(cur, (ListHandler, MovableListHandler)) or isinstance(
                cur, list
            ):
                try:
                    idx = int(part)
                except (TypeError, ValueError):
                    return None  # list segments must be numeric
                if idx < 0 or idx >= len(cur):
                    return None
                cur = cur[idx] if isinstance(cur, list) else cur.get(idx)
            elif hasattr(cur, "get"):
                # map handler or plain dict: string keys
                cur = cur.get(part)
            else:
                return None
            if cur is None:
                return None
        return cur

    # -- history inspection (reference: change meta APIs) --------------
    def len_changes(self) -> int:
        return self.oplog.total_changes()

    def get_change(self, id: ID) -> Optional[Dict[str, Any]]:
        """Change metadata covering `id` (reference: ChangeMeta)."""
        ch = self.oplog.change_at(id)
        if ch is None:
            return None
        return {
            "id": ch.id,
            "peer": ch.peer,
            "lamport": ch.lamport,
            "timestamp": ch.timestamp,
            "deps": ch.deps,
            "len": ch.atom_len(),
            "message": ch.message,
        }

    def travel_change_ancestors(self, ids: List[ID], cb) -> None:
        """Walk the causal ancestors of the given change ids in reverse
        lamport order, calling cb(change_meta); cb returning False stops
        the walk (reference: loro.rs travel_change_ancestors)."""
        import heapq

        seen = set()
        heap = []
        for i in ids:
            ch = self.oplog.change_at(i)
            if ch is None:
                raise LoroError(f"change not found: {i}")
            if ch.id not in seen:
                seen.add(ch.id)
                heapq.heappush(heap, (-ch.lamport, ch.peer, ch.id))
        while heap:
            _, _, cid = heapq.heappop(heap)
            ch = self.oplog.change_at(cid)
            if ch is None:
                continue
            if cb(self.get_change(ch.id)) is False:
                return
            for dep in ch.deps:
                dch = self.oplog.change_at(dep)
                if dch is not None and dch.id not in seen:
                    seen.add(dch.id)
                    heapq.heappush(heap, (-dch.lamport, dch.peer, dch.id))

    def get_changed_containers_in(self, id: ID, length: int) -> set:
        """Container ids touched by ops in [id, id+len)."""
        out = set()
        ch = self.oplog.change_at(id)
        while ch is not None and ch.ctr_start < id.counter + length:
            for op in ch.ops:
                if op.ctr_end > id.counter and op.counter < id.counter + length:
                    out.add(op.container)
            nxt = ID(id.peer, ch.ctr_end)
            if nxt.counter >= id.counter + length:
                break
            ch = self.oplog.change_at(nxt)
        return out

    def len_ops(self) -> int:
        return self.oplog.total_ops()

    def has_container(self, cid: Union[ContainerID, str]) -> bool:
        if isinstance(cid, str):
            cid = ContainerID.parse(cid)
        return cid in self.state.states

    def get_pending_txn_len(self) -> int:
        return 0 if self._txn is None else self._txn.atom_len()

    def delete_root_container(self, cid: Union[ContainerID, str]) -> None:
        """Clear a root container's content so it reads as empty
        (reference: LoroDoc::delete_root_container)."""
        if isinstance(cid, str):
            cid = ContainerID.parse(cid)
        h = self.get_container(cid)
        if cid.ctype == ContainerType.Tree:
            for root in list(h.roots()):
                h.delete(root)
        elif cid.ctype == ContainerType.Counter:
            v = h.get_value()
            if v:
                h.decrement(v)
        elif hasattr(h, "clear"):
            h.clear()
        elif hasattr(h, "delete") and hasattr(h, "__len__"):
            h.delete(0, len(h))
        self._barrier()

    # -- shallow introspection (reference: is_shallow / shallow_since) -
    def is_shallow(self) -> bool:
        return self._shallow_base is not None

    def shallow_since_vv(self) -> VersionVector:
        return self.oplog.dag.shallow_since_vv.copy()

    def shallow_since_frontiers(self) -> Frontiers:
        return self.oplog.dag.shallow_since_frontiers

    # -- version algebra (reference: cmp/minimize frontiers) -----------
    def cmp_with_frontiers(self, f: Frontiers) -> int:
        """Compare the doc version with `f`: -1 behind, 0 equal, 1
        ahead (raises on concurrent — reference returns Ordering)."""
        va = self.oplog.vv
        vb = self.oplog.dag.frontiers_to_vv(f)
        if va == vb:
            return 0
        if va <= vb:
            return -1
        if vb <= va:
            return 1
        raise LoroError("versions are concurrent")

    def cmp_frontiers(self, a: Frontiers, b: Frontiers) -> Optional[int]:
        """Partial compare of two frontiers: -1/0/1 or None when
        concurrent (reference: LoroDoc::cmp_frontiers)."""
        va = self.oplog.dag.frontiers_to_vv(a)
        vb = self.oplog.dag.frontiers_to_vv(b)
        if va == vb:
            return 0
        if va <= vb:
            return -1
        if vb <= va:
            return 1
        return None

    def minimize_frontiers(self, f: Frontiers) -> Frontiers:
        """Drop heads dominated by other heads' closures."""
        return self.oplog.dag.vv_to_frontiers(self.oplog.dag.frontiers_to_vv(f))

    def find_id_spans_between(self, from_f: Frontiers, to_f: Frontiers) -> VersionRange:
        """Per-peer id spans in to_f's closure but not from_f's
        (reference: LoroDoc::find_id_spans_between)."""
        va = self.oplog.dag.frontiers_to_vv(from_f)
        vb = self.oplog.dag.frontiers_to_vv(to_f)
        out = VersionRange()
        for p in vb:
            lo, hi = va.get(p), vb.get(p)
            if hi > lo:
                out.extend_to_include(IdSpan(p, lo, hi))
        return out

    # -- commit options / config sugar ---------------------------------
    def set_next_commit_message(self, message: str) -> None:
        """Message for the NEXT non-empty commit (stored on the doc, not
        an eager empty transaction — empty txns are discarded by any
        implicit commit and would go stale across set_peer_id)."""
        self._next_commit_message = message

    def set_next_commit_origin(self, origin: str) -> None:
        self._next_commit_origin = origin

    def set_next_commit_timestamp(self, timestamp: int) -> None:
        """Unix-seconds timestamp for the NEXT commit, overriding both
        the clock and record_timestamp (reference:
        LoroDoc::set_next_commit_timestamp)."""
        self._next_commit_timestamp = timestamp

    def set_next_commit_options(
        self,
        origin: Optional[str] = None,
        message: Optional[str] = None,
        timestamp: Optional[int] = None,
    ) -> None:
        """reference: LoroDoc::set_next_commit_options (CommitOptions)."""
        if origin is not None:
            self._next_commit_origin = origin
        if message is not None:
            self._next_commit_message = message
        if timestamp is not None:
            self._next_commit_timestamp = timestamp

    def clear_next_commit_options(self) -> None:
        """reference: LoroDoc::clear_next_commit_options."""
        self._next_commit_message = None
        self._next_commit_origin = None
        self._next_commit_timestamp = None

    def commit_with(
        self,
        origin: str = "",
        message: Optional[str] = None,
        timestamp: Optional[int] = None,
    ) -> None:
        """Commit with explicit options (reference: LoroDoc::commit_with).
        Options apply to THIS commit only — with nothing pending they are
        dropped, unlike set_next_commit_* which persists to the next
        non-empty commit."""
        if timestamp is not None and self._txn is not None and not self._txn.is_empty():
            self._next_commit_timestamp = timestamp
        self.commit(origin=origin, message=message)

    def set_record_timestamp(self, record: bool) -> None:
        self.config.record_timestamp = record

    def set_hide_empty_root_containers(self, hide: bool) -> None:
        """reference: LoroDoc::set_hide_empty_root_containers."""
        self.config.hide_empty_root_containers = hide

    def config_text_style(self, styles: Dict[str, str]) -> None:
        """Set per-key mark expand behavior: "after" (default), "before",
        "both", "none" (reference: LoroDoc::config_text_style /
        StyleConfigMap)."""
        for key, expand in styles.items():
            if expand not in ("after", "before", "both", "none"):
                raise LoroError(f"invalid expand behavior {expand!r} for style {key!r}")
            self.config.text_style_config[key] = expand

    def config_default_text_style(self, expand: Optional[str]) -> None:
        """Default expand behavior for keys not in text_style_config
        (reference: LoroDoc::config_default_text_style; None resets to
        the built-in "after")."""
        if expand is None:
            self.config.default_text_style = "after"
            return
        if expand not in ("after", "before", "both", "none"):
            raise LoroError(f"invalid expand behavior {expand!r}")
        self.config.default_text_style = expand

    def set_change_merge_interval(self, interval_s: int) -> None:
        self.config.merge_interval_s = interval_s

    set_merge_interval = set_change_merge_interval

    def has_history_cache(self) -> bool:
        """Whether checkout/diff checkpoint floors are materialized
        (reference: LoroDoc::has_history_cache)."""
        return len(self._state_cache) > 0

    def free_history_cache(self) -> None:
        """Drop checkout checkpoint floors; time travel re-replays from
        scratch until re-warmed (reference: LoroDoc::free_history_cache)."""
        self._state_cache.clear()

    def free_diff_calculator(self) -> None:
        """reference: LoroDoc::free_diff_calculator.  The merge engine
        here is stateless between imports (structure-holding states, no
        persistent tracker), so there is nothing to free beyond the
        checkout checkpoints."""
        self.free_history_cache()

    def check_state_correctness_slow(self) -> None:
        """Deep self-check (reference: LoroDoc::check_state_correctness_slow):
        replay full history into a fresh doc and require identical deep
        values + identical frontiers; run structural invariant checkers
        on every sequence CRDT."""
        self._barrier()
        if self.is_shallow():
            # replay floor is the frozen base; rebuild via snapshot
            fresh = LoroDoc.from_snapshot(self.export(ExportMode.Snapshot))
        else:
            fresh = LoroDoc()
            fresh.import_(self.export_updates())
        if not self._detached:
            a, b = self.get_deep_value(), fresh.get_deep_value()
            if a != b:
                raise LoroError(f"state mismatch vs replay: {a!r} != {b!r}")
        for cid, st in self.state.states.items():
            seq = getattr(st, "seq", None)
            if seq is not None and hasattr(seq, "check_invariants"):
                seq.check_invariants()

    def log_internal_state(self) -> str:
        """Dump sizes + per-container analysis (reference:
        LoroDoc::log_internal_state); returns the dump and logs it
        through the tracing layer."""
        import json as _json

        dump = _json.dumps(
            {
                "peer": self.peer,
                "detached": self._detached,
                "oplog": self.diagnose_size(),
                "frontiers": str(self.oplog.frontiers),
                "containers": self.analyze(),
            },
            indent=2,
            default=str,
        )
        tracing.instant("doc.internal_state", dump=dump)
        return dump

    def compact_change_store(self) -> None:
        """Push hot decoded history back into sealed compressed blocks
        and free the Change objects (reference:
        LoroDoc::compact_change_store)."""
        self._barrier()
        self.oplog.compact()

    @staticmethod
    def decode_import_blob_meta(blob: bytes) -> Dict[str, Any]:
        """Inspect a blob without importing it (reference:
        LoroDoc::decode_import_blob_meta): mode, format version, and for
        update payloads the per-peer span range + change count."""
        from .codec import binary as bcodec

        version, mode, payload = parse_envelope_header(blob)
        meta: Dict[str, Any] = {"mode": mode.name, "version": version}
        if mode in (EncodeMode.ColumnarUpdates, EncodeMode.ColumnarSnapshot):
            start = VersionRange()
            changes = bcodec.decode_changes(payload)
            end_vv = VersionVector()
            n = 0
            for ch in changes:
                start.extend_to_include(IdSpan(ch.peer, ch.ctr_start, ch.ctr_end))
                end_vv.set_end(ch.peer, max(end_vv.get(ch.peer), ch.ctr_end))
                n += 1
            meta["change_num"] = n
            meta["partial_start_vv"] = {p: s for p, (s, _e) in start.items()}
            meta["partial_end_vv"] = dict(end_vv.items())
        return meta

    # -- cursor / jsonpath / path sugar (reference exposes these as doc
    # methods; the implementations live in their modules) --------------
    def get_cursor(self, container, pos: int, side=None):
        from .cursor import CursorSide, get_cursor

        return get_cursor(self, container, pos, side if side is not None else CursorSide.Middle)

    def get_cursor_pos(self, cursor):
        from .cursor import get_cursor_pos

        return get_cursor_pos(self, cursor)

    def jsonpath(self, path: str) -> List[Any]:
        from .jsonpath import query

        return query(self, path)

    def subscribe_jsonpath(self, path: str, cb):
        from .jsonpath import subscribe_jsonpath

        return subscribe_jsonpath(self, path, cb)

    def get_path_to_container(self, cid: Union[ContainerID, str]):
        if isinstance(cid, str):
            cid = ContainerID.parse(cid)
        if cid not in self.state.states:
            return None
        return self.state.path_of(cid)

    def get_by_path(self, parts) -> Any:
        """Navigate a path given as a sequence of keys/indexes,
        segment-by-segment (reference: get_by_path) — lazy, returns
        live handlers for sub-containers, and keys containing "/" keep
        their meaning (unlike the string form)."""
        return self._navigate_parts(list(parts))

    def export_json_in_id_span(self, span: IdSpan) -> List[Dict[str, Any]]:
        """JSON form of the changes covering one peer's id span
        (reference: LoroDoc::export_json_in_id_span)."""
        self._barrier()
        chs = self.oplog.changes_between(
            VersionVector({span.peer: span.start}),
            VersionVector({span.peer: span.end}),
        )
        return [jcodec.change_to_json(ch) for ch in chs]

    def diagnose_size(self) -> Dict[str, int]:
        return self.oplog.diagnose_size()

    def analyze(self) -> Dict[str, Dict[str, Any]]:
        """Per-container size introspection (reference: state/analyzer.rs
        DocAnalysis)."""
        out: Dict[str, Dict[str, Any]] = {}
        for cid, st in self.state.states.items():
            info: Dict[str, Any] = {"type": cid.ctype.name, "depth": self.state.depth_of(cid)}
            seq = getattr(st, "seq", None)
            if seq is not None:
                n_deleted = 0
                n_anchors = 0
                for e in seq.all_elems():
                    if e.deleted:
                        n_deleted += 1
                    elif getattr(e, "is_anchor", False):
                        n_anchors += 1
                info["elements"] = seq.total_len
                info["visible"] = seq.visible_len
                info["tombstones"] = n_deleted  # live anchors are not garbage
                if n_anchors:
                    info["anchors"] = n_anchors
            elif hasattr(st, "entries"):
                info["entries"] = len(st.entries)
            elif hasattr(st, "nodes"):
                info["nodes"] = len(st.nodes)
                info["moves"] = len(st.moves)
            out[str(cid)] = info
        return out

    def __len__(self) -> int:
        return len(self.state.states)


def _state_diff(sa: DocState, sb: DocState) -> Dict[ContainerID, Any]:
    """Value-level DiffBatch turning sa's values into sb's (map/counter
    only — identity containers come from _seq_diff_batch)."""
    va = {cid: st.get_value() for cid, st in sa.states.items()}
    vb = {cid: st.get_value() for cid, st in sb.states.items()}
    return _diff_values(va, vb, sb)


def _diff_values(
    va: Dict[ContainerID, Any],
    vb: Dict[ContainerID, Any],
    target_state: DocState,
) -> Dict[ContainerID, Any]:
    from .event import CounterDiff

    out: Dict[ContainerID, Any] = {}
    for cid in set(va) | set(vb):
        if cid.ctype in (
            ContainerType.Text,
            ContainerType.List,
            ContainerType.MovableList,
            ContainerType.Tree,
        ):
            continue  # exact identity deltas computed separately
        old_v = va.get(cid)
        new_v = vb.get(cid)
        if old_v == new_v:
            continue
        if cid.ctype == ContainerType.Map:
            d = MapDiff()
            old_m = old_v or {}
            new_m = new_v or {}
            for k in new_m:
                if k not in old_m or old_m[k] != new_m[k]:
                    d.updated[k] = new_m[k]
            for k in old_m:
                if k not in new_m:
                    d.deleted.add(k)
            if not d.is_empty():
                out[cid] = d
        elif cid.ctype == ContainerType.Counter:
            out[cid] = CounterDiff((new_v or 0.0) - (old_v or 0.0))
    return out


def parse_envelope_header(data: bytes) -> Tuple[int, "EncodeMode", bytes]:
    """The single LTPU envelope validator: magic, version gate, mode,
    crc.  Returns (version, mode, payload)."""
    if len(data) < ENVELOPE_LEN or data[:4] != MAGIC:
        raise DecodeError("bad magic")
    version, mode_b = data[4], data[5]
    if version > FORMAT_VERSION:
        raise DecodeError(f"unsupported format version {version}")
    crc = int.from_bytes(data[6:10], "little")
    payload = data[ENVELOPE_LEN:]
    if zlib.crc32(payload) != crc:
        raise DecodeError("checksum mismatch")
    try:
        mode = EncodeMode(mode_b)
    except ValueError as e:
        raise DecodeError(f"unknown encode mode {mode_b}") from e
    # v1 snapshot layouts (pre-BlockStore, uncompressed state) are not
    # decodable by this version — fail with a version error, not a
    # confusing zlib/malformed one.
    if version < _min_version_for_mode(mode):
        raise DecodeError(
            f"{mode.name} blob written by format v{version}; this build reads "
            f"v{_min_version_for_mode(mode)}+"
        )
    return version, mode, payload


def strip_envelope(blob: bytes) -> bytes:
    """Validate the LTPU envelope and return the bare payload (the form
    the native SoA decoder and device-batch ingest paths consume)."""
    return parse_envelope_header(blob)[2]


def _decode_state_z(state_bytes: bytes):
    """Decode a (zlib-compressed) doc-state section.  All shallow-base
    and snapshot state sections ship compressed (reference compresses
    change blocks with LZ4; we extend the same treatment to state)."""
    import zlib as _z

    from .codec import snapshot as scodec

    return scodec.decode_doc_state(_z.decompress(state_bytes))
