"""UndoManager: undo/redo of local edits with OT against concurrent
remote edits.

reference: crates/loro-internal/src/undo.rs — local commit spans are
recorded on a stack; undo computes the inverse DiffBatch between the
span's end and start versions (history replay) and transforms it
through everything that has been applied since (remote imports and
later local edits), then applies it as *new* ops; redo mirrors.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .core.ids import ContainerID
from .core.version import Frontiers
from .doc import LoroDoc
from .event import CounterDiff, Delta, DocDiff, EventTriggerKind, MapDiff, TreeDiff

UNDO_ORIGIN = "undo"
REDO_ORIGIN = "redo"


@dataclass
class UndoItem:
    from_f: Frontiers
    to_f: Frontiers
    # diffs applied after this item, per container (for transform)
    post: Dict[ContainerID, Any] = field(default_factory=dict)
    # user meta captured by the on_push callback (reference:
    # UndoItemMeta{value, cursors})
    meta: Any = None


def _transform_batch(
    batch: Dict[ContainerID, Any], post: Dict[ContainerID, Any]
) -> Dict[ContainerID, Any]:
    """Transform an inverse DiffBatch through later diffs: sequence
    deltas are OT-transformed; map keys touched later are dropped (the
    later write wins, an undo must not clobber it); tree items whose
    target was touched later are dropped (reference DiffBatch::transform
    semantics, undo.rs:63-70)."""
    out: Dict[ContainerID, Any] = {}
    for cid, d in batch.items():
        p = post.get(cid)
        if p is None:
            out[cid] = d
            continue
        if isinstance(d, Delta) and isinstance(p, Delta):
            t = p.transform(d, priority_left=True)
            if not t.is_empty():
                out[cid] = t
        elif isinstance(d, MapDiff) and isinstance(p, MapDiff):
            touched = set(p.updated) | set(p.deleted)
            t = MapDiff(
                {k: v for k, v in d.updated.items() if k not in touched},
                {k for k in d.deleted if k not in touched},
            )
            if not t.is_empty():
                out[cid] = t
        elif isinstance(d, TreeDiff) and isinstance(p, TreeDiff):
            touched = {it.target for it in p.items}
            t = TreeDiff([it for it in d.items if it.target not in touched])
            if not t.is_empty():
                out[cid] = t
        elif isinstance(d, CounterDiff):
            out[cid] = d  # sums commute
    return out


class UndoManager:
    def __init__(
        self,
        doc: LoroDoc,
        max_stack: int = 100,
        merge_interval_ms: int = 0,
        exclude_origin_prefixes: Optional[List[str]] = None,
    ):
        """merge_interval_ms: consecutive local commits closer than this
        merge into one undo step (reference: UndoManager merge
        interval); group_start()/group_end() group explicitly;
        exclude_origin_prefixes: local commits whose origin starts with
        any prefix are not recorded as undo steps (reference:
        excludeOriginPrefixes).  Exclusion takes precedence over
        grouping: an excluded commit inside a group splits it (an undo
        span must never extend across work that must not be undone —
        the inverse diff would revert it)."""
        self.doc = doc
        self.max_stack = max_stack
        self.merge_interval_ms = merge_interval_ms
        self.exclude_origin_prefixes = list(exclude_origin_prefixes or [])
        self.undo_stack: List[UndoItem] = []
        self.redo_stack: List[UndoItem] = []
        self._unsub = doc.subscribe_root(self._on_event)
        self._grouping = False
        self._group_fresh = False
        self._last_push_ms = 0.0

    def close(self) -> None:
        self._unsub()

    # -- introspection (reference: UndoManager counts / peek) ----------
    def undo_count(self) -> int:
        return len(self.undo_stack)

    def redo_count(self) -> int:
        return len(self.redo_stack)

    def set_max_undo_steps(self, n: int) -> None:
        self.max_stack = n
        while len(self.undo_stack) > n:
            self.undo_stack.pop(0)

    def add_exclude_origin_prefix(self, prefix: str) -> None:
        """Commits whose origin starts with `prefix` neither push undo
        items nor clear the redo stack (reference:
        UndoManager::add_exclude_origin_prefix)."""
        self.exclude_origin_prefixes.append(prefix)

    def set_on_push(self, cb) -> None:
        """Called with (is_undo: bool, span frontiers) when a stack item
        is pushed; its return value (if any) is stored as the item's
        meta, readable via top_undo_meta/top_redo_meta (reference:
        OnPush returning UndoItemMeta — used to capture cursors/meta)."""
        self._on_push = cb

    def set_on_pop(self, cb) -> None:
        self._on_pop = cb

    def set_merge_interval(self, interval_ms: int) -> None:
        """reference: UndoManager::set_merge_interval (0 = no merge)."""
        self.merge_interval_ms = interval_ms

    @property
    def peer(self) -> int:
        """reference: UndoManager::peer."""
        return self.doc.peer

    def clear(self) -> None:
        """Drop both stacks (reference: UndoManager::clear)."""
        self.undo_stack.clear()
        self.redo_stack.clear()

    def record_new_checkpoint(self) -> None:
        """Commit pending work and force the next local commit to open
        a new undo item even inside the merge interval / a group
        (reference: UndoManager::record_new_checkpoint)."""
        self.doc._barrier()
        self._last_push_ms = float("-inf")
        self._group_fresh = True

    def _top_meta(self, stack: List[UndoItem]):
        return stack[-1].meta if stack else None

    def top_undo_meta(self):
        return self._top_meta(self.undo_stack)

    def top_redo_meta(self):
        return self._top_meta(self.redo_stack)

    def top_undo_value(self):
        m = self.top_undo_meta()
        return m.get("value") if isinstance(m, dict) else m

    def top_redo_value(self):
        m = self.top_redo_meta()
        return m.get("value") if isinstance(m, dict) else m

    # -- grouping (reference: undo group_start/group_end) --------------
    def group_start(self) -> None:
        self.doc._barrier()
        self._grouping = True
        self._group_fresh = True  # first in-group commit opens a new item

    def group_end(self) -> None:
        self.doc._barrier()
        self._grouping = False

    # ------------------------------------------------------------------
    def _on_event(self, ev: DocDiff) -> None:
        if ev.by == EventTriggerKind.Checkout:
            return
        if ev.by == EventTriggerKind.Local:
            # local history is linear: stack discipline alone keeps
            # inverse diffs applicable (later items are undone first),
            # so local diffs never fold into `post` — only remote
            # concurrency transforms the stacks (reference undo.rs).
            if ev.origin == UNDO_ORIGIN:
                self.redo_stack.append(UndoItem(ev.from_frontiers, ev.to_frontiers))
                cb = getattr(self, "_on_push", None)
                if cb is not None:
                    self.redo_stack[-1].meta = cb(False, (ev.from_frontiers, ev.to_frontiers))
            elif ev.origin == REDO_ORIGIN:
                self.undo_stack.append(UndoItem(ev.from_frontiers, ev.to_frontiers))
                cb = getattr(self, "_on_push", None)
                if cb is not None:
                    self.undo_stack[-1].meta = cb(True, (ev.from_frontiers, ev.to_frontiers))
            elif any(ev.origin.startswith(p) for p in self.exclude_origin_prefixes):
                # excluded local work behaves like remote concurrency:
                # it must transform the stacks, not become a step
                self._fold_post({cd.id: cd.diff for cd in ev.diffs})
            else:
                import time as _time

                now = _time.monotonic() * 1000
                if self._grouping:
                    want_merge = not self._group_fresh
                    self._group_fresh = False
                else:
                    want_merge = bool(
                        self.merge_interval_ms
                        and now - self._last_push_ms < self.merge_interval_ms
                    )
                mergeable = want_merge and self.undo_stack and not self.undo_stack[-1].post
                if mergeable:
                    # extend the top item's span to cover this commit
                    top = self.undo_stack[-1]
                    self.undo_stack[-1] = UndoItem(
                        top.from_f, ev.to_frontiers, top.post, top.meta
                    )
                else:
                    self.undo_stack.append(UndoItem(ev.from_frontiers, ev.to_frontiers))
                    if len(self.undo_stack) > self.max_stack:
                        self.undo_stack.pop(0)
                    cb = getattr(self, "_on_push", None)
                    if cb is not None:
                        self.undo_stack[-1].meta = cb(True, (ev.from_frontiers, ev.to_frontiers))
                self._last_push_ms = now
                self.redo_stack.clear()
            return
        # remote import: transform both stacks
        self._fold_post({cd.id: cd.diff for cd in ev.diffs})

    def _fold_post(self, ev_batch: Dict[ContainerID, Any]) -> None:
        from .event import compose_diff

        for stack in (self.undo_stack, self.redo_stack):
            for it in stack:
                for cid, d in ev_batch.items():
                    it.post[cid] = compose_diff(it.post.get(cid), d)

    # ------------------------------------------------------------------
    def can_undo(self) -> bool:
        return bool(self.undo_stack)

    def can_redo(self) -> bool:
        return bool(self.redo_stack)

    def undo(self) -> bool:
        return self._pop_apply(self.undo_stack, UNDO_ORIGIN)

    def redo(self) -> bool:
        return self._pop_apply(self.redo_stack, REDO_ORIGIN)

    def _pop_apply(self, stack: List[UndoItem], origin: str) -> bool:
        self.doc._barrier()
        if not stack:
            return False
        item = stack.pop()
        cb = getattr(self, "_on_pop", None)
        if cb is not None:
            # reference OnPop receives the popped item's meta (cursor
            # restore); legacy 2-arg callbacks keep working
            import inspect

            try:
                takes_meta = len(inspect.signature(cb).parameters) >= 3
            except (TypeError, ValueError):
                takes_meta = False
            if takes_meta:
                cb(stack is self.undo_stack, (item.from_f, item.to_f), item.meta)
            else:
                cb(stack is self.undo_stack, (item.from_f, item.to_f))
        inv = self.doc.diff(item.to_f, item.from_f)  # inverse of the span
        inv = _transform_batch(inv, item.post)
        if not inv:
            return True  # fully cancelled by later edits; still consumed
        self.doc.apply_diff(inv, origin=origin)
        return True
