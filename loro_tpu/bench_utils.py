"""Editing-trace loaders for benchmarks.

Analog of the reference's bench-utils crate (crates/bench-utils/src/
lib.rs:27-56 get_automerge_actions): loads the automerge-perf linear
editing trace and converts it into the framework's op/element model.
The extracted columnar element table is cached on disk because the
conversion (running the host engine once to compute Fugue placements,
i.e. the "source replica" role) is a one-time cost.
"""
from __future__ import annotations

import gzip
import json
import os
import zipfile
import zlib
from typing import List, Optional, Tuple

import numpy as np

TRACE_PATH = "/root/reference/crates/loro-internal/benches/automerge-paper.json.gz"
CACHE_PATH = os.path.join(os.path.dirname(__file__), "..", ".bench_cache_automerge.npz")
SYN_CACHE_PATH = os.path.join(
    os.path.dirname(__file__), "..", ".bench_cache_automerge_syn.npz"
)

# Extract-cache schema version.  Bump whenever the SeqExtract layout or
# the chain/run extraction semantics feeding it change: a cache written
# before such a change must be REBUILT, not mis-decoded (loads check the
# tag and fall through to regeneration on mismatch — including caches
# from before the tag existed).
CACHE_SCHEMA = 2

# flips to True when load_automerge_patches had to synthesize a trace
# (no /root/reference checkout and no committed cache in this image);
# bench.py tags its record so synthetic-trace numbers never get
# compared against real-trace rounds
SYNTHETIC_FALLBACK = False


def _load_extract_cache(path: str):
    """SeqExtract + n_ops from an npz cache, or None when the cache is
    absent, carries a stale/missing schema tag, or is unreadable (a
    bench child killed mid-savez leaves a truncated zip — rebuild and
    overwrite instead of crashing every later run)."""
    from .ops.columnar import SeqExtract

    if not os.path.exists(path):
        return None
    try:
        z = np.load(path)
        if "schema" not in z.files or int(z["schema"]) != CACHE_SCHEMA:
            return None
        return SeqExtract(
            parent=z["parent"],
            side=z["side"],
            peer=z["peer"],
            counter=z["counter"],
            deleted=z["deleted"],
            content=z["content"],
            valid=z["valid"],
            peers=[int(p) for p in z["peers"]],
        ), int(z["n_ops"])
    except (OSError, EOFError, KeyError, ValueError, zipfile.BadZipFile,
            zlib.error):
        # stale/foreign/truncated cache file: rebuild instead of crashing
        return None


def _synthetic_patches(limit: Optional[int]) -> List[Tuple[int, int, str]]:
    """Deterministic single-char editing trace with the automerge-perf
    shape (typing runs, ~10% deletes, positions valid at apply time).
    Everything downstream replays patches through the host engine, so
    the whole bench pipeline (variants, extraction, correctness gates)
    works unchanged — only the absolute numbers aren't comparable to
    real-trace rounds."""
    import random

    rng = random.Random(0xA07031)
    n = (limit or 20000)
    patches: List[Tuple[int, int, str]] = []
    length = 0
    pos = 0
    run_left = 0
    while len(patches) < n:
        if run_left == 0:  # new editing burst at a fresh position
            pos = rng.randrange(length + 1)
            run_left = rng.randint(4, 24)
        run_left -= 1
        if length > 8 and rng.random() < 0.1:
            p = min(pos, length - 1)
            patches.append((p, 1, ""))
            length -= 1
            pos = min(p, length)
        else:
            p = min(pos, length)
            patches.append((p, 0, "etaoin shrdlu"[rng.randrange(13)]))
            length += 1
            pos = p + 1
    return patches


def load_automerge_patches(path: str = TRACE_PATH, limit: Optional[int] = None):
    """[(pos, del_len, insert_str)] single-char patches + final content.
    Falls back to a seeded synthetic trace when the reference trace
    file is absent (fresh containers without /root/reference)."""
    if not os.path.exists(path):
        global SYNTHETIC_FALLBACK
        SYNTHETIC_FALLBACK = True
        return _synthetic_patches(limit), ""
    with gzip.open(path) as f:
        data = json.load(f)
    patches: List[Tuple[int, int, str]] = []
    for txn in data["txns"][:limit] if limit else data["txns"]:
        for p in txn["patches"]:
            patches.append((p[0], p[1], p[2]))
    return patches, data.get("endContent", "")


def automerge_seq_extract(limit: Optional[int] = None, use_cache: bool = True):
    """SeqExtract of the full automerge trace (peer 1, linear history).
    Applies the trace through the host engine once to derive each op's
    Fugue (parent, side) placement, then explodes to columns."""
    from .doc import LoroDoc
    from .ops.columnar import SeqExtract, extract_seq_container

    # provenance-matched cache: a stale real-trace cache must not be
    # served when the trace file is gone (the ground-truth text would
    # replay the SYNTHETIC patches and the bench correctness gate
    # would fail mid-run) — synthetic extracts cache under their own
    # name and never shadow the real one
    if limit is not None:
        cache = None
    elif os.path.exists(TRACE_PATH):
        cache = CACHE_PATH
    else:
        cache = SYN_CACHE_PATH
        global SYNTHETIC_FALLBACK
        SYNTHETIC_FALLBACK = True  # even on a cache hit: tag the record
    if use_cache and cache:
        hit = _load_extract_cache(cache)
        if hit is not None:
            return hit

    patches, _ = load_automerge_patches(limit=limit)
    doc = LoroDoc(peer=1)
    t = doc.get_text("text")
    for pos, dels, ins in patches:
        if dels:
            t.delete(pos, dels)
        if ins:
            t.insert(pos, ins)
    doc.commit()
    changes = doc.oplog.changes_in_causal_order()
    ex = extract_seq_container(changes, t.id)
    n_ops = len(patches)
    if use_cache and cache:
        np.savez_compressed(
            cache,
            parent=ex.parent,
            side=ex.side,
            peer=ex.peer,
            counter=ex.counter,
            deleted=ex.deleted,
            content=ex.content,
            valid=ex.valid,
            peers=np.asarray(ex.peers, np.uint64),
            n_ops=n_ops,
            schema=np.int64(CACHE_SCHEMA),
        )
    return ex, n_ops


def automerge_final_text(limit: Optional[int] = None) -> str:
    """Ground-truth final text by direct patch application."""
    patches, end = load_automerge_patches(limit=limit)
    buf: List[str] = []
    s = ""
    for pos, dels, ins in patches:
        s = s[:pos] + ins + s[pos + dels :]
    return s


VARIANT_CACHE_DIR = os.path.join(
    os.path.dirname(__file__), "..", ".bench_cache_variants"
)


def concurrent_trace_variants(
    n_variants: int = 8,
    n_peers: int = 4,
    sync_every: int = 4000,
    limit: Optional[int] = None,
    use_cache: bool = True,
):
    """Genuinely-concurrent multi-peer variants of the automerge trace.

    Each variant routes the patch stream across `n_peers` replicas in
    randomized windows (editing sessions interleave at window
    granularity — this preserves the trace's typing runs while creating
    real concurrency), syncing all replicas every `sync_every` patches
    and fully at the end.  Every variant is a distinct document: the
    concurrency windows, peer ids, and resulting Fugue trees differ per
    variant seed.

    Returns a list of dicts per variant:
      payload: envelope-stripped update bytes (full history, all peers)
      extract: SeqExtract ((peer, counter)-sorted element table)
      text:    the converged document text (host-engine oracle)

    Results cache to disk — generation replays the trace through the
    host engine n_variants times (the one-time "source replica" cost).
    """
    import pickle
    import random

    from .doc import LoroDoc
    from .ops.columnar import SeqExtract, extract_seq_container

    tag = f"v{n_variants}_p{n_peers}_s{sync_every}_l{limit or 'full'}_n2"
    if not os.path.exists(TRACE_PATH):
        tag += "_syn"  # synthetic-trace variants cache separately
    # gzip-pickled so the full-trace cache is small enough to COMMIT:
    # a cold regeneration costs ~26s/variant on a 1-core image, which
    # blew the round-2 driver bench budget before the first device op
    cache = os.path.join(VARIANT_CACHE_DIR, tag + ".pkl.gz") if use_cache else None
    if cache and os.path.exists(cache):
        with gzip.open(cache, "rb") as f:
            return pickle.load(f)
    legacy = cache[: -len(".gz")] if cache else None
    if legacy and os.path.exists(legacy):
        with open(legacy, "rb") as f:
            return pickle.load(f)

    patches, _ = load_automerge_patches(limit=limit)
    out = []
    for v in range(n_variants):
        rng = random.Random(0xBE5C + v)
        docs = [LoroDoc(peer=((v + 1) << 8) + i + 1) for i in range(n_peers)]
        texts = [d.get_text("text") for d in docs]

        def sync_all():
            for d in docs[1:]:
                docs[0].import_(d.export_updates(docs[0].oplog_vv()))
            for d in docs[1:]:
                d.import_(docs[0].export_updates(d.oplog_vv()))

        cur = 0
        window_left = 0
        n_applied = 0  # trace events actually applied (clamped deletes drop)
        for i, (pos, dels, ins) in enumerate(patches):
            if window_left == 0:
                cur = rng.randrange(n_peers)
                window_left = rng.randint(32, 256)
            window_left -= 1
            t = texts[cur]
            L = len(t)
            p = min(pos, L)
            applied = False
            if dels:
                d = min(dels, L - p)
                if d:
                    t.delete(p, d)
                    applied = True
            if ins:
                t.insert(p, ins)
                applied = True
            if applied:  # same unit as the pristine n_ops: patch events
                n_applied += 1
            if (i + 1) % sync_every == 0:
                sync_all()
        sync_all()
        sync_all()  # second round so every replica converges
        ref = docs[0]
        text = texts[0].to_string()
        for d, t in zip(docs[1:], texts[1:]):
            assert t.to_string() == text, "variant replicas failed to converge"
        from .doc import strip_envelope

        payload = strip_envelope(ref.export_updates())
        ex = extract_seq_container(ref.oplog.changes_in_causal_order(), texts[0].id)
        out.append({"payload": payload, "extract": ex, "text": text, "n_ops": n_applied})
        del docs, texts

    if cache:
        os.makedirs(VARIANT_CACHE_DIR, exist_ok=True)
        tmp = cache + ".tmp"
        with gzip.open(tmp, "wb", compresslevel=6) as f:
            pickle.dump(out, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, cache)
    return out


RICHTEXT_KEYS = ["bold", "italic", "color", "link"]


def richtext_bench_docs(
    n_distinct: int = 8,
    n_chars: int = 12288,
    n_marks: int = 768,
    n_peers: int = 3,
    sync_every: int = 1024,
    use_cache: bool = True,
):
    """Concurrent rich-text fleet documents for BASELINE config 4
    (concurrent formatting spans + text edits): each distinct doc is
    built by n_peers replicas interleaving insert/delete/mark/unmark in
    randomized windows with periodic syncs, converged at the end.

    Returns (docs, pad_n, pad_p, pad_c): per distinct doc a dict with
      cols: padded numpy RichtextChainCols (uniform pads across docs)
      keys/values: style dictionaries for segment reconstruction
      oracle: host get_richtext_value() segments (the correctness gate)
      n_ops: chars + deletes + 2*mark-anchors integrated
    """
    import pickle
    import random

    from .doc import LoroDoc
    from .ops.richtext_batch import extract_richtext_chain, pad_richtext_chain_cols

    tag = f"rt{n_distinct}_c{n_chars}_m{n_marks}_p{n_peers}_s{sync_every}_n2"
    cache = os.path.join(VARIANT_CACHE_DIR, tag + ".pkl.gz") if use_cache else None
    if cache and os.path.exists(cache):
        with gzip.open(cache, "rb") as f:
            return pickle.load(f)

    raw = []
    for v in range(n_distinct):
        rng = random.Random(0x51C9 + v)
        docs = [LoroDoc(peer=((v + 1) << 8) + i + 1) for i in range(n_peers)]
        texts = [d.get_text("text") for d in docs]

        def sync_all():
            for d in docs[1:]:
                docs[0].import_(d.export_updates(docs[0].oplog_vv()))
            for d in docs[1:]:
                d.import_(docs[0].export_updates(d.oplog_vv()))

        n_ops = 0
        chars_left, marks_left = n_chars, n_marks
        i = 0
        cur, window_left = 0, 0
        while chars_left > 0 or marks_left > 0:
            if window_left == 0:
                cur = rng.randrange(n_peers)
                window_left = rng.randint(16, 128)
            window_left -= 1
            t = texts[cur]
            L = len(t)
            r = rng.random()
            if marks_left > 0 and L >= 2 and (chars_left == 0 or r < 0.12):
                s = rng.randrange(L - 1)
                e = rng.randint(s + 1, min(L, s + 1 + rng.randint(1, 64)))
                k = rng.choice(RICHTEXT_KEYS)
                if rng.random() < 0.3:
                    t.unmark(s, e, k)
                else:
                    t.mark(s, e, k, rng.choice([True, "red", "blue", 7]))
                marks_left -= 1
                n_ops += 2  # two anchors integrated
            elif L > 8 and r < 0.18:
                p = rng.randrange(L - 1)
                d = min(rng.randint(1, 4), L - p)
                t.delete(p, d)
                n_ops += d
            elif chars_left > 0:
                run = min(rng.randint(1, 8), chars_left)
                t.insert(
                    rng.randint(0, L),
                    "".join(rng.choice("abcdefgh ") for _ in range(run)),
                )
                chars_left -= run
                n_ops += run
            i += 1
            if i % sync_every == 0:
                sync_all()
        sync_all()
        sync_all()
        oracle = texts[0].get_richtext_value()
        for t in texts[1:]:
            assert t.get_richtext_value() == oracle, "richtext replicas diverged"
        ref = docs[0]
        cols, keys, values = extract_richtext_chain(
            ref.oplog.changes_in_causal_order(), texts[0].id
        )
        raw.append((cols, keys, values, oracle, n_ops))

    def pad_to(n: int, q: int) -> int:
        return -(-max(n, 1) // q) * q

    pad_n = pad_to(max(c[0].chain.chain_id.shape[0] for c in raw), 1024)
    pad_c = pad_to(max(c[0].chain.c_parent.shape[0] for c in raw), 256)
    pad_p = pad_to(max(c[0].pair_start.shape[0] for c in raw), 128)
    out = []
    for cols, keys, values, oracle, n_ops in raw:
        padded = pad_richtext_chain_cols(cols, pad_n=pad_n, pad_c=pad_c, pad_p=pad_p)
        out.append(
            {"cols": padded, "keys": keys, "values": values, "oracle": oracle, "n_ops": n_ops}
        )
    result = (out, pad_n, pad_p, pad_c)
    if cache:
        os.makedirs(VARIANT_CACHE_DIR, exist_ok=True)
        tmp = cache + ".tmp"
        with gzip.open(tmp, "wb", compresslevel=6) as f:
            pickle.dump(result, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, cache)
    return result
