"""Editing-trace loaders for benchmarks.

Analog of the reference's bench-utils crate (crates/bench-utils/src/
lib.rs:27-56 get_automerge_actions): loads the automerge-perf linear
editing trace and converts it into the framework's op/element model.
The extracted columnar element table is cached on disk because the
conversion (running the host engine once to compute Fugue placements,
i.e. the "source replica" role) is a one-time cost.
"""
from __future__ import annotations

import gzip
import json
import os
from typing import List, Optional, Tuple

import numpy as np

TRACE_PATH = "/root/reference/crates/loro-internal/benches/automerge-paper.json.gz"
CACHE_PATH = os.path.join(os.path.dirname(__file__), "..", ".bench_cache_automerge.npz")


def load_automerge_patches(path: str = TRACE_PATH, limit: Optional[int] = None):
    """[(pos, del_len, insert_str)] single-char patches + final content."""
    with gzip.open(path) as f:
        data = json.load(f)
    patches: List[Tuple[int, int, str]] = []
    for txn in data["txns"][:limit] if limit else data["txns"]:
        for p in txn["patches"]:
            patches.append((p[0], p[1], p[2]))
    return patches, data.get("endContent", "")


def automerge_seq_extract(limit: Optional[int] = None, use_cache: bool = True):
    """SeqExtract of the full automerge trace (peer 1, linear history).
    Applies the trace through the host engine once to derive each op's
    Fugue (parent, side) placement, then explodes to columns."""
    from .doc import LoroDoc
    from .ops.columnar import SeqExtract, extract_seq_container

    cache = CACHE_PATH if limit is None else None
    if use_cache and cache and os.path.exists(cache):
        z = np.load(cache)
        return SeqExtract(
            parent=z["parent"],
            side=z["side"],
            peer=z["peer"],
            counter=z["counter"],
            deleted=z["deleted"],
            content=z["content"],
            valid=z["valid"],
            peers=[int(p) for p in z["peers"]],
        ), int(z["n_ops"])

    patches, _ = load_automerge_patches(limit=limit)
    doc = LoroDoc(peer=1)
    t = doc.get_text("text")
    for pos, dels, ins in patches:
        if dels:
            t.delete(pos, dels)
        if ins:
            t.insert(pos, ins)
    doc.commit()
    changes = doc.oplog.changes_in_causal_order()
    ex = extract_seq_container(changes, t.id)
    n_ops = len(patches)
    if use_cache and cache:
        np.savez_compressed(
            cache,
            parent=ex.parent,
            side=ex.side,
            peer=ex.peer,
            counter=ex.counter,
            deleted=ex.deleted,
            content=ex.content,
            valid=ex.valid,
            peers=np.asarray(ex.peers, np.uint64),
            n_ops=n_ops,
        )
    return ex, n_ops


def automerge_final_text(limit: Optional[int] = None) -> str:
    """Ground-truth final text by direct patch application."""
    patches, end = load_automerge_patches(limit=limit)
    buf: List[str] = []
    s = ""
    for pos, dels, ins in patches:
        s = s[:pos] + ins + s[pos + dels :]
    return s
