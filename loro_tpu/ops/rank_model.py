"""Gather-count accounting for the ranking kernels (numpy, host-side).

The v5e profile (CLAUDE.md) says ranking gathers are ~all of chain-merge
cost: random row gathers from an O(m) table run at the ~80-100M rows/s
HBM ceiling while sorts/cumsums/scatters are ~free.  Perf work on the
rank path is therefore judged by COUNTS, not wall clock: this module is
the single place that knows how many gather rows each algorithm
schedules, so the bench A/B, the rank.* obs counters and the
count-based perf guards (tests/test_rank_blocked.py) all share one
model.

Three layers:

- ``gather_model(m, algo)``    — analytic worst-case/cap counts from
  the static ring length alone (what the obs counters tick — cheap,
  trace-free).
- ``simulate(succ, algo)``     — numpy re-execution of the algorithm's
  control flow on a REAL ring, counting the rounds the adaptive loops
  actually run (the "measured" side of the bench A/B) and returning
  the distances (a host oracle for the differential tests).
- ``build_ring`` / ``ring_stats`` — the host mirror of _order_core's
  slot-numbered Euler-ring construction + run statistics (n_runs is
  the exact coalesced-ring occupancy, so callers can size the static
  ``ring_budget`` the way DeviceDocBatch sizes c_pad).

Row classes: ``global_rows`` are random gathers addressed into an
O(m)-row table (the HBM-ceiling class); ``local_rows`` are block-local
gathers (VMEM-window rotate loop on TPU, contiguous-block
take_along_axis in XLA); ``small_rows`` are gathers from tables O(m/k)
and below (cache/VMEM-resident).  Reductions quoted anywhere in the
repo mean global_rows unless said otherwise.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

BIG = 2**30


def _log2ceil(x: int) -> int:
    return max(1, int(np.ceil(np.log2(max(int(x), 2)))))


# ---------------------------------------------------------------------------
# analytic model
# ---------------------------------------------------------------------------


def gather_model(
    m: int,
    algo: str,
    k: int = 8,
    block: int = 1024,
    r_pad: Optional[int] = None,
) -> Dict[str, int]:
    """Scheduled gather-row counts for a ring of m tokens (worst case:
    adaptive loops priced at their round CAP; `simulate` gives the
    realized counts).  Keys: rounds (cap of the dominant global loop),
    global_rows, local_rows, small_rows.  Counts are backend-neutral:
    they price the ROW SCHEDULE, which is identical for the XLA and
    pallas formulations (the pallas rotate-loop constant factors are a
    kernel concern, not a schedule one)."""
    lm = _log2ceil(m)
    if algo == "wyllie":
        return {"rounds": lm, "global_rows": lm * m, "local_rows": 0, "small_rows": 0}
    if algo == "ruling":
        # dense table = ceil(m/k) ruler slots + the sink row (exactly
        # what _sim_ruling and the kernels rank)
        mr = -(-m // k) + 1
        return {
            "rounds": lm,
            "global_rows": lm * m + _log2ceil(mr) * mr,
            "local_rows": 0,
            "small_rows": m,  # recombine gather from the dense table
        }
    if algo == "blocked":
        # mirror _blocked_dist exactly: block clamped to the lane-padded
        # ring, then the ring padded to a block multiple — phase B runs
        # over mp tokens, so the ledger must price mp, not m
        b = min(block, max(128, -(-m // 128) * 128))
        mp = -(-m // b) * b
        la = _log2ceil(b)
        sub = gather_model(mp, "ruling", k=k)
        return {
            "rounds": sub["rounds"],
            "global_rows": sub["global_rows"],
            "local_rows": la * mp,
            "small_rows": sub["small_rows"],
        }
    if algo == "coalesced":
        # mirror _coalesced_dist's budget rounding; the ruling sub-rank
        # sees rp+1 tokens (sink slot), and the contraction performs
        # TWO rp-row gathers into O(m) tables (succ[tail_tok] and
        # run_id[succ_tail])
        rp = max(128, -(-(r_pad if r_pad is not None else m) // 128) * 128)
        sub = gather_model(rp + 1, "ruling", k=k)
        return {
            "rounds": sub["rounds"],
            "global_rows": sub["global_rows"] + 2 * rp,
            "local_rows": 0,
            "small_rows": sub["small_rows"],  # expansion is scatter+cumsum
        }
    raise ValueError(f"unknown rank algo {algo!r}")


# ---------------------------------------------------------------------------
# host ring mirror (numpy twin of _order_core's construction)
# ---------------------------------------------------------------------------


def build_ring(
    parent_in: np.ndarray,
    side_in: np.ndarray,
    valid_in: np.ndarray,
    sib_keys: Optional[Tuple[np.ndarray, ...]] = None,
) -> np.ndarray:
    """succ i32[2*(n+1)] — the exact slot-numbered Euler-tour successor
    ring _order_core builds on device (ENTER(e) = sibling-sort slot,
    EXIT(e) = m-1-slot, invalid tokens chained by index).  Kept in
    lockstep with _order_core; tests/test_rank_blocked.py diffs ring
    run counts computed here against the in-jit ring_run_heads."""
    n = parent_in.shape[0]
    n1 = n + 1
    root = n
    parent = np.concatenate([np.where(valid_in, parent_in, BIG), [BIG]]).astype(np.int64)
    parent[:n] = np.where(valid_in & (parent_in < 0), root, parent[:n])
    side = np.concatenate([side_in, [1]]).astype(np.int64)
    valid = np.concatenate([valid_in, [False]])
    key = np.where(parent < BIG, parent * 2 + side, BIG)
    if sib_keys is None:
        order = np.argsort(key, kind="stable")
    else:
        minor = [np.concatenate([k.astype(np.uint32), [0]]) for k in sib_keys]
        order = np.lexsort(tuple(reversed(minor)) + (key,))
    slot = np.empty(n1, np.int64)
    slot[order] = np.arange(n1)
    p_s, s_s = parent[order], side[order]
    prev_same = (p_s == np.roll(p_s, 1)) & (s_s == np.roll(s_s, 1))
    prev_same[0] = False
    is_first = ~prev_same
    nxt_same = (p_s == np.roll(p_s, -1)) & (s_s == np.roll(s_s, -1))
    nxt_same[-1] = False
    is_last = ~nxt_same
    elem_s = order
    next_sib_s = np.where(nxt_same, np.roll(elem_s, -1), -1)
    next_sib = np.zeros(n1, np.int64)
    next_sib[elem_s] = next_sib_s
    is_child = p_s < BIG
    first_l = np.full(n1, -1, np.int64)
    first_r = np.full(n1, -1, np.int64)
    msk = is_first & is_child & (s_s == 0)
    first_l[p_s[msk]] = elem_s[msk]
    msk = is_first & is_child & (s_s == 1)
    first_r[p_s[msk]] = elem_s[msk]
    has_next_sib = next_sib >= 0
    has_l = first_l >= 0
    has_r = first_r >= 0

    m = 2 * n1
    ent = slot
    ext = (m - 1) - slot
    e_ids = np.arange(n1)
    post_l = np.where(has_r, ent[np.clip(first_r, 0, n)], ext[e_ids])
    succ_enter = np.where(has_l, ent[np.clip(first_l, 0, n)], post_l)
    par = np.where(parent < BIG, parent, root).astype(np.int64)
    succ_exit = np.where(
        has_next_sib,
        ent[np.clip(next_sib, 0, n)],
        np.where(side == 0, post_l[par], ext[par]),
    )
    succ_exit[root] = ext[root]
    succ = np.concatenate([succ_enter[order], succ_exit[order][::-1]])
    tok_valid = np.concatenate([valid[order], valid[order][::-1]])
    tok_ids = np.arange(m)
    chain_next = np.minimum(tok_ids + 1, m - 1)
    keep = tok_valid | (tok_ids == ext[root]) | (tok_ids == ent[root])
    succ = np.where(keep, succ, chain_next)
    succ[ent[root]] = succ_enter[root]
    succ[ext[root]] = ext[root]
    return succ.astype(np.int32)


def run_heads(succ: np.ndarray) -> np.ndarray:
    """bool[m] — host twin of fugue_batch.ring_run_heads."""
    m = succ.shape[0]
    tok = np.arange(m)
    indeg = np.bincount(succ, minlength=m)
    is_term = succ == tok
    absorbed = np.zeros(m, bool)
    absorbed[1:] = (succ[:-1] == tok[1:]) & (indeg[1:] == 1) & ~is_term[1:]
    return ~absorbed


def ring_stats(succ: np.ndarray) -> Dict[str, float]:
    m = int(succ.shape[0])
    n_runs = int(run_heads(succ).sum())
    return {"ring_tokens": m, "n_runs": n_runs, "mean_run": m / max(n_runs, 1)}


def coalesce_budget(n_runs_max: int, slack: int = 128) -> int:
    """Static ring_budget from a measured max run count: one slack
    quantum on top, rounded to lanes (the shape the pallas sub-rank
    pads to anyway)."""
    return -(-(n_runs_max + slack) // 128) * 128


# ---------------------------------------------------------------------------
# simulators (realized rounds/rows on a concrete ring + oracle dists)
# ---------------------------------------------------------------------------


def _sim_wyllie(d: np.ndarray, t: np.ndarray) -> Tuple[np.ndarray, int]:
    rounds = _log2ceil(len(t))
    for _ in range(rounds):
        d = d + d[t]
        t = t[t]
    return d, rounds


def _sim_ruling(
    d: np.ndarray, t: np.ndarray, k: int = 8
) -> Tuple[np.ndarray, Dict[str, int]]:
    """Numpy re-execution of _ruling_dist_from: adaptive phase-1 with
    the exactness cap, dense ruler ring, recombine."""
    m = len(t)
    tok = np.arange(m)
    is_term = t == tok
    is_stop = ((tok % k) == 0) | is_term
    d1, t1 = d.copy(), t.copy()
    frozen = is_term | is_stop[t1]
    cap = _log2ceil(m)
    r1 = 0
    while not frozen.all() and r1 < cap:
        nd = np.where(frozen, d1, d1 + d1[t1])
        nt = np.where(frozen, t1, t1[t1])
        d1, t1 = nd, nt
        frozen = is_term | is_stop[t1]
        r1 += 1
    mr = (m + k - 1) // k
    r_tok = np.arange(mr) * k

    def dense(tt):
        return np.where(is_term[tt], mr, tt // k)

    rD = np.append(d1[r_tok], 0)
    rT = np.append(dense(t1[r_tok]), mr)
    rD, dense_rounds = _sim_wyllie(rD, rT)
    dist = d1 + rD[dense(t1)]
    counts = {
        "rounds": r1,
        "global_rows": r1 * m + dense_rounds * (mr + 1),
        "local_rows": 0,
        "small_rows": m,
    }
    return dist, counts


def _sim_blocked(
    succ: np.ndarray, block: int = 1024, k: int = 8
) -> Tuple[np.ndarray, Dict[str, int]]:
    m = len(succ)
    # mirror _blocked_dist: clamp the block to the lane-padded ring,
    # pad to a block multiple (self-loop pads), phase B over mp
    b = min(block, max(128, -(-m // 128) * 128))
    mp = -(-m // b) * b
    succ = np.concatenate([succ.astype(np.int64), np.arange(m, mp)])
    tok = np.arange(mp)
    d = np.where(succ == tok, 0, 1)
    t = succ.copy()
    la = _log2ceil(b)
    for _ in range(la):
        active = (t // b == tok // b) & (t != tok)
        d = np.where(active, d + d[t], d)
        t = np.where(active, t[t], t)
    dist, counts = _sim_ruling(d, t, k=k)
    counts["local_rows"] = la * mp
    return dist[:m], counts


def _sim_coalesced(
    succ: np.ndarray, r_pad: Optional[int] = None, k: int = 8
) -> Tuple[np.ndarray, Dict[str, int]]:
    m = len(succ)
    tok = np.arange(m)
    heads = run_heads(succ)
    n_runs = int(heads.sum())
    r = r_pad if r_pad is not None else m
    if n_runs > r:
        raise ValueError(f"ring_budget {r} < n_runs {n_runs}")
    head_tok = np.flatnonzero(heads)
    run_id = np.cumsum(heads) - 1
    tail_tok = np.append(head_tok[1:], m) - 1
    succ_tail = succ[tail_tok]
    is_term_run = succ_tail == tail_tok
    w = (tail_tok - head_tok) + np.where(is_term_run, 0, 1)
    t = np.where(is_term_run, n_runs, run_id[succ_tail])
    # sink node + budget pads (self-loops), mirroring _coalesced_dist
    rp = max(128, -(-r // 128) * 128)
    w1 = np.zeros(rp + 1, np.int64)
    t1 = np.arange(rp + 1)
    w1[:n_runs] = w
    t1[:n_runs] = np.where(t == n_runs, rp, t)  # terminals -> sink slot rp
    dist_c, counts = _sim_ruling(w1, t1, k=k)
    dist = dist_c[run_id] - (tok - head_tok[run_id])
    # the succ[tail_tok] + run_id[succ_tail] contraction gathers (two
    # rp-row random gathers into O(m) tables)
    counts["global_rows"] += 2 * rp
    counts["n_runs"] = n_runs
    return dist, counts


def simulate(
    succ: np.ndarray,
    algo: str,
    k: int = 8,
    block: int = 1024,
    r_pad: Optional[int] = None,
) -> Tuple[np.ndarray, Dict[str, int]]:
    """(dist, counts) — realized gather-row counts of `algo` on a real
    ring plus the distances themselves (host oracle: every algorithm
    must produce bit-identical distances)."""
    m = len(succ)
    tok = np.arange(m)
    if algo == "wyllie":
        d, rounds = _sim_wyllie(np.where(succ == tok, 0, 1), succ.copy())
        return d, {
            "rounds": rounds,
            "global_rows": rounds * m,
            "local_rows": 0,
            "small_rows": 0,
        }
    if algo == "ruling":
        return _sim_ruling(np.where(succ == tok, 0, 1), succ.copy(), k=k)
    if algo == "blocked":
        return _sim_blocked(succ, block=block, k=k)
    if algo == "coalesced":
        return _sim_coalesced(succ, r_pad=r_pad, k=k)
    raise ValueError(f"unknown rank algo {algo!r}")
