"""Batched MovableList merge kernel.

reference semantics: MovableListDiffCalculator (diff_calc.rs:1669-2020)
— position slots live in the shared Fugue sequence; per element the
winning slot (last move, max (lamport, peer)) and winning value (last
set) are LWW selections.  Device formulation: the shared Fugue order
kernel ranks *slots*; two scatter-max passes pick winners; an element is
visible iff its winning slot is not tombstoned (a newer concurrent move
revives it — matching models/movable_list_state.py).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .fugue_batch import SeqColumns, fugue_order, rank_bound

NEG = jnp.int32(-(2**31) + 1)


class MovableCols(NamedTuple):
    """[S] slot rows + [K] set rows for one doc (padded).

    Slots (sequence elements): seq (SeqColumns over slots; `content` is
    the slot's element index), lamport i32[S].
    Sets: set_elem i32[K] element index, set_lamport, set_peer,
    set_value i32[K] value-dictionary index, set_valid bool[K].
    n_elems is carried statically by the caller.
    """

    seq: SeqColumns
    lamport: jax.Array
    set_elem: jax.Array
    set_lamport: jax.Array
    set_peer: jax.Array
    set_value: jax.Array
    set_valid: jax.Array


def movable_merge_doc(cols: MovableCols, n_elems: int) -> Tuple[jax.Array, jax.Array]:
    """Returns (ordered value indexes i32[S] padded with -1, count).

    CONTRACT: every element index in cols (seq.content, set_elem) must
    be < n_elems — larger indexes are silently clamped into the dump
    slot by XLA scatter semantics.  Callers must size/assert n_elems
    host-side (see extract_movable's elems list)."""
    seq = cols.seq
    s = seq.parent.shape[0]
    elem = jnp.where(seq.valid, seq.content, n_elems)  # pads -> dump elem

    # winning slot per element: max (lamport, peer); tie-break by peer is
    # safe because slot ids are unique per (lamport, peer)
    lam = jnp.where(seq.valid, cols.lamport, NEG)
    win_lam = jnp.full(n_elems + 1, NEG, jnp.int32).at[elem].max(lam)
    at_lam = seq.valid & (cols.lamport == win_lam[elem])
    peer = jnp.where(at_lam, seq.peer, NEG)
    win_peer = jnp.full(n_elems + 1, NEG, jnp.int32).at[elem].max(peer)
    is_win_slot = at_lam & (seq.peer == win_peer[elem])
    # among winner candidates with equal (lamport, peer) (same-run slots
    # impossible: one move per counter) — unique winner
    win_deleted = jnp.full(n_elems + 1, 0, jnp.int32).at[
        jnp.where(is_win_slot, elem, n_elems)
    ].max(jnp.where(seq.deleted, 1, 0))

    # winning value per element (creation values ship as set rows too)
    sv_lam = jnp.where(cols.set_valid, cols.set_lamport, NEG)
    se = jnp.where(cols.set_valid, cols.set_elem, n_elems)
    v_lam = jnp.full(n_elems + 1, NEG, jnp.int32).at[se].max(sv_lam)
    at_v = cols.set_valid & (cols.set_lamport == v_lam[se])
    v_peer = jnp.full(n_elems + 1, NEG, jnp.int32).at[
        jnp.where(at_v, se, n_elems)
    ].max(jnp.where(at_v, cols.set_peer, NEG))
    is_win_set = at_v & (cols.set_peer == v_peer[se])
    win_value = jnp.full(n_elems + 1, -1, jnp.int32).at[
        jnp.where(is_win_set, se, n_elems)
    ].max(jnp.where(is_win_set, cols.set_value, -1))

    # visible slots: the element's winning slot, not tombstoned
    visible = is_win_slot & ~seq.deleted & (win_deleted[elem] == 0)
    rank = fugue_order(seq)
    m = rank_bound(s)
    rk = jnp.clip(rank, 0, m - 1)
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(visible, rk, m - 1)].add(
        visible.astype(jnp.int32)
    )
    pos_of_rank = jnp.cumsum(hist) - hist
    pos = pos_of_rank[rk]
    count = visible.sum().astype(jnp.int32)
    out = jnp.full(s, -1, jnp.int32).at[jnp.where(visible, pos, s)].set(
        win_value[jnp.clip(elem, 0, n_elems)], mode="drop"
    )
    return out, count


@functools.partial(jax.jit, static_argnums=(1,))
def movable_merge_batch(cols: MovableCols, n_elems: int):
    return jax.vmap(lambda c: movable_merge_doc(c, n_elems))(cols)


def extract_movable(changes, cid):
    """Host: explode a MovableList container's ops into MovableCols
    (numpy) + (elems list, values list).  Rows follow the
    (peer, counter) ordering contract of fugue_order."""
    from ..core.change import MovableMove, MovableSet, SeqDelete, SeqInsert
    from ..oplog.oplog import _RunCont

    peers_seen = sorted({ch.peer for ch in changes})
    peer_rank = {p: i for i, p in enumerate(peers_seen)}
    slots = []  # (parent_idx, side, peer_rank, counter, lamport, elem_idx)
    id2slot = {}
    elems = []  # elem ids
    elem_idx = {}
    values = []
    sets = []  # (elem_idx, lamport, peer_rank, value_idx)
    deletes = []

    def eidx(eid):
        if eid not in elem_idx:
            elem_idx[eid] = len(elems)
            elems.append(eid)
        return elem_idx[eid]

    for ch in changes:
        for op in ch.ops:
            if op.container != cid:
                continue
            c = op.content
            lam = ch.lamport + (op.counter - ch.ctr_start)
            if isinstance(c, SeqInsert):
                body = c.content
                for j in range(len(body)):
                    if j == 0:
                        if isinstance(c.parent, _RunCont):
                            pidx = id2slot[(ch.peer, op.counter - 1)]
                        elif c.parent is None:
                            pidx = -1
                        else:
                            pidx = id2slot[(c.parent.peer, c.parent.counter)]
                        side = int(c.side)
                    else:
                        pidx = len(slots) - 1
                        side = 1
                    eid = (ch.peer, op.counter + j)
                    ei = eidx(eid)
                    id2slot[eid] = len(slots)
                    slots.append((pidx, side, peer_rank[ch.peer], op.counter + j, lam + j, ei))
                    vi = len(values)
                    values.append(body[j])
                    sets.append((ei, lam + j, peer_rank[ch.peer], vi))
            elif isinstance(c, MovableMove):
                if isinstance(c.parent, _RunCont):
                    pidx = id2slot[(ch.peer, op.counter - 1)]
                elif c.parent is None:
                    pidx = -1
                else:
                    pidx = id2slot[(c.parent.peer, c.parent.counter)]
                ei = eidx((c.elem.peer, c.elem.counter))
                id2slot[(ch.peer, op.counter)] = len(slots)
                slots.append((pidx, int(c.side), peer_rank[ch.peer], op.counter, lam, ei))
            elif isinstance(c, MovableSet):
                ei = eidx((c.elem.peer, c.elem.counter))
                vi = len(values)
                values.append(c.value)
                sets.append((ei, lam, peer_rank[ch.peer], vi))
            elif isinstance(c, SeqDelete):
                for sp in c.spans:
                    deletes.append((sp.peer, sp.start, sp.end))

    n = len(slots)
    arr = np.asarray(slots, np.int64).reshape(n, 6) if n else np.zeros((0, 6), np.int64)
    deleted = np.zeros(n, bool)
    for peer, start, end in deletes:
        for ctr in range(start, end):
            i = id2slot.get((peer, ctr))
            if i is not None:
                deleted[i] = True
    from .columnar import peer_counter_perm

    perm, _inv, parent = peer_counter_perm(arr[:, 2], arr[:, 3], arr[:, 0])
    k = len(sets)
    sarr = np.asarray(sets, np.int64).reshape(k, 4) if k else np.zeros((0, 4), np.int64)
    seq = SeqColumns(
        parent=parent.astype(np.int32),
        side=arr[perm, 1].astype(np.int32),
        peer=arr[perm, 2].astype(np.int32),
        counter=arr[perm, 3].astype(np.int32),
        deleted=deleted[perm],
        content=arr[perm, 5].astype(np.int32),  # element index
        valid=np.ones(n, bool),
    )
    cols = MovableCols(
        seq=seq,
        lamport=arr[perm, 4].astype(np.int32),
        set_elem=sarr[:, 0].astype(np.int32),
        set_lamport=sarr[:, 1].astype(np.int32),
        set_peer=sarr[:, 2].astype(np.int32),
        set_value=sarr[:, 3].astype(np.int32),
        set_valid=np.ones(k, bool),
    )
    return cols, elems, values


@jax.jit
def movable_by_key_batch(valid, deleted, key_hi, key_lo, win_row, win_lam, val_idx):
    """RESIDENT materialization (DeviceMovableBatch): element-level
    output from standing state — per element, the move-winner's slot
    row (LWW fold) carries the element's standing ShadowOrder key and
    tombstone; ONE [E]-sized sort realizes the list (E elements, not S
    slots).  Returns (value ordinals i32[D, E] padded -1, counts).

    valid/deleted/key_hi/key_lo: [D, S] slot-buffer columns;
    win_row/win_lam: [D, E] move-winner fold (row index, lamport;
    win_lam == NEG means the element was never placed);
    val_idx: [D, E] value-winner fold (value ordinals)."""

    def per_doc(v, dl, kh, kl, wrow, wlam, vidx):
        s = v.shape[0]
        e_cap = wrow.shape[0]
        row = jnp.clip(wrow, 0, s - 1)
        alive = (wlam > NEG) & v[row] & ~dl[row]
        ekh = jnp.where(alive, kh[row], jnp.uint32(0xFFFFFFFF))
        ekl = jnp.where(alive, kl[row], jnp.uint32(0xFFFFFFFF))
        alive_i = alive.astype(jnp.int32)
        _, _, vis_s, vid_s = jax.lax.sort((ekh, ekl, alive_i, vidx), num_keys=2)
        pos = jnp.cumsum(vis_s) - vis_s
        out = jnp.full(e_cap, -1, jnp.int32).at[
            jnp.where(vis_s == 1, pos, e_cap)
        ].set(vid_s, mode="drop")
        return out, alive_i.sum()

    return jax.vmap(per_doc)(valid, deleted, key_hi, key_lo, win_row, win_lam, val_idx)


class LazyPayloadValue:
    """Undecoded value: payload bytes + offset (decoded only if it wins
    the set-LWW — mirrors the map batch's lazy cells)."""

    __slots__ = ("payload", "offset", "cids")

    def __init__(self, payload: bytes, offset: int, cids):
        self.payload = payload
        self.offset = offset
        self.cids = cids

    def get(self):
        from ..native import decode_value_at

        return decode_value_at(self.payload, self.offset, self.cids)


def extract_movable_from_payload(payload: bytes, cid):
    """Native fast path: binary updates payload -> (MovableCols, elems,
    values) with lazy value cells (same contract as extract_movable).
    Returns None when the native library is unavailable; raises
    ValueError on malformed payloads / out-of-payload references
    (caller falls back to Python)."""
    from ..codec.binary import read_tables
    from ..native import available, explode_movable_payload

    if not available():
        return None
    peers_wire, _keys, cids, _r = read_tables(payload)
    try:
        target = cids.index(cid)
    except ValueError:
        target = -1
    if target < 0:
        return extract_movable([], cid)
    out = explode_movable_payload(payload, target)
    sl, st, dl = out["slots"], out["sets"], out["dels"]
    n = len(sl["parent"])
    from .columnar import pack_wire_ids, wire_peer_ranks

    rank_of = wire_peer_ranks(peers_wire)

    # vectorized element dictionary over slot + set references: pack
    # (wire peer idx, ctr) into i64 and unique+inverse
    k = len(st["elem_peer_idx"])
    se_packed = pack_wire_ids(sl["elem_peer_idx"], sl["elem_ctr"])
    st_packed = pack_wire_ids(st["elem_peer_idx"], st["elem_ctr"])
    uniq, inv = np.unique(np.concatenate([se_packed, st_packed]), return_inverse=True)
    elems = [
        (int(peers_wire[int(q) >> 32]), int(q) & 0xFFFFFFFF) for q in uniq
    ]
    slot_elem = inv[:n].astype(np.int32)
    set_elem = inv[n:].astype(np.int32)

    # tombstones: resolve delete spans through the packed slot id map
    # (spans referencing slots outside the payload drop, matching the
    # Python fallback's id2slot.get semantics)
    deleted = np.zeros(n, bool)
    if n:
        slot_packed = pack_wire_ids(sl["peer_idx"], sl["counter"])
        slot_order = np.argsort(slot_packed, kind="stable")
        slot_sorted = slot_packed[slot_order]
        for j in range(len(dl["peer_idx"])):
            dp = np.int64(int(dl["peer_idx"][j])) << 32
            span = np.arange(int(dl["start"][j]), int(dl["end"][j]), dtype=np.int64) | dp
            pos = np.searchsorted(slot_sorted, span)
            pos = np.clip(pos, 0, n - 1)
            hit = slot_sorted[pos] == span
            deleted[slot_order[pos[hit]]] = True

    from .columnar import peer_counter_perm

    slot_rank = rank_of[sl["peer_idx"]].astype(np.int64) if n else np.zeros(0, np.int64)
    perm, _inv, parent = peer_counter_perm(slot_rank, sl["counter"], sl["parent"])
    from .fugue_batch import SeqColumns

    seq = SeqColumns(
        parent=parent.astype(np.int32),
        side=sl["side"][perm].astype(np.int32),
        peer=slot_rank[perm].astype(np.int32),
        counter=sl["counter"][perm].astype(np.int32),
        deleted=deleted[perm],
        content=slot_elem[perm].astype(np.int32),
        valid=np.ones(n, bool),
    )
    values = [LazyPayloadValue(payload, int(off), cids) for off in st["value_off"]]
    cols = MovableCols(
        seq=seq,
        lamport=sl["lamport"][perm].astype(np.int32),
        set_elem=set_elem,
        set_lamport=st["lamport"].astype(np.int32),
        set_peer=rank_of[st["peer_idx"]].astype(np.int32) if k else np.zeros(0, np.int32),
        set_valid=np.ones(k, bool),
        set_value=np.arange(k, dtype=np.int32),
    )
    return cols, elems, values
