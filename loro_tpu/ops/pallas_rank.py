"""Pallas TPU kernel for Wyllie list-ranking — the gather-bound heart
of the Fugue order solve.

The XLA formulation (ops/fugue_batch._order_core) round-trips the succ/
dist arrays through HBM on every pointer-doubling step; profiling on a
v5e showed that loop dominating merge time (random-access gathers at
~100M elem/s).  A chain-contracted ring (typically <=48k tokens =
<=200KB) fits in VMEM (~16MB/core), so this kernel keeps both arrays
on-chip for all ceil(log2(m)) rounds and only touches HBM twice.

Status: semantics validated in interpreter mode (tests); real-TPU
lowering of the in-kernel dynamic gather (jnp.take along lanes) is
gated behind use_pallas_rank()/PALLAS_RANK=1 until profiled on
hardware — the XLA path remains the default.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is part of jax, but keep the import soft for safety
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def use_pallas_rank() -> bool:
    return HAVE_PALLAS and os.environ.get("PALLAS_RANK", "") not in ("", "0")


def _rank_kernel(succ_ref, dist_ref, n_steps: int):
    m = succ_ref.shape[-1]
    succ = succ_ref[0, :]
    idx = jax.lax.broadcasted_iota(jnp.int32, (m,), 0)
    dist = jnp.where(succ == idx, jnp.int32(0), jnp.int32(1))

    def body(_, carry):
        d, s = carry
        d = d + jnp.take(d, s, axis=0)
        s = jnp.take(s, s, axis=0)
        return d, s

    dist, _ = jax.lax.fori_loop(0, n_steps, body, (dist, succ))
    dist_ref[0, :] = dist


def wyllie_rank(succ: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    """dist-to-terminal for a successor ring (terminal = self-loop).
    succ: i32[m]; returns i32[m].  `interpret=None` auto-selects the
    interpreter off-TPU (CI / CPU mesh runs)."""
    m = succ.shape[0]
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fn = pl.pallas_call(
        functools.partial(_rank_kernel, n_steps=n_steps),
        out_shape=jax.ShapeDtypeStruct((1, m), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return fn(succ.reshape(1, m))[0]


def wyllie_rank_xla(succ: jax.Array) -> jax.Array:
    """Reference XLA implementation of plain two-gather Wyllie ranking.
    NOTE: production (_order_core) now fuses (dist, succ) into one
    [m, 2] row so each round is a single gather (measured 2.3x on v5e);
    this reference keeps the textbook formulation — both compute the
    same distances, which is what the differential tests assert."""
    m = succ.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    dist = jnp.where(succ == idx, 0, 1).astype(jnp.int32)
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))

    def body(_, carry):
        d, s = carry
        return d + d[s], s[s]

    dist, _ = jax.lax.fori_loop(0, n_steps, body, (dist, succ))
    return dist
