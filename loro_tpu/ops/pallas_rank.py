"""Pallas TPU kernel for Wyllie list-ranking — the gather-bound heart
of the Fugue order solve.

The XLA formulation (ops/fugue_batch._order_core) round-trips the succ/
dist arrays through HBM on every pointer-doubling step; profiling on a
v5e showed that loop dominating merge time (random-access gathers at
~100M elem/s).  A chain-contracted ring (typically <=48k tokens =
<=200KB) fits in VMEM (~16MB/core), so this kernel keeps both arrays
on-chip for all ceil(log2(m)) rounds and only touches HBM twice.

Status: validated AND profiled on a real v5e (2026-07-29).  The
deployed Mosaic toolchain only lowers dynamic_gather along lanes
(axis=1, <=128 lanes; axis-0 gathers past one 8-sublane vreg fail
remote compile), so the arbitrary gather is decomposed as an R-step
row-rotate loop (see _vmem_gather).  Measured on the flagship ring
shape (m=32896), amortized over distinct rings in one jit:
  single ring: 5.0 ms vs 11.1 ms XLA textbook loop
  vmap8 chunk: 15.2 ms vs 128.2 ms XLA  (8.4x on the bench shape;
    grid programs pipeline, so per-ring cost drops to 1.9 ms)
Default: ON when the backend is TPU and the ring fits
PALLAS_RANK_MAX_M; force with PALLAS_RANK=1, disable with
PALLAS_RANK=0.  Off-TPU the XLA path remains the default (the
interpreter-mode kernel is for differential tests).

PALLAS_RANK_ALGO selects ruling (default) | wyllie for rings <= 65536.
The ruling-set kernel (phase-1 adaptive freeze at index%8 rulers with
terminal-absorption detection, dense m/8 ruler ring + sink row,
small-table recombine) measured 12.6 ms vs 15.6 ms wyllie on the vmap8
bench chunk once the phase-1 early exit also recognised non-ruler
terminals; flagship bench 70.2M -> 79.3M ops/s.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is part of jax, but keep the import soft for safety
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover — tpulint: disable=LT-EXC(soft import probe: any pallas breakage means "no pallas", not a crash)
    HAVE_PALLAS = False


PALLAS_RANK_ALGOS = ("wyllie", "ruling", "blocked")


def _pallas_rank_algo() -> str:
    """Kernel algorithm (PALLAS_RANK_ALGO): ruling (default) | wyllie |
    blocked.  Validated at first use with a typed ConfigError — never a
    silent fall-back."""
    from ..errors import ConfigError

    algo = os.environ.get("PALLAS_RANK_ALGO", "ruling")
    if algo not in PALLAS_RANK_ALGOS:
        raise ConfigError("PALLAS_RANK_ALGO", algo, "|".join(PALLAS_RANK_ALGOS))
    return algo


def use_pallas_rank() -> bool:
    """PALLAS_RANK=1 forces on, =0 forces off; unset = auto (on iff the
    backend is TPU — measured 8.4x over the XLA rank on v5e)."""
    if not HAVE_PALLAS:
        return False
    flag = os.environ.get("PALLAS_RANK", "")
    if flag == "0":
        return False
    if flag:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # tpulint: disable=LT-EXC(backend init failure means stay on the XLA path, whatever the backend threw)
        return False


# Above this ring length the R-step rotate loop (R = m/128 iterations
# per doubling round) loses to the HBM gather formulation; callers fall
# back to the XLA path.
PALLAS_RANK_MAX_M = 1 << 17


def pallas_rank_applicable(m: int) -> bool:
    return use_pallas_rank() and m <= PALLAS_RANK_MAX_M


_LANES = 128


def _vmem_gather(tbl, rows, cols):
    """Full dynamic gather out[i,j] = tbl[rows[i,j], cols[i,j]] from the
    one dynamic_gather form the deployed Mosaic accepts: within-row lane
    gather (take_along_axis axis=1, <=128 lanes, any sublane count;
    axis-0 gathers beyond one 8-sublane vreg fail to compile on this
    libtpu).  Arbitrary (row, lane) addressing is decomposed as an
    R-step row-rotate loop: after t rolls, rot[i, :] = tbl[(i+t) % R, :],
    so a lane-gather with `cols` yields tbl[(i+t) % R, cols[i,j]], kept
    wherever rows[i,j] == (i+t) % R.  All operands stay in
    VMEM/registers; per-iteration work is ~5 VPU ops on a [R, 128]
    tile, so the whole loop is ~1 ms — vs an HBM round-trip per
    doubling round in the XLA formulation."""
    shape = tbl.shape
    n_rows = shape[0]
    iota0 = jax.lax.broadcasted_iota(jnp.int32, shape, 0)

    def body(t, carry):
        acc, rot = carry
        g = jnp.take_along_axis(rot, cols, axis=1, mode="promise_in_bounds")
        src = iota0 + t
        src = jnp.where(src >= n_rows, src - n_rows, src)
        acc = jnp.where(rows == src, g, acc)
        return acc, pltpu.roll(rot, n_rows - 1, axis=0)

    acc = jnp.zeros(shape, tbl.dtype)
    acc, _ = jax.lax.fori_loop(0, n_rows, body, (acc, tbl))
    return acc


def _vmem_gather_near(tbl, rows, cols, radius: int):
    """Windowed variant of _vmem_gather: only resolves addresses whose
    target row lies within `radius` rows of the output row (others keep
    the zero fill — callers mask them off).  The rotate loop then runs
    min(2*radius+1, R) iterations instead of R: this is what makes the
    blocked kernel's phase-A gathers block-local (a b-token block is
    b/128 consecutive rows, so radius = b/128 - 1 covers every in-block
    target).  Out-of-window rows that happen to alias through the
    modular rotation are still gathered CORRECTLY (the hit test matches
    the true source row), just not guaranteed."""
    shape = tbl.shape
    n_rows = shape[0]
    span = min(2 * radius + 1, n_rows)
    iota0 = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    rot0 = pltpu.roll(tbl, radius % n_rows, axis=0) if radius % n_rows else tbl

    def body(t, carry):
        acc, rot = carry
        g = jnp.take_along_axis(rot, cols, axis=1, mode="promise_in_bounds")
        src = iota0 + (t - radius)
        src = jnp.where(src < 0, src + n_rows, src)
        src = jnp.where(src >= n_rows, src - n_rows, src)
        acc = jnp.where(rows == src, g, acc)
        return acc, pltpu.roll(rot, n_rows - 1, axis=0)

    acc = jnp.zeros(shape, tbl.dtype)
    acc, _ = jax.lax.fori_loop(0, span, body, (acc, rot0))
    return acc


def _vmem_gather2(tbl_a, tbl_b, rows, cols):
    """Gather TWO same-shape tables at the same (rows, cols) addresses in
    one rotate loop (shared hit masks; used when (dist, succ) cannot
    pack into one u32)."""
    shape = tbl_a.shape
    n_rows = shape[0]
    iota0 = jax.lax.broadcasted_iota(jnp.int32, shape, 0)

    def body(t, carry):
        acc_a, acc_b, rot_a, rot_b = carry
        ga = jnp.take_along_axis(rot_a, cols, axis=1, mode="promise_in_bounds")
        gb = jnp.take_along_axis(rot_b, cols, axis=1, mode="promise_in_bounds")
        src = iota0 + t
        src = jnp.where(src >= n_rows, src - n_rows, src)
        hit = rows == src
        return (
            jnp.where(hit, ga, acc_a),
            jnp.where(hit, gb, acc_b),
            pltpu.roll(rot_a, n_rows - 1, axis=0),
            pltpu.roll(rot_b, n_rows - 1, axis=0),
        )

    acc_a = jnp.zeros(shape, tbl_a.dtype)
    acc_b = jnp.zeros(shape, tbl_b.dtype)
    acc_a, acc_b, _, _ = jax.lax.fori_loop(
        0, n_rows, body, (acc_a, acc_b, tbl_a, tbl_b)
    )
    return acc_a, acc_b


def _rank_kernel_wide(succ_ref, w_ref, dist_ref, n_steps: int):
    """Dual-table variant for rings longer than 65536 tokens (dist no
    longer fits 16 bits): carry (dist i32, succ i32) separately and
    gather both per round with shared address masks."""
    rows, cols = succ_ref.shape
    succ = succ_ref[:, :]
    dist = w_ref[:, :].astype(jnp.int32)

    def round_body(_, carry):
        d, s = carry
        gd, gs = _vmem_gather2(
            d, s, jnp.right_shift(s, 7), jnp.bitwise_and(s, 0x7F)
        )
        return d + gd, gs

    dist, _ = jax.lax.fori_loop(0, n_steps, round_body, (dist, succ))
    dist_ref[:, :] = dist


def _vmem_gather_from(tbl, rows, cols, out_shape_like):
    """Gather from a (possibly differently-sized) VMEM table:
    out[i,j] = tbl[rows[i,j], cols[i,j]].  Loops over the TABLE's rows
    (broadcast one row per iteration), so gathering m outputs from a
    small Rt-row table costs Rt iterations — the cheap recombine path
    of the ruling-set kernel."""
    n_rows = tbl.shape[0]

    def body(t, carry):
        acc, rot = carry
        brow = rot[0:1, :]  # static slice; roll brings row t here at step t
        g = jnp.take_along_axis(
            jnp.broadcast_to(brow, out_shape_like.shape), cols, axis=1,
            mode="promise_in_bounds",
        )
        acc = jnp.where(rows == t, g, acc)
        return acc, pltpu.roll(rot, n_rows - 1, axis=0)

    acc = jnp.zeros(out_shape_like.shape, tbl.dtype)
    acc, _ = jax.lax.fori_loop(0, n_rows, body, (acc, tbl))
    return acc


def _rank_kernel_ruling(succ_ref, w_ref, dist_ref, n_steps: int, k: int = 8):
    """Ruling-set variant of the packed kernel (see _rank_kernel for the
    u32 (dist, succ) packing).  Init from the caller's weights, then the
    shared ruling phases."""
    succ = succ_ref[:, :]
    packed = jnp.bitwise_or(
        jnp.left_shift(w_ref[:, :].astype(jnp.uint32), 16), succ.astype(jnp.uint32)
    )
    dist_ref[:, :] = _ruling_from_packed(packed, n_steps, k)


def _rank_kernel_blocked(
    succ_ref, w_ref, dist_ref, n_steps: int, k: int = 8, block: int = 1024
):
    """Blocked two-level variant (PALLAS_RANK_ALGO=blocked): phase A
    collapses in-block pointer chains with WINDOWED rotate gathers
    (radius = block/128 - 1 rows, so each of the ceil(log2(block))
    rounds costs ~2·block/128 rotate iterations instead of m/128 —
    the dense-VMEM-inside-blocks half of the two-level plan), then the
    shared ruling phases rank the weighted block-exit graph (short
    inter-block work; the adaptive phase-1 freeze converges in few
    rounds when blocks actually collapse chains, and its cap keeps the
    worst case exact)."""
    rows, cols = succ_ref.shape
    m = rows * cols
    succ = succ_ref[:, :]
    packed = jnp.bitwise_or(
        jnp.left_shift(w_ref[:, :].astype(jnp.uint32), 16), succ.astype(jnp.uint32)
    )
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * _LANES
        + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    )
    shift = int(np.log2(block))
    radius = min(block // _LANES - 1, rows - 1)
    n_a = max(1, int(np.ceil(np.log2(min(block, m)))))

    def phase_a(_, p):
        s = jnp.bitwise_and(p, jnp.uint32(0xFFFF)).astype(jnp.int32)
        in_blk = jnp.right_shift(s, shift) == jnp.right_shift(flat_idx, shift)
        g = _vmem_gather_near(
            p, jnp.right_shift(s, 7), jnp.bitwise_and(s, 0x7F), radius
        )
        p2 = jnp.bitwise_and(p, jnp.uint32(0xFFFF0000)) + g
        return jnp.where(in_blk, p2, p)

    packed = jax.lax.fori_loop(0, n_a, phase_a, packed)
    dist_ref[:, :] = _ruling_from_packed(packed, n_steps, k)


def _ruling_from_packed(packed, n_steps: int, k: int = 8):
    """The ruling-set phases over a generic packed (dist:16 | succ:16)
    pointer state — dist(i) = d_i + dist(t_i), terminals are (0, self)
    self-loops.  Shared by the ruling kernel (unit/caller weights) and
    the blocked kernel (phase-A block-collapsed state).  Rulers are
    tokens with index % k == 0 — a pure bit test on the packed low
    half, so the phase-1 freeze check needs NO extra gather.

    Phase 1: double every pointer whose target is not yet a ruler;
    terminals absorb automatically (gathering a self-loop adds dist 0).
    Adaptive while_loop — typically ~log2(k*ln m) rounds of the
    expensive full-ring rotate gather instead of log2(m); the round cap
    keeps the worst case exact (a pointer that never froze has doubled
    log2(m) times and so rests on a terminal, and at fixpoint every
    non-ruler stop is a terminal).

    Phase 2: dense ruler ring (slot r <-> token r*k) + one extra
    128-lane row holding the absorbing sink at slot mr: ruler-terminal
    slots are naturally absorbing ((0, self)); rulers resting on a
    non-ruler terminal edge to the sink.  Rotate gathers here are
    k-times cheaper.

    Phase 3: dist = d1 + dense_dist[t1 / k] via one small-table gather
    (pointers resting on non-ruler terminals take d1 alone)."""
    rows, cols = packed.shape
    m = rows * cols

    def tgt(p):
        return jnp.bitwise_and(p, jnp.uint32(0xFFFF)).astype(jnp.int32)

    def phase1_cond(carry):
        # done carried as i32 0/1 (i1 vectors in while carries fail
        # Mosaic legalization)
        i, p, done = carry
        return (i < n_steps) & jnp.any(done == 0)

    def phase1_body(carry):
        i, p, done = carry
        s = tgt(p)
        at_ruler = (s & (k - 1)) == 0
        g = _vmem_gather(p, jnp.right_shift(s, 7), jnp.bitwise_and(s, 0x7F))
        # target's own target: t2 == s means the target is a terminal —
        # the pointer has absorbed (applying the update is a no-op), so
        # it is done even when the terminal is not a ruler
        t2 = jnp.bitwise_and(g, jnp.uint32(0xFFFF)).astype(jnp.int32)
        done_now = at_ruler | (t2 == s)
        p2 = jnp.bitwise_and(p, jnp.uint32(0xFFFF0000)) + g
        p_next = jnp.where(at_ruler, p, p2)
        return i + 1, p_next, jnp.maximum(done, done_now.astype(jnp.int32))

    done0 = ((tgt(packed) & (k - 1)) == 0).astype(jnp.int32)
    _, p1, _ = jax.lax.while_loop(
        phase1_cond, phase1_body, (jnp.int32(0), packed, done0)
    )

    # ---- dense ruler ring + sink row ---------------------------------
    mr = m // k  # caller pads m to a multiple of 128*k, so mr % 128 == 0
    rows_d = mr // _LANES
    d_idx = (
        jax.lax.broadcasted_iota(jnp.int32, (rows_d, cols), 0) * _LANES
        + jax.lax.broadcasted_iota(jnp.int32, (rows_d, cols), 1)
    )
    r_tok = d_idx * k
    pr = _vmem_gather_from(
        p1, jnp.right_shift(r_tok, 7), jnp.bitwise_and(r_tok, 0x7F), d_idx
    )
    d1r = jnp.right_shift(pr, 16)
    t1r = jnp.bitwise_and(pr, jnp.uint32(0xFFFF)).astype(jnp.int32)
    # at fixpoint a non-ruler stop is a terminal -> edge to the sink
    # (slot mr, dist 0 self-loop); ruler stops edge to t1r / k.  Ruler
    # terminals come out naturally absorbing: (d1=0, t1=self).
    dense_t = jnp.where(
        (t1r & (k - 1)) != 0, jnp.int32(mr), t1r // k
    ).astype(jnp.uint32)
    ring_top = jnp.bitwise_or(jnp.left_shift(d1r, 16), dense_t)
    # sink row: every slot in [mr, mr+128) is a (0, self) absorber
    sink_row = (jnp.uint32(mr) + jax.lax.broadcasted_iota(
        jnp.uint32, (1, cols), 1
    ))
    ring_d = jnp.concatenate([ring_top, sink_row], axis=0)  # [rows_d+1, 128]

    n_steps_d = max(1, int(np.ceil(np.log2(max(mr, 2)))))

    def round_d(_, p):
        s = tgt(p)
        g = _vmem_gather(p, jnp.right_shift(s, 7), jnp.bitwise_and(s, 0x7F))
        return jnp.bitwise_and(p, jnp.uint32(0xFFFF0000)) + g

    ring_d = jax.lax.fori_loop(0, n_steps_d, round_d, ring_d)
    dist_d = jnp.right_shift(ring_d, 16).astype(jnp.int32)  # [rows_d+1, 128]

    # ---- recombine ---------------------------------------------------
    t1 = tgt(p1)
    d1 = jnp.right_shift(p1, 16).astype(jnp.int32)
    dense_all = t1 // k
    extra = _vmem_gather_from(
        dist_d, jnp.right_shift(dense_all, 7), jnp.bitwise_and(dense_all, 0x7F),
        t1,
    )
    at_nonruler_term = (t1 & (k - 1)) != 0
    return d1 + jnp.where(at_nonruler_term, 0, extra)


def _rank_kernel(succ_ref, w_ref, dist_ref, n_steps: int):
    """(dist, succ) packed as one u32 per element — dist in the high 16
    bits, succ in the low 16 (legal while m <= 65536; dist-to-terminal
    is < m so the high half never carries).  One packed gather per
    Wyllie round: g = p[s];  p' = (p & 0xffff0000) + g  gives
    dist' = dist + dist[s], succ' = succ[s] in two VPU ops."""
    succ = succ_ref[:, :]
    packed = jnp.bitwise_or(
        jnp.left_shift(w_ref[:, :].astype(jnp.uint32), 16), succ.astype(jnp.uint32)
    )

    def round_body(_, p):
        s = jnp.bitwise_and(p, jnp.uint32(0xFFFF)).astype(jnp.int32)
        g = _vmem_gather(p, jnp.right_shift(s, 7), jnp.bitwise_and(s, 0x7F))
        return jnp.bitwise_and(p, jnp.uint32(0xFFFF0000)) + g

    packed = jax.lax.fori_loop(0, n_steps, round_body, packed)
    dist_ref[:, :] = jnp.right_shift(packed, 16).astype(jnp.int32)


def wyllie_rank(
    succ: jax.Array,
    interpret: Optional[bool] = None,
    algo: Optional[str] = None,
    weights: Optional[jax.Array] = None,
    dist_bound: Optional[int] = None,
) -> jax.Array:
    """dist-to-terminal for a successor ring (terminal = self-loop).
    succ: i32[m]; returns i32[m].  `interpret=None` auto-selects the
    interpreter off-TPU (CI / CPU mesh runs).  Pads internally to a
    multiple of 128 lanes (pad tokens are self-loop terminals, dist 0);
    rings <= 65536 tokens use the packed-u32 kernel (PALLAS_RANK_ALGO
    selects wyllie | ruling | blocked — read at TRACE time like
    RANK_ALGO: set it before the first merge of the process,
    already-jitted kernels do not retrace on env changes; an explicit
    `algo` argument beats the env), longer rings the dual-table one.

    `weights` generalizes to a weighted pointer state: dist(i) =
    weights[i] + dist(succ[i]), with terminals carrying weight 0 — the
    run-coalesced path ranks its contracted super-node ring this way.
    Weighted callers MUST pass `dist_bound` (an exclusive upper bound
    on any resulting distance, e.g. the pre-contraction ring length):
    the packed kernels carry dist in 16 bits, so a bound past 65535
    forces the dual-table wide kernel even when the ring itself is
    short — silent u16 overflow otherwise."""
    from ..errors import ConfigError

    m = succ.shape[0]
    if algo is None:
        algo = _pallas_rank_algo()
    elif algo not in PALLAS_RANK_ALGOS:
        raise ConfigError("pallas rank algo", algo, "|".join(PALLAS_RANK_ALGOS))
    # ruler spacing: phase-1 rounds grow ~log2(k*ln m) while the dense
    # phase-2 ring shrinks k-fold — PALLAS_RULING_K exposes the
    # tradeoff for on-chip sweeps (power of two; read at trace time;
    # capped at 512 so the 128*k pad quantum stays within the packed
    # kernel's 65536-token domain)
    if algo in ("ruling", "blocked"):
        raw_k = os.environ.get("PALLAS_RULING_K", "8")
        try:
            k = int(raw_k)
        except ValueError:
            k = -1
        if not 2 <= k <= 512 or (k & (k - 1)) != 0:
            raise ConfigError(
                "PALLAS_RULING_K", raw_k, "a power of two in [2, 512]"
            )
        quantum = _LANES * k  # dense ruler ring must be 128-aligned
        if -(-m // quantum) * quantum > 65536 >= m:
            # the k-aligned pad would leave the packed-kernel domain
            # (and the wide kernel ignores k anyway, with up to 2x pad
            # waste) — fall back to the plain packed wyllie kernel,
            # which only needs lane alignment
            algo = "wyllie"
            quantum = _LANES
    else:
        k = 8  # unused off the ruling path
        quantum = _LANES
    block = 0
    if algo == "blocked":
        from .fugue_batch import _rank_block

        block = _rank_block()
    # the packed kernels hold dist in 16 bits: both the ring length AND
    # the weighted-distance domain must fit (a short contracted ring
    # can still carry pre-contraction distances past u16).  Wide rings
    # ignore the ruler quantum — pad to lanes only.
    needs_wide = (-(-m // _LANES) * _LANES) > 65536 or (
        weights is not None and dist_bound is not None and dist_bound > 65536
    )
    if needs_wide:
        quantum = _LANES
    mp = -(-m // quantum) * quantum
    if mp > PALLAS_RANK_MAX_M:
        raise ValueError(f"ring too long for VMEM ranking: {m}")
    tok = jnp.arange(m, dtype=jnp.int32)
    w = (
        jnp.where(succ == tok, 0, 1).astype(jnp.int32)
        if weights is None
        else weights.astype(jnp.int32)
    )
    if mp != m:
        pad_ids = jnp.arange(m, mp, dtype=jnp.int32)
        succ = jnp.concatenate([succ.astype(jnp.int32), pad_ids])
        w = jnp.concatenate([w, jnp.zeros(mp - m, jnp.int32)])
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows = mp // _LANES
    if weights is not None and dist_bound is None:
        raise ValueError("weighted wyllie_rank needs dist_bound (see docstring)")
    if not needs_wide:
        if algo == "ruling":
            kernel = functools.partial(_rank_kernel_ruling, k=k)
        elif algo == "blocked":
            kernel = functools.partial(_rank_kernel_blocked, k=k, block=block)
        else:
            kernel = _rank_kernel
    else:
        kernel = _rank_kernel_wide
    fn = pl.pallas_call(
        functools.partial(kernel, n_steps=n_steps),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return fn(succ.reshape(rows, _LANES), w.reshape(rows, _LANES)).reshape(mp)[:m]


def wyllie_rank_xla(succ: jax.Array) -> jax.Array:
    """Reference XLA implementation of plain two-gather Wyllie ranking.
    NOTE: production (_order_core) now fuses (dist, succ) into one
    [m, 2] row so each round is a single gather (measured 2.3x on v5e);
    this reference keeps the textbook formulation — both compute the
    same distances, which is what the differential tests assert."""
    m = succ.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    dist = jnp.where(succ == idx, 0, 1).astype(jnp.int32)
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))

    def body(_, carry):
        d, s = carry
        return d + d[s], s[s]

    dist, _ = jax.lax.fori_loop(0, n_steps, body, (dist, succ))
    return dist
