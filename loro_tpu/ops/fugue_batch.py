"""Batched Fugue sequence-order kernel.

The device-side merge engine for Text/List/MovableList — the TPU
reformulation of the reference's tracker replay
(crates/loro-internal/src/container/richtext/tracker/crdt_rope.rs
Fugue integration + tracker.rs diff extraction).

Because our wire format ships each insert's Fugue tree placement
`(parent, side)` (see core/change.py), integrating a batch of inserts
needs no sequential origin-scan.  The final sequence order is the
in-order traversal of the Fugue tree with siblings sorted by
(peer, counter).  We compute it fully in parallel:

1. lexsort elements by (parent, side, peer, counter) -> sibling groups
2. build the Euler-tour successor ring over 2 tokens per node
   (ENTER / EXIT — the directed-edge tour).  A node's in-order moment
   needs no third token: it is anchored just after EXIT(last L-child)
   when L-children exist, else just after its own ENTER; anchors are
   distinct tokens, so anchor rank orders elements exactly
3. Wyllie pointer-doubling list ranking (ceil(log2(2N)) rounds; dist
   and succ ride one [m, 2] row so each round is a single row gather —
   measured 2.3x over two separate [m] gathers on v5e)
4. element order = rank of its anchor token

Work O(N log N), depth O(log N), all gathers/sorts — ideal XLA/TPU
shapes.  `vmap` batches the whole thing across documents; the fleet
layer (parallel/fleet.py) shards the doc axis over the device mesh.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SeqColumns(NamedTuple):
    """Columnar element table for one document (padded to fixed N).

    parent: i32[N]  index of fugue parent element; -1 = virtual root
    side:   i32[N]  0 = Left child, 1 = Right child
    peer:   i32[N]  peer *rank* in the batch peer dictionary (order-
                    preserving w.r.t. u64 peer ids -> sibling order
                    matches the host engine)
    counter:i32[N]
    deleted:bool[N] tombstone flag
    content:i32[N]  codepoint / value-dictionary index
    valid:  bool[N] False for padding rows
    """

    parent: jax.Array
    side: jax.Array
    peer: jax.Array
    counter: jax.Array
    deleted: jax.Array
    content: jax.Array
    valid: jax.Array


def rank_bound(n: int) -> int:
    """Exclusive upper bound of fugue_order rank keys for an n-element
    table: ring distances live in [0, 2*(n+1))."""
    return 2 * (n + 1)


def _rank_algo() -> str:
    """Ranking algorithm: "wyllie" (default) or "ruling" (two-level
    ruling-set; ~2x fewer gather rows in expectation, adaptive round
    count — opt-in via RANK_ALGO=ruling until TPU-profiled).  Read at
    TRACE time: set it before the first merge call of the process
    (already-jitted kernels do not retrace on env changes)."""
    import os

    algo = os.environ.get("RANK_ALGO", "wyllie")
    if algo not in ("wyllie", "ruling"):
        raise ValueError(f"RANK_ALGO must be 'wyllie' or 'ruling', got {algo!r}")
    return algo


def _double(T: jax.Array, n_steps: int) -> jax.Array:
    """Weighted pointer doubling on (dist, target) [m, 2] rows — one row
    gather per round (the measured 2.3x-over-two-gathers layout)."""

    def body(_, T):
        g = jnp.take(T, T[:, 1], axis=0)  # one row gather: (d[t], t[t])
        return jnp.stack([T[:, 0] + g[:, 0], g[:, 1]], axis=1)

    return jax.lax.fori_loop(0, n_steps, body, T)


def _wyllie_dist(succ: jax.Array) -> jax.Array:
    """Distance-to-terminal by pointer doubling."""
    m = succ.shape[0]
    tok_ids = jnp.arange(m, dtype=jnp.int32)
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))
    dist0 = jnp.where(succ == tok_ids, 0, 1).astype(jnp.int32)
    T = _double(jnp.stack([dist0, succ], axis=1), n_steps)
    return T[:, 0]


def make_ring_rank_sharded(mesh, m: int):
    """Op-axis-sharded Wyllie ranking (SURVEY.md §2.4 item 2 for the
    sequence kernel): succ [D, m] sharded P(docs, ops) -> dist [D, m].

    Each op-shard owns m/S contiguous ring rows; every doubling round
    all_gathers the (dist, succ) row table along the op axis and updates
    only its local rows — the random-row gathers (the measured ~all of
    the merge cost on v5e) divide by S while each round moves m*8B per
    doc over ICI.  Communication-optimal doubling would need an
    all-to-all of exactly the requested rows; the all_gather variant is
    the XLA-collective formulation of the same plan and is already
    latency-bound, not bandwidth-bound, at CRDT ring sizes (m*8B =
    ~260KB at the flagship m=32896).  Doc-axis sharding stays the
    default — see ARCHITECTURE.md §"Op-axis ranking verdict"."""
    from jax.sharding import PartitionSpec as P

    try:  # jax >= 0.8
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    from ..parallel.mesh import DOC_AXIS, OP_AXIS

    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))

    def local(succ_sh: jax.Array) -> jax.Array:  # [d_local, ms] global ids
        ms = succ_sh.shape[1]
        tok0 = jax.lax.axis_index(OP_AXIS).astype(jnp.int32) * ms
        tok = tok0 + jnp.arange(ms, dtype=jnp.int32)[None, :]
        dist0 = jnp.where(succ_sh == tok, 0, 1).astype(jnp.int32)
        T = jnp.stack([dist0, succ_sh], axis=-1)  # [d, ms, 2]

        def body(_, T):
            T_full = jax.lax.all_gather(T, OP_AXIS, axis=1, tiled=True)  # [d, m, 2]
            g = jax.vmap(lambda full, t: jnp.take(full, t, axis=0))(
                T_full, T[:, :, 1]
            )  # [d, ms, 2]: (dist[t], succ[t])
            return jnp.stack([T[:, :, 0] + g[:, :, 0], g[:, :, 1]], axis=-1)

        T = jax.lax.fori_loop(0, n_steps, body, T)
        return T[:, :, 0]

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(DOC_AXIS, OP_AXIS),),
            out_specs=P(DOC_AXIS, OP_AXIS),
        )
    )


def _ruling_dist(succ: jax.Array, k: int = 8) -> jax.Array:
    """Distance-to-terminal via a two-level ruling set.

    Rulers are the statically-chosen token indices i % k == 0 (so the
    dense ruler ring has a static size m//k + 1 with no compaction
    sort).  Phase 1 doubles pointers that STOP at rulers/terminals —
    adaptive rounds, ~log2(k·ln m) on ring orders without adversarial
    ruler gaps, never more than the plain-Wyllie round count.  Phase 2
    runs weighted pointer doubling on the dense ruler ring (m/k rows).
    Phase 3 recombines with one gather.  Exact same output as
    _wyllie_dist (self-loops are terminals; unreachable pads self-loop
    and keep dist 0)."""
    m = succ.shape[0]
    tok = jnp.arange(m, dtype=jnp.int32)
    is_term = succ == tok
    is_ruler = (tok % k) == 0
    is_stop = is_ruler | is_term

    d0 = jnp.where(is_term, 0, 1).astype(jnp.int32)
    T0 = jnp.stack([d0, succ], axis=1)  # (dist-to-target, target)
    frozen0 = is_term | is_stop[succ]
    max_rounds = max(1, int(np.ceil(np.log2(max(m, 2)))))

    def cond(carry):
        i, T, frozen = carry
        return (i < max_rounds) & ~frozen.all()

    def body(carry):
        i, T, frozen = carry
        g = jnp.take(T, T[:, 1], axis=0)  # (d[t], t[t]) in one row gather
        d = jnp.where(frozen, T[:, 0], T[:, 0] + g[:, 0])
        t = jnp.where(frozen, T[:, 1], g[:, 1])
        return i + 1, jnp.stack([d, t], axis=1), is_term | is_stop[t]

    _, T, _ = jax.lax.while_loop(cond, body, (jnp.int32(0), T0, frozen0))
    d1, t1 = T[:, 0], T[:, 1]

    # dense ruler ring: slot r <-> token r*k; slot mr = terminal sink
    mr = (m + k - 1) // k

    def dense(t):
        # frozen targets are rulers or terminals; terminals sink to mr
        return jnp.where(is_term[t], mr, t // k).astype(jnp.int32)

    # (terminal rulers already have d1 == 0 and dense(t1) == mr from
    # phase 1, so no special-casing here)
    r_tok = jnp.arange(mr, dtype=jnp.int32) * k  # (mr-1)*k <= m-1 always
    rD0 = d1[r_tok]
    rT0 = dense(t1[r_tok])
    R = jnp.stack(
        [jnp.append(rD0, jnp.int32(0)), jnp.append(rT0, jnp.int32(mr))], axis=1
    )  # [mr+1, 2]
    R = _double(R, max(1, int(np.ceil(np.log2(max(mr + 1, 2))))))
    return d1 + R[:, 0][dense(t1)]


def fugue_order(cols: SeqColumns) -> jax.Array:
    """Return rank i32[N]: a key whose ascending order is the in-order
    position of each element in the Fugue traversal (keys may have gaps;
    pads get large keys).

    CONTRACT: rows must be pre-sorted by (peer, counter) — which the
    host extraction produces for free as per-peer concatenation, no
    comparison sort (SeqExtract.sort_by_peer_counter).  Sibling order is
    then one *stable* single-key sort by packed (parent, side), the only
    sort in the whole kernel."""
    return _order_core(cols.parent, cols.side, cols.valid)


def _order_core(
    parent_in: jax.Array,
    side_in: jax.Array,
    valid_in: jax.Array,
    sib_keys: Optional[Tuple[jax.Array, ...]] = None,
    rank_impl: Optional[str] = None,
) -> jax.Array:
    """Euler-tour in-order ranking over generic node arrays (element- or
    chain-level).  Without `sib_keys`, rows must obey the (peer, counter)
    order contract (fugue_order); with `sib_keys` (e.g. peer_hi, peer_lo,
    counter arrays) sibling order comes from an explicit lexsort instead
    — row order becomes irrelevant, which the incremental/append path
    needs (appended rows land at the end of the buffer)."""
    n = parent_in.shape[0]
    n1 = n + 1
    root = n  # virtual root element index
    big = jnp.int32(2**30)

    # -- extended element arrays incl. virtual root -------------------
    parent = jnp.concatenate([jnp.where(valid_in, parent_in, big), jnp.array([big], jnp.int32)])
    parent = parent.at[:n].set(jnp.where(valid_in & (parent_in < 0), root, parent[:n]))
    side = jnp.concatenate([side_in.astype(jnp.int32), jnp.array([1], jnp.int32)])
    valid = jnp.concatenate([valid_in, jnp.array([False])])  # root not a child

    key = jnp.where(parent < big, parent * 2 + side, big)
    if sib_keys is None:
        # ONE stable sort by (parent, side); (peer, counter) order within
        # groups comes from the input row-order contract
        order = jnp.argsort(key, stable=True).astype(jnp.int32)
    else:
        minor = [
            jnp.concatenate([k.astype(jnp.uint32), jnp.zeros(1, jnp.uint32)]) for k in sib_keys
        ]
        order = jnp.lexsort(tuple(reversed(minor)) + (key,)).astype(jnp.int32)
    p_s = parent[order]
    s_s = side[order]
    prev_same = (p_s == jnp.roll(p_s, 1)) & (s_s == jnp.roll(s_s, 1))
    prev_same = prev_same.at[0].set(False)
    is_first = ~prev_same
    nxt_same = (p_s == jnp.roll(p_s, -1)) & (s_s == jnp.roll(s_s, -1))
    nxt_same = nxt_same.at[-1].set(False)
    is_last = ~nxt_same
    elem_s = order  # element index at each sorted slot
    next_sib_s = jnp.where(nxt_same, jnp.roll(elem_s, -1), -1)

    # scatter: per element, its next sibling; per (parent, side): the
    # first child (ring entry) and last L-child (in-order anchor)
    next_sib = jnp.zeros(n1, jnp.int32).at[elem_s].set(next_sib_s.astype(jnp.int32))
    is_child = p_s < big  # this sorted slot is a real child row
    tgt_l = jnp.where(is_first & is_child & (s_s == 0), p_s, n1)  # n1 = dump slot
    tgt_r = jnp.where(is_first & is_child & (s_s == 1), p_s, n1)
    tgt_ll = jnp.where(is_last & is_child & (s_s == 0), p_s, n1)
    first_l = jnp.full(n1 + 1, -1, jnp.int32).at[tgt_l].set(elem_s.astype(jnp.int32))[:n1]
    first_r = jnp.full(n1 + 1, -1, jnp.int32).at[tgt_r].set(elem_s.astype(jnp.int32))[:n1]
    last_l = jnp.full(n1 + 1, -1, jnp.int32).at[tgt_ll].set(elem_s.astype(jnp.int32))[:n1]

    has_next_sib = next_sib >= 0
    has_l = first_l >= 0
    has_r = first_r >= 0

    # -- Euler-tour successor ring over 2 tokens per node -------------
    # (directed-edge tour; no VISIT token — see module docstring)
    # ENTER(e) -> ENTER(first_l[e])   if has_l
    #          -> ENTER(first_r[e])   elif has_r
    #          -> EXIT(e)             else
    # EXIT(e)  -> ENTER(next_sib[e])  if has_next_sib
    #          -> post_L(parent[e])   if last sibling and side==L
    #             (post_L(p) = ENTER(first_r[p]) if has_r[p] else EXIT(p))
    #          -> EXIT(parent[e])     if last sibling and side==R
    # EXIT(root) -> itself (ring terminal)
    ENTER0, EXIT0 = 0, n1
    m = 2 * n1
    e_ids = jnp.arange(n1, dtype=jnp.int32)
    post_l = jnp.where(has_r, ENTER0 + first_r, EXIT0 + e_ids)  # [n1]
    succ_enter = jnp.where(has_l, ENTER0 + first_l, post_l)
    par = jnp.where(parent < big, parent, root).astype(jnp.int32)
    succ_exit = jnp.where(
        has_next_sib,
        ENTER0 + next_sib,
        jnp.where(side == 0, post_l[par], EXIT0 + par),
    )
    succ_exit = succ_exit.at[root].set(EXIT0 + root)  # terminal self-loop
    succ = jnp.concatenate([succ_enter, succ_exit]).astype(jnp.int32)

    # invalid elements: make their tokens tight self-loops so they don't
    # perturb the ring (they are unreachable from the root anyway)
    tok_valid = jnp.concatenate([valid, valid])
    tok_ids = jnp.arange(m, dtype=jnp.int32)
    succ = jnp.where(tok_valid | (tok_ids == EXIT0 + root), succ, tok_ids)
    # root ENTER is a valid ring member:
    succ = succ.at[ENTER0 + root].set(succ_enter[root])

    # -- Wyllie list ranking: distance to terminal --------------------
    from .pallas_rank import pallas_rank_applicable, wyllie_rank

    # precedence: an explicit rank_impl argument (phased bench runs need
    # both paths jitted in one process — env knobs bake at trace time)
    # beats env; then an explicit RANK_ALGO=ruling beats the auto-on
    # pallas default (so algo comparisons stay honest), but an explicit
    # PALLAS_RANK=1 beats everything
    explicit_pallas = os.environ.get("PALLAS_RANK", "") not in ("", "0")
    if rank_impl == "pallas":
        dist = wyllie_rank(succ)
    elif rank_impl == "xla":
        dist = _ruling_dist(succ) if _rank_algo() == "ruling" else _wyllie_dist(succ)
    elif rank_impl is not None:
        raise ValueError(f"rank_impl must be pallas|xla|None, got {rank_impl!r}")
    elif pallas_rank_applicable(int(succ.shape[0])) and (
        _rank_algo() != "ruling" or explicit_pallas
    ):
        # VMEM-resident pointer doubling (default on TPU; falls back to
        # the XLA formulation for rings too long for the rotate loop)
        dist = wyllie_rank(succ)
    elif _rank_algo() == "ruling":
        dist = _ruling_dist(succ)
    else:
        dist = _wyllie_dist(succ)

    # in-order anchor: EXIT(last L-child) when L-children exist, else
    # the node's own ENTER; anchors are distinct tokens, so their ring
    # distances order elements exactly (larger distance = earlier)
    anchor = jnp.where(has_l, EXIT0 + last_l, ENTER0 + e_ids)  # [n1]
    anchor_dist = dist[anchor]
    rank = anchor_dist[root] - anchor_dist[:n]  # monotone along the traversal
    # pads / unreachable: push to the end
    rank = jnp.where(valid_in, rank, big)
    return rank.astype(jnp.int32)


def visible_order(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """(perm, visible_count): perm[i] = element index of the i-th element
    in final order, with visible elements first in document order; count
    of visible elements."""
    rank = fugue_order(cols)
    visible = cols.valid & ~cols.deleted
    big = jnp.int32(2**30)
    key = jnp.where(visible, rank, big)  # visible first (stable argsort)
    perm = jnp.argsort(key, stable=True)
    return perm.astype(jnp.int32), visible.sum().astype(jnp.int32)


def _compact(rank: jax.Array, visible: jax.Array, content: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sort-free compaction shared by both element-table layouts: ranks
    are unique values < rank_bound(N) = 2*(N+1), so a scatter into an
    m-bucket histogram + exclusive cumsum yields each visible element's
    final position directly; invisible rows scatter out of range
    (dropped)."""
    n = rank.shape[0]
    m = rank_bound(n)
    rk = jnp.clip(rank, 0, m - 1)
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(visible, rk, m - 1)].add(
        visible.astype(jnp.int32)
    )
    pos_of_rank = jnp.cumsum(hist) - hist  # exclusive prefix sum
    pos = pos_of_rank[rk]
    count = visible.sum().astype(jnp.int32)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos, n)].set(
        content, mode="drop"
    )
    return codes, count


def materialize_content(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """Gather content codes of visible elements in document order.
    Returns (codes i32[N] with tail padding = -1, count)."""
    rank = fugue_order(cols)
    return _compact(rank, cols.valid & ~cols.deleted, cols.content)


class SeqColumnsU(NamedTuple):
    """Row-order-free element table for the incremental/append path:
    peers carried as explicit u64 halves so sibling order needs no
    batch-wide rank dictionary and appended rows may sit anywhere."""

    parent: jax.Array  # i32[N]
    side: jax.Array  # i32[N]
    peer_hi: jax.Array  # u32[N]
    peer_lo: jax.Array  # u32[N]
    counter: jax.Array  # i32[N] (non-negative)
    deleted: jax.Array  # bool[N]
    content: jax.Array  # i32[N]
    valid: jax.Array  # bool[N]


def fugue_order_u(cols: SeqColumnsU) -> jax.Array:
    return _order_core(
        cols.parent,
        cols.side,
        cols.valid,
        sib_keys=(cols.peer_hi, cols.peer_lo, cols.counter.astype(jnp.uint32)),
    )


def materialize_content_u(cols: SeqColumnsU) -> Tuple[jax.Array, jax.Array]:
    """Order + compact for the row-order-free table (content=-1 rows —
    anchors — are invisible)."""
    rank = fugue_order_u(cols)
    visible = cols.valid & ~cols.deleted & (cols.content >= 0)
    return _compact(rank, visible, cols.content)


materialize_content_u_batch = jax.vmap(materialize_content_u)


@jax.jit
def merge_docs_u(cols: SeqColumnsU) -> Tuple[jax.Array, jax.Array]:
    return materialize_content_u_batch(cols)


class ChainColumns(NamedTuple):
    """Chain-contracted document batch (see columnar.contract_chains):
    chain-level tree arrays [C] + element-level arrays [N]."""

    c_parent: jax.Array  # i32[C]
    c_side: jax.Array  # i32[C]
    c_valid: jax.Array  # bool[C]
    head_row: jax.Array  # i32[C]
    chain_id: jax.Array  # i32[N] element -> chain
    deleted: jax.Array  # bool[N]
    content: jax.Array  # i32[N]
    valid: jax.Array  # bool[N]


def _place_algo() -> str:
    """Element placement: "sort" (default — one stable sort; measured
    ~2x the scatter formulation on v5e, where random HBM access costs
    ~100M rows/s but a [8, 188k] sort is ~10 ms) or "scatter" (the
    histogram + gather + positional-scatter formulation).  Read at
    TRACE time: set it before the first merge call of the process
    (already-jitted kernels do not retrace on env changes)."""
    algo = os.environ.get("PLACE_ALGO", "sort")
    if algo not in ("sort", "scatter"):
        raise ValueError(f"PLACE_ALGO must be 'sort' or 'scatter', got {algo!r}")
    return algo


def _place_by_chain(
    crank: jax.Array,
    c_valid: jax.Array,
    chain_id: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
    content: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Shared element placement for both chain paths (PLACE_ALGO)."""
    if _place_algo() == "sort":
        return _place_by_chain_sort(crank, c_valid, head_row, visible, content)
    return _place_by_chain_scatter(crank, c_valid, chain_id, head_row, visible, content)


def chain_positions(
    crank: jax.Array,
    c_valid: jax.Array,
    chain_id: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Histogram placement core: (pos i32[N], count) where pos[row] =
    number of visible rows strictly before the row in final document
    order — defined for EVERY row (zero-width/deleted rows included;
    the richtext anchors need exactly that).  Chain base positions from
    a rank histogram + exclusive cumsum, within-chain offsets from row
    cumsums (chain rows are contiguous)."""
    c = crank.shape[0]
    n = chain_id.shape[0]
    vis_i = visible.astype(jnp.int32)
    cid = jnp.clip(chain_id, 0, c)  # dump slot c for pads/overflow
    w = jnp.zeros(c + 1, jnp.int32).at[cid].add(vis_i)[:c]
    m = rank_bound(c)
    rk = jnp.clip(crank, 0, m - 1)
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(c_valid, rk, m - 1)].add(
        jnp.where(c_valid, w, 0)
    )
    base_of_rank = jnp.cumsum(hist) - hist
    base = base_of_rank[rk]  # i32[C]
    row_excl = jnp.cumsum(vis_i) - vis_i
    head_excl = row_excl[jnp.clip(head_row, 0, n - 1)]  # i32[C]
    within = row_excl - head_excl[jnp.clip(chain_id, 0, c - 1)]
    pos = base[jnp.clip(chain_id, 0, c - 1)] + within
    count = vis_i.sum().astype(jnp.int32)
    return pos, count


def _place_by_chain_scatter(
    crank: jax.Array,
    c_valid: jax.Array,
    chain_id: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
    content: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Histogram placement (see chain_positions) + positional scatter of
    the content codes."""
    n = chain_id.shape[0]
    pos, count = chain_positions(crank, c_valid, chain_id, head_row, visible)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos, n)].set(
        content, mode="drop"
    )
    return codes, count


def _place_by_chain_sort(
    crank: jax.Array,
    c_valid: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
    content: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Sort placement: expand chain ranks to elements with a C-scatter
    of telescoping rank deltas at head rows + one N-cumsum (chain rows
    are contiguous and chain ids ascend with row, so the cumsum
    reconstructs crank[chain_id[row]] exactly, including int32
    wraparound), then ONE stable sort of (key, content) realizes the
    whole placement: ascending rank = document order, stability keeps
    within-chain row order.  Every invisible row (deleted, pad,
    overflow) gets the absolute max key so it sorts behind ALL visible
    rows and the first `count` sorted codes are exactly the document."""
    n = visible.shape[0]
    vis_i = visible.astype(jnp.int32)
    # invalid chains are trailing (both contraction paths), so the
    # telescoping prev of any valid chain is valid (or the 0 seed)
    prev = jnp.concatenate([jnp.zeros(1, crank.dtype), crank[:-1]])
    delta = jnp.where(c_valid, crank - prev, 0)
    seg = (
        jnp.zeros(n + 1, jnp.int32)
        .at[jnp.where(c_valid, head_row, n)]
        .add(delta, mode="drop")[:n]
    )
    crank_elem = jnp.cumsum(seg)
    key = jnp.where(
        visible, crank_elem.astype(jnp.uint32), jnp.uint32(0xFFFFFFFF)
    )
    _, content_sorted = jax.lax.sort((key, content), num_keys=1, is_stable=True)
    count = vis_i.sum().astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    codes = jnp.where(idx < count, content_sorted, jnp.int32(-1))
    return codes, count


def chain_materialize(
    cols: ChainColumns, rank_impl: Optional[str] = None
) -> Tuple[jax.Array, jax.Array]:
    """Merge via chain contraction: rank C chains (C << N), then place
    all N elements via _place_by_chain (default: rank expansion by
    C-scatter + N-cumsum, then one stable N-row sort; PLACE_ALGO=scatter
    selects the histogram + gather + positional-scatter formulation) —
    the gather-heavy ranking runs on the contracted tree only.
    Returns (codes i32[N] padded with -1, visible count)."""
    c = cols.c_parent.shape[0]
    crank = _order_core(
        cols.c_parent, cols.c_side, cols.c_valid, rank_impl=rank_impl
    )  # i32[C]
    visible = cols.valid & ~cols.deleted
    chain_id = jnp.where(cols.valid, cols.chain_id, c)
    return _place_by_chain(
        crank, cols.c_valid, chain_id, cols.head_row, visible, cols.content
    )


chain_materialize_batch = jax.vmap(chain_materialize)


@jax.jit
def chain_merge_docs(cols: ChainColumns) -> Tuple[jax.Array, jax.Array]:
    """One launch: chain-contracted merge for a doc batch ([D,C]/[D,N])."""
    return chain_materialize_batch(cols)


def _weighted_checksum(codes: jax.Array) -> jax.Array:
    """Order-sensitive per-doc checksum of merged codes [D, N] -> [D]."""
    n = codes.shape[1]
    wgt = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)) % jnp.uint32(1 << 30)
    return ((jnp.where(codes >= 0, codes, 0).astype(jnp.uint32) * wgt[None, :]) % (1 << 30)).sum(
        axis=1, dtype=jnp.uint32
    )


@jax.jit
def chain_merge_docs_checksum(cols: ChainColumns) -> Tuple[jax.Array, jax.Array]:
    codes, counts = chain_materialize_batch(cols)
    return _weighted_checksum(codes), counts


@functools.partial(jax.jit, static_argnames=("rank_impl",))
def chain_merge_docs_v(
    cols: ChainColumns, rank_impl: Optional[str] = None
) -> Tuple[jax.Array, jax.Array]:
    """chain_merge_docs with an explicit ranking implementation —
    phased bench runs measure the XLA path first (banking a safe device
    number), then the pallas path, inside ONE process (env knobs bake
    at trace time, so this must be a static argument)."""
    return jax.vmap(lambda c: chain_materialize(c, rank_impl))(cols)


@functools.partial(jax.jit, static_argnames=("rank_impl",))
def chain_merge_docs_checksum_v(
    cols: ChainColumns, rank_impl: Optional[str] = None
) -> Tuple[jax.Array, jax.Array]:
    codes, counts = jax.vmap(lambda c: chain_materialize(c, rank_impl))(cols)
    return _weighted_checksum(codes), counts


@functools.partial(jax.jit, static_argnames=("rank_impl",))
def chain_rank_checksum_v(
    cols: ChainColumns, rank_impl: Optional[str] = None
) -> jax.Array:
    """Ranking phase ONLY (scalar-reduced for cheap fetches): the
    measured-roofline bench phase times this against the full merge to
    split rank vs placement cost on chip."""

    def one(c: ChainColumns) -> jax.Array:
        crank = _order_core(c.c_parent, c.c_side, c.c_valid, rank_impl=rank_impl)
        return crank.astype(jnp.uint32).sum(dtype=jnp.uint32)

    return jax.vmap(one)(cols)


# ---- packed single-buffer transport (ingest pipeline) ----------------
# The e2e pipeline ships one chunk as ONE contiguous u8 buffer instead
# of 8 separate device_puts with loose dtypes: per-put tunnel overhead
# disappears and the byte-tight layout (u16 chain ids, u8 flags) is
# ~1.3x smaller than the i32 ChainColumns transport.  Layout per doc
# row (little-endian, matching both x86 hosts and TPU bitcast):
#   [0        : 2C)        c_parent  u16   (0xFFFF == -1 root)
#   [2C       : 2C+2N)     chain_id  u16   (pad rows carry 0; the dump
#                                           remap to pad_c happens
#                                           on-device via the valid mask)
#   [..       : +4C)       head_row  i32
#   [..       : +4N)       content   i32   (-1 == invisible)
#   [..       : +C)        c_side    u8
#   [..       : +C)        c_valid   u8
#   [..       : +N)        deleted   u8
#   [..       : +N)        valid     u8
# Total 8C + 8N bytes.  Requires pad_c < 0xFFFF.


def packed_row_bytes(pad_c: int, pad_n: int) -> int:
    assert pad_c < 0xFFFF, "u16 chain ids need pad_c < 65535"
    return 8 * pad_c + 8 * pad_n


def pack_chain_doc_into(cols: ChainColumns, out_row: np.ndarray) -> None:
    """Serialize one doc's numpy ChainColumns into a packed u8 row
    (shape [packed_row_bytes(C, N)]); the inverse of the in-jit
    unpack in chain_merge_docs_packed."""
    c = cols.c_parent.shape[0]
    n = cols.chain_id.shape[0]
    assert out_row.dtype == np.uint8 and out_row.shape[0] == packed_row_bytes(c, n)
    o = 0

    def sec(nbytes):
        nonlocal o
        s = out_row[o : o + nbytes]
        o += nbytes
        return s

    sec(2 * c).view("<u2")[:] = cols.c_parent.astype(np.int32).astype(np.uint16)
    sec(2 * n).view("<u2")[:] = cols.chain_id.astype(np.int32).astype(np.uint16)
    sec(4 * c).view("<i4")[:] = cols.head_row.astype(np.int32)
    sec(4 * n).view("<i4")[:] = cols.content.astype(np.int32)
    sec(c)[:] = cols.c_side.astype(np.uint8)
    sec(c)[:] = cols.c_valid.astype(np.uint8)
    sec(n)[:] = cols.deleted.astype(np.uint8)
    sec(n)[:] = cols.valid.astype(np.uint8)
    assert o == out_row.shape[0]


def _unpack_chain_batch(packed: jax.Array, pad_c: int, pad_n: int) -> ChainColumns:
    """In-jit inverse of pack_chain_doc_into ([D, W] u8 -> ChainColumns)."""
    d = packed.shape[0]
    c, n = pad_c, pad_n
    offs = [0]
    for nbytes in (2 * c, 2 * n, 4 * c, 4 * n, c, c, n, n):
        offs.append(offs[-1] + nbytes)

    def sec(i):
        return packed[:, offs[i] : offs[i + 1]]

    def u16(i, count):
        return jax.lax.bitcast_convert_type(
            sec(i).reshape(d, count, 2), jnp.uint16
        ).astype(jnp.int32)

    def i32(i, count):
        return jax.lax.bitcast_convert_type(sec(i).reshape(d, count, 4), jnp.int32)

    cp = u16(0, c)
    return ChainColumns(
        c_parent=jnp.where(cp == 0xFFFF, -1, cp),
        c_side=sec(4).astype(jnp.int32),
        c_valid=sec(5).astype(bool),
        head_row=i32(2, c),
        chain_id=u16(1, n),
        deleted=sec(6).astype(bool),
        content=i32(3, n),
        valid=sec(7).astype(bool),
    )


@functools.partial(jax.jit, static_argnums=(1, 2))
def chain_merge_docs_packed(packed: jax.Array, pad_c: int, pad_n: int):
    """One launch: unpack the u8 transport buffer + chain merge."""
    return chain_materialize_batch(_unpack_chain_batch(packed, pad_c, pad_n))


@functools.partial(jax.jit, static_argnums=(1, 2))
def chain_merge_docs_packed_checksum(packed: jax.Array, pad_c: int, pad_n: int):
    codes, counts = chain_materialize_batch(_unpack_chain_batch(packed, pad_c, pad_n))
    return _weighted_checksum(codes), counts


def chain_contract_materialize_u(
    cols: SeqColumnsU, c_pad: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Device-side chain contraction + order + compaction for the
    row-order-free layout (the resident-batch path).

    Chains (right-spine runs, columnar.contract_chains conditions) are
    detected on device: row i links to row i-1 iff parent==i-1, side=R,
    row i-1 has exactly one child and no L-children, and row i has no
    L-children.  Cross-epoch runs simply stay split (appended rows are
    only adjacent within their block) — correctness is unaffected, the
    contraction is just slightly less aggressive.

    `c_pad` is the static chain budget; returns (codes, count,
    n_chains).  When n_chains > c_pad the output is INVALID and the
    caller must retry with a bigger budget (DeviceDocBatch does)."""
    n = cols.parent.shape[0]
    valid = cols.valid
    pgt = jnp.clip(cols.parent, 0, n - 1)
    has_parent = valid & (cols.parent >= 0)
    cc = jnp.zeros(n, jnp.int32).at[jnp.where(has_parent, pgt, n - 1)].add(
        has_parent.astype(jnp.int32)
    )
    is_l = has_parent & (cols.side == 0)
    lc = jnp.zeros(n, jnp.int32).at[jnp.where(is_l, pgt, n - 1)].add(is_l.astype(jnp.int32))

    idx = jnp.arange(n, dtype=jnp.int32)
    prev_ok = jnp.concatenate([jnp.zeros(1, bool), valid[:-1]])
    link = (
        valid
        & prev_ok
        & (cols.parent == idx - 1)
        & (cols.side == 1)
        & (jnp.roll(cc, 1) == 1)
        & (jnp.roll(lc, 1) == 0)
        & (lc == 0)
    )
    link = link.at[0].set(False)
    is_head = valid & ~link
    chain_id = jnp.cumsum(is_head.astype(jnp.int32)) - 1  # per valid row
    chain_id = jnp.where(valid, chain_id, c_pad)  # pads -> dump
    n_chains = is_head.sum().astype(jnp.int32)

    cid_clip = jnp.clip(chain_id, 0, c_pad)
    # chain-level attributes scattered from head rows (chain_id is the
    # compact index — no sort needed)
    def head_scatter(src, fill):
        return jnp.full(c_pad + 1, fill, src.dtype).at[
            jnp.where(is_head, cid_clip, c_pad)
        ].set(src, mode="drop")[:c_pad]

    head_row = head_scatter(idx, 0)
    c_parent_row = head_scatter(jnp.where(cols.parent >= 0, cols.parent, -1), -1)
    c_parent = jnp.where(
        c_parent_row >= 0, chain_id[jnp.clip(c_parent_row, 0, n - 1)], -1
    ).astype(jnp.int32)
    c_side = head_scatter(cols.side.astype(jnp.int32), 0)
    c_hi = head_scatter(cols.peer_hi, 0)
    c_lo = head_scatter(cols.peer_lo, 0)
    c_ctr = head_scatter(cols.counter.astype(jnp.uint32), 0)
    c_valid = jnp.arange(c_pad) < n_chains

    crank = _order_core(
        c_parent, c_side, c_valid, sib_keys=(c_hi, c_lo, c_ctr)
    )  # [c_pad]

    visible = valid & ~cols.deleted & (cols.content >= 0)
    codes, count = _place_by_chain(
        crank, c_valid, chain_id, head_row, visible, cols.content
    )
    return codes, count, n_chains


@functools.partial(jax.jit, static_argnums=(1,))
def chain_merge_docs_u(cols: SeqColumnsU, c_pad: int):
    return jax.vmap(lambda c: chain_contract_materialize_u(c, c_pad))(cols)


@jax.jit
def materialize_by_key(cols: SeqColumnsU, key_hi, key_lo):
    """Visible content from standing order keys (incremental path):
    one multi-key sort by (key_hi, key_lo) replaces the rank solve —
    the host ShadowOrder (parallel/order_maintenance.py) guarantees
    ascending key == Fugue traversal order.  [D, N] -> (codes, counts)
    with the same contract as chain_merge_docs_u."""
    d, n = cols.content.shape
    inf = jnp.uint32(0xFFFFFFFF)
    hi = jnp.where(cols.valid, key_hi, inf)
    lo = jnp.where(cols.valid, key_lo, inf)
    visible = cols.valid & ~cols.deleted & (cols.content >= 0)
    _hi_s, _lo_s, content_s, vis_s = jax.lax.sort(
        (hi, lo, cols.content, visible.astype(jnp.int32)), dimension=1, num_keys=2
    )
    vis_s = vis_s.astype(bool)
    pos = jnp.cumsum(vis_s.astype(jnp.int32), axis=1) - 1
    counts = vis_s.sum(axis=1)
    target = jnp.where(vis_s, pos, n)  # invisible rows -> dump column
    out = jnp.full((d, n + 1), -1, cols.content.dtype)
    d_idx = jnp.broadcast_to(jnp.arange(d)[:, None], (d, n))
    out = out.at[d_idx, target].set(content_s, mode="drop")
    return out[:, :n], counts


# batched-over-documents variants --------------------------------------
fugue_order_batch = jax.vmap(fugue_order)
visible_order_batch = jax.vmap(visible_order)
materialize_content_batch = jax.vmap(materialize_content)

# jitted single-doc entry (one compilation per padded size — callers
# should bucket-pad N, e.g. to powers of two)
materialize_content_jit = jax.jit(materialize_content)


def pad_bucket(n: int, floor: int = 64) -> int:
    """Next power-of-two bucket >= n (bounds XLA recompilations)."""
    b = floor
    while b < n:
        b *= 2
    return b


def pad_seq_columns(cols: SeqColumns, n: int) -> SeqColumns:
    """Pad numpy SeqColumns to n rows (invalid tail)."""

    def pad(a, fill):
        if a.shape[0] == n:
            return a
        out = np.full(n, fill, a.dtype)
        out[: a.shape[0]] = a
        return out

    return SeqColumns(
        parent=pad(cols.parent, -1),
        side=pad(cols.side, 0),
        peer=pad(cols.peer, 0),
        counter=pad(cols.counter, 0),
        deleted=pad(cols.deleted, True),
        content=pad(cols.content, -1),
        valid=pad(cols.valid, False),
    )


@functools.partial(jax.jit, donate_argnums=())
def merge_docs(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """One XLA launch: resolve order + materialize visible content for a
    whole batch of documents.  cols arrays are [D, N]."""
    return materialize_content_batch(cols)


@jax.jit
def merge_docs_checksum(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """Merge but return only a per-doc order-sensitive checksum [D] +
    counts [D].  Used by benchmarks: the merged state stays device-
    resident (the fleet model); only O(D) scalars cross the host link."""
    codes, counts = materialize_content_batch(cols)
    n = codes.shape[1]
    w = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)) % jnp.uint32(1 << 30)
    cs = ((jnp.where(codes >= 0, codes, 0).astype(jnp.uint32) * w[None, :]) % (1 << 30)).sum(
        axis=1, dtype=jnp.uint32
    )
    return cs, counts
