"""Batched Fugue sequence-order kernel.

The device-side merge engine for Text/List/MovableList — the TPU
reformulation of the reference's tracker replay
(crates/loro-internal/src/container/richtext/tracker/crdt_rope.rs
Fugue integration + tracker.rs diff extraction).

Because our wire format ships each insert's Fugue tree placement
`(parent, side)` (see core/change.py), integrating a batch of inserts
needs no sequential origin-scan.  The final sequence order is the
in-order traversal of the Fugue tree with siblings sorted by
(peer, counter).  We compute it fully in parallel:

1. lexsort elements by (parent, side, peer, counter) -> sibling groups
2. build the Euler-tour successor ring over 2 tokens per node
   (ENTER / EXIT — the directed-edge tour).  A node's in-order moment
   needs no third token: it is anchored just after EXIT(last L-child)
   when L-children exist, else just after its own ENTER; anchors are
   distinct tokens, so anchor rank orders elements exactly
3. Wyllie pointer-doubling list ranking (ceil(log2(2N)) rounds; dist
   and succ ride one [m, 2] row so each round is a single row gather —
   measured 2.3x over two separate [m] gathers on v5e)
4. element order = rank of its anchor token

Work O(N log N), depth O(log N), all gathers/sorts — ideal XLA/TPU
shapes.  `vmap` batches the whole thing across documents; the fleet
layer (parallel/fleet.py) shards the doc axis over the device mesh.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SeqColumns(NamedTuple):
    """Columnar element table for one document (padded to fixed N).

    parent: i32[N]  index of fugue parent element; -1 = virtual root
    side:   i32[N]  0 = Left child, 1 = Right child
    peer:   i32[N]  peer *rank* in the batch peer dictionary (order-
                    preserving w.r.t. u64 peer ids -> sibling order
                    matches the host engine)
    counter:i32[N]
    deleted:bool[N] tombstone flag
    content:i32[N]  codepoint / value-dictionary index
    valid:  bool[N] False for padding rows
    """

    parent: jax.Array
    side: jax.Array
    peer: jax.Array
    counter: jax.Array
    deleted: jax.Array
    content: jax.Array
    valid: jax.Array


def rank_bound(n: int) -> int:
    """Exclusive upper bound of fugue_order rank keys for an n-element
    table: ring distances live in [0, 2*(n+1))."""
    return 2 * (n + 1)


RANK_ALGOS = ("wyllie", "ruling", "blocked", "coalesced")


def _rank_algo() -> str:
    """XLA ranking algorithm (RANK_ALGO): "wyllie" (default), "ruling"
    (two-level ruling-set; ~2x fewer gather rows in expectation),
    "blocked" (phase-A block-local doubling + phase-B weighted ruling
    over the exit graph) or "coalesced" (run-coalesce the ring, rank
    the contracted super-node ring, expand by cumsum/scatter).  Read at
    TRACE time: set it before the first merge call of the process
    (already-jitted kernels do not retrace on env changes)."""
    from ..errors import ConfigError

    algo = os.environ.get("RANK_ALGO", "wyllie")
    if algo not in RANK_ALGOS:
        raise ConfigError("RANK_ALGO", algo, "|".join(RANK_ALGOS))
    return algo


def _rank_block() -> int:
    """Block size (tokens) for the blocked two-level rank (RANK_BLOCK,
    default 1024): phase A ranks inside blocks of this many tokens with
    block-local gathers only.  Power of two, multiple of 128, in
    [128, 65536] (the 128-lane alignment the pallas twin needs)."""
    from ..errors import ConfigError

    raw = os.environ.get("RANK_BLOCK", "1024")
    try:
        b = int(raw)
    except ValueError:
        b = -1
    if not (128 <= b <= 65536) or (b & (b - 1)) != 0:
        raise ConfigError(
            "RANK_BLOCK", raw, "a power of two in [128, 65536]"
        )
    return b


def _double(T: jax.Array, n_steps: int) -> jax.Array:
    """Weighted pointer doubling on (dist, target) [m, 2] rows — one row
    gather per round (the measured 2.3x-over-two-gathers layout)."""

    def body(_, T):
        g = jnp.take(T, T[:, 1], axis=0)  # one row gather: (d[t], t[t])
        return jnp.stack([T[:, 0] + g[:, 0], g[:, 1]], axis=1)

    return jax.lax.fori_loop(0, n_steps, body, T)


def _wyllie_dist(succ: jax.Array) -> jax.Array:
    """Distance-to-terminal by pointer doubling."""
    m = succ.shape[0]
    tok_ids = jnp.arange(m, dtype=jnp.int32)
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))
    dist0 = jnp.where(succ == tok_ids, 0, 1).astype(jnp.int32)
    T = _double(jnp.stack([dist0, succ], axis=1), n_steps)
    return T[:, 0]


def make_ring_rank_sharded(mesh, m: int, algo: str = "wyllie"):
    """Op-axis-sharded Wyllie ranking (SURVEY.md §2.4 item 2 for the
    sequence kernel): succ [D, m] sharded P(docs, ops) -> dist [D, m].
    algo="blocked" prepends a SHARD-LOCAL phase A (freeze-at-shard-exit
    doubling, zero collectives) and makes the all_gather doubling
    adaptive (early exit when every pointer rests on a terminal — rings
    with shard locality then pay far fewer all_gather rounds; the
    round cap keeps arbitrary rings exact).

    Each op-shard owns m/S contiguous ring rows; every doubling round
    all_gathers the (dist, succ) row table along the op axis and updates
    only its local rows — the random-row gathers (the measured ~all of
    the merge cost on v5e) divide by S while each round moves m*8B per
    doc over ICI.  Communication-optimal doubling would need an
    all-to-all of exactly the requested rows; the all_gather variant is
    the XLA-collective formulation of the same plan and is already
    latency-bound, not bandwidth-bound, at CRDT ring sizes (m*8B =
    ~260KB at the flagship m=32896).  Doc-axis sharding stays the
    default — see ARCHITECTURE.md §"Op-axis ranking verdict"."""
    from jax.sharding import PartitionSpec as P

    try:  # jax >= 0.8
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    from ..parallel.mesh import DOC_AXIS, OP_AXIS

    if algo not in ("wyllie", "blocked"):
        from ..errors import ConfigError

        raise ConfigError("sharded rank algo", algo, "wyllie|blocked")
    n_steps = max(1, int(np.ceil(np.log2(max(m, 2)))))

    def local(succ_sh: jax.Array) -> jax.Array:  # [d_local, ms] global ids
        ms = succ_sh.shape[1]
        tok0 = jax.lax.axis_index(OP_AXIS).astype(jnp.int32) * ms
        tok = tok0 + jnp.arange(ms, dtype=jnp.int32)[None, :]
        dist0 = jnp.where(succ_sh == tok, 0, 1).astype(jnp.int32)
        T = jnp.stack([dist0, succ_sh], axis=-1)  # [d, ms, 2]

        if algo == "blocked":
            # phase A: collapse in-shard chains without touching ICI —
            # a pointer composes only while its target is a LOCAL row
            def body_a(_, T):
                t = T[:, :, 1]
                lt = t - tok0
                in_shard = (lt >= 0) & (lt < ms) & (t != tok)
                lt = jnp.clip(lt, 0, ms - 1)
                g = jnp.take_along_axis(T, lt[:, :, None], axis=1)
                return jnp.stack(
                    [
                        jnp.where(in_shard, T[:, :, 0] + g[:, :, 0], T[:, :, 0]),
                        jnp.where(in_shard, g[:, :, 1], T[:, :, 1]),
                    ],
                    axis=-1,
                )

            T = jax.lax.fori_loop(
                0, max(1, int(np.ceil(np.log2(max(ms, 2))))), body_a, T
            )

        def gather_step(T):
            T_full = jax.lax.all_gather(T, OP_AXIS, axis=1, tiled=True)  # [d, m, 2]
            g = jax.vmap(lambda full, t: jnp.take(full, t, axis=0))(
                T_full, T[:, :, 1]
            )  # [d, ms, 2]: (dist[t], succ[t])
            return jnp.stack([T[:, :, 0] + g[:, :, 0], g[:, :, 1]], axis=-1)

        if algo == "blocked":
            # adaptive all_gather doubling: T stabilizes exactly when
            # every pointer rests on a terminal (terminals are the only
            # fixpoint rows), so comparing post- vs pre-update targets
            # detects completion with ZERO extra gathers (one round
            # later than a lookahead check, but gathers are the cost
            # being minimized); agreement psum'd across the op shards
            def body(carry):
                i, T, _done = carry
                T_new = gather_step(T)
                local_done = jnp.all(T_new[:, :, 1] == T[:, :, 1])
                done = (
                    jax.lax.psum((~local_done).astype(jnp.int32), OP_AXIS) == 0
                )
                return i + 1, T_new, done

            def cond(carry):
                i, _T, done = carry
                return (i < n_steps) & ~done

            _, T, _ = jax.lax.while_loop(
                cond, body, (jnp.int32(0), T, jnp.bool_(False))
            )
        else:
            T = jax.lax.fori_loop(0, n_steps, lambda _, T: gather_step(T), T)
        return T[:, :, 0]

    kw = {}
    if algo == "blocked":
        # shard_map has no replication rule for while_loop; the adaptive
        # loop's outputs are explicitly sharded, so the check is safely
        # skipped
        kw["check_rep"] = False
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(DOC_AXIS, OP_AXIS),),
            out_specs=P(DOC_AXIS, OP_AXIS),
            **kw,
        )
    )


def _ruling_dist(succ: jax.Array, k: int = 8) -> jax.Array:
    """Distance-to-terminal via a two-level ruling set.

    Rulers are the statically-chosen token indices i % k == 0 (so the
    dense ruler ring has a static size m//k + 1 with no compaction
    sort).  Phase 1 doubles pointers that STOP at rulers/terminals —
    adaptive rounds, ~log2(k·ln m) on ring orders without adversarial
    ruler gaps, never more than the plain-Wyllie round count.  Phase 2
    runs weighted pointer doubling on the dense ruler ring (m/k rows).
    Phase 3 recombines with one gather.  Exact same output as
    _wyllie_dist (self-loops are terminals; unreachable pads self-loop
    and keep dist 0)."""
    m = succ.shape[0]
    tok = jnp.arange(m, dtype=jnp.int32)
    d0 = jnp.where(succ == tok, 0, 1).astype(jnp.int32)
    return _ruling_dist_from(d0, succ, k=k)


def _ruling_dist_from(d0: jax.Array, t0: jax.Array, k: int = 8) -> jax.Array:
    """Ruling-set ranking from a generic WEIGHTED pointer state:
    dist(i) = d0[i] + dist(t0[i]), terminal nodes are self-loops with
    d0 == 0.  This is the ruling machinery the blocked and coalesced
    paths compose with (their phase-A / contraction output is exactly
    such a weighted state); _ruling_dist is the unit-weight wrapper.
    The phase-1 round cap stays exact for arbitrary states: after
    ceil(log2(m)) doublings every pointer rests on a terminal."""
    m = t0.shape[0]
    tok = jnp.arange(m, dtype=jnp.int32)
    succ = t0
    is_term = succ == tok
    is_ruler = (tok % k) == 0
    is_stop = is_ruler | is_term

    T0 = jnp.stack([d0.astype(jnp.int32), succ], axis=1)  # (dist, target)
    frozen0 = is_term | is_stop[succ]
    max_rounds = max(1, int(np.ceil(np.log2(max(m, 2)))))

    def cond(carry):
        i, T, frozen = carry
        return (i < max_rounds) & ~frozen.all()

    def body(carry):
        i, T, frozen = carry
        g = jnp.take(T, T[:, 1], axis=0)  # (d[t], t[t]) in one row gather
        d = jnp.where(frozen, T[:, 0], T[:, 0] + g[:, 0])
        t = jnp.where(frozen, T[:, 1], g[:, 1])
        return i + 1, jnp.stack([d, t], axis=1), is_term | is_stop[t]

    _, T, _ = jax.lax.while_loop(cond, body, (jnp.int32(0), T0, frozen0))
    d1, t1 = T[:, 0], T[:, 1]

    # dense ruler ring: slot r <-> token r*k; slot mr = terminal sink
    mr = (m + k - 1) // k

    def dense(t):
        # frozen targets are rulers or terminals; terminals sink to mr
        return jnp.where(is_term[t], mr, t // k).astype(jnp.int32)

    # (terminal rulers already have d1 == 0 and dense(t1) == mr from
    # phase 1, so no special-casing here)
    r_tok = jnp.arange(mr, dtype=jnp.int32) * k  # (mr-1)*k <= m-1 always
    rD0 = d1[r_tok]
    rT0 = dense(t1[r_tok])
    R = jnp.stack(
        [jnp.append(rD0, jnp.int32(0)), jnp.append(rT0, jnp.int32(mr))], axis=1
    )  # [mr+1, 2]
    R = _double(R, max(1, int(np.ceil(np.log2(max(mr + 1, 2))))))
    return d1 + R[:, 0][dense(t1)]


def _blocked_dist(succ: jax.Array, block: Optional[int] = None) -> jax.Array:
    """Blocked two-level ranking (the XLA twin of the pallas blocked
    kernel; RANK_ALGO=blocked).

    Phase A collapses every in-block pointer chain by doubling that
    FREEZES at block exits: a pointer composes with its target only
    while the target sits in the same `block`-token block, so every
    gather is a within-block take_along_axis on the [n_blocks, block]
    reshape (contiguous block-local rows — never a random full-ring
    HBM gather).  After ceil(log2(block)) rounds each token holds
    (d, t) with t its first out-of-block stop or an in-block terminal.

    Phase B ranks the resulting weighted exit graph with the ruling-set
    machinery (_ruling_dist_from); its round cap keeps the result exact
    on rings with no block locality (the exit graph then is nearly the
    original ring).  O(n log b) block-local + O(adaptive·n + (n/k)
    log(n/k)) global gather rows vs O(n log n) global for Wyllie."""
    m = succ.shape[0]
    b = block if block is not None else _rank_block()
    # clamp the block to the lane-padded ring: a block bigger than the
    # ring only inflates the [nb, b] pad that phase B then pays for
    b = min(b, max(128, -(-m // 128) * 128))
    mp = -(-m // b) * b
    if mp != m:
        pad_ids = jnp.arange(m, mp, dtype=jnp.int32)
        succ = jnp.concatenate([succ.astype(jnp.int32), pad_ids])
    nb = mp // b
    tok2 = jnp.arange(mp, dtype=jnp.int32).reshape(nb, b)
    base = (jnp.arange(nb, dtype=jnp.int32) * b)[:, None]
    T = succ.reshape(nb, b)
    D = jnp.where(T == tok2, 0, 1).astype(jnp.int32)
    n_a = max(1, int(np.ceil(np.log2(max(b, 2)))))

    def body(_, carry):
        D, T = carry
        lt = T - base
        in_blk = (lt >= 0) & (lt < b)
        active = in_blk & (T != tok2)
        lt = jnp.clip(lt, 0, b - 1)
        gd = jnp.take_along_axis(D, lt, axis=1)
        gt = jnp.take_along_axis(T, lt, axis=1)
        return jnp.where(active, D + gd, D), jnp.where(active, gt, T)

    D, T = jax.lax.fori_loop(0, n_a, body, (D, T))
    return _ruling_dist_from(D.reshape(mp), T.reshape(mp))[:m]


def ring_run_heads(succ: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(is_head bool[m], n_runs): maximal index-consecutive successor
    runs.  Token j is absorbed into its predecessor's run iff
    succ[j-1] == j, j is j's ONLY predecessor, and j is not a terminal
    self-loop — which guarantees (a) runs are index intervals and (b) a
    run tail's successor is always some run's head, so the contracted
    super-node ring is well formed.  The slot-numbered Euler ring
    (_order_core) is laid out so that real traces produce long runs
    here (leaf ENTER->EXIT pairs, sibling groups, chained pads)."""
    m = succ.shape[0]
    tok = jnp.arange(m, dtype=jnp.int32)
    indeg = jnp.zeros(m, jnp.int32).at[succ].add(1)
    is_term = succ == tok
    absorbed = (
        jnp.concatenate([jnp.zeros(1, bool), succ[:-1] == tok[1:]])
        & (indeg == 1)
        & ~is_term
    )
    is_head = ~absorbed
    return is_head, is_head.sum().astype(jnp.int32)


def _coalesced_dist(
    succ: jax.Array,
    r_pad: Optional[int] = None,
    use_pallas: bool = False,
) -> jax.Array:
    """Run-coalesced ranking (RANK_ALGO=coalesced): contract maximal
    successor runs into super-nodes, rank the contracted ring (weighted
    ruling set, or the weighted pallas kernel when use_pallas), then
    expand ranks back to tokens with one scatter + one cumsum — no
    per-token gather.

    `r_pad` is the STATIC contracted-ring budget.  The default (r_pad =
    m, rounded to lanes) is always safe (n_runs <= m) but saves only
    round count; callers that know their ring statistics (bench does,
    via rank_model.ring_stats) pass a tight budget for the full
    gather-row reduction.  OVERFLOW IS NOT DETECTED HERE: with
    r_pad < n_runs the result is garbage — callers passing a tight
    budget own the check (ring_run_heads / host ring_stats), exactly
    like the c_pad/n_chains contract of chain_contract_materialize_u."""
    m = succ.shape[0]
    r = r_pad if r_pad is not None else m
    r = max(128, -(-r // 128) * 128)
    tok = jnp.arange(m, dtype=jnp.int32)
    is_head, n_runs = ring_run_heads(succ)
    run_id = jnp.cumsum(is_head.astype(jnp.int32)) - 1  # token -> run
    # compact head/tail token tables [r] (+ sink slot r for the ruling
    # sub-rank: terminal runs edge to it, matching its dense-ring idiom)
    rid_clip = jnp.where(is_head, jnp.minimum(run_id, r), r)
    head_tok = (
        jnp.full(r + 1, 0, jnp.int32).at[rid_clip].set(tok, mode="drop")[:r]
    )
    ridx = jnp.arange(r, dtype=jnp.int32)
    valid_run = ridx < n_runs
    nxt_head = jnp.concatenate([head_tok[1:], jnp.array([m], jnp.int32)])
    tail_tok = jnp.where(ridx + 1 < n_runs, nxt_head, m) - 1
    tail_tok = jnp.where(valid_run, tail_tok, head_tok)
    succ_tail = succ[jnp.clip(tail_tok, 0, m - 1)]
    is_term_run = succ_tail == tail_tok
    w = jnp.where(
        valid_run,
        (tail_tok - head_tok) + jnp.where(is_term_run, 0, 1),
        0,
    ).astype(jnp.int32)
    t = jnp.where(
        valid_run & ~is_term_run,
        run_id[jnp.clip(succ_tail, 0, m - 1)],
        jnp.where(valid_run, r, ridx),  # terminal runs -> sink; pads self
    ).astype(jnp.int32)
    w1 = jnp.concatenate([w, jnp.zeros(1, jnp.int32)])
    t1 = jnp.concatenate([t, jnp.array([r], jnp.int32)])  # sink self-loop
    if use_pallas:
        from .pallas_rank import PALLAS_RANK_MAX_M, _LANES, wyllie_rank

        # contracted ring is r+1 tokens (sink slot): lane-pad must stay
        # within the VMEM cap (the default budget r = round128(m) makes
        # r+1 overflow it for m at the cap itself) — fall back to the
        # XLA weighted ruling rather than raise for a ring the
        # applicability gate approved
        if -(-(r + 1) // _LANES) * _LANES > PALLAS_RANK_MAX_M:
            use_pallas = False
    if use_pallas:
        # dist_bound = m: contracted distances are pre-contraction step
        # counts, so a short super-node ring from a long ring must still
        # take the wide (i32) kernel
        D = wyllie_rank(t1, weights=w1, dist_bound=m)[:r]
    else:
        D = _ruling_dist_from(w1, t1)[:r]
    # expansion: dist[tok] = D[run] - (tok - head_tok[run]); runs are
    # index intervals with ascending ids, so one telescoped scatter at
    # head tokens + a cumsum reconstructs D[run] + head_tok[run] per
    # token exactly (int32 wraparound-safe, same trick as
    # _place_by_chain_sort) — no per-token gather.
    val = jnp.where(valid_run, D + head_tok, 0)
    prev = jnp.concatenate([jnp.zeros(1, jnp.int32), val[:-1]])
    delta = jnp.where(valid_run, val - prev, 0)
    seg = (
        jnp.zeros(m + 1, jnp.int32)
        .at[jnp.where(valid_run, head_tok, m)]
        .add(delta, mode="drop")[:m]
    )
    return jnp.cumsum(seg) - tok


def fugue_order(cols: SeqColumns) -> jax.Array:
    """Return rank i32[N]: a key whose ascending order is the in-order
    position of each element in the Fugue traversal (keys may have gaps;
    pads get large keys).

    CONTRACT: rows must be pre-sorted by (peer, counter) — which the
    host extraction produces for free as per-peer concatenation, no
    comparison sort (SeqExtract.sort_by_peer_counter).  Sibling order is
    then one *stable* single-key sort by packed (parent, side), the only
    sort in the whole kernel."""
    return _order_core(cols.parent, cols.side, cols.valid)


def _resolve_rank_spec(rank_impl: Optional[str], m: int) -> Tuple[str, str]:
    """(backend, algo) for a ring of m tokens.  `rank_impl` accepts the
    legacy "pallas" / "xla" (algo from the PALLAS_RANK_ALGO / RANK_ALGO
    env) plus explicit "<backend>:<algo>" specs — phased bench runs and
    differential tests need several algorithms jitted in ONE process,
    and env knobs bake at trace time.  Precedence with rank_impl=None
    (auto): pallas when applicable and the XLA algo knob is untouched
    (an explicit RANK_ALGO keeps algo comparisons honest), but an
    explicit PALLAS_RANK=1 beats everything."""
    from ..errors import ConfigError
    from .pallas_rank import PALLAS_RANK_ALGOS, pallas_rank_applicable

    if rank_impl is not None and ":" in rank_impl:
        backend, algo = rank_impl.split(":", 1)
        ok = (backend == "xla" and algo in RANK_ALGOS) or (
            backend == "pallas" and algo in PALLAS_RANK_ALGOS + ("coalesced",)
        )
        if not ok:
            raise ValueError(
                f"rank_impl spec must be xla:{{{'|'.join(RANK_ALGOS)}}} or "
                f"pallas:{{{'|'.join(PALLAS_RANK_ALGOS + ('coalesced',))}}}, "
                f"got {rank_impl!r}"
            )
        return backend, algo
    if rank_impl == "pallas":
        from .pallas_rank import _pallas_rank_algo

        return "pallas", _pallas_rank_algo()
    if rank_impl == "xla":
        return "xla", _rank_algo()
    if rank_impl is not None:
        raise ValueError(
            f"rank_impl must be pallas|xla|<backend>:<algo>|None, got {rank_impl!r}"
        )
    algo = _rank_algo()
    explicit_pallas = os.environ.get("PALLAS_RANK", "") not in ("", "0")
    if pallas_rank_applicable(m) and (algo == "wyllie" or explicit_pallas):
        if algo == "coalesced":
            # coalesced + PALLAS_RANK=1: pallas sub-rank of the
            # contracted ring
            return "pallas", "coalesced"
        # the pallas kernel's own algo knob picks the kernel variant
        from .pallas_rank import _pallas_rank_algo

        return "pallas", _pallas_rank_algo()
    return "xla", algo


def _rank_dist(
    succ: jax.Array,
    backend: str,
    algo: str,
    ring_budget: Optional[int] = None,
) -> jax.Array:
    """Distance-to-terminal of a successor ring under a resolved
    (backend, algo) spec — the single ranking dispatch point."""
    if algo == "coalesced":
        return _coalesced_dist(succ, ring_budget, use_pallas=backend == "pallas")
    if backend == "pallas":
        from .pallas_rank import wyllie_rank

        return wyllie_rank(succ, algo=algo)
    if algo == "ruling":
        return _ruling_dist(succ)
    if algo == "blocked":
        return _blocked_dist(succ)
    return _wyllie_dist(succ)


def _ring_and_anchors(
    parent_in: jax.Array,
    side_in: jax.Array,
    valid_in: jax.Array,
    sib_keys: Optional[Tuple[jax.Array, ...]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(succ i32[2*(n+1)], anchor i32[n+1]) — the Euler-tour successor
    ring and each node's in-order anchor token (the virtual root at
    element index n).  Split from _order_core so tests can diff the
    in-jit ring against the host mirror (ops.rank_model.build_ring,
    which must stay in lockstep with this function)."""
    n = parent_in.shape[0]
    n1 = n + 1
    root = n  # virtual root element index
    big = jnp.int32(2**30)

    # -- extended element arrays incl. virtual root -------------------
    parent = jnp.concatenate([jnp.where(valid_in, parent_in, big), jnp.array([big], jnp.int32)])
    parent = parent.at[:n].set(jnp.where(valid_in & (parent_in < 0), root, parent[:n]))
    side = jnp.concatenate([side_in.astype(jnp.int32), jnp.array([1], jnp.int32)])
    valid = jnp.concatenate([valid_in, jnp.array([False])])  # root not a child

    key = jnp.where(parent < big, parent * 2 + side, big)
    if sib_keys is None:
        # ONE stable sort by (parent, side); (peer, counter) order within
        # groups comes from the input row-order contract
        order = jnp.argsort(key, stable=True).astype(jnp.int32)
    else:
        minor = [
            jnp.concatenate([k.astype(jnp.uint32), jnp.zeros(1, jnp.uint32)]) for k in sib_keys
        ]
        order = jnp.lexsort(tuple(reversed(minor)) + (key,)).astype(jnp.int32)
    p_s = parent[order]
    s_s = side[order]
    prev_same = (p_s == jnp.roll(p_s, 1)) & (s_s == jnp.roll(s_s, 1))
    prev_same = prev_same.at[0].set(False)
    is_first = ~prev_same
    nxt_same = (p_s == jnp.roll(p_s, -1)) & (s_s == jnp.roll(s_s, -1))
    nxt_same = nxt_same.at[-1].set(False)
    is_last = ~nxt_same
    elem_s = order  # element index at each sorted slot
    next_sib_s = jnp.where(nxt_same, jnp.roll(elem_s, -1), -1)

    # scatter: per element, its next sibling; per (parent, side): the
    # first child (ring entry) and last L-child (in-order anchor)
    next_sib = jnp.zeros(n1, jnp.int32).at[elem_s].set(next_sib_s.astype(jnp.int32))
    is_child = p_s < big  # this sorted slot is a real child row
    tgt_l = jnp.where(is_first & is_child & (s_s == 0), p_s, n1)  # n1 = dump slot
    tgt_r = jnp.where(is_first & is_child & (s_s == 1), p_s, n1)
    tgt_ll = jnp.where(is_last & is_child & (s_s == 0), p_s, n1)
    first_l = jnp.full(n1 + 1, -1, jnp.int32).at[tgt_l].set(elem_s.astype(jnp.int32))[:n1]
    first_r = jnp.full(n1 + 1, -1, jnp.int32).at[tgt_r].set(elem_s.astype(jnp.int32))[:n1]
    last_l = jnp.full(n1 + 1, -1, jnp.int32).at[tgt_ll].set(elem_s.astype(jnp.int32))[:n1]

    has_next_sib = next_sib >= 0
    has_l = first_l >= 0
    has_r = first_r >= 0

    # -- Euler-tour successor ring over 2 tokens per node -------------
    # (directed-edge tour; no VISIT token — see module docstring)
    # ENTER(e) -> ENTER(first_l[e])   if has_l
    #          -> ENTER(first_r[e])   elif has_r
    #          -> EXIT(e)             else
    # EXIT(e)  -> ENTER(next_sib[e])  if has_next_sib
    #          -> post_L(parent[e])   if last sibling and side==L
    #             (post_L(p) = ENTER(first_r[p]) if has_r[p] else EXIT(p))
    #          -> EXIT(parent[e])     if last sibling and side==R
    # EXIT(root) -> itself (ring terminal)
    #
    # TOKEN NUMBERING: tokens are numbered by sibling-sort SLOT, not by
    # element row — ENTER(e) = slot[e], EXIT(e) = m-1-slot[e].  Real
    # traces then put consecutive ring steps at consecutive token
    # indices (a leaf run ENTER(c1)..EXIT(ck) walks slots s, s+1, ...
    # on the way in and mirrored indices on the way out; invalid
    # elements all sort into one contiguous slot range and chain below)
    # — exactly the index-adjacency ring_run_heads contracts.  Any
    # bijective numbering yields the same ORDER (ranks are compared,
    # never interpreted), so correctness is layout-free.
    m = 2 * n1
    slot = jnp.zeros(n1, jnp.int32).at[order].set(jnp.arange(n1, dtype=jnp.int32))
    ent = slot  # [n1] token id of ENTER(e)
    ext = (m - 1) - slot  # [n1] token id of EXIT(e)
    e_ids = jnp.arange(n1, dtype=jnp.int32)
    post_l = jnp.where(has_r, ent[jnp.clip(first_r, 0, n)], ext[e_ids])  # [n1]
    succ_enter = jnp.where(has_l, ent[jnp.clip(first_l, 0, n)], post_l)
    par = jnp.where(parent < big, parent, root).astype(jnp.int32)
    succ_exit = jnp.where(
        has_next_sib,
        ent[jnp.clip(next_sib, 0, n)],
        jnp.where(side == 0, post_l[par], ext[par]),
    )
    succ_exit = succ_exit.at[root].set(ext[root])  # terminal self-loop
    # token layout: first half = ENTER tokens in slot order, second
    # half = EXIT tokens in REVERSE slot order (ext = m-1-slot)
    succ = jnp.concatenate(
        [succ_enter[order], jnp.flip(succ_exit[order])]
    ).astype(jnp.int32)

    # invalid elements: chain their tokens by index (one coalescable
    # run per contiguous range instead of per-token self-loops; their
    # distances are never read — ranks of invalid rows are overwritten
    # below).  The ring-proper tokens keep their successors.
    tok_valid = jnp.concatenate([valid[order], jnp.flip(valid[order])])
    tok_ids = jnp.arange(m, dtype=jnp.int32)
    chain_next = jnp.minimum(tok_ids + 1, m - 1)
    keep = tok_valid | (tok_ids == ext[root]) | (tok_ids == ent[root])
    succ = jnp.where(keep, succ, chain_next)
    # root tokens: ENTER is a valid ring member, EXIT the terminal
    succ = succ.at[ent[root]].set(succ_enter[root])
    succ = succ.at[ext[root]].set(ext[root])

    # in-order anchor: EXIT(last L-child) when L-children exist, else
    # the node's own ENTER; anchors are distinct tokens, so their ring
    # distances order elements exactly (larger distance = earlier)
    anchor = jnp.where(has_l, ext[jnp.clip(last_l, 0, n)], ent[e_ids])  # [n1]
    return succ, anchor


def _order_core(
    parent_in: jax.Array,
    side_in: jax.Array,
    valid_in: jax.Array,
    sib_keys: Optional[Tuple[jax.Array, ...]] = None,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> jax.Array:
    """Euler-tour in-order ranking over generic node arrays (element- or
    chain-level).  Without `sib_keys`, rows must obey the (peer, counter)
    order contract (fugue_order); with `sib_keys` (e.g. peer_hi, peer_lo,
    counter arrays) sibling order comes from an explicit lexsort instead
    — row order becomes irrelevant, which the incremental/append path
    needs (appended rows land at the end of the buffer)."""
    n = parent_in.shape[0]
    root = n
    big = jnp.int32(2**30)
    succ, anchor = _ring_and_anchors(parent_in, side_in, valid_in, sib_keys)

    # -- list ranking: distance to terminal ---------------------------
    backend, algo = _resolve_rank_spec(rank_impl, int(succ.shape[0]))
    dist = _rank_dist(succ, backend, algo, ring_budget)

    anchor_dist = dist[anchor]
    rank = anchor_dist[root] - anchor_dist[:n]  # monotone along the traversal
    # pads / unreachable: push to the end
    rank = jnp.where(valid_in, rank, big)
    return rank.astype(jnp.int32)


def visible_order(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """(perm, visible_count): perm[i] = element index of the i-th element
    in final order, with visible elements first in document order; count
    of visible elements."""
    rank = fugue_order(cols)
    visible = cols.valid & ~cols.deleted
    big = jnp.int32(2**30)
    key = jnp.where(visible, rank, big)  # visible first (stable argsort)
    perm = jnp.argsort(key, stable=True)
    return perm.astype(jnp.int32), visible.sum().astype(jnp.int32)


def _compact(rank: jax.Array, visible: jax.Array, content: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sort-free compaction shared by both element-table layouts: ranks
    are unique values < rank_bound(N) = 2*(N+1), so a scatter into an
    m-bucket histogram + exclusive cumsum yields each visible element's
    final position directly; invisible rows scatter out of range
    (dropped)."""
    n = rank.shape[0]
    m = rank_bound(n)
    rk = jnp.clip(rank, 0, m - 1)
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(visible, rk, m - 1)].add(
        visible.astype(jnp.int32)
    )
    pos_of_rank = jnp.cumsum(hist) - hist  # exclusive prefix sum
    pos = pos_of_rank[rk]
    count = visible.sum().astype(jnp.int32)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos, n)].set(
        content, mode="drop"
    )
    return codes, count


def materialize_content(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """Gather content codes of visible elements in document order.
    Returns (codes i32[N] with tail padding = -1, count)."""
    rank = fugue_order(cols)
    return _compact(rank, cols.valid & ~cols.deleted, cols.content)


class SeqColumnsU(NamedTuple):
    """Row-order-free element table for the incremental/append path:
    peers carried as explicit u64 halves so sibling order needs no
    batch-wide rank dictionary and appended rows may sit anywhere."""

    parent: jax.Array  # i32[N]
    side: jax.Array  # i32[N]
    peer_hi: jax.Array  # u32[N]
    peer_lo: jax.Array  # u32[N]
    counter: jax.Array  # i32[N] (non-negative)
    deleted: jax.Array  # bool[N]
    content: jax.Array  # i32[N]
    valid: jax.Array  # bool[N]


def fugue_order_u(cols: SeqColumnsU) -> jax.Array:
    return _order_core(
        cols.parent,
        cols.side,
        cols.valid,
        sib_keys=(cols.peer_hi, cols.peer_lo, cols.counter.astype(jnp.uint32)),
    )


def materialize_content_u(cols: SeqColumnsU) -> Tuple[jax.Array, jax.Array]:
    """Order + compact for the row-order-free table (content=-1 rows —
    anchors — are invisible)."""
    rank = fugue_order_u(cols)
    visible = cols.valid & ~cols.deleted & (cols.content >= 0)
    return _compact(rank, visible, cols.content)


materialize_content_u_batch = jax.vmap(materialize_content_u)


@jax.jit
def merge_docs_u(cols: SeqColumnsU) -> Tuple[jax.Array, jax.Array]:
    return materialize_content_u_batch(cols)


class ChainColumns(NamedTuple):
    """Chain-contracted document batch (see columnar.contract_chains):
    chain-level tree arrays [C] + element-level arrays [N]."""

    c_parent: jax.Array  # i32[C]
    c_side: jax.Array  # i32[C]
    c_valid: jax.Array  # bool[C]
    head_row: jax.Array  # i32[C]
    chain_id: jax.Array  # i32[N] element -> chain
    deleted: jax.Array  # bool[N]
    content: jax.Array  # i32[N]
    valid: jax.Array  # bool[N]


def _place_algo() -> str:
    """Element placement: "sort" (default — one stable sort; measured
    ~2x the scatter formulation on v5e, where random HBM access costs
    ~100M rows/s but a [8, 188k] sort is ~10 ms) or "scatter" (the
    histogram + gather + positional-scatter formulation).  Read at
    TRACE time: set it before the first merge call of the process
    (already-jitted kernels do not retrace on env changes)."""
    from ..errors import ConfigError

    algo = os.environ.get("PLACE_ALGO", "sort")
    if algo not in ("sort", "scatter"):
        raise ConfigError("PLACE_ALGO", algo, "sort|scatter")
    return algo


def _place_by_chain(
    crank: jax.Array,
    c_valid: jax.Array,
    chain_id: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
    content: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Shared element placement for both chain paths (PLACE_ALGO)."""
    if _place_algo() == "sort":
        return _place_by_chain_sort(crank, c_valid, head_row, visible, content)
    return _place_by_chain_scatter(crank, c_valid, chain_id, head_row, visible, content)


def chain_positions(
    crank: jax.Array,
    c_valid: jax.Array,
    chain_id: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Histogram placement core: (pos i32[N], count) where pos[row] =
    number of visible rows strictly before the row in final document
    order — defined for EVERY row (zero-width/deleted rows included;
    the richtext anchors need exactly that).  Chain base positions from
    a rank histogram + exclusive cumsum, within-chain offsets from row
    cumsums (chain rows are contiguous)."""
    c = crank.shape[0]
    n = chain_id.shape[0]
    vis_i = visible.astype(jnp.int32)
    cid = jnp.clip(chain_id, 0, c)  # dump slot c for pads/overflow
    w = jnp.zeros(c + 1, jnp.int32).at[cid].add(vis_i)[:c]
    m = rank_bound(c)
    rk = jnp.clip(crank, 0, m - 1)
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(c_valid, rk, m - 1)].add(
        jnp.where(c_valid, w, 0)
    )
    base_of_rank = jnp.cumsum(hist) - hist
    base = base_of_rank[rk]  # i32[C]
    row_excl = jnp.cumsum(vis_i) - vis_i
    head_excl = row_excl[jnp.clip(head_row, 0, n - 1)]  # i32[C]
    within = row_excl - head_excl[jnp.clip(chain_id, 0, c - 1)]
    pos = base[jnp.clip(chain_id, 0, c - 1)] + within
    count = vis_i.sum().astype(jnp.int32)
    return pos, count


def _place_by_chain_scatter(
    crank: jax.Array,
    c_valid: jax.Array,
    chain_id: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
    content: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Histogram placement (see chain_positions) + positional scatter of
    the content codes."""
    n = chain_id.shape[0]
    pos, count = chain_positions(crank, c_valid, chain_id, head_row, visible)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos, n)].set(
        content, mode="drop"
    )
    return codes, count


def _place_by_chain_sort(
    crank: jax.Array,
    c_valid: jax.Array,
    head_row: jax.Array,
    visible: jax.Array,
    content: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Sort placement: expand chain ranks to elements with a C-scatter
    of telescoping rank deltas at head rows + one N-cumsum (chain rows
    are contiguous and chain ids ascend with row, so the cumsum
    reconstructs crank[chain_id[row]] exactly, including int32
    wraparound), then ONE stable sort of (key, content) realizes the
    whole placement: ascending rank = document order, stability keeps
    within-chain row order.  Every invisible row (deleted, pad,
    overflow) gets the absolute max key so it sorts behind ALL visible
    rows and the first `count` sorted codes are exactly the document."""
    n = visible.shape[0]
    vis_i = visible.astype(jnp.int32)
    # invalid chains are trailing (both contraction paths), so the
    # telescoping prev of any valid chain is valid (or the 0 seed)
    prev = jnp.concatenate([jnp.zeros(1, crank.dtype), crank[:-1]])
    delta = jnp.where(c_valid, crank - prev, 0)
    seg = (
        jnp.zeros(n + 1, jnp.int32)
        .at[jnp.where(c_valid, head_row, n)]
        .add(delta, mode="drop")[:n]
    )
    crank_elem = jnp.cumsum(seg)
    key = jnp.where(
        visible, crank_elem.astype(jnp.uint32), jnp.uint32(0xFFFFFFFF)
    )
    _, content_sorted = jax.lax.sort((key, content), num_keys=1, is_stable=True)
    count = vis_i.sum().astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    codes = jnp.where(idx < count, content_sorted, jnp.int32(-1))
    return codes, count


def chain_materialize(
    cols: ChainColumns,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Merge via chain contraction: rank C chains (C << N), then place
    all N elements via _place_by_chain (default: rank expansion by
    C-scatter + N-cumsum, then one stable N-row sort; PLACE_ALGO=scatter
    selects the histogram + gather + positional-scatter formulation) —
    the gather-heavy ranking runs on the contracted tree only.
    `ring_budget` is the static coalesced-ring budget (see
    _coalesced_dist: callers passing a tight budget own the n_runs
    check; None is always safe).
    Returns (codes i32[N] padded with -1, visible count)."""
    c = cols.c_parent.shape[0]
    crank = _order_core(
        cols.c_parent,
        cols.c_side,
        cols.c_valid,
        rank_impl=rank_impl,
        ring_budget=ring_budget,
    )  # i32[C]
    visible = cols.valid & ~cols.deleted
    chain_id = jnp.where(cols.valid, cols.chain_id, c)
    return _place_by_chain(
        crank, cols.c_valid, chain_id, cols.head_row, visible, cols.content
    )


chain_materialize_batch = jax.vmap(chain_materialize)


def _tick_rank_obs(
    n_docs: int,
    n_nodes: int,
    rank_impl: Optional[str],
    ring_budget: Optional[int] = None,
) -> None:
    """rank.* obs counters (docs/OBSERVABILITY.md) from the analytic
    gather model — ticked at host-level jit entry points only (inside a
    trace the counts would be trace-time noise), with the caller's
    ring_budget and the live k/block knob values threaded through so
    budgeted/tuned runs are priced as scheduled.  Never raises: the
    merge path must not depend on the obs package."""
    try:
        m = 2 * (n_nodes + 1)
        backend, algo = _resolve_rank_spec(rank_impl, m)
        from ..obs import metrics as obs_m

        from .rank_model import gather_model

        kw = {}
        if algo == "coalesced":
            kw["r_pad"] = ring_budget
        if algo == "blocked":
            kw["block"] = _rank_block()
        if backend == "pallas" and algo in ("ruling", "blocked", "coalesced"):
            # coalesced's pallas sub-rank rides the same kernel knob
            kw["k"] = int(os.environ.get("PALLAS_RULING_K", "8"))
        mdl = gather_model(m, algo, **kw)
        label = f"{backend}:{algo}"
        obs_m.counter("rank.ring_tokens").inc(n_docs * m, algo=label)
        obs_m.counter("rank.rounds_total").inc(n_docs * mdl["rounds"], algo=label)
        obs_m.counter("rank.gather_rows_total").inc(
            n_docs * mdl["global_rows"], algo=label, kind="global"
        )
        if mdl.get("local_rows"):
            obs_m.counter("rank.gather_rows_total").inc(
                n_docs * mdl["local_rows"], algo=label, kind="local"
            )
    except Exception:  # tpulint: disable=LT-EXC(gather-ledger metrics are an estimate; accounting must never break the merge)
        pass


@jax.jit
def _chain_merge_docs_jit(cols: ChainColumns) -> Tuple[jax.Array, jax.Array]:
    return chain_materialize_batch(cols)


def chain_merge_docs(cols: ChainColumns) -> Tuple[jax.Array, jax.Array]:
    """One launch: chain-contracted merge for a doc batch ([D,C]/[D,N])."""
    _tick_rank_obs(cols.c_parent.shape[0], cols.c_parent.shape[1], None)
    return _chain_merge_docs_jit(cols)


def _weighted_checksum(codes: jax.Array) -> jax.Array:
    """Order-sensitive per-doc checksum of merged codes [D, N] -> [D]."""
    n = codes.shape[1]
    wgt = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)) % jnp.uint32(1 << 30)
    return ((jnp.where(codes >= 0, codes, 0).astype(jnp.uint32) * wgt[None, :]) % (1 << 30)).sum(
        axis=1, dtype=jnp.uint32
    )


@jax.jit
def _chain_merge_docs_checksum_jit(cols: ChainColumns) -> Tuple[jax.Array, jax.Array]:
    codes, counts = chain_materialize_batch(cols)
    return _weighted_checksum(codes), counts


def chain_merge_docs_checksum(cols: ChainColumns) -> Tuple[jax.Array, jax.Array]:
    _tick_rank_obs(cols.c_parent.shape[0], cols.c_parent.shape[1], None)
    return _chain_merge_docs_checksum_jit(cols)


@functools.partial(jax.jit, static_argnames=("rank_impl", "ring_budget"))
def _chain_merge_docs_v_jit(
    cols: ChainColumns,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    return jax.vmap(lambda c: chain_materialize(c, rank_impl, ring_budget))(cols)


def chain_merge_docs_v(
    cols: ChainColumns,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """chain_merge_docs with an explicit ranking implementation —
    phased bench runs measure several rank paths inside ONE process
    (env knobs bake at trace time, so this must be a static argument).
    `rank_impl` accepts "xla" / "pallas" or explicit "<backend>:<algo>"
    specs (e.g. "xla:coalesced"); `ring_budget` is the static
    coalesced-ring budget (caller-checked, see _coalesced_dist)."""
    _tick_rank_obs(cols.c_parent.shape[0], cols.c_parent.shape[1], rank_impl, ring_budget)
    return _chain_merge_docs_v_jit(cols, rank_impl, ring_budget)


@functools.partial(jax.jit, static_argnames=("rank_impl", "ring_budget"))
def _chain_merge_docs_checksum_v_jit(
    cols: ChainColumns,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    codes, counts = jax.vmap(lambda c: chain_materialize(c, rank_impl, ring_budget))(
        cols
    )
    return _weighted_checksum(codes), counts


def chain_merge_docs_checksum_v(
    cols: ChainColumns,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    _tick_rank_obs(cols.c_parent.shape[0], cols.c_parent.shape[1], rank_impl, ring_budget)
    return _chain_merge_docs_checksum_v_jit(cols, rank_impl, ring_budget)


@functools.partial(jax.jit, static_argnames=("rank_impl", "ring_budget"))
def chain_rank_checksum_v(
    cols: ChainColumns,
    rank_impl: Optional[str] = None,
    ring_budget: Optional[int] = None,
) -> jax.Array:
    """Ranking phase ONLY (scalar-reduced for cheap fetches): the
    measured-roofline bench phase times this against the full merge to
    split rank vs placement cost on chip."""

    def one(c: ChainColumns) -> jax.Array:
        crank = _order_core(
            c.c_parent,
            c.c_side,
            c.c_valid,
            rank_impl=rank_impl,
            ring_budget=ring_budget,
        )
        return crank.astype(jnp.uint32).sum(dtype=jnp.uint32)

    return jax.vmap(one)(cols)


# ---- packed single-buffer transport (ingest pipeline) ----------------
# The e2e pipeline ships one chunk as ONE contiguous u8 buffer instead
# of 8 separate device_puts with loose dtypes: per-put tunnel overhead
# disappears and the byte-tight layout (u16 chain ids, u8 flags) is
# ~1.3x smaller than the i32 ChainColumns transport.  Layout per doc
# row (little-endian, matching both x86 hosts and TPU bitcast):
#   [0        : 2C)        c_parent  u16   (0xFFFF == -1 root)
#   [2C       : 2C+2N)     chain_id  u16   (pad rows carry 0; the dump
#                                           remap to pad_c happens
#                                           on-device via the valid mask)
#   [..       : +4C)       head_row  i32
#   [..       : +4N)       content   i32   (-1 == invisible)
#   [..       : +C)        c_side    u8
#   [..       : +C)        c_valid   u8
#   [..       : +N)        deleted   u8
#   [..       : +N)        valid     u8
# Total 8C + 8N bytes.  Requires pad_c < 0xFFFF.


def packed_row_bytes(pad_c: int, pad_n: int) -> int:
    assert pad_c < 0xFFFF, "u16 chain ids need pad_c < 65535"
    return 8 * pad_c + 8 * pad_n


def pack_chain_doc_into(cols: ChainColumns, out_row: np.ndarray) -> None:
    """Serialize one doc's numpy ChainColumns into a packed u8 row
    (shape [packed_row_bytes(C, N)]); the inverse of the in-jit
    unpack in chain_merge_docs_packed."""
    c = cols.c_parent.shape[0]
    n = cols.chain_id.shape[0]
    assert out_row.dtype == np.uint8 and out_row.shape[0] == packed_row_bytes(c, n)
    o = 0

    def sec(nbytes):
        nonlocal o
        s = out_row[o : o + nbytes]
        o += nbytes
        return s

    sec(2 * c).view("<u2")[:] = cols.c_parent.astype(np.int32).astype(np.uint16)
    sec(2 * n).view("<u2")[:] = cols.chain_id.astype(np.int32).astype(np.uint16)
    sec(4 * c).view("<i4")[:] = cols.head_row.astype(np.int32)
    sec(4 * n).view("<i4")[:] = cols.content.astype(np.int32)
    sec(c)[:] = cols.c_side.astype(np.uint8)
    sec(c)[:] = cols.c_valid.astype(np.uint8)
    sec(n)[:] = cols.deleted.astype(np.uint8)
    sec(n)[:] = cols.valid.astype(np.uint8)
    assert o == out_row.shape[0]


def _unpack_chain_batch(packed: jax.Array, pad_c: int, pad_n: int) -> ChainColumns:
    """In-jit inverse of pack_chain_doc_into ([D, W] u8 -> ChainColumns)."""
    d = packed.shape[0]
    c, n = pad_c, pad_n
    offs = [0]
    for nbytes in (2 * c, 2 * n, 4 * c, 4 * n, c, c, n, n):
        offs.append(offs[-1] + nbytes)

    def sec(i):
        return packed[:, offs[i] : offs[i + 1]]

    def u16(i, count):
        return jax.lax.bitcast_convert_type(
            sec(i).reshape(d, count, 2), jnp.uint16
        ).astype(jnp.int32)

    def i32(i, count):
        return jax.lax.bitcast_convert_type(sec(i).reshape(d, count, 4), jnp.int32)

    cp = u16(0, c)
    return ChainColumns(
        c_parent=jnp.where(cp == 0xFFFF, -1, cp),
        c_side=sec(4).astype(jnp.int32),
        c_valid=sec(5).astype(bool),
        head_row=i32(2, c),
        chain_id=u16(1, n),
        deleted=sec(6).astype(bool),
        content=i32(3, n),
        valid=sec(7).astype(bool),
    )


@functools.partial(jax.jit, static_argnums=(1, 2))
def chain_merge_docs_packed(packed: jax.Array, pad_c: int, pad_n: int):
    """One launch: unpack the u8 transport buffer + chain merge."""
    return chain_materialize_batch(_unpack_chain_batch(packed, pad_c, pad_n))


@functools.partial(jax.jit, static_argnums=(1, 2))
def chain_merge_docs_packed_checksum(packed: jax.Array, pad_c: int, pad_n: int):
    codes, counts = chain_materialize_batch(_unpack_chain_batch(packed, pad_c, pad_n))
    return _weighted_checksum(codes), counts


def chain_contract_materialize_u(
    cols: SeqColumnsU, c_pad: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Device-side chain contraction + order + compaction for the
    row-order-free layout (the resident-batch path).

    Chains (right-spine runs, columnar.contract_chains conditions) are
    detected on device: row i links to row i-1 iff parent==i-1, side=R,
    row i-1 has exactly one child and no L-children, and row i has no
    L-children.  Cross-epoch runs simply stay split (appended rows are
    only adjacent within their block) — correctness is unaffected, the
    contraction is just slightly less aggressive.

    `c_pad` is the static chain budget; returns (codes, count,
    n_chains).  When n_chains > c_pad the output is INVALID and the
    caller must retry with a bigger budget (DeviceDocBatch does)."""
    n = cols.parent.shape[0]
    valid = cols.valid
    pgt = jnp.clip(cols.parent, 0, n - 1)
    has_parent = valid & (cols.parent >= 0)
    cc = jnp.zeros(n, jnp.int32).at[jnp.where(has_parent, pgt, n - 1)].add(
        has_parent.astype(jnp.int32)
    )
    is_l = has_parent & (cols.side == 0)
    lc = jnp.zeros(n, jnp.int32).at[jnp.where(is_l, pgt, n - 1)].add(is_l.astype(jnp.int32))

    idx = jnp.arange(n, dtype=jnp.int32)
    prev_ok = jnp.concatenate([jnp.zeros(1, bool), valid[:-1]])
    link = (
        valid
        & prev_ok
        & (cols.parent == idx - 1)
        & (cols.side == 1)
        & (jnp.roll(cc, 1) == 1)
        & (jnp.roll(lc, 1) == 0)
        & (lc == 0)
    )
    link = link.at[0].set(False)
    is_head = valid & ~link
    chain_id = jnp.cumsum(is_head.astype(jnp.int32)) - 1  # per valid row
    chain_id = jnp.where(valid, chain_id, c_pad)  # pads -> dump
    n_chains = is_head.sum().astype(jnp.int32)

    cid_clip = jnp.clip(chain_id, 0, c_pad)
    # chain-level attributes scattered from head rows (chain_id is the
    # compact index — no sort needed)
    def head_scatter(src, fill):
        return jnp.full(c_pad + 1, fill, src.dtype).at[
            jnp.where(is_head, cid_clip, c_pad)
        ].set(src, mode="drop")[:c_pad]

    head_row = head_scatter(idx, 0)
    c_parent_row = head_scatter(jnp.where(cols.parent >= 0, cols.parent, -1), -1)
    c_parent = jnp.where(
        c_parent_row >= 0, chain_id[jnp.clip(c_parent_row, 0, n - 1)], -1
    ).astype(jnp.int32)
    c_side = head_scatter(cols.side.astype(jnp.int32), 0)
    c_hi = head_scatter(cols.peer_hi, 0)
    c_lo = head_scatter(cols.peer_lo, 0)
    c_ctr = head_scatter(cols.counter.astype(jnp.uint32), 0)
    c_valid = jnp.arange(c_pad) < n_chains

    crank = _order_core(
        c_parent, c_side, c_valid, sib_keys=(c_hi, c_lo, c_ctr)
    )  # [c_pad]

    visible = valid & ~cols.deleted & (cols.content >= 0)
    codes, count = _place_by_chain(
        crank, c_valid, chain_id, head_row, visible, cols.content
    )
    return codes, count, n_chains


@functools.partial(jax.jit, static_argnums=(1,))
def _chain_merge_docs_u_jit(cols: SeqColumnsU, c_pad: int):
    return jax.vmap(lambda c: chain_contract_materialize_u(c, c_pad))(cols)


def chain_merge_docs_u(cols: SeqColumnsU, c_pad: int):
    _tick_rank_obs(cols.parent.shape[0], c_pad, None)
    return _chain_merge_docs_u_jit(cols, c_pad)


@jax.jit
def materialize_by_key(cols: SeqColumnsU, key_hi, key_lo):
    """Visible content from standing order keys (incremental path):
    one multi-key sort by (key_hi, key_lo) replaces the rank solve —
    the host ShadowOrder (parallel/order_maintenance.py) guarantees
    ascending key == Fugue traversal order.  [D, N] -> (codes, counts)
    with the same contract as chain_merge_docs_u."""
    d, n = cols.content.shape
    inf = jnp.uint32(0xFFFFFFFF)
    hi = jnp.where(cols.valid, key_hi, inf)
    lo = jnp.where(cols.valid, key_lo, inf)
    visible = cols.valid & ~cols.deleted & (cols.content >= 0)
    _hi_s, _lo_s, content_s, vis_s = jax.lax.sort(
        (hi, lo, cols.content, visible.astype(jnp.int32)), dimension=1, num_keys=2
    )
    vis_s = vis_s.astype(bool)
    pos = jnp.cumsum(vis_s.astype(jnp.int32), axis=1) - 1
    counts = vis_s.sum(axis=1)
    target = jnp.where(vis_s, pos, n)  # invisible rows -> dump column
    out = jnp.full((d, n + 1), -1, cols.content.dtype)
    d_idx = jnp.broadcast_to(jnp.arange(d)[:, None], (d, n))
    out = out.at[d_idx, target].set(content_s, mode="drop")
    return out[:, :n], counts


# batched-over-documents variants --------------------------------------
fugue_order_batch = jax.vmap(fugue_order)
visible_order_batch = jax.vmap(visible_order)
materialize_content_batch = jax.vmap(materialize_content)

# jitted single-doc entry (one compilation per padded size — callers
# should bucket-pad N, e.g. to powers of two)
materialize_content_jit = jax.jit(materialize_content)


def pad_bucket(n: int, floor: int = 64) -> int:
    """Next power-of-two bucket >= n (bounds XLA recompilations)."""
    b = floor
    while b < n:
        b *= 2
    return b


def pad_seq_columns(cols: SeqColumns, n: int) -> SeqColumns:
    """Pad numpy SeqColumns to n rows (invalid tail)."""

    def pad(a, fill):
        if a.shape[0] == n:
            return a
        out = np.full(n, fill, a.dtype)
        out[: a.shape[0]] = a
        return out

    return SeqColumns(
        parent=pad(cols.parent, -1),
        side=pad(cols.side, 0),
        peer=pad(cols.peer, 0),
        counter=pad(cols.counter, 0),
        deleted=pad(cols.deleted, True),
        content=pad(cols.content, -1),
        valid=pad(cols.valid, False),
    )


@functools.partial(jax.jit, donate_argnums=())
def _merge_docs_jit(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    return materialize_content_batch(cols)


def merge_docs(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """One XLA launch: resolve order + materialize visible content for a
    whole batch of documents.  cols arrays are [D, N]."""
    _tick_rank_obs(cols.parent.shape[0], cols.parent.shape[1], None)
    return _merge_docs_jit(cols)


@jax.jit
def _merge_docs_checksum_jit(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    codes, counts = materialize_content_batch(cols)
    n = codes.shape[1]
    w = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)) % jnp.uint32(1 << 30)
    cs = ((jnp.where(codes >= 0, codes, 0).astype(jnp.uint32) * w[None, :]) % (1 << 30)).sum(
        axis=1, dtype=jnp.uint32
    )
    return cs, counts


def merge_docs_checksum(cols: SeqColumns) -> Tuple[jax.Array, jax.Array]:
    """Merge but return only a per-doc order-sensitive checksum [D] +
    counts [D].  Used by benchmarks: the merged state stays device-
    resident (the fleet model); only O(D) scalars cross the host link."""
    _tick_rank_obs(cols.parent.shape[0], cols.parent.shape[1], None)
    return _merge_docs_checksum_jit(cols)
