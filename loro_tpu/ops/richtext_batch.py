"""Batched rich-text merge kernel: text order + style resolution.

reference semantics: the Peritext-style style anchors of
crates/loro-internal/src/container/richtext (StyleAnchor rope elements,
style_range_map.rs): a (start, end) anchor pair styles the characters
between them; per key the winning pair covering a char is the one with
max (lamport, peer); value None = unstyled.

Device formulation: anchors ride the same Fugue order kernel as chars
(zero-width).  With P pairs per doc, anchor positions induce <= 2P+1
constant-style regions; winners resolve as masked maxima over the
[P, R, K] cover tensor — tiny dense work after the big order solve.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .fugue_batch import (
    ChainColumns,
    SeqColumns,
    _order_core,
    chain_positions,
    fugue_order,
    rank_bound,
)

NEG = jnp.int32(-(2**31) + 1)


def _resolve_styles(
    pair_valid, pair_key, pair_value, pair_lamport, pair_peer, a_start, a_end, count, n_keys
):
    """Shared style-winner resolution from anchor char-positions.

    Winner per (region, key) = covering pair with max (lamport, peer) —
    the host tuple comparison (text_state._resolve_attrs).  Pairs get a
    dense i32 priority (rank in (lamport, peer) order via a tiny P-row
    lexsort; the tuple is unique per pair, so max priority IS the
    lexicographic winner).  Each pair covers a CONTIGUOUS run of
    regions (lo/hi are sorted), so winners resolve as range-chmax of
    priorities on an iterative segment forest (one subtree per style
    key, <= 2 node updates per pair per level) + per-leaf ancestor-max
    queries: O((P + K R) log R) work, replacing the dense [P, R, K]
    masked-max passes that dominated the richtext merge (measured ~5x
    the rest of the kernel combined, on CPU and in the byte model).

    Returns (bounds i32[2P+2], win_value i32[2P+1, n_keys])."""
    p = pair_valid.shape[0]
    bounds = jnp.sort(jnp.concatenate([a_start, a_end]))  # [2P]
    lo = jnp.concatenate([jnp.zeros(1, jnp.int32), bounds])  # [2P+1]
    hi = jnp.concatenate([bounds, count[None].astype(jnp.int32)])
    out_bounds = jnp.concatenate([lo, hi[-1:]])
    r_count = 2 * p + 1
    if p == 0:
        return out_bounds, jnp.full((r_count, n_keys), -1, jnp.int32)
    order = jnp.lexsort((pair_peer, pair_lamport))  # ascending (lam, peer)
    prio = jnp.zeros(p, jnp.int32).at[order].set(jnp.arange(p, dtype=jnp.int32))

    # pair i covers exactly the contiguous region run [r_lo_i, r_hi_i):
    # lo/hi are sorted, so {r : a_start_i <= lo[r]} is a suffix and
    # {r : a_end_i >= hi[r]} a prefix.  Range-chmax the pair's priority
    # over its run on an iterative segment tree (<= 2 nodes per level),
    # then point-query each (region, key): O((P + K R) log R) total work
    # instead of the dense [P, R] cover relation.
    r_lo = jnp.searchsorted(lo, a_start, side="left").astype(jnp.int32)
    r_hi = jnp.searchsorted(hi, a_end, side="right").astype(jnp.int32)
    r_lo = jnp.where(pair_valid, r_lo, 0)
    r_hi = jnp.where(pair_valid, r_hi, 0)
    s = 1
    while s < r_count:
        s *= 2
    levels = s.bit_length()  # node depth of the size-s tree
    key_c = jnp.clip(pair_key, 0, n_keys - 1)
    base = key_c * (2 * s)  # per-key subtree offset in the flat forest
    tree_size = n_keys * 2 * s
    tree = jnp.full(tree_size + 1, -1, jnp.int32)  # +1 dump slot
    lcur = r_lo + s
    rcur = r_hi + s
    for _ in range(levels):
        upd_l = ((lcur & 1) == 1) & (lcur < rcur)
        tree = tree.at[jnp.where(upd_l, base + lcur, tree_size)].max(
            jnp.where(upd_l, prio, -1), mode="drop"
        )
        lcur = lcur + upd_l
        upd_r = ((rcur & 1) == 1) & (lcur < rcur)
        rcur = rcur - upd_r
        tree = tree.at[jnp.where(upd_r, base + rcur, tree_size)].max(
            jnp.where(upd_r, prio, -1), mode="drop"
        )
        lcur = lcur >> 1
        rcur = rcur >> 1
    pos = jnp.arange(r_count, dtype=jnp.int32) + s  # leaf ids [R]
    kbase = (jnp.arange(n_keys, dtype=jnp.int32) * (2 * s))[:, None]
    win_prio = jnp.full((n_keys, r_count), -1, jnp.int32)
    lev = pos[None, :]
    for _ in range(levels):
        win_prio = jnp.maximum(win_prio, tree[kbase + lev])
        lev = lev >> 1
    win_pair = order[jnp.clip(win_prio, 0, p - 1)]
    win_value = jnp.where(win_prio >= 0, pair_value[win_pair], -1)  # [K, R]
    # empty regions (lo >= hi) style nothing — match the dense cover's
    # (lo < hi) conjunct
    win_value = jnp.where((lo < hi)[None, :], win_value, -1)
    return out_bounds, win_value.T  # [R, K]


class RichtextCols(NamedTuple):
    """[N] element rows (chars: content = codepoint; anchors: content=-1)
    + [P] anchor-pair rows."""

    seq: SeqColumns
    pair_start: jax.Array  # i32[P] element row of the start anchor
    pair_end: jax.Array  # i32[P] element row of the end anchor
    pair_key: jax.Array  # i32[P] style-key index
    pair_value: jax.Array  # i32[P] value index; -1 = null (unmark)
    pair_lamport: jax.Array
    pair_peer: jax.Array
    pair_valid: jax.Array  # bool[P] (False for pads / deleted anchors)


def richtext_merge_doc(
    cols: RichtextCols, n_keys: int
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (codes i32[N] in order (-1 pad tail), char count,
    region boundaries i32[2P+2] (ascending char positions, padded with
    count), winner value idx i32[2P+1, n_keys] (-1 = unstyled))."""
    seq = cols.seq
    n = seq.parent.shape[0]
    p = cols.pair_start.shape[0]
    rank = fugue_order(seq)
    m = rank_bound(n)
    rk = jnp.clip(rank, 0, m - 1)
    is_char = seq.content >= 0
    visible = seq.valid & ~seq.deleted & is_char
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(visible, rk, m - 1)].add(
        visible.astype(jnp.int32)
    )
    pos_of_rank = jnp.cumsum(hist) - hist
    pos = pos_of_rank[rk]
    count = visible.sum().astype(jnp.int32)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos, n)].set(
        seq.content, mode="drop"
    )

    # anchor char-positions (chars before the anchor in final order).
    # pair_end < 0 = end anchor deleted while the start lives: the host
    # walk never pops the active entry, so the style runs to EOF
    ps = jnp.clip(cols.pair_start, 0, n - 1)
    pe = jnp.clip(cols.pair_end, 0, n - 1)
    a_start = jnp.where(cols.pair_valid, pos[ps], count)
    a_end = jnp.where(cols.pair_valid & (cols.pair_end >= 0), pos[pe], count)

    bounds, win_value = _resolve_styles(
        cols.pair_valid,
        cols.pair_key,
        cols.pair_value,
        cols.pair_lamport,
        cols.pair_peer,
        a_start,
        a_end,
        count,
        n_keys,
    )
    return codes, count, bounds, win_value


@functools.partial(jax.jit, static_argnums=(1,))
def richtext_merge_batch(cols: RichtextCols, n_keys: int):
    return jax.vmap(lambda c: richtext_merge_doc(c, n_keys))(cols)


class RichtextChainCols(NamedTuple):
    """Chain-contracted richtext batch: the gather-heavy ranking runs on
    the contracted chain tree (C << N — char runs contract exactly like
    the flagship text path), while anchors/deleted chars keep per-row
    positions via one stable N-row sort."""

    chain: ChainColumns
    pair_start: jax.Array  # i32[P] element row of the start anchor
    pair_end: jax.Array
    pair_key: jax.Array
    pair_value: jax.Array
    pair_lamport: jax.Array
    pair_peer: jax.Array
    pair_valid: jax.Array


def richtext_chain_merge_doc(
    cols: RichtextChainCols, n_keys: int
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Chain-contracted richtext merge: rank C chains (not N elements —
    char runs contract exactly as in the flagship text kernel), then
    realize every row's char-position with the histogram placement
    (chain-rank histogram + cumsum for chain bases, row-cumsum for
    within-chain offsets) — positions exist for ALL rows, so zero-width
    anchors get theirs for free.  Output contract matches
    richtext_merge_doc."""
    ch = cols.chain
    c = ch.c_parent.shape[0]
    n = ch.chain_id.shape[0]
    crank = _order_core(ch.c_parent, ch.c_side, ch.c_valid)  # i32[C]
    is_char = ch.content >= 0
    visible = ch.valid & ~ch.deleted & is_char
    cid = jnp.where(ch.valid, ch.chain_id, c)
    pos_row, count = chain_positions(crank, ch.c_valid, cid, ch.head_row, visible)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos_row, n)].set(
        ch.content, mode="drop"
    )
    # pair_end < 0 = deleted end anchor -> style runs to EOF (host walk)
    ps = jnp.clip(cols.pair_start, 0, n - 1)
    pe = jnp.clip(cols.pair_end, 0, n - 1)
    a_start = jnp.where(cols.pair_valid, pos_row[ps], count)
    a_end = jnp.where(cols.pair_valid & (cols.pair_end >= 0), pos_row[pe], count)
    bounds, win_value = _resolve_styles(
        cols.pair_valid,
        cols.pair_key,
        cols.pair_value,
        cols.pair_lamport,
        cols.pair_peer,
        a_start,
        a_end,
        count,
        n_keys,
    )
    return codes, count, bounds, win_value


@functools.partial(jax.jit, static_argnums=(1,))
def richtext_chain_merge_batch(cols: RichtextChainCols, n_keys: int):
    return jax.vmap(lambda c: richtext_chain_merge_doc(c, n_keys))(cols)


class RichtextPairs(NamedTuple):
    """Anchor-pair table for the RESIDENT richtext path ([D, P] device
    rows into a SeqColumnsU buffer; see DeviceDocBatch.richtexts)."""

    start: jax.Array  # i32[P] device row of the start anchor
    end: jax.Array
    key: jax.Array  # i32[P] batch-uniform style-key index
    value: jax.Array  # i32[P] per-doc value ordinal; -1 = null (unmark)
    lamport: jax.Array
    peer: jax.Array  # i32[P] per-doc peer rank (order-isomorphic to id)
    valid: jax.Array


def _richtext_by_key_doc(cols, key_hi, key_lo, pairs: RichtextPairs, n_keys: int):
    """Resident richtext materialization: ONE stable multi-key sort by
    the standing ShadowOrder keys realizes the text AND every row's
    char-position (anchors are zero-width rows needing positions), then
    styles resolve on the segment forest.  The incremental analog of
    richtext_chain_merge_doc — no rank solve, order work happened on
    ingest (O(delta))."""
    n = cols.content.shape[0]
    inf = jnp.uint32(0xFFFFFFFF)
    hi = jnp.where(cols.valid, key_hi, inf)
    lo = jnp.where(cols.valid, key_lo, inf)
    visible = cols.valid & ~cols.deleted & (cols.content >= 0)
    vis_i = visible.astype(jnp.int32)
    row_idx = jnp.arange(n, dtype=jnp.int32)
    _, _, vis_s, row_s, content_s = jax.lax.sort(
        (hi, lo, vis_i, row_idx, cols.content), num_keys=2, is_stable=True
    )
    pos_s = jnp.cumsum(vis_s) - vis_s
    count = vis_i.sum().astype(jnp.int32)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(vis_s == 1, pos_s, n)].set(
        content_s, mode="drop"
    )
    pos_row = jnp.zeros(n, jnp.int32).at[row_s].set(pos_s)
    # end < 0 = deleted end anchor -> style runs to EOF (host walk)
    ps = jnp.clip(pairs.start, 0, n - 1)
    pe = jnp.clip(pairs.end, 0, n - 1)
    a_start = jnp.where(pairs.valid, pos_row[ps], count)
    a_end = jnp.where(pairs.valid & (pairs.end >= 0), pos_row[pe], count)
    bounds, win_value = _resolve_styles(
        pairs.valid,
        pairs.key,
        pairs.value,
        pairs.lamport,
        pairs.peer,
        a_start,
        a_end,
        count,
        n_keys,
    )
    return codes, count, bounds, win_value


@functools.partial(jax.jit, static_argnums=(4,))
def richtext_by_key_batch(cols, key_hi, key_lo, pairs: RichtextPairs, n_keys: int):
    return jax.vmap(
        lambda c, h, lo_, p: _richtext_by_key_doc(c, h, lo_, p, n_keys)
    )(cols, key_hi, key_lo, pairs)


def segments_from_device(codes, count, bounds, win, keys, values):
    """Reconstruct Quill-style [{insert, attributes?}] segments from one
    doc's device outputs — the comparison form against the host's
    TextState.get_richtext_value() (differential tests + bench gates)."""
    count = int(count)
    text = "".join(chr(c) for c in np.asarray(codes)[:count])
    bounds = np.asarray(bounds)
    win = np.asarray(win)
    segs = []
    for r in range(len(bounds) - 1):
        lo, hi = int(bounds[r]), int(bounds[r + 1])
        if lo >= hi:
            continue
        attrs = {}
        for k in range(len(keys)):
            vi = int(win[r, k])
            if vi >= 0:
                attrs[keys[k]] = values[vi]
        seg = {"insert": text[lo:hi]}
        if attrs:
            seg["attributes"] = attrs
        if segs and segs[-1].get("attributes") == seg.get("attributes"):
            segs[-1]["insert"] += seg["insert"]
        else:
            segs.append(seg)
    return segs


def _explode_richtext(changes, cid):
    """Host: explode a Text container (chars + anchors) into a
    SeqExtract (anchors carry content=-1) + pair arrays + (keys,
    values).  Pairing invariant: a start anchor at id (p, c) pairs with
    the end anchor (p, c+1) (TextHandler.mark emits exactly that)."""
    from ..core.change import SeqDelete, SeqInsert, StyleAnchor
    from ..oplog.oplog import _RunCont

    peers_seen = sorted({ch.peer for ch in changes})
    peer_rank = {pr: i for i, pr in enumerate(peers_seen)}
    rows = []  # (parent, side, peer_rank, counter, content)
    id2row = {}
    keys, key_idx = [], {}
    values = []
    anchors = {}  # (peer, counter) -> dict
    deletes = []

    def kidx(k):
        if k not in key_idx:
            key_idx[k] = len(keys)
            keys.append(k)
        return key_idx[k]

    for ch in changes:
        for op in ch.ops:
            if op.container != cid:
                continue
            c = op.content
            lam = ch.lamport + (op.counter - ch.ctr_start)
            if isinstance(c, SeqInsert):
                if isinstance(c.parent, _RunCont):
                    pidx = id2row[(ch.peer, op.counter - 1)]
                elif c.parent is None:
                    pidx = -1
                else:
                    pidx = id2row[(c.parent.peer, c.parent.counter)]
                if isinstance(c.content, StyleAnchor):
                    a = c.content
                    row = len(rows)
                    id2row[(ch.peer, op.counter)] = row
                    rows.append((pidx, int(c.side), peer_rank[ch.peer], op.counter, -1))
                    if a.value is None:
                        vi = -1
                    else:
                        vi = len(values)
                        values.append(a.value)
                    anchors[(ch.peer, op.counter)] = {
                        "row": row,
                        "key": kidx(a.key),
                        "value": vi,
                        "lamport": lam,
                        "peer": peer_rank[ch.peer],
                        "start": a.is_start,
                        "deleted": False,
                    }
                else:
                    for j, chr_ in enumerate(c.content):
                        row = len(rows)
                        id2row[(ch.peer, op.counter + j)] = row
                        rows.append(
                            (
                                pidx if j == 0 else row - 1,
                                int(c.side) if j == 0 else 1,
                                peer_rank[ch.peer],
                                op.counter + j,
                                ord(chr_),
                            )
                        )
            elif isinstance(c, SeqDelete):
                for sp in c.spans:
                    deletes.append((sp.peer, sp.start, sp.end))

    n = len(rows)
    arr = np.asarray(rows, np.int64).reshape(n, 5) if n else np.zeros((0, 5), np.int64)
    deleted = np.zeros(n, bool)
    for peer, start, end in deletes:
        for ctr in range(start, end):
            i = id2row.get((peer, ctr))
            if i is not None:
                deleted[i] = True
                a = anchors.get((peer, ctr))
                if a is not None:
                    a["deleted"] = True
    from .columnar import SeqExtract, peer_counter_perm

    perm, inv, parent = peer_counter_perm(arr[:, 2], arr[:, 3], arr[:, 0])
    ex = SeqExtract(
        parent=parent.astype(np.int32),
        side=arr[perm, 1].astype(np.int32),
        peer=arr[perm, 2].astype(np.int32),
        counter=arr[perm, 3].astype(np.int32),
        deleted=deleted[perm],
        content=arr[perm, 4].astype(np.int32),
        valid=np.ones(n, bool),
        peers=peers_seen,
    )
    # pairs: start anchor (p,c) + end anchor (p,c+1).  Host-walk
    # semantics (_iter_char_attrs): a pair is active iff its START
    # anchor is live; a deleted END anchor never pops the active entry,
    # so the style runs to EOF — encoded as end row -1
    pairs = []
    for (peer, ctr), a in anchors.items():
        if not a["start"]:
            continue
        end = anchors.get((peer, ctr + 1))
        if end is None or end["start"]:
            continue  # unpaired (mid-transfer); inactive
        pairs.append(
            (
                inv[a["row"]],
                -1 if end["deleted"] else inv[end["row"]],
                a["key"],
                a["value"],
                a["lamport"],
                a["peer"],
                not a["deleted"],
            )
        )
    pp = len(pairs)
    parr = np.asarray(pairs, np.int64).reshape(pp, 7) if pp else np.zeros((0, 7), np.int64)
    return ex, parr, keys, values


def _pair_fields(parr: np.ndarray) -> dict:
    return dict(
        pair_start=parr[:, 0].astype(np.int32),
        pair_end=parr[:, 1].astype(np.int32),
        pair_key=parr[:, 2].astype(np.int32),
        pair_value=parr[:, 3].astype(np.int32),
        pair_lamport=parr[:, 4].astype(np.int32),
        pair_peer=parr[:, 5].astype(np.int32),
        pair_valid=parr[:, 6].astype(bool),
    )


def extract_richtext(changes, cid):
    """Host: RichtextCols (numpy) + (keys, values) — the uncontracted
    element-level kernel input (kept as the differential second
    implementation; the fleet/bench path is extract_richtext_chain)."""
    ex, parr, keys, values = _explode_richtext(changes, cid)
    return (
        RichtextCols(seq=ex.to_seq_columns(), **_pair_fields(parr)),
        keys,
        values,
    )


def pad_richtext_chain_cols(
    cols: RichtextChainCols, pad_n: int, pad_c: int, pad_p: int
) -> RichtextChainCols:
    """Pad numpy RichtextChainCols to uniform (N, C, P) device shapes."""

    def pad(a, size, fill):
        if a.shape[0] >= size:
            return a
        out = np.full(size, fill, a.dtype)
        out[: a.shape[0]] = a
        return out

    ch = cols.chain
    chain = ChainColumns(
        c_parent=pad(ch.c_parent, pad_c, -1),
        c_side=pad(ch.c_side, pad_c, 0),
        c_valid=pad(ch.c_valid, pad_c, False),
        head_row=pad(ch.head_row, pad_c, 0),
        chain_id=pad(ch.chain_id, pad_n, 0),
        deleted=pad(ch.deleted, pad_n, True),
        content=pad(ch.content, pad_n, -1),
        valid=pad(ch.valid, pad_n, False),
    )
    return RichtextChainCols(
        chain=chain,
        pair_start=pad(cols.pair_start, pad_p, 0),
        pair_end=pad(cols.pair_end, pad_p, 0),
        pair_key=pad(cols.pair_key, pad_p, 0),
        pair_value=pad(cols.pair_value, pad_p, -1),
        pair_lamport=pad(cols.pair_lamport, pad_p, 0),
        pair_peer=pad(cols.pair_peer, pad_p, 0),
        pair_valid=pad(cols.pair_valid, pad_p, False),
    )


def extract_richtext_chain(changes, cid):
    """Host: chain-contracted RichtextChainCols (numpy) + (keys, values)
    — ranking cost scales with chain count C, not element count N.
    Pad to device shapes with pad_richtext_chain_cols."""
    from .columnar import chain_columns

    ex, parr, keys, values = _explode_richtext(changes, cid)
    return (
        RichtextChainCols(chain=chain_columns(ex), **_pair_fields(parr)),
        keys,
        values,
    )
