"""Batched rich-text merge kernel: text order + style resolution.

reference semantics: the Peritext-style style anchors of
crates/loro-internal/src/container/richtext (StyleAnchor rope elements,
style_range_map.rs): a (start, end) anchor pair styles the characters
between them; per key the winning pair covering a char is the one with
max (lamport, peer); value None = unstyled.

Device formulation: anchors ride the same Fugue order kernel as chars
(zero-width).  With P pairs per doc, anchor positions induce <= 2P+1
constant-style regions; winners resolve as masked maxima over the
[P, R, K] cover tensor — tiny dense work after the big order solve.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .fugue_batch import SeqColumns, fugue_order, rank_bound

NEG = jnp.int32(-(2**31) + 1)


class RichtextCols(NamedTuple):
    """[N] element rows (chars: content = codepoint; anchors: content=-1)
    + [P] anchor-pair rows."""

    seq: SeqColumns
    pair_start: jax.Array  # i32[P] element row of the start anchor
    pair_end: jax.Array  # i32[P] element row of the end anchor
    pair_key: jax.Array  # i32[P] style-key index
    pair_value: jax.Array  # i32[P] value index; -1 = null (unmark)
    pair_lamport: jax.Array
    pair_peer: jax.Array
    pair_valid: jax.Array  # bool[P] (False for pads / deleted anchors)


def richtext_merge_doc(
    cols: RichtextCols, n_keys: int
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (codes i32[N] in order (-1 pad tail), char count,
    region boundaries i32[2P+2] (ascending char positions, padded with
    count), winner value idx i32[2P+1, n_keys] (-1 = unstyled))."""
    seq = cols.seq
    n = seq.parent.shape[0]
    p = cols.pair_start.shape[0]
    rank = fugue_order(seq)
    m = rank_bound(n)
    rk = jnp.clip(rank, 0, m - 1)
    is_char = seq.content >= 0
    visible = seq.valid & ~seq.deleted & is_char
    hist = jnp.zeros(m, jnp.int32).at[jnp.where(visible, rk, m - 1)].add(
        visible.astype(jnp.int32)
    )
    pos_of_rank = jnp.cumsum(hist) - hist
    pos = pos_of_rank[rk]
    count = visible.sum().astype(jnp.int32)
    codes = jnp.full(n, -1, jnp.int32).at[jnp.where(visible, pos, n)].set(
        seq.content, mode="drop"
    )

    # anchor char-positions (chars before the anchor in final order)
    ps = jnp.clip(cols.pair_start, 0, n - 1)
    pe = jnp.clip(cols.pair_end, 0, n - 1)
    a_start = jnp.where(cols.pair_valid, pos[ps], count)
    a_end = jnp.where(cols.pair_valid, pos[pe], count)

    # region boundaries: sorted anchor positions, 0 and count implicit
    bounds = jnp.sort(jnp.concatenate([a_start, a_end]))  # [2P]
    lo = jnp.concatenate([jnp.zeros(1, jnp.int32), bounds])  # [2P+1]
    hi = jnp.concatenate([bounds, count[None].astype(jnp.int32)])

    # cover[i, r]: pair i styles region r (non-empty regions only matter)
    cover = (
        cols.pair_valid[:, None]
        & (a_start[:, None] <= lo[None, :])
        & (a_end[:, None] >= hi[None, :])
        & (lo[None, :] < hi[None, :])
    )  # [P, R]
    key_onehot = (
        cols.pair_key[:, None] == jnp.arange(n_keys, dtype=jnp.int32)[None, :]
    )  # [P, K]
    mask = cover[:, :, None] & key_onehot[:, None, :]  # [P, R, K]
    # winner = max (lamport, peer) — two overflow-free passes, matching
    # the host's tuple comparison (text_state._resolve_attrs) for any
    # lamport / peer-rank magnitudes
    win_lam = jnp.max(jnp.where(mask, cols.pair_lamport[:, None, None], NEG), axis=0)
    at_lam = mask & (cols.pair_lamport[:, None, None] == win_lam[None, :, :])
    win_peer = jnp.max(jnp.where(at_lam, cols.pair_peer[:, None, None], NEG), axis=0)
    is_winner = at_lam & (cols.pair_peer[:, None, None] == win_peer[None, :, :])
    win_value = jnp.max(
        jnp.where(is_winner, cols.pair_value[:, None, None], -1), axis=0
    )  # [R, K]; stays -1 when no cover or null value
    styled = win_lam > NEG
    win_value = jnp.where(styled, win_value, -1)
    return codes, count, jnp.concatenate([lo, hi[-1:]]), win_value


@functools.partial(jax.jit, static_argnums=(1,))
def richtext_merge_batch(cols: RichtextCols, n_keys: int):
    return jax.vmap(lambda c: richtext_merge_doc(c, n_keys))(cols)


def segments_from_device(codes, count, bounds, win, keys, values):
    """Reconstruct Quill-style [{insert, attributes?}] segments from one
    doc's device outputs — the comparison form against the host's
    TextState.get_richtext_value() (differential tests + bench gates)."""
    count = int(count)
    text = "".join(chr(c) for c in np.asarray(codes)[:count])
    bounds = np.asarray(bounds)
    win = np.asarray(win)
    segs = []
    for r in range(len(bounds) - 1):
        lo, hi = int(bounds[r]), int(bounds[r + 1])
        if lo >= hi:
            continue
        attrs = {}
        for k in range(len(keys)):
            vi = int(win[r, k])
            if vi >= 0:
                attrs[keys[k]] = values[vi]
        seg = {"insert": text[lo:hi]}
        if attrs:
            seg["attributes"] = attrs
        if segs and segs[-1].get("attributes") == seg.get("attributes"):
            segs[-1]["insert"] += seg["insert"]
        else:
            segs.append(seg)
    return segs


def extract_richtext(changes, cid):
    """Host: explode a Text container (chars + anchors) into
    RichtextCols (numpy) + (keys list, values list).  Pairing invariant:
    a start anchor at id (p, c) pairs with the end anchor (p, c+1)
    (TextHandler.mark emits exactly that)."""
    from ..core.change import SeqDelete, SeqInsert, StyleAnchor
    from ..oplog.oplog import _RunCont

    peers_seen = sorted({ch.peer for ch in changes})
    peer_rank = {pr: i for i, pr in enumerate(peers_seen)}
    rows = []  # (parent, side, peer_rank, counter, content)
    id2row = {}
    keys, key_idx = [], {}
    values = []
    anchors = {}  # (peer, counter) -> dict
    deletes = []

    def kidx(k):
        if k not in key_idx:
            key_idx[k] = len(keys)
            keys.append(k)
        return key_idx[k]

    for ch in changes:
        for op in ch.ops:
            if op.container != cid:
                continue
            c = op.content
            lam = ch.lamport + (op.counter - ch.ctr_start)
            if isinstance(c, SeqInsert):
                if isinstance(c.parent, _RunCont):
                    pidx = id2row[(ch.peer, op.counter - 1)]
                elif c.parent is None:
                    pidx = -1
                else:
                    pidx = id2row[(c.parent.peer, c.parent.counter)]
                if isinstance(c.content, StyleAnchor):
                    a = c.content
                    row = len(rows)
                    id2row[(ch.peer, op.counter)] = row
                    rows.append((pidx, int(c.side), peer_rank[ch.peer], op.counter, -1))
                    if a.value is None:
                        vi = -1
                    else:
                        vi = len(values)
                        values.append(a.value)
                    anchors[(ch.peer, op.counter)] = {
                        "row": row,
                        "key": kidx(a.key),
                        "value": vi,
                        "lamport": lam,
                        "peer": peer_rank[ch.peer],
                        "start": a.is_start,
                        "deleted": False,
                    }
                else:
                    for j, chr_ in enumerate(c.content):
                        row = len(rows)
                        id2row[(ch.peer, op.counter + j)] = row
                        rows.append(
                            (
                                pidx if j == 0 else row - 1,
                                int(c.side) if j == 0 else 1,
                                peer_rank[ch.peer],
                                op.counter + j,
                                ord(chr_),
                            )
                        )
            elif isinstance(c, SeqDelete):
                for sp in c.spans:
                    deletes.append((sp.peer, sp.start, sp.end))

    n = len(rows)
    arr = np.asarray(rows, np.int64).reshape(n, 5) if n else np.zeros((0, 5), np.int64)
    deleted = np.zeros(n, bool)
    for peer, start, end in deletes:
        for ctr in range(start, end):
            i = id2row.get((peer, ctr))
            if i is not None:
                deleted[i] = True
                a = anchors.get((peer, ctr))
                if a is not None:
                    a["deleted"] = True
    from .columnar import peer_counter_perm

    perm, inv, parent = peer_counter_perm(arr[:, 2], arr[:, 3], arr[:, 0])
    seq = SeqColumns(
        parent=parent.astype(np.int32),
        side=arr[perm, 1].astype(np.int32),
        peer=arr[perm, 2].astype(np.int32),
        counter=arr[perm, 3].astype(np.int32),
        deleted=deleted[perm],
        content=arr[perm, 4].astype(np.int32),
        valid=np.ones(n, bool),
    )
    # pairs: start anchor (p,c) + end anchor (p,c+1)
    pairs = []
    for (peer, ctr), a in anchors.items():
        if not a["start"]:
            continue
        end = anchors.get((peer, ctr + 1))
        if end is None or end["start"]:
            continue  # unpaired (mid-transfer); inactive
        active = not a["deleted"] and not end["deleted"]
        pairs.append(
            (
                inv[a["row"]],
                inv[end["row"]],
                a["key"],
                a["value"],
                a["lamport"],
                a["peer"],
                active,
            )
        )
    pp = len(pairs)
    parr = np.asarray(pairs, np.int64).reshape(pp, 7) if pp else np.zeros((0, 7), np.int64)
    cols = RichtextCols(
        seq=seq,
        pair_start=parr[:, 0].astype(np.int32),
        pair_end=parr[:, 1].astype(np.int32),
        pair_key=parr[:, 2].astype(np.int32),
        pair_value=parr[:, 3].astype(np.int32),
        pair_lamport=parr[:, 4].astype(np.int32),
        pair_peer=parr[:, 5].astype(np.int32),
        pair_valid=parr[:, 6].astype(bool),
    )
    return cols, keys, values
