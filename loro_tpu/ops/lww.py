"""Batched LWW-map merge + counter-sum kernels.

The device equivalents of MapDiffCalculator (reference diff_calc.rs:
515-538: keep max (lamport, peer) per key) and CounterState.  A whole
batch of documents' map ops merges in one launch: three scatter-max
passes over (doc, slot) cells — no sorting, no host loop.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

NEG = jnp.int32(-(2**31) + 1)


class MapOpCols(NamedTuple):
    """[D, M] per-doc padded op columns (see columnar.MapExtract)."""

    slot: jax.Array  # i32 (doc-local slot id in [0, S))
    lamport: jax.Array
    peer: jax.Array
    value_idx: jax.Array
    valid: jax.Array  # bool


def lww_merge_doc(cols: MapOpCols, n_slots: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-doc LWW: winner per slot.

    Returns (value_idx i32[S] (-2 = slot untouched, -1 = deleted),
    win_lamport i32[S], win_peer i32[S])."""
    slot = jnp.where(cols.valid, cols.slot, n_slots)  # pads -> dump slot
    # pass 1: max lamport per slot
    lam = jnp.where(cols.valid, cols.lamport, NEG)
    win_lam = jnp.full(n_slots + 1, NEG, jnp.int32).at[slot].max(lam)
    # pass 2: among max-lamport ops, max peer
    at_max = cols.valid & (cols.lamport == win_lam[slot])
    peer = jnp.where(at_max, cols.peer, NEG)
    win_peer = jnp.full(n_slots + 1, NEG, jnp.int32).at[slot].max(peer)
    # pass 3: the unique winner's value (op ids are unique per
    # (slot, lamport, peer), so exactly one op matches)
    is_win = at_max & (cols.peer == win_peer[slot])
    val = jnp.where(is_win, cols.value_idx, NEG)
    win_val = jnp.full(n_slots + 1, NEG, jnp.int32).at[slot].max(val)
    untouched = win_lam[:n_slots] == NEG
    value_idx = jnp.where(untouched, -2, win_val[:n_slots])
    return value_idx, win_lam[:n_slots], win_peer[:n_slots]


def counter_merge_doc(slot: jax.Array, delta: jax.Array, valid: jax.Array, n_slots: int) -> jax.Array:
    """Sum deltas per (doc-local) counter slot: f32[S]."""
    s = jnp.where(valid, slot, n_slots)
    d = jnp.where(valid, delta, 0.0)
    return jnp.zeros(n_slots + 1, jnp.float32).at[s].add(d)[:n_slots]


@functools.partial(jax.jit, static_argnums=(1,))
def lww_merge_batch(cols: MapOpCols, n_slots: int):
    """[D, M] op columns -> per-doc winners [D, S] in one launch."""
    return jax.vmap(lambda c: lww_merge_doc(c, n_slots))(cols)


@functools.partial(jax.jit, static_argnums=(3,))
def counter_merge_batch(slot, delta, valid, n_slots: int):
    return jax.vmap(lambda s, d, v: counter_merge_doc(s, d, v, n_slots))(slot, delta, valid)
