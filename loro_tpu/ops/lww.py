"""Batched LWW-map merge + counter-sum kernels.

The device equivalents of MapDiffCalculator (reference diff_calc.rs:
515-538: keep max (lamport, peer) per key) and CounterState.  A whole
batch of documents' map ops merges in one launch: three scatter-max
passes over (doc, slot) cells — no sorting, no host loop.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

NEG = jnp.int32(-(2**31) + 1)


class MapOpCols(NamedTuple):
    """[D, M] per-doc padded op columns (see columnar.MapExtract)."""

    slot: jax.Array  # i32 (doc-local slot id in [0, S))
    lamport: jax.Array
    peer: jax.Array
    value_idx: jax.Array
    valid: jax.Array  # bool


def lww_merge_doc(cols: MapOpCols, n_slots: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-doc LWW: winner per slot.

    Returns (value_idx i32[S] (-2 = slot untouched, -1 = deleted),
    win_lamport i32[S], win_peer i32[S])."""
    slot = jnp.where(cols.valid, cols.slot, n_slots)  # pads -> dump slot
    # pass 1: max lamport per slot
    lam = jnp.where(cols.valid, cols.lamport, NEG)
    win_lam = jnp.full(n_slots + 1, NEG, jnp.int32).at[slot].max(lam)
    # pass 2: among max-lamport ops, max peer
    at_max = cols.valid & (cols.lamport == win_lam[slot])
    peer = jnp.where(at_max, cols.peer, NEG)
    win_peer = jnp.full(n_slots + 1, NEG, jnp.int32).at[slot].max(peer)
    # pass 3: the unique winner's value (op ids are unique per
    # (slot, lamport, peer), so exactly one op matches)
    is_win = at_max & (cols.peer == win_peer[slot])
    val = jnp.where(is_win, cols.value_idx, NEG)
    win_val = jnp.full(n_slots + 1, NEG, jnp.int32).at[slot].max(val)
    untouched = win_lam[:n_slots] == NEG
    value_idx = jnp.where(untouched, -2, win_val[:n_slots])
    return value_idx, win_lam[:n_slots], win_peer[:n_slots]


def counter_merge_doc(slot: jax.Array, delta: jax.Array, valid: jax.Array, n_slots: int) -> jax.Array:
    """Sum deltas per (doc-local) counter slot: f32[S]."""
    s = jnp.where(valid, slot, n_slots)
    d = jnp.where(valid, delta, 0.0)
    return jnp.zeros(n_slots + 1, jnp.float32).at[s].add(d)[:n_slots]


@functools.partial(jax.jit, static_argnums=(1,))
def lww_merge_batch(cols: MapOpCols, n_slots: int):
    """[D, M] op columns -> per-doc winners [D, S] in one launch."""
    return jax.vmap(lambda c: lww_merge_doc(c, n_slots))(cols)


@functools.partial(jax.jit, static_argnums=(3,))
def counter_merge_batch(slot, delta, valid, n_slots: int):
    return jax.vmap(lambda s, d, v: counter_merge_doc(s, d, v, n_slots))(slot, delta, valid)


def make_lww_sharded(mesh, n_slots: int):
    """Op-axis-sharded LWW merge (SURVEY.md §2.4 item 2: "sequence
    parallelism" for very large imports).  Each (docs, ops) shard
    computes per-slot partial winners with the same three scatter-max
    passes as lww_merge_doc; partials combine across the ops axis with
    three pmax collectives over the lexicographic (lamport, peer,
    value) order.  Returns a jitted fn: MapOpCols [D, M] sharded
    P(docs, ops) -> (value_idx, lamport, peer) [D, S] P(docs)."""
    from jax.sharding import PartitionSpec as P

    try:  # jax >= 0.8
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    from ..parallel.mesh import DOC_AXIS, OP_AXIS

    def local(cols: MapOpCols):
        def per_doc(c: MapOpCols):
            v, l, p = lww_merge_doc(c, n_slots)
            return v, l, p

        val, lam, peer = jax.vmap(per_doc)(cols)
        # cross-shard lexicographic argmax, one field at a time
        g_lam = jax.lax.pmax(lam, OP_AXIS)
        peer_c = jnp.where(lam == g_lam, peer, NEG)
        g_peer = jax.lax.pmax(peer_c, OP_AXIS)
        val_c = jnp.where((lam == g_lam) & (peer == g_peer), val, jnp.int32(-2))
        g_val = jax.lax.pmax(val_c, OP_AXIS)
        g_val = jnp.where(g_lam == NEG, -2, g_val)
        return g_val, g_lam, g_peer

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(MapOpCols(*([P(DOC_AXIS, OP_AXIS)] * 5)),),
            out_specs=(P(DOC_AXIS), P(DOC_AXIS), P(DOC_AXIS)),
        )
    )


class LwwResident(NamedTuple):
    """Device-resident per-(doc, slot) LWW winners.  Peers as u64 halves
    so no batch-wide rank dictionary is needed (append path)."""

    lamport: jax.Array  # i32[D, S]; NEG = slot untouched
    peer_hi: jax.Array  # u32[D, S]
    peer_lo: jax.Array  # u32[D, S]
    value: jax.Array  # i32[D, S]; -1 = deleted, -2 = untouched


def _blk_winners(slot, lam, hi, lo, val, valid, n_slots: int):
    """Per-slot winners of one op block (four scatter-max passes over
    the (lamport, peer_hi, peer_lo) order)."""
    s = jnp.where(valid, slot, n_slots)
    l = jnp.where(valid, lam, NEG)
    w_l = jnp.full(n_slots + 1, NEG, jnp.int32).at[s].max(l)
    at_l = valid & (lam == w_l[s])
    # peers compare as unsigned u32 halves; sentinel 0 is safe for max
    # because every slot with w_l > NEG has >= 1 candidate
    h = jnp.where(at_l, hi, jnp.uint32(0))
    w_h = jnp.zeros(n_slots + 1, jnp.uint32).at[jnp.where(at_l, s, n_slots)].max(h)
    at_h = at_l & (hi == w_h[s])
    lo_c = jnp.where(at_h, lo, jnp.uint32(0))
    w_lo = jnp.zeros(n_slots + 1, jnp.uint32).at[jnp.where(at_h, s, n_slots)].max(lo_c)
    is_win = at_h & (lo == w_lo[s])
    w_v = jnp.full(n_slots + 1, -2, jnp.int32).at[jnp.where(is_win, s, n_slots)].max(
        jnp.where(is_win, val, -2)
    )
    return w_l[:n_slots], w_h[:n_slots], w_lo[:n_slots], w_v[:n_slots]


@functools.partial(jax.jit, static_argnums=(6,), donate_argnums=(0,))
def lww_update_resident(
    res: LwwResident, slot, lam, hi, lo_, valid, n_slots: int, value=None
) -> LwwResident:
    """Fold one append block into the resident winners (donated update).
    `value` rides as the last arg for jit-arity reasons."""

    def per_doc(r_lam, r_hi, r_lo, r_val, b_slot, b_lam, b_hi, b_lo, b_val, b_valid):
        w_l, w_h, w_lo, w_v = _blk_winners(b_slot, b_lam, b_hi, b_lo, b_val, b_valid, n_slots)
        blk_newer = (w_l > r_lam) | (
            (w_l == r_lam) & ((w_h > r_hi) | ((w_h == r_hi) & (w_lo > r_lo)))
        )
        take = blk_newer & (w_l > NEG)
        return (
            jnp.where(take, w_l, r_lam),
            jnp.where(take, w_h, r_hi),
            jnp.where(take, w_lo, r_lo),
            jnp.where(take, w_v, r_val),
        )

    out = jax.vmap(per_doc)(
        res.lamport, res.peer_hi, res.peer_lo, res.value, slot, lam, hi, lo_, value, valid
    )
    return LwwResident(*out)
