"""Host-side columnar (SoA) extraction: change lists -> padded device
arrays.

The analog of the reference's columnar block decode
(crates/loro-internal/src/oplog/change_store/block_encode.rs) feeding
the merge engine: ops are exploded into per-element / per-atom columns
that the device kernels consume directly.  numpy only — this is the
host pipeline stage that overlaps with device compute in the fleet.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.change import Change, MapSet, SeqDelete, SeqInsert, StyleAnchor
from ..core.ids import ContainerID
from ..oplog.oplog import _RunCont
from .fugue_batch import SeqColumns


@dataclass
class SeqExtract:
    """Numpy element table for one container's full history."""

    parent: np.ndarray  # i32[N], -1 root
    side: np.ndarray  # i32[N]
    peer: np.ndarray  # i32[N] peer rank
    counter: np.ndarray  # i32[N]
    deleted: np.ndarray  # bool[N]
    content: np.ndarray  # i32[N] codepoint (text) or value index
    valid: np.ndarray  # bool[N]
    peers: List[int]  # rank -> peer id dictionary (sorted)
    values: Optional[List] = None  # value dictionary for list payloads

    @property
    def n(self) -> int:
        return int(self.parent.shape[0])

    def sort_by_peer_counter(self) -> "SeqExtract":
        """Reorder rows to (peer, counter) order and remap parent indices
        — the input contract of ops.fugue_batch.fugue_order (lets the
        device do a single stable sort).  Ordering plumbing (incl. the
        radix fast path for causally-ordered rows) is shared with the
        other extractors via peer_counter_perm."""
        perm, _inv, parent = peer_counter_perm(
            self.peer, self.counter, self.parent
        )
        return SeqExtract(
            parent=parent,
            side=self.side[perm],
            peer=self.peer[perm],
            counter=self.counter[perm],
            deleted=self.deleted[perm],
            content=self.content[perm],
            valid=self.valid[perm],
            peers=self.peers,
            values=self.values,
        )

    def to_seq_columns(self, pad_to: Optional[int] = None) -> SeqColumns:
        n = self.n if pad_to is None else pad_to
        assert n >= self.n

        def pad(a, fill):
            if n == a.shape[0]:
                return a
            out = np.full(n, fill, a.dtype)
            out[: a.shape[0]] = a
            return out

        return SeqColumns(
            parent=pad(self.parent, -1),
            side=pad(self.side, 0),
            peer=pad(self.peer, 0),
            counter=pad(self.counter, 0),
            deleted=pad(self.deleted, True),
            content=pad(self.content, -1),
            valid=pad(self.valid, False),
        )


def extract_seq_container(
    changes: Sequence[Change], cid: ContainerID, as_text: bool = True
) -> SeqExtract:
    """Explode all SeqInsert/SeqDelete ops targeting `cid` (causal order)
    into an element table.  Anchors and movable-list machinery are out of
    scope here (plain text/list payloads)."""
    peers_seen = sorted({ch.peer for ch in changes})
    peer_rank = {p: i for i, p in enumerate(peers_seen)}
    parents: List[int] = []
    sides: List[int] = []
    peers: List[int] = []
    counters: List[int] = []
    contents: List[int] = []
    values: List = []
    id2idx: Dict[Tuple[int, int], int] = {}
    deletes: List[Tuple[int, int, int]] = []  # (peer, start, end)

    for ch in changes:
        for op in ch.ops:
            if op.container != cid:
                continue
            c = op.content
            if isinstance(c, SeqInsert):
                if isinstance(c.content, StyleAnchor):
                    continue
                body = c.content
                for j in range(len(body)):
                    if j == 0:
                        if isinstance(c.parent, _RunCont):
                            pkey = (ch.peer, op.counter - 1)
                            pidx = id2idx[pkey]
                        elif c.parent is None:
                            pidx = -1
                        else:
                            pidx = id2idx[(c.parent.peer, c.parent.counter)]
                        side = int(c.side)
                    else:
                        pidx = len(parents) - 1
                        side = 1
                    idx = len(parents)
                    id2idx[(ch.peer, op.counter + j)] = idx
                    parents.append(pidx)
                    sides.append(side)
                    peers.append(peer_rank[ch.peer])
                    counters.append(op.counter + j)
                    if as_text:
                        contents.append(ord(body[j]))
                    else:
                        contents.append(len(values))
                        values.append(body[j])
            elif isinstance(c, SeqDelete):
                for s in c.spans:
                    deletes.append((s.peer, s.start, s.end))

    n = len(parents)
    deleted = np.zeros(n, bool)
    for peer, start, end in deletes:
        for ctr in range(start, end):
            idx = id2idx.get((peer, ctr))
            if idx is not None:
                deleted[idx] = True
    return SeqExtract(
        parent=np.asarray(parents, np.int32),
        side=np.asarray(sides, np.int32),
        peer=np.asarray(peers, np.int32),
        counter=np.asarray(counters, np.int32),
        deleted=deleted,
        content=np.asarray(contents, np.int32),
        valid=np.ones(n, bool),
        peers=peers_seen,
        values=values if not as_text else None,
    ).sort_by_peer_counter()


@dataclass
class MapExtract:
    """Columns for batched LWW map merge: one row per MapSet atom."""

    slot: np.ndarray  # i32[M] (container,key) slot index
    lamport: np.ndarray  # i32[M]
    peer: np.ndarray  # i32[M] peer rank
    value_idx: np.ndarray  # i32[M]
    valid: np.ndarray  # bool[M]
    slots: List[Tuple[ContainerID, str]]  # slot dictionary
    values: List  # value dictionary (index -1 = deletion)
    peers: List[int]


def extract_map_ops(changes: Sequence[Change]) -> MapExtract:
    peers_seen = sorted({ch.peer for ch in changes})
    peer_rank = {p: i for i, p in enumerate(peers_seen)}
    slot_of: Dict[Tuple[ContainerID, str], int] = {}
    slots: List[Tuple[ContainerID, str]] = []
    values: List = []
    rows: List[Tuple[int, int, int, int]] = []
    for ch in changes:
        for op in ch.ops:
            c = op.content
            if not isinstance(c, MapSet):
                continue
            key = (op.container, c.key)
            if key not in slot_of:
                slot_of[key] = len(slots)
                slots.append(key)
            lam = ch.lamport + (op.counter - ch.ctr_start)
            if c.deleted:
                vi = -1
            else:
                vi = len(values)
                values.append(c.value)
            rows.append((slot_of[key], lam, peer_rank[ch.peer], vi))
    m = len(rows)
    arr = np.asarray(rows, np.int64).reshape(m, 4) if m else np.zeros((0, 4), np.int64)
    return MapExtract(
        slot=arr[:, 0].astype(np.int32),
        lamport=arr[:, 1].astype(np.int32),
        peer=arr[:, 2].astype(np.int32),
        value_idx=arr[:, 3].astype(np.int32),
        valid=np.ones(m, bool),
        slots=slots,
        values=values,
        peers=peers_seen,
    )


def peer_counter_perm(peer: np.ndarray, counter: np.ndarray, parent: np.ndarray):
    """Shared (peer, counter)-ordering plumbing for extractors: returns
    (perm, inv, remapped_parent) where parent indexes are rewritten
    through the permutation (the fugue_order input contract); `inv` maps
    old row -> new row for remapping any other row references.

    Fast path: causally-ordered inputs already have counters ascending
    within each peer in row order, so a single-key stable radix argsort
    by peer suffices (measured 1.6 ms vs 7.0 ms for the two-key lexsort
    on the 182k-row trace); the post-condition is verified vectorized
    and falls back to the full lexsort for arbitrary row orders."""
    n = len(peer)
    if n == 0:
        perm = np.zeros(0, np.int64)
    else:
        perm = np.argsort(peer, kind="stable")
        if n > 1:
            ctr_s = counter[perm].astype(np.int64)
            peer_s = peer[perm].astype(np.int64)
            if not ((np.diff(ctr_s) > 0) | (np.diff(peer_s) != 0)).all():
                perm = np.lexsort((counter, peer))
    inv = np.empty(n, np.int64)
    inv[perm] = np.arange(n)
    out_parent = np.asarray(parent)[perm].astype(np.int64)
    mask = out_parent >= 0
    out_parent[mask] = inv[out_parent[mask]]
    return perm, inv, out_parent.astype(np.int32)


def wire_peer_ranks(peers_wire) -> np.ndarray:
    """rank_of[wire_idx] -> sorted-u64 peer rank (the LWW/sibling
    tie-break ordering contract; wire registration order must not
    leak)."""
    peer_u64 = np.asarray(peers_wire, np.uint64)
    rank_of = np.empty(len(peers_wire), np.int64)
    rank_of[np.argsort(peer_u64, kind="stable")] = np.arange(len(peers_wire))
    return rank_of


def pack_wire_ids(peer_idx, ctr) -> np.ndarray:
    """(wire peer idx, counter) packed into i64 for vectorized id
    dictionaries (peer indexes are small; counters non-negative)."""
    return (np.asarray(peer_idx, np.int64) << 32) | np.asarray(ctr, np.int64)


def extract_seq_from_payload(payload: bytes, cid: ContainerID) -> Optional[SeqExtract]:
    """Native-decoder fast path: binary updates payload -> SeqExtract
    without materializing Python Change objects (the fleet ingest path;
    ~1000x the Python explode loop).  Returns None when the native
    library is unavailable; raises ValueError on malformed payloads."""
    from ..codec.binary import read_tables
    from ..native import available, explode_seq_payload

    if not available():
        return None
    peers, _keys, cids, _r = read_tables(payload)
    try:
        target = cids.index(cid)
    except ValueError:
        return SeqExtract(
            parent=np.zeros(0, np.int32),
            side=np.zeros(0, np.int32),
            peer=np.zeros(0, np.int32),
            counter=np.zeros(0, np.int32),
            deleted=np.zeros(0, bool),
            content=np.zeros(0, np.int32),
            valid=np.zeros(0, bool),
            peers=[],
        )
    out = explode_seq_payload(payload, target)
    if out is None:
        return None
    parent, side, peer_idx, counter, deleted, content = out
    # wire peer table is registration-ordered; the kernel contract needs
    # order-preserving ranks of the sorted u64 peer ids
    order = np.argsort(np.asarray(peers, np.uint64), kind="stable")
    rank_of = np.empty(len(peers), np.int32)
    rank_of[order] = np.arange(len(peers), dtype=np.int32)
    peer_rank = rank_of[peer_idx] if len(peers) else peer_idx
    return SeqExtract(
        parent=parent,
        side=side,
        peer=peer_rank.astype(np.int32),
        counter=counter,
        deleted=deleted,
        content=content,
        valid=np.ones(parent.shape[0], bool),
        peers=sorted(peers),
    ).sort_by_peer_counter()


def pad_rows(a: np.ndarray, n: int, fill) -> np.ndarray:
    if a.shape[0] == n:
        return a
    out = np.full((n,) + a.shape[1:], fill, a.dtype)
    out[: a.shape[0]] = a
    return out


@dataclass
class ChainExtract:
    """Right-spine chains (RLE runs) of a SeqExtract — the contraction
    that makes device ranking cheap (the reference's FugueSpan RLE
    serves the same purpose for its B-tree, fugue_span.rs runs).

    An element i is chained to row i-1 iff parent[i]==i-1, side==Right,
    row i-1 has exactly one child and no left children, and row i has no
    left children.  On the *final* tree these conditions make chain
    units contiguous in traversal, so contracting them is exact.
    Chains are contiguous row ranges; `chain_id` maps element row ->
    chain index (chains numbered in row order, preserving the
    (peer, counter) sibling-order contract at chain level)."""

    parent: np.ndarray  # i32[C] chain-level fugue parent (chain idx, -1 root)
    side: np.ndarray  # i32[C]
    valid: np.ndarray  # bool[C]
    head_row: np.ndarray  # i32[C] first element row of each chain
    chain_id: np.ndarray  # i32[N] element row -> chain

    @property
    def n_chains(self) -> int:
        return int(self.parent.shape[0])


def chain_columns(
    ex: SeqExtract, pad_n: Optional[int] = None, pad_c: Optional[int] = None, bucket: bool = False
):
    """Padded numpy ChainColumns for the chain-contracted device path.
    With bucket=True, both dims pad to power-of-two buckets (shares the
    jit cache across varying sizes) without a separate contract pass."""
    from .fugue_batch import ChainColumns, pad_bucket

    ch = contract_chains(ex)
    if bucket:
        n = pad_n or pad_bucket(max(1, ex.n))
        c = pad_c or pad_bucket(max(1, ch.n_chains))
    else:
        n = pad_n or ex.n
        c = pad_c or ch.n_chains

    def pad(a, size, fill):
        if a.shape[0] == size:
            return a
        out = np.full(size, fill, a.dtype)
        out[: a.shape[0]] = a
        return out

    return ChainColumns(
        c_parent=pad(ch.parent, c, -1),
        c_side=pad(ch.side, c, 0),
        c_valid=pad(ch.valid, c, False),
        head_row=pad(ch.head_row, c, 0),
        chain_id=pad(ch.chain_id, n, 0),
        deleted=pad(ex.deleted, n, True),
        content=pad(ex.content, n, -1),
        valid=pad(ex.valid, n, False),
    )


def contract_chains(ex: SeqExtract) -> ChainExtract:
    n = ex.n
    if n == 0:
        z = np.zeros(0, np.int32)
        return ChainExtract(z, z, np.zeros(0, bool), z, z)
    parent, side = ex.parent, ex.side
    pp = np.maximum(parent, 0)
    cc = np.bincount(parent[parent >= 0], minlength=n)
    lc = np.bincount(parent[(parent >= 0) & (side == 0)], minlength=n)
    rows = np.arange(n)
    link = (
        (parent == rows - 1)
        & (side == 1)
        & (cc[pp] == 1)
        & (lc[pp] == 0)
        & (lc[rows] == 0)
        & (parent >= 0)
    )
    chain_id = np.cumsum(~link) - 1
    head_mask = ~link
    head_row = np.flatnonzero(head_mask).astype(np.int32)
    c_parent_elem = parent[head_row]  # element row of the chain's parent
    c_parent = np.where(c_parent_elem >= 0, chain_id[np.maximum(c_parent_elem, 0)], -1)
    return ChainExtract(
        parent=c_parent.astype(np.int32),
        side=side[head_row].astype(np.int32),
        valid=np.ones(len(head_row), bool),
        head_row=head_row,
        chain_id=chain_id.astype(np.int32),
    )
