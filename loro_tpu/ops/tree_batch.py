"""Batched movable-tree merge kernel.

reference semantics: crates/loro-internal/src/diff_calc/tree.rs —
moves apply in global (lamport, peer, counter) order; a move whose new
parent lies in the target's subtree at that moment is skipped
(`effected = false`, tree.rs:499-508).  Deletion = move under TRASH.

Device formulation: the move log (host-sorted by key — cheap numpy
radix) replays as a `lax.scan`; the per-move cycle check is a bounded
parent-pointer walk (`d_max` gathers), all vmapped across documents so
one scan step advances every doc in the batch.  Sibling order
(fractional index) is resolved host-side at materialization — the
device's job is the structural fixpoint, the part that is sequential
per doc but embarrassingly parallel across docs.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

ROOT = -1
TRASH = -2
ABSENT = -3


class TreeOpCols(NamedTuple):
    """[M] per-doc move log, sorted by (lamport, peer, counter).

    target: i32[M] node index (per-doc node dictionary)
    parent: i32[M] node index, ROOT, or TRASH
    valid:  bool[M] padding mask
    """

    target: jax.Array
    parent: jax.Array
    valid: jax.Array


def tree_merge_doc(
    cols: TreeOpCols, n_nodes: int, d_max: Optional[int] = None
) -> Tuple[jax.Array, jax.Array]:
    """Replay one doc's sorted move log.  Returns (parent i32[n_nodes]
    with ABSENT for never-created nodes, effected bool[M] per move).

    `d_max` bounds the cycle-check walk.  Soundness requires
    d_max >= max tree depth; the default (n_nodes) is always sound —
    pass a smaller bound only when the workload guarantees a depth cap.
    """
    if d_max is None:
        d_max = n_nodes
    init = jnp.full(n_nodes, ABSENT, jnp.int32)

    def step(state, mv):
        t, p, v = mv

        # cycle check: does walking up from p reach t?  Early-exit
        # while_loop — cost follows the ACTUAL ancestor-chain depth,
        # not the d_max bound (the sound default d_max = n_nodes is
        # only the worst-case cap; typical trees walk O(depth) steps)
        def cond(carry):
            cur, hit, steps = carry
            return (cur >= 0) & ~hit & (steps < d_max)

        def walk(carry):
            cur, hit, steps = carry
            hit = hit | (cur == t)
            nxt = jnp.where(
                hit, jnp.int32(ROOT - 10), state[jnp.clip(cur, 0, n_nodes - 1)]
            )
            return nxt, hit, steps + 1

        cur, cycle, _ = jax.lax.while_loop(
            cond, walk, (p, jnp.bool_(False), jnp.int32(0))
        )
        cycle = cycle | (cur == t)
        ok = v & ~(cycle & (p >= 0))
        new_state = jnp.where(
            ok, state.at[jnp.clip(t, 0, n_nodes - 1)].set(p), state
        )
        return new_state, ok

    final, effected = jax.lax.scan(step, init, (cols.target, cols.parent, cols.valid))
    return final, effected


@functools.partial(jax.jit, static_argnums=(1, 2))
def tree_merge_batch(
    cols: TreeOpCols, n_nodes: int, d_max: Optional[int] = None
) -> Tuple[jax.Array, jax.Array]:
    """[D, M] move logs -> ([D, n_nodes] parents, [D, M] effected)."""
    return jax.vmap(lambda c: tree_merge_doc(c, n_nodes, d_max))(cols)


class TreeLogCols(NamedTuple):
    """[M] UNSORTED device-resident move log (append order; the
    resident path's buffer — DeviceTreeBatch).  Peers ship as u64
    halves; the global move key (lamport, peer, counter) is sorted on
    device at materialization."""

    lamport: jax.Array  # i32[M]
    peer_hi: jax.Array  # u32[M]
    peer_lo: jax.Array  # u32[M]
    counter: jax.Array  # i32[M]
    target: jax.Array  # i32[M] node ordinal
    parent: jax.Array  # i32[M] node ordinal, ROOT, or TRASH
    valid: jax.Array  # bool[M]


@functools.partial(jax.jit, static_argnums=(1, 2))
def tree_replay_log_batch(
    cols: TreeLogCols, n_nodes: int, d_max: Optional[int] = None
) -> Tuple[jax.Array, jax.Array]:
    """Sort each doc's standing move log by the global move key and
    replay the scan.  Returns ([D, n_nodes] parents, [D, M] effected in
    ROW (append) order — the host resolves sibling positions from the
    last effected non-delete move per node in key order)."""

    def per_doc(c: TreeLogCols):
        m = c.lamport.shape[0]
        big = jnp.int32(2**31 - 1)
        lam = jnp.where(c.valid, c.lamport, big)  # pads sort last
        row_idx = jnp.arange(m, dtype=jnp.int32)
        _, _, _, _, t_s, p_s, v_s, row_s = jax.lax.sort(
            (
                lam,
                c.peer_hi,
                c.peer_lo,
                c.counter,
                c.target,
                c.parent,
                c.valid.astype(jnp.int32),
                row_idx,
            ),
            num_keys=4,
        )
        parents, eff = tree_merge_doc(
            TreeOpCols(target=t_s, parent=p_s, valid=v_s.astype(bool)),
            n_nodes,
            d_max,
        )
        eff_rows = jnp.zeros(m, bool).at[row_s].set(eff)
        return parents, eff_rows

    return jax.vmap(per_doc)(cols)


def is_deleted_batch(parents: jax.Array) -> jax.Array:
    """bool[D, N]: node is trash-reachable (pointer-doubling ancestor
    resolution — log-depth, fully parallel)."""

    def per_doc(par):
        n = par.shape[0]

        def body(_, p):
            # jump: p[i] <- p[p[i]] when parent is a real node
            nxt = jnp.where(p >= 0, p[jnp.clip(p, 0, n - 1)], p)
            return nxt

        # log2(n) doublings cover any depth <= n
        p = jax.lax.fori_loop(0, int(np.ceil(np.log2(max(n, 2)))) + 1, body, par)
        return p == TRASH

    return jax.vmap(per_doc)(parents)


def extract_tree_ops(changes, cid):
    """Host: explode TreeMove ops for `cid` into sorted columns + node
    dictionary.  Returns (TreeOpCols numpy, nodes list, row_positions
    list aligned with rows — resolve winners with positions_of after the
    kernel reports which moves were effected)."""
    from ..core.change import TreeMove

    rows = []  # (lamport, peer, counter, target, parent, position)
    node_ids = {}
    nodes = []

    def node_idx(tid):
        if tid not in node_ids:
            node_ids[tid] = len(nodes)
            nodes.append(tid)
        return node_ids[tid]

    for ch in changes:
        for op in ch.ops:
            if op.container != cid or not isinstance(op.content, TreeMove):
                continue
            c = op.content
            lam = ch.lamport + (op.counter - ch.ctr_start)
            t = node_idx(c.target)
            if c.is_delete:
                p = TRASH
            elif c.parent is None:
                p = ROOT
            else:
                p = node_idx(c.parent)
            rows.append((lam, ch.peer, op.counter, t, p, c.position))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    m = len(rows)
    target = np.asarray([r[3] for r in rows], np.int32)
    parent = np.asarray([r[4] for r in rows], np.int32)
    row_positions = [r[5] for r in rows]
    cols = TreeOpCols(target=target, parent=parent, valid=np.ones(m, bool))
    return cols, nodes, row_positions


def positions_of(cols: TreeOpCols, row_positions, effected) -> dict:
    """Winning fractional index per node: the last *effected*, non-delete
    move in key order (deletes ship position=None and cycle-losing moves
    must not clobber the position the effective tree actually has)."""
    out: dict = {}
    effected = np.asarray(effected)
    for i in range(len(row_positions)):
        if not effected[i]:
            continue
        if int(cols.parent[i]) == TRASH:
            continue
        out[int(cols.target[i])] = row_positions[i]
    return out


def pad_tree_cols(cols: TreeOpCols, m: int) -> TreeOpCols:
    def pad(a, fill, dtype):
        out = np.full(m, fill, dtype)
        out[: a.shape[0]] = a
        return out

    return TreeOpCols(
        target=pad(cols.target, 0, np.int32),
        parent=pad(cols.parent, ROOT, np.int32),
        valid=pad(cols.valid, False, bool),
    )


class _LazyPositions:
    """Row-indexed fractional-index bytes, sliced from the payload on
    demand (positions_of touches only effected rows — no per-row copy
    for the losers)."""

    __slots__ = ("payload", "off", "ln", "has")

    def __init__(self, payload, off, ln, has):
        self.payload = payload
        self.off = off
        self.ln = ln
        self.has = has

    def __len__(self):
        return len(self.off)

    def __getitem__(self, i):
        if not self.has[i]:
            return None
        o = int(self.off[i])
        return bytes(self.payload[o : o + int(self.ln[i])])

    def __eq__(self, other):
        return list(self) == list(other)


def extract_tree_from_payload(payload: bytes, cid):
    """Native fast path: binary updates payload -> (TreeOpCols, nodes,
    row_positions) without Python Change objects (same contract as
    extract_tree_ops).  Returns None when the native library is
    unavailable; raises ValueError on malformed payloads."""
    from ..codec.binary import read_tables
    from ..native import available, explode_tree_payload

    if not available():
        return None
    from ..core.ids import TreeID

    peers_wire, _keys, cids, _r = read_tables(payload)
    try:
        target = cids.index(cid)
    except ValueError:
        return TreeOpCols(
            target=np.zeros(0, np.int32),
            parent=np.zeros(0, np.int32),
            valid=np.zeros(0, bool),
        ), [], []
    out = explode_tree_payload(payload, target)
    n = len(out["lamport"])
    peer_u64 = np.asarray(peers_wire, np.uint64)
    order = np.lexsort(
        (out["counter"], peer_u64[out["peer_idx"]] if n else out["peer_idx"], out["lamport"])
    )
    tp = out["target_peer_idx"][order].astype(np.int64)
    tc = out["target_ctr"][order].astype(np.int64)
    fl = out["flags"][order]
    pp = out["parent_peer_idx"][order].astype(np.int64)
    pc = out["parent_ctr"][order].astype(np.int64)
    po = out["pos_off"][order]
    pl = out["pos_len"][order]
    # vectorized node dictionary: pack (wire peer idx, ctr) into i64
    # (peer indexes are small; counters non-negative), unique+inverse
    from .columnar import pack_wire_ids

    has_parent = (fl & 4) != 0
    t_packed = pack_wire_ids(tp, tc)
    p_packed = pack_wire_ids(pp[has_parent], pc[has_parent])
    uniq, inv = np.unique(np.concatenate([t_packed, p_packed]), return_inverse=True)
    nodes = [TreeID(int(peers_wire[int(k) >> 32]), int(k) & 0xFFFFFFFF) for k in uniq]
    target_col = inv[:n].astype(np.int32)
    parent_col = np.full(n, ROOT, np.int32)
    parent_col[has_parent] = inv[n:].astype(np.int32)
    parent_col[(fl & 2) != 0] = TRASH
    cols = TreeOpCols(target=target_col, parent=parent_col, valid=np.ones(n, bool))
    return cols, nodes, _LazyPositions(payload, po, pl, (fl & 8) != 0)
