"""Batched delta-export selection: the device half of the read plane.

The sync pull path serves "updates since the client frontier" — the
eg-walker retreat/advance reconstruction (PAPERS.md) — and the repo's
write path already batches, pipelines and tiers ingest across the doc
axis.  This module gives READS the same shape: one vmapped launch
answers a whole window of ``(doc, frontier)`` pull requests at once.

The device holds a **change-span index**: one row per stored change,
in oracle import order, carrying exactly the columns row selection
needs — the u64 peer halves (the ``SeqColumnsU`` convention: sibling
and export order never needs a batch-wide peer dictionary), the
counter span ``[ctr_start, ctr_end)`` and the lamport stamp.  A pull
request is a frontier table; the kernel computes, per request,

- the beyond-frontier mask: a row is selected iff ``ctr_end >
  frontier[peer]`` (absent peers read 0 — exactly
  ``OpLog.changes_since``'s per-peer trim bound);
- the oracle's export order ``(lamport, peer, ctr_start)`` — with the
  straddle correction: a change half-known to the client exports
  TRIMMED, so its sort key uses ``lamport + (frontier_ctr -
  ctr_start)`` and ``max(ctr_start, frontier_ctr)``, matching the
  ``trim_known_prefix`` rewrite byte-for-byte;
- the compact gather: selected row indices first, in export order.

Framing back into the columnar-updates envelope stays on the host
(``sync/readbatch.py``): the index keeps the stored ``Change`` objects
per doc, and the wire bytes carry values/deps/timestamps the device
columns never see.  The host-side contract that makes the bytes
identical: ``note_changes`` applies the same known-prefix trim the
oracle's ``plan_import`` applies, so index rows ARE the oracle's
stored changes.

Shapes bucket-pad (``pad_bucket``) on all three axes — row capacity,
requests per window, frontier width — so the jit cache stays a handful
of entries however traffic fluctuates.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.version import VersionVector
from ..obs import metrics as obs
from .fugue_batch import pad_bucket

_U32 = np.uint32
_MASK32 = (1 << 32) - 1


def _split_peer(peer: int) -> Tuple[int, int]:
    return (peer >> 32) & _MASK32, peer & _MASK32


def _pad_request_shapes(n_req: int, n_peers: int) -> Tuple[int, int]:
    """Request-table bucketing shared by ``select`` and ``warm`` — one
    place, so warmed shapes can never drift from the shapes real
    windows launch."""
    return (pad_bucket(max(1, int(n_req)), floor=8),
            pad_bucket(max(1, int(n_peers)), floor=4))


def _scatter_rows(dev_cols, host_cols, idx):
    """The dirty-doc delta upload shared by ``_device_cols`` and
    ``warm``: one functional scatter per column, returning the new
    device tuple."""
    return tuple(
        dev.at[idx].set(host[idx]) for dev, host in zip(dev_cols, host_cols)
    )


def _select_fn():
    """Build (once) the jitted batched selection kernel."""
    import jax
    import jax.numpy as jnp

    def one(doc, f_hi, f_lo, f_ctr, f_n, hi, lo, cs, ce, lam, n_rows):
        # gather this request's doc rows (the only cross-request axis)
        dhi, dlo = hi[doc], lo[doc]
        dcs, dce, dlam = cs[doc], ce[doc], lam[doc]
        n = n_rows[doc]
        cap = dhi.shape[0]
        rows = jnp.arange(cap, dtype=jnp.int32)
        valid = rows < n
        # frontier counter per row: match the row peer over the
        # request's (padded) frontier table; absent peers read 0
        fi = jnp.arange(f_hi.shape[0], dtype=jnp.int32)
        m = (
            (dhi[:, None] == f_hi[None, :])
            & (dlo[:, None] == f_lo[None, :])
            & (fi[None, :] < f_n)
        )
        fctr = jnp.max(jnp.where(m, f_ctr[None, :], 0), axis=1)
        sel = valid & (dce > fctr)
        # straddle-corrected export keys (trim_known_prefix semantics)
        off = jnp.maximum(0, fctr - dcs)
        eff_lam = dlam + off
        eff_cs = jnp.maximum(dcs, fctr)
        unsel = (~sel).astype(jnp.int32)  # selected rows sort first
        srt = jax.lax.sort(
            (unsel, eff_lam, dhi, dlo, eff_cs, rows), num_keys=5
        )
        return srt[-1], jnp.sum(sel.astype(jnp.int32))

    batched = jax.vmap(one, in_axes=(0, 0, 0, 0, 0) + (None,) * 6)
    return jax.jit(batched)


_SELECT = None


def select_since_batch(doc, f_hi, f_lo, f_ctr, f_n,
                       hi, lo, cs, ce, lam, n_rows):
    """One launch: per-request beyond-frontier row selection in export
    order.  Returns ``(order i32[R, cap], count i32[R])`` — the first
    ``count[r]`` entries of ``order[r]`` are the selected row indices
    of doc ``doc[r]`` in oracle export order."""
    global _SELECT
    if _SELECT is None:
        _SELECT = _select_fn()
    return _SELECT(doc, f_hi, f_lo, f_ctr, f_n, hi, lo, cs, ce, lam, n_rows)


class ExportIndex:
    """Host-fed, device-resident change-span index over one doc fleet.

    Feed (``note_changes``) mirrors the oracle's import rule exactly:
    fully-known spans drop, straddles trim (``trim_known_prefix``), the
    per-doc head VV advances — so row ``i`` of doc ``di`` IS the
    oracle's ``i``-th stored change and the device selection reproduces
    ``OpLog.changes_since`` row-for-row.  The stored ``Change`` objects
    ride along per doc for host framing.

    The device copy syncs lazily: appends land in numpy staging arrays
    and ship right before the next ``select`` launch — the full grid
    on first sync / capacity grow, a dirty-doc scatter delta otherwise
    (the grid re-pads through ``pad_bucket`` so capacity growth costs
    a bounded number of recompiles).  ``floor_vv`` is each doc's index
    birth frontier: a pull whose client frontier does not dominate it
    needs history the index never saw and must stay on the oracle
    path (docs/SYNC.md "Read plane").

    Retention: the index (host ``Change`` lists + device rows) keeps
    every change since its floor; ``prune_below(di, floor_vv)`` drops
    rows fully below a frontier every connected session already holds
    and advances ``floor_vvs`` past them, so frontiers under the new
    floor re-route to the oracle through the existing ``covers`` path
    (the SyncServer wires it to ``compact()`` — docs/SYNC.md "Read
    plane").  Straddling rows stay: a client at the floor may still
    need their trimmed tails.

    Thread contract: the OWNER serializes calls (the read plane takes
    ``sync.readplane`` around every entry); this class has no lock of
    its own.
    """

    def __init__(self, n_docs: int, family: str = "",
                 floor_vvs: Optional[Sequence[VersionVector]] = None,
                 capacity: int = 256):
        self.n_docs = int(n_docs)
        self.family = family
        cap = pad_bucket(max(1, int(capacity)))
        self._cap = cap
        self._hi = np.zeros((n_docs, cap), _U32)
        self._lo = np.zeros((n_docs, cap), _U32)
        self._cs = np.zeros((n_docs, cap), np.int32)
        self._ce = np.zeros((n_docs, cap), np.int32)
        self._lam = np.zeros((n_docs, cap), np.int32)
        self._n = np.zeros((n_docs,), np.int32)
        self.changes: List[List] = [[] for _ in range(n_docs)]
        self.head_vvs: List[VersionVector] = [
            VersionVector() for _ in range(n_docs)
        ]
        self.floor_vvs: List[VersionVector] = [
            (floor_vvs[i].copy() if floor_vvs is not None else VersionVector())
            for i in range(n_docs)
        ]
        for i in range(n_docs):
            self.head_vvs[i].merge(self.floor_vvs[i])
        self._dev = None          # device tuple, or None before first sync
        # docs whose host rows moved past the device copy; None means
        # the whole grid must re-upload (first sync / capacity grow)
        self._dirty_docs: Optional[set] = None
        self.launches = 0         # count guard: one per select() call
        self.warm_launches = 0    # warm() pre-compiles, never windows
        self.rows_fed = 0
        self.rows_pruned = 0

    # -- feed (owner holds the read-plane lock) ------------------------
    def note_changes(self, di: int, chs: Sequence) -> None:
        """Append one committed round's changes for doc ``di`` with the
        oracle's dedup/trim rule.  Known-decodable, gate-passed changes
        only (the sync commit path hands us exactly those)."""
        from ..oplog.oplog import trim_known_prefix

        vv = self.head_vvs[di]
        for ch in chs:
            known = vv.get(ch.peer)
            if ch.ctr_end <= known:
                continue  # fully known: the oracle dropped it too
            if ch.ctr_start < known:
                ch = trim_known_prefix(ch, known)
            self._append_row(di, ch)
            vv.set_end(ch.peer, max(vv.get(ch.peer), ch.ctr_end))

    def _append_row(self, di: int, ch) -> None:
        n = int(self._n[di])
        if n >= self._cap:
            self._grow()
        hi, lo = _split_peer(ch.peer)
        self._hi[di, n] = hi
        self._lo[di, n] = lo
        self._cs[di, n] = ch.ctr_start
        self._ce[di, n] = ch.ctr_end
        self._lam[di, n] = ch.lamport
        self._n[di] = n + 1
        self.changes[di].append(ch)
        if self._dirty_docs is not None:
            self._dirty_docs.add(di)
        self.rows_fed += 1

    def _grow(self) -> None:
        new_cap = pad_bucket(self._cap * 2)
        for name in ("_hi", "_lo", "_cs", "_ce", "_lam"):
            old = getattr(self, name)
            fresh = np.zeros((self.n_docs, new_cap), old.dtype)
            fresh[:, : self._cap] = old
            setattr(self, name, fresh)
        self._cap = new_cap
        self._dev = None
        self._dirty_docs = None  # shape changed: full re-upload

    def head_vv(self, di: int) -> VersionVector:
        return self.head_vvs[di].copy()

    def prune_below(self, di: int, floor_vv: VersionVector) -> int:
        """Drop rows wholly at/under ``floor_vv`` (every connected
        session already holds them) and advance the doc's index floor
        past it: pruned history re-routes to the oracle through
        ``covers`` — never a silently-short delta.  Straddling rows
        survive whole (a client at the floor needs their trimmed
        tails; selection's straddle correction keeps serving them).
        Device rows rewrite via the ordinary dirty-doc scatter; rows
        past the new count stay allocated but masked by ``n_rows``.
        Returns rows pruned."""
        old = self.changes[di]
        keep = [ch for ch in old if ch.ctr_end > floor_vv.get(ch.peer)]
        pruned = len(old) - len(keep)
        if pruned == 0:
            return 0
        self.changes[di] = keep
        for j, ch in enumerate(keep):
            hi, lo = _split_peer(ch.peer)
            self._hi[di, j] = hi
            self._lo[di, j] = lo
            self._cs[di, j] = ch.ctr_start
            self._ce[di, j] = ch.ctr_end
            self._lam[di, j] = ch.lamport
        self._n[di] = len(keep)
        # floor advances by REFERENCE SWAP, never in-place merge:
        # ``covers`` reads the floor lock-free under the server lock
        # while pruning holds only the plane lock — a reader must see
        # a complete old or complete new floor, never a half-merged VV
        # (and never a dict mutating under its iteration)
        new_floor = self.floor_vvs[di].copy()
        new_floor.merge(floor_vv)
        self.floor_vvs[di] = new_floor
        if self._dirty_docs is not None:
            self._dirty_docs.add(di)
        self.rows_pruned += pruned
        obs.counter(
            "readbatch.index_rows_pruned_total",
            "change-span index rows dropped below the session ack "
            "floors at compaction",
        ).inc(pruned, family=self.family)
        return pruned

    def covers(self, di: int, from_vv: VersionVector) -> bool:
        """Whether a pull from ``from_vv`` is servable off the index:
        the client must already hold everything below the index floor
        (else the delta needs pre-index history only the oracle has)."""
        return self.floor_vvs[di] <= from_vv

    # -- device sync + selection ---------------------------------------
    def _device_cols(self):
        """Lazy device sync.  First sync (and every capacity grow)
        uploads the whole grid; steady-state commits re-ship only the
        DIRTY doc rows as one scatter-update per column — a window
        after K docs committed pays O(K x cap), not O(n_docs x cap)
        (the full grid would be a whole-HBM transfer per read window
        on a real chip)."""
        import jax.numpy as jnp

        if self._dev is not None and not self._dirty_docs:
            return self._dev
        if self._dev is None or self._dirty_docs is None:
            self._dev = tuple(
                jnp.asarray(a)
                for a in (self._hi, self._lo, self._cs, self._ce, self._lam,
                          self._n)
            )
            kind = "full"
        else:
            docs = sorted(self._dirty_docs)
            # pad the dirty-doc list (repeat the first index — the
            # scatter is idempotent) so the update shapes bucket
            idx = np.asarray(docs, np.int32)
            pad = pad_bucket(len(docs), floor=8)
            idx = np.concatenate([idx, np.full(pad - len(docs), idx[0],
                                               np.int32)])
            hosts = (self._hi, self._lo, self._cs, self._ce, self._lam)
            self._dev = _scatter_rows(self._dev[:5], hosts, idx) + (
                jnp.asarray(self._n),
            )
            kind = "delta"
        self._dirty_docs = set()
        obs.counter(
            "readbatch.index_uploads_total",
            "change-span index uploads to device (full grid or "
            "dirty-doc delta scatter)",
        ).inc(family=self.family, kind=kind)
        return self._dev

    def select(self, requests: Sequence[Tuple[int, VersionVector]]
               ) -> List[np.ndarray]:
        """ONE launch for the whole window: per request ``(di,
        from_vv)``, the beyond-frontier row indices of doc ``di`` in
        oracle export order.  The caller frames them into wire bytes
        host-side."""
        import jax.numpy as jnp

        cols = self._device_cols()
        r_pad, f_pad = _pad_request_shapes(
            len(requests),
            max((len(vv) for _di, vv in requests), default=1),
        )
        doc = np.zeros((r_pad,), np.int32)
        f_hi = np.zeros((r_pad, f_pad), _U32)
        f_lo = np.zeros((r_pad, f_pad), _U32)
        f_ctr = np.zeros((r_pad, f_pad), np.int32)
        f_n = np.zeros((r_pad,), np.int32)
        for r, (di, vv) in enumerate(requests):
            doc[r] = di
            for j, (peer, ctr) in enumerate(vv.items()):
                f_hi[r, j], f_lo[r, j] = _split_peer(peer)
                f_ctr[r, j] = ctr
            f_n[r] = len(vv)
        order, count = select_since_batch(
            jnp.asarray(doc), jnp.asarray(f_hi), jnp.asarray(f_lo),
            jnp.asarray(f_ctr), jnp.asarray(f_n), *cols,
        )
        self.launches += 1
        obs.counter(
            "readbatch.export_launches_total",
            "batched delta-export selection launches (one per window)",
        ).inc(family=self.family)
        order = np.asarray(order)  # fetch drains the launch
        count = np.asarray(count)
        return [
            order[r, : int(count[r])] for r in range(len(requests))
        ]

    def warm(self, max_requests: int, max_peers: int = 4) -> int:
        """Pre-compile the selection kernel over the request-bucket
        ladder up to ``pad_bucket(max_requests)`` (frontier width
        bucketed from ``max_peers`` — pass the widest per-doc writer
        count expected, or wider frontier buckets still compile on
        first use) at the CURRENT row capacity.  The kernel jit-caches
        per (requests, frontier-width, capacity) bucket, so without
        this the first window at each fresh bucket pays the XLA
        compile INSIDE a session's pull latency — a p99 spike, and on
        a real chip a remote-compile round-trip.  Also pre-compiles
        the dirty-doc scatter delta (``_device_cols``) over its own
        idx-bucket ladder — on the CPU mesh the scatter's first
        compile dominates the first post-commit window, not the
        selection kernel.

        Every warm launch runs against throwaway all-zero tables and
        columns of the LIVE shapes (the jit cache keys on shape +
        dtype, and ``_pad_request_shapes`` / ``_scatter_rows`` are the
        same code real windows run): no index or device state is read
        or written, so the owner may call this WITHOUT holding the
        read-plane lock across the multi-hundred-ms compiles — serving
        never stalls behind a warm.  Counted separately
        (``warm_launches``): warm launches are not windows, so the
        launches <= windows count guard stays exact.  Capacity is
        sampled once at entry; a concurrent grow (or a later one)
        re-pads the row axis and re-compiles once per bucket — re-warm
        after a known bulk load if first-window latency matters."""
        import jax.numpy as jnp

        n_docs, cap = self.n_docs, self._cap
        dtypes = (_U32, _U32, np.int32, np.int32, np.int32)
        dev = tuple(jnp.zeros((n_docs, cap), d) for d in dtypes)
        cols = dev + (jnp.zeros((n_docs,), np.int32),)
        target, f_pad = _pad_request_shapes(max_requests, max_peers)
        done = 0
        r = 8
        while r <= target:
            doc = jnp.zeros((r,), jnp.int32)
            f_hi = jnp.zeros((r, f_pad), jnp.uint32)
            f_lo = jnp.zeros((r, f_pad), jnp.uint32)
            f_ctr = jnp.zeros((r, f_pad), jnp.int32)
            f_n = jnp.zeros((r,), jnp.int32)
            _order, count = select_since_batch(
                doc, f_hi, f_lo, f_ctr, f_n, *cols
            )
            np.asarray(count)  # fetch drains the compile + launch
            done += 1
            r *= 2
        hosts = tuple(np.zeros((n_docs, cap), d) for d in dtypes)
        k = 8
        kmax = pad_bucket(n_docs, floor=8)
        while k <= kmax:
            idx = np.zeros((k,), np.int32)
            scat = _scatter_rows(dev, hosts, idx)
            np.asarray(scat[0])  # fetch drains the compile + launch
            done += 1
            k *= 2
        self.warm_launches += done
        return done

    def report(self) -> Dict[str, int]:
        return {
            "rows": int(self._n.sum()),
            "capacity": self._cap,
            "launches": self.launches,
            "warm_launches": self.warm_launches,
            "rows_fed": self.rows_fed,
            "rows_pruned": self.rows_pruned,
        }
