"""Fleet health plane: windowed rates, detectors, one status verdict
(docs/OBSERVABILITY.md "Health & heat").

``HealthPlane`` turns the always-on registry (metrics.py) into
*windowed* telemetry: ``tick()`` appends one bounded sample (flattened
counter/gauge totals + merged histogram bucket counts + follower lag +
a heat snapshot) to a ring, and ``rate(name, window)`` /
``window_quantile(hist, q, window)`` difference two ring samples — the
"how fast *right now*" the lifetime counters cannot answer.  The clock
is injected (LT-TIME): fake-clock tests drive windows deterministically
and a live process runs ``start(period_s)``'s daemon sampler.

**Detectors** are pure predicates over the windows, evaluated at each
tick with fire/clear hysteresis (``fire_after``/``clear_after``
consecutive breaching/clean ticks).  Firing records a flight event and
ticks ``health.alerts_total{kind}`` — never an exception into serving
code.  Kinds:

- ``shard_saturation``   heat skew ratio above ``shard_skew_max`` with
  real ingest traffic (the rebalancer trigger)
- ``tier_hit_collapse``  windowed tier hit rate below ``tier_hit_min``
  (the hot set no longer fits)
- ``repl_lag``           a follower's ``lag_epochs`` at/above
  ``repl_lag_epochs_max`` and not shrinking
- ``p2v_slo``            windowed push-to-visible p99 above
  ``p2v_slo_ms`` (SLO burn)
- ``degradation_spike``  ``resilience.degradations_total`` grew by
  ``degradation_burst`` within one window

**Status surface**: ``status()`` composes serving reports (sync,
resident/shards, followers, net), persist/repl watermarks, heat and
the open alerts into one JSON verdict ``ok|degraded|critical`` +
reasons.  It is served at ``/status.json`` (exposition.serve), answered
over the wire by the STATUS frame (net/wire.py) and rendered by
``python -m loro_tpu.obs.top``.

Fault site ``health_tick``: an armed raise/delay hits ONE sampler tick
— the window is skipped and counted (``health.ticks_skipped_total``),
serving never sees it (the blast-radius regression in
tests/test_health.py).

Lock contract: ``obs.health`` is a near-leaf (analysis/lockorder.py) —
attachment ``report()`` calls and registry reads happen with the plane
lock RELEASED; only ring/alert state mutates under it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..analysis.lockwitness import named_lock
from ..resilience import faultinject
from . import flight
from . import heat as heat_mod
from . import metrics as _m

faultinject.register_site(
    "health_tick", "HealthPlane.tick: raise/delay one sampler tick — "
    "the window is skipped (counted), serving never sees it")

SEVERITIES = ("ok", "degraded", "critical")

#: detector kind -> verdict severity while its alert is open
DETECTOR_SEVERITY = {
    "shard_saturation": "degraded",
    "tier_hit_collapse": "degraded",
    "repl_lag": "critical",
    "p2v_slo": "degraded",
    "degradation_spike": "critical",
}


def _worse(a: str, b: str) -> str:
    return a if SEVERITIES.index(a) >= SEVERITIES.index(b) else b


class HealthPlane:
    """Bounded snapshot ring + detectors + the status verdict."""

    def __init__(self, *, clock=time.monotonic,
                 registry: Optional[_m.Registry] = None,
                 heat: Optional[heat_mod.HeatAccountant] = None,
                 window_s: float = 60.0, capacity: int = 64,
                 p2v_slo_ms: float = 1000.0,
                 shard_skew_max: float = 4.0,
                 shard_min_ingest_heat: float = 4.0,
                 tier_hit_min: float = 0.5,
                 tier_min_touches: int = 8,
                 p2v_min_samples: int = 4,
                 repl_lag_epochs_max: int = 3,
                 degradation_burst: int = 3,
                 fire_after: int = 2, clear_after: int = 2):
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        self._clock = clock
        self._reg = registry or _m.registry()
        self.heat = heat or heat_mod.accountant()
        self.window_s = float(window_s)
        self.p2v_slo_ms = float(p2v_slo_ms)
        self.shard_skew_max = float(shard_skew_max)
        self.shard_min_ingest_heat = float(shard_min_ingest_heat)
        self.tier_hit_min = float(tier_hit_min)
        self.tier_min_touches = int(tier_min_touches)
        self.p2v_min_samples = int(p2v_min_samples)
        self.repl_lag_epochs_max = int(repl_lag_epochs_max)
        self.degradation_burst = int(degradation_burst)
        self.fire_after = max(1, int(fire_after))
        self.clear_after = max(1, int(clear_after))
        self._lock = named_lock("obs.health")
        self._ring: deque = deque(maxlen=max(2, int(capacity)))
        self._ticks = 0
        self._skipped = 0
        self._alerts: Dict[str, dict] = {}   # kind -> open alert
        self._breach: Dict[str, int] = {}    # kind -> breach streak
        self._clean: Dict[str, int] = {}     # kind -> clean streak
        # attachments (reports are read lock-free at tick/status time)
        self._sync = None
        self._resident = None
        self._net = None
        self._followers: List = []
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- attachments ----------------------------------------------------
    def attach_sync(self, srv) -> "HealthPlane":
        self._sync = srv
        if self._resident is None:
            self._resident = getattr(srv, "resident", None)
        return self

    def attach_resident(self, srv) -> "HealthPlane":
        self._resident = srv
        return self

    def attach_follower(self, fol) -> "HealthPlane":
        self._followers.append(fol)
        return self

    def set_followers(self, fols) -> "HealthPlane":
        """Replace the follower set (topology churn: promote/reopen
        retire old follower generations)."""
        self._followers = list(fols)
        return self

    def attach_net(self, netsrv) -> "HealthPlane":
        self._net = netsrv
        return self

    # -- sampling -------------------------------------------------------
    def _build_sample(self, now: float) -> dict:
        """One flattened registry snapshot + attachment gauges.  Runs
        WITHOUT the plane lock (registry metrics have their own leaf
        locks; attachment reads take serving locks)."""
        num: Dict[str, float] = {}
        hist: Dict[str, tuple] = {}
        for m in self._reg.metrics():
            snap = m.snapshot()
            if m.kind == "histogram":
                counts = [0] * (len(m.buckets) + 1)
                count = 0
                total = 0.0
                for r in snap["values"]:
                    prev = 0
                    for i, (_le, cum) in enumerate(r["buckets"]):
                        counts[i] += cum - prev
                        prev = cum
                    count += r["count"]
                    total += r["sum"]
                hist[m.name] = (m.buckets, counts, count, total)
                num[m.name] = float(count)
                continue
            rows = snap["values"]
            num[m.name] = float(sum(r["value"] for r in rows))
            for r in rows:
                if not r["labels"]:
                    continue
                key = m.name + "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(r["labels"].items())
                ) + "}"
                num[key] = num.get(key, 0.0) + float(r["value"])
                # outcome-level rollups the detectors difference without
                # caring which family produced them
                out = r["labels"].get("outcome")
                if out is not None:
                    rkey = f"{m.name}{{outcome={out}}}"
                    if rkey != key:
                        num[rkey] = num.get(rkey, 0.0) + float(r["value"])
        lag_max = 0
        fols = []
        for fol in list(self._followers):
            try:
                lag = int(getattr(fol, "lag_epochs", 0))
                fols.append({
                    "id": getattr(fol, "follower_id", None),
                    "lag_epochs": lag,
                    "applied_epoch": getattr(fol, "applied_epoch", None),
                })
                lag_max = max(lag_max, lag)
            except Exception:  # tpulint: disable=LT-EXC(a mid-teardown follower is not a sample; the tick must survive it)
                continue
        num["health.fol_lag_max"] = float(lag_max)
        return {"t": now, "num": num, "hist": hist,
                "heat": self.heat.report(), "followers": fols}

    def tick(self):
        """Take one sample + evaluate detectors.  NEVER raises into the
        caller: a failing tick (the ``health_tick`` fault site, or any
        sampling surprise) skips this window, counted."""
        now = self._clock()
        try:
            faultinject.check("health_tick")
            sample = self._build_sample(now)
        except Exception as e:  # tpulint: disable=LT-EXC(the tick contract: ANY sampling failure skips the window, counted — never raises into serving)
            with self._lock:
                self._skipped += 1
            _m.counter(
                "health.ticks_skipped_total",
                "sampler ticks that failed and skipped their window "
                "(serving never sees the failure)",
            ).inc(error=type(e).__name__)
            flight.record("health.tick_skipped", error=type(e).__name__)
            return []
        with self._lock:
            self._ring.append(sample)
            self._ticks += 1
        _m.counter("health.ticks_total", "health sampler ticks").inc()
        fired = self._evaluate(sample)
        rep = sample["heat"]
        _m.gauge("heat.skew_ratio",
                 "per-shard ingest skew vs uniform (1.0 = balanced)").set(
            rep["skew_ratio"] if rep["skew_ratio"] is not None else 1.0)
        _m.gauge("heat.tracked_docs", "docs with live heat state").set(
            rep["tracked_docs"])
        _m.gauge("health.open_alerts", "currently-open health alerts").set(
            len(self._alerts))
        return fired

    # -- windowed reads -------------------------------------------------
    def _edges(self, window: Optional[float]):
        """(base, latest) ring samples spanning ~the window (caller
        picks apart); None when fewer than 2 samples exist."""
        w = self.window_s if window is None else float(window)
        with self._lock:
            samples = list(self._ring)
        if len(samples) < 2:
            return None
        latest = samples[-1]
        cutoff = latest["t"] - w
        base = samples[0]
        for s in samples[:-1]:
            if s["t"] <= cutoff:
                base = s
            else:
                break
        if base is latest:
            return None
        return base, latest

    def delta(self, name: str, window: Optional[float] = None):
        """Windowed increase of a flattened series (bare metric name or
        ``name{k=v}``); None without two samples."""
        edges = self._edges(window)
        if edges is None:
            return None
        base, latest = edges
        return latest["num"].get(name, 0.0) - base["num"].get(name, 0.0)

    def rate(self, name: str, window: Optional[float] = None):
        """Windowed per-second rate of a flattened series."""
        edges = self._edges(window)
        if edges is None:
            return None
        base, latest = edges
        dt = latest["t"] - base["t"]
        if dt <= 0:
            return None
        dv = latest["num"].get(name, 0.0) - base["num"].get(name, 0.0)
        return dv / dt

    def window_quantile(self, name: str, q: float,
                        window: Optional[float] = None):
        """Quantile of a histogram's observations WITHIN the window
        (bucket-count differencing); None when the window holds no
        observations."""
        edges = self._edges(window)
        if edges is None:
            return None
        base, latest = edges
        cur = latest["hist"].get(name)
        if cur is None:
            return None
        bounds, counts, count, _total = cur
        old = base["hist"].get(name)
        if old is not None and old[0] == bounds:
            counts = [c - o for c, o in zip(counts, old[1])]
            count = count - old[2]
        if count <= 0:
            return None
        return _m._hist_quantile(bounds, counts, count, q)

    def window_count(self, name: str, window: Optional[float] = None):
        """Observations a histogram took within the window."""
        d = self.delta(name, window)
        return None if d is None else int(d)

    def rates_report(self, per_label: bool = False) -> dict:
        """The headline windowed rates (the ``obs.report`` "windowed
        rates" section): every ``*_total`` series with a nonzero rate."""
        edges = self._edges(None)
        if edges is None:
            return {}
        base, latest = edges
        dt = latest["t"] - base["t"]
        if dt <= 0:
            return {}
        out = {}
        for name, v in latest["num"].items():
            if not per_label and "{" in name:
                continue
            if not name.endswith("_total"):
                continue
            dv = v - base["num"].get(name, 0.0)
            if dv > 0:
                out[name] = round(dv / dt, 4)
        return out

    # -- detectors ------------------------------------------------------
    def _predicates(self, sample: dict) -> Dict[str, Optional[str]]:
        """kind -> breach detail (None = clean) — pure reads over the
        ring + the tick's sample."""
        out: Dict[str, Optional[str]] = {}
        rep = sample["heat"]

        skew = rep["skew_ratio"]
        ingest = sum(s["ingest"] for s in rep["shards"].values())
        if (skew is not None and skew > self.shard_skew_max
                and ingest >= self.shard_min_ingest_heat):
            out["shard_saturation"] = (
                f"shard ingest skew {skew}x vs uniform "
                f"(max {self.shard_skew_max}x, ingest heat {ingest:.1f})")
        else:
            out["shard_saturation"] = None

        hits = self.delta("residency.touch_total{outcome=hit}")
        misses = self.delta("residency.touch_total{outcome=miss}")
        detail = None
        if hits is not None and misses is not None:
            touches = hits + misses
            if touches >= self.tier_min_touches:
                hr = hits / touches
                if hr < self.tier_hit_min:
                    detail = (f"windowed tier hit rate {hr:.2f} < "
                              f"{self.tier_hit_min} over {int(touches)} "
                              "touches")
        out["tier_hit_collapse"] = detail

        lag = sample["num"].get("health.fol_lag_max", 0.0)
        prev_lag = None
        edges = self._edges(None)
        if edges is not None:
            prev_lag = edges[0]["num"].get("health.fol_lag_max")
        if lag >= self.repl_lag_epochs_max and (
                prev_lag is None or lag >= prev_lag):
            out["repl_lag"] = (
                f"follower lag {int(lag)} epochs >= "
                f"{self.repl_lag_epochs_max} and not shrinking")
        else:
            out["repl_lag"] = None

        detail = None
        n = self.window_count("sync.push_to_visible_seconds")
        if n is not None and n >= self.p2v_min_samples:
            p99 = self.window_quantile("sync.push_to_visible_seconds", 0.99)
            if p99 is not None and p99 * 1e3 > self.p2v_slo_ms:
                detail = (f"windowed push-to-visible p99 "
                          f"{p99 * 1e3:.1f}ms > SLO {self.p2v_slo_ms}ms "
                          f"({n} pushes)")
        out["p2v_slo"] = detail

        dg = self.delta("resilience.degradations_total")
        if dg is not None and dg >= self.degradation_burst:
            out["degradation_spike"] = (
                f"{int(dg)} degradations within the window "
                f"(burst threshold {self.degradation_burst})")
        else:
            out["degradation_spike"] = None
        return out

    def _evaluate(self, sample: dict) -> List[str]:
        verdicts = self._predicates(sample)
        fired: List[str] = []
        cleared: List[str] = []
        with self._lock:
            for kind, detail in verdicts.items():
                if detail is not None:
                    self._breach[kind] = self._breach.get(kind, 0) + 1
                    self._clean[kind] = 0
                    if (kind not in self._alerts
                            and self._breach[kind] >= self.fire_after):
                        self._alerts[kind] = {
                            "kind": kind,
                            "severity": DETECTOR_SEVERITY[kind],
                            "since": sample["t"],
                            "detail": detail,
                        }
                        fired.append(kind)
                    elif kind in self._alerts:
                        self._alerts[kind]["detail"] = detail
                else:
                    self._clean[kind] = self._clean.get(kind, 0) + 1
                    self._breach[kind] = 0
                    if (kind in self._alerts
                            and self._clean[kind] >= self.clear_after):
                        self._alerts.pop(kind)
                        cleared.append(kind)
        for kind in fired:
            _m.counter("health.alerts_total",
                       "health detector alerts fired").inc(kind=kind)
            flight.record("health.alert", alert=kind,
                          detail=verdicts[kind])
        for kind in cleared:
            _m.counter("health.alerts_cleared_total",
                       "health detector alerts cleared").inc(kind=kind)
            flight.record("health.alert_cleared", alert=kind)
        return fired

    def alerts(self) -> List[dict]:
        """Open alerts (copies), most severe first."""
        with self._lock:
            out = [dict(a) for a in self._alerts.values()]
        out.sort(key=lambda a: SEVERITIES.index(a["severity"]), reverse=True)
        return out

    # -- the status surface ---------------------------------------------
    def _safe_report(self, obj) -> Optional[dict]:
        if obj is None:
            return None
        try:
            return obj.report()
        except Exception as e:  # tpulint: disable=LT-EXC(status must render whatever a wedged layer throws)
            return {"unavailable": f"{type(e).__name__}: {e}"}

    def status(self) -> dict:
        """The aggregated JSON verdict: ``ok|degraded|critical`` +
        reasons, composed from open alerts, serving reports, shard
        occupancy/degradation, persist/repl watermarks, follower lag
        and net connections."""
        now = self._clock()
        alerts = self.alerts()
        verdict = "ok"
        reasons: List[str] = []
        for a in alerts:
            verdict = _worse(verdict, a["severity"])
            reasons.append(f"alert {a['kind']}: {a['detail']}")
        resident = self._resident
        shards_sec: Optional[dict] = None
        persist_sec: Optional[dict] = None
        if resident is not None:
            try:
                degraded = list(resident.degraded_shards())
            except AttributeError:
                degraded = None
            if degraded is None:
                flat = bool(getattr(resident, "degraded", False))
                if flat:
                    verdict = _worse(verdict, "critical")
                    reasons.append("resident server degraded to host mirror")
            else:
                n_sh = getattr(resident, "n_shards", len(degraded) or 1)
                shards_sec = {"n_shards": n_sh, "degraded": degraded}
                if degraded:
                    verdict = _worse(verdict, "degraded")
                    reasons.append(
                        f"shards degraded to host mirror: {degraded}")
            de = getattr(resident, "durable_epoch", None)
            if de is not None:
                persist_sec = {"durable_epoch": de}
        fol_sec: List[dict] = []
        for fol in list(self._followers):
            try:
                fol_sec.append({
                    "id": getattr(fol, "follower_id", None),
                    "applied_epoch": getattr(fol, "applied_epoch", None),
                    "lag_epochs": int(getattr(fol, "lag_epochs", 0)),
                })
            except Exception as e:  # tpulint: disable=LT-EXC(status must render a mid-teardown follower, not raise)
                fol_sec.append(
                    {"unavailable": f"{type(e).__name__}: {e}"})
        net_rep = self._safe_report(self._net)
        with self._lock:
            ticks, skipped = self._ticks, self._skipped
        return {
            "t": round(now, 6),
            "verdict": verdict,
            "reasons": reasons,
            "alerts": alerts,
            "ticks": ticks,
            "skipped_ticks": skipped,
            "window_s": self.window_s,
            "rates": self.rates_report(),
            "heat": self.heat.report(),
            "serving": self._safe_report(self._sync),
            "shards": shards_sec,
            "persist": persist_sec,
            "repl": {"followers": fol_sec} if fol_sec else None,
            "net": ({"connections": net_rep.get("connections"),
                     "addr": net_rep.get("addr"),
                     "frame_errors": net_rep.get("frame_errors")}
                    if isinstance(net_rep, dict) else None),
        }

    # -- background sampler ---------------------------------------------
    def start(self, period_s: float = 5.0) -> "HealthPlane":
        """Daemon sampler: one ``tick()`` per period until ``stop()``."""
        if self._thread is not None:
            return self
        stop = self._stop = threading.Event()

        def _run():
            while not stop.wait(period_s):
                self.tick()

        self._thread = threading.Thread(
            target=_run, daemon=True, name="loro-health-sampler")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self._stop = None


# -- process-global active plane ---------------------------------------
_active: Optional[HealthPlane] = None


def install(plane: Optional[HealthPlane]) -> Optional[HealthPlane]:
    """Make ``plane`` the process's active health plane (``/status.json``,
    the STATUS frame and ``obs.top`` resolve it); returns the previous
    one.  Pass None to uninstall."""
    global _active
    prev, _active = _active, plane
    return prev


def active() -> Optional[HealthPlane]:
    return _active


def status_payload() -> dict:
    """The dict ``/status.json`` and the STATUS frame serve: the active
    plane's ``status()``, or an 'unknown' verdict when none is
    installed."""
    plane = _active
    if plane is None:
        return {"verdict": "unknown",
                "reasons": ["no health plane active"], "alerts": []}
    return plane.status()
