"""Exposition formats for the obs registry: Prometheus text, JSON
snapshot, the bench sidecar object, and an optional scrape server.

- ``prometheus_text()`` — the classic ``/metrics`` text format
  (text/plain; version=0.0.4): dotted metric names map to underscores,
  histograms expose cumulative ``_bucket{le=...}`` / ``_sum`` /
  ``_count`` series, uniques export as gauges.
- ``snapshot_json()`` — the registry snapshot as a JSON string (the
  same dict ``metrics.snapshot()`` returns; report.py renders either).
- ``sidecar()`` — the compact flat dict bench.py embeds in its one
  JSON output line: counters/gauges/uniques as plain numbers (bare
  name = cross-label total, ``name{k=v}`` per label set), histograms
  as ``{count, sum, mean, p50, p99}`` summaries.
- ``serve(port)`` — a daemon-thread HTTP server exposing ``/metrics``
  (Prometheus), ``/metrics.json`` and ``/status.json`` (the active
  health plane's aggregated verdict — docs/OBSERVABILITY.md "Health &
  heat") for live scrapes of a long-lived fleet server process.
"""
from __future__ import annotations

import json
import re
import threading
from typing import Optional

from . import metrics as _m

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (_LABEL_RE.sub("_", k), str(v).replace("\\", r"\\").replace('"', r"\""))
        for k, v in sorted(items.items())
    )
    return "{" + body + "}"


def _fmt(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def prometheus_text(registry: Optional[_m.Registry] = None) -> str:
    reg = registry or _m.registry()
    lines = []
    for m in reg.metrics():
        pname = _prom_name(m.name)
        if m.help:
            lines.append(f"# HELP {pname} {m.help}")
        ptype = {"unique": "gauge"}.get(m.kind, m.kind)
        lines.append(f"# TYPE {pname} {ptype}")
        snap = m.snapshot()
        if m.kind == "histogram":
            for row in snap["values"]:
                for le, cum in row["buckets"]:
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(row['labels'], {'le': le})} {cum}"
                    )
                lines.append(f"{pname}_sum{_prom_labels(row['labels'])} {_fmt(row['sum'])}")
                lines.append(f"{pname}_count{_prom_labels(row['labels'])} {row['count']}")
        else:
            rows = snap["values"] or [{"labels": {}, "value": 0}]
            for row in rows:
                lines.append(f"{pname}{_prom_labels(row['labels'])} {_fmt(row['value'])}")
    return "\n".join(lines) + "\n"


def snapshot_json(registry: Optional[_m.Registry] = None, indent: Optional[int] = None) -> str:
    reg = registry or _m.registry()
    return json.dumps(reg.snapshot(), indent=indent, sort_keys=True)


def sidecar(registry: Optional[_m.Registry] = None) -> dict:
    """Flat metrics object for one-line JSON records (bench.py).  Keys
    are metric names; labeled counters additionally emit per-label-set
    entries so BENCH_r*.json trajectories can diff e.g. pad waste per
    family across rounds."""
    reg = registry or _m.registry()
    out: dict = {}
    for m in reg.metrics():
        if m.kind == "histogram":
            out[m.name] = m.summary()
            continue
        out[m.name] = _num(m.total())
        rows = m.snapshot()["values"]
        if len(rows) == 1 and not rows[0]["labels"]:
            continue
        for row in rows:
            if not row["labels"]:
                continue
            key = m.name + "{" + ",".join(
                f"{k}={v}" for k, v in sorted(row["labels"].items())
            ) + "}"
            out[key] = _num(row["value"])
    return out


def _num(v: float):
    f = float(v)
    return int(f) if f == int(f) else round(f, 6)


def serve(port: int = 9464, addr: str = "127.0.0.1",
          registry: Optional[_m.Registry] = None):
    """Start a daemon-thread scrape endpoint; returns the HTTPServer
    (``.shutdown()`` to stop).  ``GET /metrics`` -> Prometheus text,
    ``GET /metrics.json`` -> JSON snapshot, ``GET /status.json`` ->
    the active health plane's verdict (``health.status_payload()``)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    reg = registry or _m.registry()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib naming)
            if self.path.startswith("/status.json"):
                from . import health as _health

                body = json.dumps(_health.status_payload()).encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics.json"):
                body = snapshot_json(reg).encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics"):
                body = prometheus_text(reg).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrapes are not stderr news
            pass

    srv = HTTPServer((addr, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True, name="loro-obs-serve")
    t.start()
    return srv
