"""loro_tpu.obs: metrics + profiling for the fleet merge path.

Always-on process-wide registry (metrics.py), Prometheus/JSON/sidecar
exposition (exposition.py), a one-screen report (report.py; also
``python -m loro_tpu.obs.report``), EWMA heat accounting (heat.py),
the windowed health plane (health.py, lazily imported; rendered by
``python -m loro_tpu.obs.top``).  See docs/OBSERVABILITY.md for the
metric catalogue and how the pieces fit the tracing subsystem.

Quick use::

    from loro_tpu import obs
    obs.counter("fleet.ops_merged_total").inc(1024, family="text")
    print(obs.prometheus_text())       # /metrics text
    print(obs.sidecar())               # compact dict for JSON records
    obs.enable_span_metrics()          # tracing.span -> histograms
"""
from __future__ import annotations

from . import flight
from . import heat
from .exposition import prometheus_text, serve, sidecar, snapshot_json
from .metrics import (
    Registry,
    counter,
    gauge,
    histogram,
    registry,
    reset,
    snapshot,
    unique,
)

__all__ = [
    "Registry",
    "counter",
    "gauge",
    "histogram",
    "registry",
    "reset",
    "snapshot",
    "unique",
    "prometheus_text",
    "snapshot_json",
    "sidecar",
    "serve",
    "enable_span_metrics",
    "disable_span_metrics",
    "measure_tunnel_rtt",
    "flight",
    "heat",
]

# NOTE: loro_tpu.obs.health is imported lazily (`from loro_tpu.obs
# import health`) — it registers the `health_tick` fault site, and
# pulling resilience.faultinject into every bare `import loro_tpu.obs`
# would be needless weight on the metrics hot path.

# -- tracing bridge ----------------------------------------------------
# One instrumentation point, two sinks: a tracing.span() on a hot path
# feeds the chrome-trace event list when tracing is enabled AND (when
# this bridge is on) a duration histogram per span name.  The bridge is
# opt-in so tracing.span keeps its zero-cost-when-off contract.
_span_observer = None


def _observe_span(name: str, dur_s: float) -> None:
    histogram("trace.span_seconds").observe(dur_s, span=name)


def enable_span_metrics() -> None:
    """Feed every tracing.span duration into the
    ``trace.span_seconds{span=...}`` histogram (works with chrome-trace
    collection on or off)."""
    global _span_observer
    from ..utils import tracing

    if _span_observer is None:
        _span_observer = _observe_span
        tracing.add_span_observer(_span_observer)


def disable_span_metrics() -> None:
    global _span_observer
    from ..utils import tracing

    if _span_observer is not None:
        tracing.remove_span_observer(_span_observer)
        _span_observer = None


# -- tunnel health -----------------------------------------------------
def measure_tunnel_rtt(reps: int = 3):
    """The CLAUDE.md ``x+1``-fetch probe as a metric feeder: median of
    ``reps`` scalar round trips through the device queue (the honest
    sync primitive under the axon tunnel — block_until_ready lies).
    Sets the ``tunnel.rtt_ms`` gauge, ticks ``tunnel.probes_total`` and
    returns the RTT in seconds.  Uses whatever backend jax resolves, so
    on the CPU mesh it measures dispatch overhead (~ms)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    tiny = jax.jit(lambda v: v + 1)  # tpulint: disable=LT-DEV(the RTT probe IS the measurement; supervised routing would add the overhead it measures)
    np.asarray(tiny(jnp.zeros(8, jnp.int32)))  # compile + warm — tpulint: disable=LT-DEV(the RTT probe IS the measurement)
    rtts = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        np.asarray(tiny(jnp.zeros(8, jnp.int32)))  # tpulint: disable=LT-DEV(the RTT probe IS the measurement)
        rtts.append(time.perf_counter() - t0)
    rtt = sorted(rtts)[len(rtts) // 2]
    gauge("tunnel.rtt_ms", "median scalar-fetch round trip").set(rtt * 1e3)
    counter("tunnel.probes_total").inc()
    return rtt
