"""Trace/flight artifact tooling: ``python -m loro_tpu.obs.trace``.

Works on the two artifact formats this repo's observability plane
writes (docs/OBSERVABILITY.md):

- **chrome traces** — ``utils/tracing.dump()`` output
  (``{"traceEvents": [...]}``, load in chrome://tracing or Perfetto);
- **flight snapshots** — ``obs.flight.dump()`` output (``{"flight": 1,
  "events": [...]}``), the always-on black-box ring.

Subcommands::

    python -m loro_tpu.obs.trace dump [path]
        Write this process's flight snapshot (mostly useful from a
        driver script at a breakpoint); prints the path.

    python -m loro_tpu.obs.trace inspect <artifact.json>
        One-screen summary: event counts by kind/name, span time by
        name (chrome traces), the tail of the ring (flight).

    python -m loro_tpu.obs.trace merge <leader.json> <follower.json>
        Replication-lag attribution: match the leader's epoch-stamped
        commit events (``server.epoch`` / ``sync.commit``) against the
        follower's ``repl.apply`` events on the shipped epoch stamps
        and print per-epoch measured lag (count / p50 / max).  With
        ``-o out.json`` also writes a merged chrome trace (one
        process row per input) for side-by-side timeline viewing.

Exit codes: 0 ok, 2 unreadable/malformed artifact (typed ObsError
message on stderr, never a stack trace).
"""
from __future__ import annotations

import json
import sys
from typing import List, Optional

from ..errors import ObsError


def load_artifact(path: str) -> dict:
    """Read + classify one artifact; raises typed ObsError."""
    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, ValueError) as e:
        raise ObsError(f"unreadable trace artifact {path}: {e}") from e
    if not isinstance(art, dict):
        raise ObsError(f"{path}: not a trace artifact (top level is "
                       f"{type(art).__name__}, want object)")
    if "traceEvents" in art:
        art["_kind"] = "chrome"
    elif art.get("flight") == 1 and isinstance(art.get("events"), list):
        art["_kind"] = "flight"
    elif isinstance(art.get("flight"), list):
        # a chaos violation artifact: its embedded flight tail is
        # inspectable directly (the common post-mortem handoff)
        art = {"_kind": "flight", "flight": 1, "pid": None,
               "capacity": None, "recorded_total": len(art["flight"]),
               "events": art["flight"]}
    else:
        raise ObsError(
            f"{path}: neither a chrome trace (traceEvents), a flight "
            "snapshot (flight=1 + events), nor a chaos artifact with "
            "an embedded flight tail"
        )
    return art


# -- inspect ------------------------------------------------------------
def render_inspect(art: dict, path: str = "?") -> str:
    lines = [f"== {path} ({art['_kind']}) =="]
    if art["_kind"] == "chrome":
        evs = art["traceEvents"]
        by_name: dict = {}
        for e in evs:
            st = by_name.setdefault(e.get("name", "?"), [0, 0.0])
            st[0] += 1
            st[1] += float(e.get("dur", 0.0))
        lines.append(f"events: {len(evs)}")
        for name in sorted(by_name, key=lambda n: -by_name[n][1])[:20]:
            n, us = by_name[name]
            lines.append(f"  {name:<40} n={n:<8} total={us / 1e3:,.2f}ms")
    else:
        evs = art["events"]
        lines.append(
            f"pid={art.get('pid')} capacity={art.get('capacity')} "
            f"recorded_total={art.get('recorded_total')} "
            f"retained={len(evs)}"
        )
        by_kind: dict = {}
        for e in evs:
            by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
        for kind in sorted(by_kind):
            lines.append(f"  {kind:<32} n={by_kind[kind]}")
        lines.append("tail:")
        for e in evs[-10:]:
            extra = {k: v for k, v in e.items()
                     if k not in ("i", "t", "wall", "kind")}
            lines.append(f"  [{e.get('i')}] {e.get('kind')} {extra}")
    return "\n".join(lines)


# -- merge (replication-lag attribution) --------------------------------
_LEADER_COMMIT_KINDS = ("server.epoch", "sync.commit")


def merge_lag(leader: dict, follower: dict) -> dict:
    """Match leader commit events to follower ``repl.apply`` events on
    the epoch stamps; returns ``{"epochs": [...], "lag_ms_p50": ...,
    "lag_ms_max": ..., "count": N}``.  Two lag figures per epoch:

    - ``shipped_lag_ms`` — the follower's own measurement (its wall
      clock minus the WAL stamp, recorded at apply time) when present;
    - ``observed_lag_ms`` — follower apply wall time minus leader
      commit wall time from the two flight streams (the cross-check).
    """
    if leader["_kind"] != "flight" or follower["_kind"] != "flight":
        raise ObsError("merge needs two FLIGHT snapshots (the chrome "
                       "trace has no epoch-stamped commit events)")
    commits = {}
    for e in leader["events"]:
        if e.get("kind") in _LEADER_COMMIT_KINDS and "epoch" in e:
            # keep the FIRST commit sighting per epoch (server.epoch
            # fires before sync.commit for the same epoch)
            commits.setdefault(int(e["epoch"]), e)
    applies = [e for e in follower["events"]
               if e.get("kind") == "repl.apply" and "epoch" in e]
    if not commits or not applies:
        raise ObsError(
            "no matching epoch stamps: leader has "
            f"{len(commits)} stamped commits, follower has "
            f"{len(applies)} repl.apply events — are the roles swapped?"
        )
    rows: List[dict] = []
    lags: List[float] = []
    for a in applies:
        ep = int(a["epoch"])
        c = commits.get(ep)
        if c is None:
            continue  # commit scrolled out of the leader's ring
        row = {"epoch": ep, "trace": a.get("trace")}
        if a.get("lag_ms") is not None:
            row["shipped_lag_ms"] = float(a["lag_ms"])
        if a.get("wall") is not None and c.get("wall") is not None:
            row["observed_lag_ms"] = round(
                max(0.0, (float(a["wall"]) - float(c["wall"])) * 1e3), 3
            )
        rows.append(row)
        lag = row.get("shipped_lag_ms", row.get("observed_lag_ms"))
        if lag is not None:
            lags.append(lag)
    if not rows:
        raise ObsError(
            "no epoch overlap between the two snapshots (the rings are "
            "bounded — dump closer to the window you care about)"
        )
    lags.sort()
    return {
        "count": len(rows),
        "lag_ms_p50": round(lags[len(lags) // 2], 3) if lags else None,
        "lag_ms_max": round(lags[-1], 3) if lags else None,
        "epochs": rows,
    }


def merged_chrome(leader: dict, follower: dict) -> dict:
    """Both flight streams as one chrome trace: instants on two
    process rows, ts normalized to the earlier wall-clock origin."""
    origin = min(
        [e["wall"] for e in leader["events"] if "wall" in e] +
        [e["wall"] for e in follower["events"] if "wall" in e]
    )
    out = []
    for pid, art in ((1, leader), (2, follower)):
        for e in art["events"]:
            if "wall" not in e:
                continue
            out.append({
                "name": e.get("kind", "?"),
                "ph": "i",
                "s": "t",
                "ts": (float(e["wall"]) - origin) * 1e6,
                "pid": pid,
                "tid": 0,
                "args": {k: v for k, v in e.items()
                         if k not in ("t", "wall", "kind")},
            })
    return {
        "traceEvents": out,
        "metadata": {"pids": {"1": "leader", "2": "follower"}},
    }


def render_merge(report: dict) -> str:
    lines = [
        f"replication-lag attribution: {report['count']} applies matched",
        f"  lag p50 {report['lag_ms_p50']}ms  max {report['lag_ms_max']}ms",
    ]
    for row in report["epochs"][:20]:
        bits = [f"epoch {row['epoch']:<6}"]
        if row.get("trace"):
            bits.append(f"trace {row['trace']:<14}")
        if "shipped_lag_ms" in row:
            bits.append(f"shipped {row['shipped_lag_ms']:.3f}ms")
        if "observed_lag_ms" in row:
            bits.append(f"observed {row['observed_lag_ms']:.3f}ms")
        lines.append("  " + "  ".join(bits))
    if len(report["epochs"]) > 20:
        lines.append(f"  ... {len(report['epochs']) - 20} more")
    return "\n".join(lines)


# -- CLI ----------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        if not argv or argv[0] in ("-h", "--help"):
            print(__doc__)
            return 0
        cmd, rest = argv[0], argv[1:]
        if cmd == "dump":
            from . import flight

            print(flight.dump(rest[0] if rest else None))
            return 0
        if cmd == "inspect":
            if not rest:
                raise ObsError("inspect needs an artifact path")
            for path in rest:
                print(render_inspect(load_artifact(path), path))
            return 0
        if cmd == "merge":
            out_path = None
            if "-o" in rest:
                i = rest.index("-o")
                if i + 1 >= len(rest):
                    raise ObsError("-o needs an output path")
                out_path = rest[i + 1]
                rest = rest[:i] + rest[i + 2:]
            if len(rest) != 2:
                raise ObsError(
                    "merge needs exactly <leader.json> <follower.json>"
                )
            leader, follower = (load_artifact(p) for p in rest)
            report = merge_lag(leader, follower)
            print(render_merge(report))
            if out_path is not None:
                with open(out_path, "w") as f:
                    json.dump(merged_chrome(leader, follower), f)
                print(f"merged chrome trace -> {out_path}")
            return 0
        raise ObsError(
            f"unknown subcommand {cmd!r}: use dump | inspect | merge"
        )
    except ObsError as e:
        print(f"obs.trace: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
