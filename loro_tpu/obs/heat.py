"""EWMA heat accounting for the serving hot paths (docs/OBSERVABILITY.md
"Health & heat").

The metrics registry answers "how much, ever"; this module answers
"how hot is doc 37 *right now*" — the windowed signal the ROADMAP
elastic-resharding rebalancer feeds on.  One process-global
``HeatAccountant`` holds exponentially-decayed event counts:

- **per doc**: ``push`` (SyncServer commit hook), ``pull``
  (``Session.pull``) and ``touch`` (TieredBatch ingest touches);
- **per shard**: ``ingest`` rounds, ``launch``es and ``degradation``
  commits (ShardedResidentServer);
- **revive pressure**: tier misses that forced a warm/cold revive
  (ResidencyManager ``_ensure_hot``).

Each tick decays the key's running sum by ``2 ** (-dt / half_life)``
and adds the event weight, so a key's *heat* is roughly "events in the
last half-life" and ``heat * ln2 / half_life`` estimates the current
events/second rate.  ``report()`` derives the three rebalancer inputs:
the top-K hot docs, the per-shard **skew ratio** (hottest shard's
ingest heat over the uniform share — 1.0 = perfectly balanced) and the
revive rate.

Hot-path contract: ``tick_*`` is called from serving paths while their
locks are held (``sync.server``, ``residency.plan``,
``sharded.route``), so the accountant's ``obs.health`` lock is a
near-leaf in analysis/lockorder.py and nothing is called while holding
it.  The disabled path (``disable()``) is one attribute check — zero
allocations, the count guard in tests/test_health.py.  Memory is
bounded: at most ``max_docs`` tracked docs (the coldest half is pruned
when the cap is hit).
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional

from ..analysis.lockwitness import named_lock

DEFAULT_HALF_LIFE_S = 30.0
DEFAULT_TOP_K = 8
MAX_TRACKED_DOCS = 8192

DOC_KINDS = ("push", "pull", "touch")
SHARD_KINDS = ("ingest", "launch", "degradation")

_LN2 = math.log(2.0)

# per-key row layout: [last_update_t, *per-kind decayed sums]
_T = 0


class HeatAccountant:
    """Decayed per-doc / per-shard event heat with an injected clock."""

    def __init__(self, clock=time.monotonic,
                 half_life_s: float = DEFAULT_HALF_LIFE_S,
                 top_k: int = DEFAULT_TOP_K,
                 max_docs: int = MAX_TRACKED_DOCS):
        if half_life_s <= 0:
            raise ValueError(f"half_life_s must be positive, got {half_life_s}")
        self._clock = clock
        self.half_life_s = float(half_life_s)
        self.top_k = int(top_k)
        self.max_docs = max(1, int(max_docs))
        self._on = True
        self._lock = named_lock("obs.health")
        self._docs: Dict[int, list] = {}    # di -> [t, push, pull, touch]
        self._shards: Dict[int, list] = {}  # s -> [t, ingest, launch, degr]
        self._n_shards = 0
        self._revive = [0.0, 0.0]           # [t, decayed sum]

    # -- switches -------------------------------------------------------
    @property
    def on(self) -> bool:
        return self._on

    def enable(self) -> None:
        self._on = True

    def disable(self) -> None:
        self._on = False

    def reset(self) -> None:
        with self._lock:
            self._docs.clear()
            self._shards.clear()
            self._n_shards = 0
            self._revive = [0.0, 0.0]

    # -- the hot path ---------------------------------------------------
    def _decay_row(self, row: list, now: float) -> None:
        dt = now - row[_T]
        if dt > 0.0:
            f = 2.0 ** (-dt / self.half_life_s)
            for i in range(1, len(row)):
                row[i] *= f
        row[_T] = now

    def tick_doc(self, di: int, kind: str, n: float = 1.0) -> None:
        """One doc-level serving event (``push``/``pull``/``touch``)."""
        if not self._on:
            return
        idx = 1 + DOC_KINDS.index(kind)
        now = self._clock()
        with self._lock:
            row = self._docs.get(di)
            if row is None:
                if len(self._docs) >= self.max_docs:
                    self._prune(now)
                row = self._docs[di] = [now, 0.0, 0.0, 0.0]
            self._decay_row(row, now)
            row[idx] += n

    def tick_shard(self, shard: int, kind: str, n: float = 1.0,
                   of: Optional[int] = None) -> None:
        """One shard-level event (``ingest``/``launch``/``degradation``).
        ``of`` teaches the accountant the total shard count so idle
        shards weigh into the skew ratio."""
        if not self._on:
            return
        idx = 1 + SHARD_KINDS.index(kind)
        now = self._clock()
        with self._lock:
            if of is not None and of > self._n_shards:
                self._n_shards = int(of)
            row = self._shards.get(shard)
            if row is None:
                row = self._shards[shard] = [now, 0.0, 0.0, 0.0]
            self._decay_row(row, now)
            row[idx] += n

    def tick_revive(self, n: float = 1.0) -> None:
        """One tier miss that forced a revive (warm/cold -> hot)."""
        if not self._on:
            return
        now = self._clock()
        with self._lock:
            row = self._revive
            dt = now - row[0]
            if dt > 0.0:
                row[1] *= 2.0 ** (-dt / self.half_life_s)
            row[0] = now
            row[1] += n

    def _prune(self, now: float) -> None:
        """Drop the coldest half of the tracked docs (caller holds the
        lock) — the cap is a memory bound, not an accuracy contract."""
        for row in self._docs.values():
            self._decay_row(row, now)
        ranked = sorted(
            self._docs.items(), key=lambda kv: sum(kv[1][1:]), reverse=True
        )
        self._docs = dict(ranked[: self.max_docs // 2])

    # -- reads ----------------------------------------------------------
    def _rate(self, heat: float) -> float:
        return heat * _LN2 / self.half_life_s

    def doc_heat(self, di: int) -> float:
        """Current total heat (decayed event count) for one doc."""
        now = self._clock()
        with self._lock:
            row = self._docs.get(di)
            if row is None:
                return 0.0
            self._decay_row(row, now)
            return sum(row[1:])

    def skew_ratio(self) -> Optional[float]:
        """Hottest shard's ingest heat over the uniform share (1.0 =
        balanced; None until any shard event was seen)."""
        now = self._clock()
        with self._lock:
            return self._skew_locked(now)

    def _skew_locked(self, now: float) -> Optional[float]:
        n = max(self._n_shards, len(self._shards))
        if not n or not self._shards:
            return None
        for row in self._shards.values():
            self._decay_row(row, now)
        totals = [row[1] for row in self._shards.values()]
        total = sum(totals)
        if total <= 0.0:
            return 1.0
        return round(max(totals) / (total / n), 4)

    def report(self) -> dict:
        """The rebalancer feed: top-K hot docs, per-shard heat + skew
        ratio vs uniform, revive pressure."""
        now = self._clock()
        with self._lock:
            for row in self._docs.values():
                self._decay_row(row, now)
            ranked = sorted(
                self._docs.items(), key=lambda kv: sum(kv[1][1:]),
                reverse=True,
            )
            top: List[dict] = []
            for di, row in ranked[: self.top_k]:
                heat = sum(row[1:])
                if heat <= 1e-9:
                    break
                top.append({
                    "doc": di,
                    "heat": round(heat, 4),
                    "per_s": round(self._rate(heat), 4),
                    "push": round(row[1], 4),
                    "pull": round(row[2], 4),
                    "touch": round(row[3], 4),
                })
            shards = {}
            for s in sorted(self._shards):
                row = self._shards[s]
                self._decay_row(row, now)
                shards[s] = {
                    "ingest": round(row[1], 4),
                    "launch": round(row[2], 4),
                    "degradation": round(row[3], 4),
                }
            skew = self._skew_locked(now)
            rrow = self._revive
            dt = now - rrow[0]
            revive_heat = rrow[1] * (
                2.0 ** (-dt / self.half_life_s) if dt > 0.0 else 1.0
            )
            return {
                "half_life_s": self.half_life_s,
                "tracked_docs": len(self._docs),
                "docs_top": top,
                "shards": shards,
                "n_shards": max(self._n_shards, len(self._shards)),
                "skew_ratio": skew,
                "revive_heat": round(revive_heat, 4),
                "revive_per_s": round(self._rate(revive_heat), 4),
            }


# -- module-level default accountant -----------------------------------
_default = HeatAccountant()


def accountant() -> HeatAccountant:
    return _default


def tick_doc(di: int, kind: str, n: float = 1.0) -> None:
    a = _default
    if a._on:
        a.tick_doc(di, kind, n)


def tick_shard(shard: int, kind: str, n: float = 1.0,
               of: Optional[int] = None) -> None:
    a = _default
    if a._on:
        a.tick_shard(shard, kind, n, of=of)


def tick_revive(n: float = 1.0) -> None:
    a = _default
    if a._on:
        a.tick_revive(n)


def report() -> dict:
    return _default.report()


def enable() -> None:
    _default.enable()


def disable() -> None:
    _default.disable()


def reset() -> None:
    _default.reset()
