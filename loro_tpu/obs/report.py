"""One-screen human summary of the obs registry.

``python -m loro_tpu.obs.report`` renders the live process registry
(useful at the end of a driver script, or from code via ``render()``);
``python -m loro_tpu.obs.report snap.json`` renders a saved snapshot
(the dict ``metrics.snapshot()`` / ``exposition.snapshot_json()``
produce — e.g. scraped from a serving process's ``/metrics.json``);
``-`` reads the snapshot from stdin.

The report groups metrics by layer prefix (``fleet.``, ``server.``,
``doc.``, ...) and derives the two numbers nobody should have to
compute by hand: the pad-waste ratio (padded-but-dead rows as a share
of all padded rows shipped to the device) and the distinct-padded-shape
count (the jit-cache-size proxy).
"""
from __future__ import annotations

import json
import sys
from typing import Dict, Optional

from . import metrics as _m

_WIDTH = 78


def _hist_summary_from_rows(rows) -> dict:
    count = sum(r["count"] for r in rows)
    total = sum(r["sum"] for r in rows)
    return {"count": count, "sum": total, "mean": (total / count) if count else 0.0}


def _fmt_num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        n = int(f)
        return f"{n:,}"
    return f"{f:,.4g}"


def _metric_total(snap_entry: dict) -> float:
    if snap_entry["type"] == "histogram":
        return float(sum(r["count"] for r in snap_entry["values"]))
    return float(sum(r["value"] for r in snap_entry["values"]))


def _labeled_rows(snap_entry: dict):
    return [r for r in snap_entry["values"] if r["labels"]]


def _windowed_rates_lines() -> list:
    """The "windowed rates" section (live render only): the active
    health plane's per-second rates + open alerts.  Empty when no
    plane is installed or it has too few samples."""
    from . import health as _health

    plane = _health.active()
    if plane is None:
        return []
    rates = plane.rates_report()
    alerts = plane.alerts()
    if not rates and not alerts:
        return []
    lines = ["[windowed rates]  (health plane, last "
             f"{plane.window_s:g}s window)"]
    for name in sorted(rates):
        lines.append(f"  {name:<44} {rates[name]:>10,.2f}/s")
    for a in alerts:
        lines.append(
            f"  ALERT {a['kind']} ({a['severity']}): {a['detail']}")
    return lines


def render(snapshot: Optional[dict] = None) -> str:
    """Format a snapshot (default: the live default registry) as a
    one-screen text report.  The live render appends a "windowed
    rates" section when a health plane is active."""
    live = snapshot is None
    snap = snapshot if snapshot is not None else _m.snapshot()
    lines = []
    bar = "=" * _WIDTH
    lines.append(bar)
    lines.append("loro_tpu.obs — metrics summary".center(_WIDTH))
    lines.append(bar)
    if not snap:
        lines.append("(no metrics recorded)")
        return "\n".join(lines)

    # -- derived headline numbers -------------------------------------
    head = []
    ops = snap.get("fleet.ops_merged_total")
    resident = snap.get("fleet.resident_rows_total")
    waste = snap.get("fleet.pad_waste_rows_total")
    if ops or resident or waste:
        # real device rows = one-shot merge rows + resident ingest rows
        # (the resident scatter's waste counter has its real-row twin
        # in resident_rows_total, not ops_merged_total)
        real = (_metric_total(ops) if ops else 0.0) + (
            _metric_total(resident) if resident else 0.0
        )
        dead = _metric_total(waste) if waste else 0.0
        shipped = real + dead
        if shipped:
            head.append(
                f"pad waste: {dead / shipped:6.1%} of device rows are padding "
                f"({_fmt_num(dead)} / {_fmt_num(shipped)})"
            )
    shapes = snap.get("fleet.padded_shapes_distinct")
    if shapes:
        head.append(
            f"distinct padded shapes (jit-cache proxy): "
            f"{_fmt_num(_metric_total(shapes))}"
        )
    rtt = snap.get("tunnel.rtt_ms")
    if rtt and rtt["values"]:
        head.append(f"tunnel RTT: {_fmt_num(rtt['values'][0]['value'])} ms")
    for h in head:
        lines.append("  * " + h)
    if head:
        lines.append("-" * _WIDTH)

    # -- per-layer sections -------------------------------------------
    groups: Dict[str, list] = {}
    for name in sorted(snap):
        layer = name.split(".", 1)[0] if "." in name else "misc"
        groups.setdefault(layer, []).append(name)
    for layer in sorted(groups):
        lines.append(f"[{layer}]")
        for name in groups[layer]:
            e = snap[name]
            if e["type"] == "histogram":
                s = _hist_summary_from_rows(e["values"])
                lines.append(
                    f"  {name:<44} n={_fmt_num(s['count']):>8}  "
                    f"mean={s['mean'] * 1e3:,.2f}ms  sum={s['sum']:,.3f}s"
                )
            else:
                lines.append(
                    f"  {name:<44} {_fmt_num(_metric_total(e)):>12}"
                )
            for row in _labeled_rows(e)[:8]:
                lbl = ",".join(f"{k}={v}" for k, v in sorted(row["labels"].items()))
                if e["type"] == "histogram":
                    mean = (row["sum"] / row["count"]) if row["count"] else 0.0
                    lines.append(
                        f"    {{{lbl}}}".ljust(46)
                        + f"n={row['count']:>8,}  mean={mean * 1e3:,.2f}ms"
                    )
                else:
                    lines.append(
                        f"    {{{lbl}}}".ljust(46)
                        + f"{_fmt_num(row['value']):>12}"
                    )
    if live:
        rl = _windowed_rates_lines()
        if rl:
            lines.append("-" * _WIDTH)
            lines.extend(rl)
    lines.append(bar)
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    if argv:
        raw = sys.stdin.read() if argv[0] == "-" else open(argv[0]).read()
        snap = json.loads(raw)
    else:
        snap = None  # live registry of this process
    print(render(snap))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
