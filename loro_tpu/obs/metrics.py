"""Process-wide metrics registry: counters, gauges, histograms, uniques.

The fleet merge path needs always-on accounting (pad waste, jit-shape
cardinality, launch counts, epoch wall times) the way loro's hot paths
carry `tracing` spans — but aggregated, not evented.  This registry is
the aggregation side: pure-stdlib, thread-safe, cheap enough to leave
on unconditionally (one dict lookup + lock per update; the hot callers
are chunky merge/ingest calls, never per-op loops).

Four metric kinds, all label-aware:

- ``Counter``   — monotone float, ``inc(n, **labels)``
- ``Gauge``     — last-write-wins float, ``set/inc/dec``
- ``Histogram`` — bucketed observations, ``observe(v, **labels)`` and a
  ``time()`` context manager; cumulative Prometheus-style buckets
- ``Unique``    — cardinality of a key set (the jit-cache-size proxy:
  ``add(shape_tuple)`` and the exported value is ``len(set)``)

Use through the module-level default registry::

    from loro_tpu.obs import metrics
    metrics.counter("fleet.ops_merged_total").inc(1024, family="text")
    with metrics.histogram("server.epoch_seconds").time(family="text"):
        ...

Naming convention: dotted ``layer.metric_total`` names (Prometheus
exposition maps dots to underscores).  ``snapshot()`` returns a
JSON-able dict; ``reset()`` clears all values (tests).
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# default histogram buckets: wide exponential range (seconds-ish scale,
# 100us .. 100s) — epoch wall times, span durations, RTTs all fit
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)


def _label_key(labels: Dict[str, object]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    """Common shell: name, help text, per-label-set values."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._values: Dict[LabelKey, object] = {}

    # -- snapshot helpers ---------------------------------------------
    def _value_rows(self) -> List[dict]:
        with self._lock:
            items = list(self._values.items())
        return [{"labels": dict(k), "value": v} for k, v in items]

    def snapshot(self) -> dict:
        return {"type": self.kind, "help": self.help, "values": self._value_rows()}

    def total(self) -> float:
        """Sum across label sets (counters/gauges; Unique overrides)."""
        with self._lock:
            return float(sum(self._values.values())) if self._values else 0.0


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + n

    def get(self, **labels) -> float:
        with self._lock:
            return float(self._values.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def get(self, **labels) -> float:
        with self._lock:
            return float(self._values.get(_label_key(labels), 0.0))


class _HistState:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        # per-bucket exemplar trace ids (lazily allocated: observations
        # without exemplars pay nothing — the common case)
        self.exemplars: Optional[list] = None


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, lock)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if not bs:
            raise ValueError(f"histogram {name}: empty bucket list")
        self.buckets = bs  # upper bounds; +Inf is implicit

    def observe(self, v: float, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation.  ``exemplar`` (a trace id) is
        retained per BUCKET (last-writer-wins, one string slot per
        bucket — bounded memory), so a p99 bucket in a dashboard is
        explorable: ``exemplars()`` hands back a concrete request id
        that landed there (docs/OBSERVABILITY.md "Request tracing")."""
        k = _label_key(labels)
        with self._lock:
            st = self._values.get(k)
            if st is None:
                st = self._values[k] = _HistState(len(self.buckets) + 1)
            i = 0
            n = len(self.buckets)
            while i < n and v > self.buckets[i]:
                i += 1
            st.counts[i] += 1
            st.sum += v
            st.count += 1
            if exemplar is not None:
                if st.exemplars is None:
                    st.exemplars = [None] * (n + 1)
                st.exemplars[i] = str(exemplar)

    @contextmanager
    def time(self, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    # -- reads ---------------------------------------------------------
    def _merged_state(self) -> _HistState:
        out = _HistState(len(self.buckets) + 1)
        with self._lock:
            for st in self._values.values():
                for i, c in enumerate(st.counts):
                    out.counts[i] += c
                out.sum += st.sum
                out.count += st.count
        return out

    def total(self) -> float:
        return float(self._merged_state().count)

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile over all label sets (p50/p99
        summaries for the bench sidecar).  None when empty."""
        st = self._merged_state()
        return _hist_quantile(self.buckets, st.counts, st.count, q)

    def summary(self) -> dict:
        """Compact cross-label summary: count/sum/mean/p50/p99."""
        st = self._merged_state()
        mean = (st.sum / st.count) if st.count else 0.0
        return {
            "count": st.count,
            "sum": round(st.sum, 6),
            "mean": round(mean, 6),
            "p50": _hist_quantile(self.buckets, st.counts, st.count, 0.50),
            "p99": _hist_quantile(self.buckets, st.counts, st.count, 0.99),
        }

    def exemplars(self, **labels) -> Dict[str, str]:
        """Per-bucket exemplar trace ids for one label set:
        ``{"le_0.05": "t1a2f-3", ..., "le_+Inf": ...}`` (only buckets
        that retained one).  Empty when no observation ever carried an
        exemplar."""
        with self._lock:
            st = self._values.get(_label_key(labels))
            if st is None or st.exemplars is None:
                return {}
            out = {}
            for i, ex in enumerate(st.exemplars):
                if ex is not None:
                    le = self.buckets[i] if i < len(self.buckets) else "+Inf"
                    out[f"le_{le}"] = ex
            return out

    def _value_rows(self) -> List[dict]:
        rows = []
        with self._lock:
            items = list(self._values.items())
        for k, st in items:
            cum = 0
            buckets = []
            for i, le in enumerate(self.buckets):
                cum += st.counts[i]
                buckets.append([le, cum])
            buckets.append(["+Inf", cum + st.counts[-1]])
            row = {
                "labels": dict(k),
                "count": st.count,
                "sum": st.sum,
                "buckets": buckets,
            }
            if st.exemplars is not None:
                row["exemplars"] = {
                    str(self.buckets[i] if i < len(self.buckets) else "+Inf"): ex
                    for i, ex in enumerate(st.exemplars) if ex is not None
                }
            rows.append(row)
        return rows


def _hist_quantile(bounds: Sequence[float], counts: Sequence[int],
                   total: int, q: float) -> Optional[float]:
    if not total:
        return None
    rank = q * total
    cum = 0
    for i, le in enumerate(bounds):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            lo = bounds[i - 1] if i else 0.0
            frac = (rank - prev) / max(counts[i], 1)
            return round(lo + (le - lo) * frac, 6)
    return bounds[-1]  # overflow bucket: clamp to the last bound


class Unique(_Metric):
    """Cardinality metric: value = number of distinct keys seen.  The
    jit-cache-size proxy — every padded device shape adds a key; the
    exported number approximates the jit cache entry count."""

    kind = "unique"

    def add(self, key, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            s = self._values.get(k)
            if s is None:
                s = self._values[k] = set()
            s.add(key)

    def get(self, **labels) -> int:
        with self._lock:
            s = self._values.get(_label_key(labels))
            return len(s) if s else 0

    def total(self) -> float:
        with self._lock:
            return float(sum(len(s) for s in self._values.values()))

    def _value_rows(self) -> List[dict]:
        with self._lock:
            items = [(k, len(s)) for k, s in self._values.items()]
        return [{"labels": dict(k), "value": n} for k, n in items]


class Registry:
    """Get-or-create metric registry.  Metric identity is the name; a
    second registration with a different kind raises (catches typo'd
    wiring at the call site, not in the dashboard)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, threading.Lock(), **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def unique(self, name: str, help: str = "") -> Unique:
        return self._get(Unique, name, help)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """JSON-able snapshot of every metric (exposition.snapshot_json
        round-trips this through json)."""
        return {m.name: m.snapshot() for m in self.metrics()}

    def reset(self) -> None:
        """Drop all metrics AND their values (tests; a live process
        keeps its registry for life)."""
        with self._lock:
            self._metrics.clear()


# -- module-level default registry ------------------------------------
_default = Registry()


def registry() -> Registry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _default.histogram(name, help, buckets)


def unique(name: str, help: str = "") -> Unique:
    return _default.unique(name, help)


def snapshot() -> dict:
    return _default.snapshot()


def reset() -> None:
    _default.reset()
