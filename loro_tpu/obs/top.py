"""``python -m loro_tpu.obs.top`` — one-screen fleet health view.

Renders the aggregated status payload (``health.status_payload()`` —
the same object ``/status.json`` and the net STATUS frame serve):
verdict banner, open alerts, windowed rates, the heat top-K with the
per-shard skew ratio, follower lag and the net edge.  Three sources:

- no argument: the LIVE in-process health plane, refreshed every
  ``--interval`` seconds (``--once`` renders a single screen — the
  in-process mode is what a driver script or test embeds);
- a file path: a SAVED ``/status.json`` snapshot (post-mortems,
  scraped payloads); ``-`` reads the snapshot from stdin;
- an ``http(s)://...`` URL: scrape a serving process's
  ``/status.json`` each refresh (stdlib urllib, no new deps).

See docs/OBSERVABILITY.md "Health & heat" for the payload catalogue
and the skew-ratio runbook.
"""
from __future__ import annotations

import json
import sys
import time
from typing import List, Optional

_WIDTH = 78

_VERDICT_MARK = {"ok": "OK", "degraded": "DEGRADED",
                 "critical": "CRITICAL", "unknown": "UNKNOWN"}


def _bar(ch: str = "=") -> str:
    return ch * _WIDTH


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.3f}".rstrip("0").rstrip(".") or "0"
    return str(v)


def render_status(payload: dict) -> str:
    """One screen of text for a status payload dict."""
    lines: List[str] = []
    verdict = payload.get("verdict", "unknown")
    mark = _VERDICT_MARK.get(verdict, verdict.upper())
    lines.append(_bar())
    lines.append(f"loro_tpu fleet health — {mark}".center(_WIDTH))
    lines.append(_bar())
    ticks = payload.get("ticks")
    if ticks is not None:
        lines.append(
            f"  ticks={ticks}  skipped={payload.get('skipped_ticks', 0)}"
            f"  window={_fmt(payload.get('window_s'))}s")
    for r in payload.get("reasons", []):
        lines.append(f"  ! {r}")
    alerts = payload.get("alerts") or []
    if alerts:
        lines.append(_bar("-"))
        lines.append("[alerts]")
        for a in alerts:
            lines.append(f"  {a.get('severity', '?'):<9} "
                         f"{a.get('kind', '?'):<20} {a.get('detail', '')}")
    rates = payload.get("rates") or {}
    if rates:
        lines.append(_bar("-"))
        lines.append("[windowed rates]")
        for name in sorted(rates):
            lines.append(f"  {name:<52} {rates[name]:>12,.2f}/s")
    heat = payload.get("heat") or {}
    docs_top = heat.get("docs_top") or []
    shards = heat.get("shards") or {}
    if docs_top or shards:
        lines.append(_bar("-"))
        skew = heat.get("skew_ratio")
        lines.append(
            f"[heat]  tracked_docs={_fmt(heat.get('tracked_docs'))}"
            f"  n_shards={_fmt(heat.get('n_shards'))}"
            f"  skew_ratio={_fmt(skew)}"
            f"  revive/s={_fmt(heat.get('revive_per_s'))}")
        if docs_top:
            lines.append(f"  {'doc':>6} {'heat':>10} {'per_s':>10} "
                         f"{'push':>8} {'pull':>8} {'touch':>8}")
            for d in docs_top:
                lines.append(
                    f"  {d.get('doc'):>6} {d.get('heat', 0):>10,.2f} "
                    f"{d.get('per_s', 0):>10,.3f} {d.get('push', 0):>8,.1f} "
                    f"{d.get('pull', 0):>8,.1f} {d.get('touch', 0):>8,.1f}")
        for s in sorted(shards):
            row = shards[s]
            lines.append(
                f"  shard {s}: ingest={row.get('ingest', 0):,.2f} "
                f"launch={row.get('launch', 0):,.2f} "
                f"degradation={row.get('degradation', 0):,.2f}")
    sh = payload.get("shards")
    persist = payload.get("persist")
    repl = payload.get("repl")
    net = payload.get("net")
    if sh or persist or repl or net:
        lines.append(_bar("-"))
        if sh:
            lines.append(
                f"[shards]  n={_fmt(sh.get('n_shards'))}"
                f"  degraded={sh.get('degraded') or 'none'}")
        if persist:
            lines.append(
                f"[persist]  durable_epoch={_fmt(persist.get('durable_epoch'))}")
        if repl:
            for f in repl.get("followers", []):
                if "unavailable" in f:
                    lines.append(f"[repl]  follower: {f['unavailable']}")
                else:
                    lines.append(
                        f"[repl]  follower {f.get('id')}: "
                        f"lag={_fmt(f.get('lag_epochs'))} epochs  "
                        f"applied={_fmt(f.get('applied_epoch'))}")
        if net:
            lines.append(
                f"[net]  {net.get('addr', '?')}  "
                f"connections={_fmt(net.get('connections'))}  "
                f"frame_errors={_fmt(net.get('frame_errors'))}")
    serving = payload.get("serving")
    if isinstance(serving, dict) and serving:
        lines.append(_bar("-"))
        parts = []
        for k in ("family", "sessions", "pushes", "pulls", "epoch",
                  "unavailable"):
            if k in serving:
                parts.append(f"{k}={_fmt(serving[k])}")
        if not parts:  # unknown report shape: show a stable prefix
            parts = [f"{k}={_fmt(serving[k])}"
                     for k in sorted(serving)[:6]]
        lines.append("[serving]  " + "  ".join(parts))
    lines.append(_bar())
    return "\n".join(lines)


def _load(source: Optional[str]) -> dict:
    if source is None:
        from . import health as _health

        return _health.status_payload()
    if source == "-":
        return json.loads(sys.stdin.read())
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(source, timeout=10.0) as resp:
            return json.loads(resp.read().decode("utf-8"))
    with open(source) as fh:
        return json.loads(fh.read())


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    interval = 2.0
    once = False
    source: Optional[str] = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--interval":
            i += 1
            interval = float(argv[i])
        elif a.startswith("--interval="):
            interval = float(a.split("=", 1)[1])
        elif a == "--once":
            once = True
        else:
            source = a
        i += 1
    if source is not None and source != "-" and not source.startswith(
            ("http://", "https://")):
        once = True  # a saved snapshot never changes: one screen
    if source == "-":
        once = True
    while True:
        print(render_status(_load(source)))
        if once:
            return 0
        try:
            time.sleep(interval)  # tpulint: disable=LT-TIME(interactive refresh-loop CLI, not a serving path — the render itself is clock-free)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
