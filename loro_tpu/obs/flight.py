"""Always-on flight recorder: a bounded ring of structured events.

The aggregate registry (metrics.py) answers "how many / how fast"; the
chrome tracer (utils/tracing.py) answers "where did the time go" when
you turned it on IN ADVANCE.  Neither answers the question that
actually follows a wedge or a degradation: *what happened in the last
few seconds before things went wrong* — the CLAUDE.md tunnel
post-mortems all died with nothing.  This module is the black box: an
always-on, capacity-bounded ring buffer of structured events (device
launches, WAL fsyncs, epoch commits, supervisor retries, degradations,
fault-site fires, lock-witness edges) that costs ~one lock + one slot
write per event while enabled and a single attribute check when
disabled (the no-op fast path — the count-based perf guard in
tests/test_obs.py holds it to zero net allocations per event).

The ring is ON by default with a small capacity (1024 events): memory
is bounded by construction (old events are overwritten, never
accumulated) and the hot callers are per-round / per-launch paths,
never per-op loops.

Dump points (docs/OBSERVABILITY.md "Flight recorder"):

- the chaos runner embeds ``tail()`` into every violation artifact;
- ``DeviceSupervisor.note_degradation`` and the probe wedge paths call
  ``dump_on(reason)`` — a no-op unless auto-dumping is armed
  (``LORO_FLIGHT_DIR=<dir>`` or ``set_auto_dump(dir)``), so tests that
  exercise degradation on purpose never litter the tree;
- ``python -m loro_tpu.obs.trace`` inspects/merges dumped files.

Thread contract: ``record()`` may be called from any thread, including
while holding other named locks — ``obs.flight`` is registered as the
innermost level in ``analysis/lockorder.py`` and a thread-local
reentrancy guard makes nested records (the lock witness observing the
flight lock itself) a silent no-op instead of a self-deadlock.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..analysis.lockwitness import named_lock

_WALL = time.time  # injectable wall clock (LT-TIME: reference, not a call site)


class FlightRecorder:
    """Bounded ring of ``{"i", "t", "wall", "kind", ...fields}`` events.

    ``capacity`` bounds memory; ``clock`` (monotonic-ish, relative
    ordering) and ``wall`` (cross-process correlation stamps) are
    injectable for fake-clock tests."""

    def __init__(self, capacity: int = 1024, clock=time.perf_counter,
                 wall=_WALL):
        self._lock = named_lock("obs.flight")
        self._clock = clock
        self._wall = wall
        self._on = True
        self._guard = threading.local()
        self._configure(capacity)

    def _configure(self, capacity: int) -> None:
        self.capacity = max(1, int(capacity))
        self._ring: List[Optional[tuple]] = [None] * self.capacity
        self._next = 0       # ring slot the next event lands in
        self._recorded = 0   # total events ever recorded
        self._dumps = 0

    # -- switches ------------------------------------------------------
    @property
    def on(self) -> bool:
        return self._on

    def enable(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and capacity != self.capacity:
                self._configure(capacity)
            self._on = True

    def disable(self) -> None:
        self._on = False

    def clear(self) -> None:
        with self._lock:
            self._configure(self.capacity)

    # -- the hot path --------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one event.  Disabled fast path: one attribute check,
        no lock, no slot write (net-zero allocations — the perf
        guard).  Reentrant records (an observer of the flight lock
        itself) are silently dropped instead of self-deadlocking."""
        if not self._on:
            return
        if getattr(self._guard, "held", False):
            return
        self._guard.held = True
        try:
            ev = (self._clock(), self._wall(), kind, fields or None)
            with self._lock:
                self._ring[self._next] = ev
                self._next = (self._next + 1) % self.capacity
                self._recorded += 1
        finally:
            self._guard.held = False

    # -- reads ---------------------------------------------------------
    def _ordered(self) -> List[tuple]:
        with self._lock:
            if self._recorded < self.capacity:
                raw = self._ring[: self._next]
            else:
                raw = self._ring[self._next:] + self._ring[: self._next]
            first = self._recorded - min(self._recorded, self.capacity)
            return [(first + i, ev) for i, ev in enumerate(raw)
                    if ev is not None]

    def events(self) -> List[Dict[str, Any]]:
        """Every retained event, oldest first, as JSON-able dicts."""
        out = []
        for i, (t, wall, kind, fields) in self._ordered():
            ev = {"i": i, "t": round(t, 6), "wall": wall, "kind": kind}
            if fields:
                ev.update(fields)
            out.append(ev)
        return out

    def tail(self, n: int = 200) -> List[Dict[str, Any]]:
        """The newest ``n`` events (oldest-first within the tail)."""
        return self.events()[-max(0, int(n)):]

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._recorded

    def snapshot(self) -> dict:
        """JSON-able dump: config + every retained event (the artifact
        format ``python -m loro_tpu.obs.trace`` reads)."""
        with self._lock:
            recorded, dumps = self._recorded, self._dumps
        return {
            "flight": 1,  # format tag (obs.trace dispatches on it)
            "pid": os.getpid(),
            "capacity": self.capacity,
            "recorded_total": recorded,
            "dumps": dumps,
            "events": self.events(),
        }

    # -- dumping -------------------------------------------------------
    def dump(self, path: Optional[str] = None) -> str:
        """Write the snapshot as JSON; returns the path.  The default
        path (under ``./log``) is collision-free: timestamp + pid + a
        per-recorder counter."""
        with self._lock:
            self._dumps += 1
            n = self._dumps
        if path is None:
            os.makedirs("log", exist_ok=True)
            path = os.path.join(
                "log",
                f"flight-{int(self._wall())}-{os.getpid()}-{n}.json",
            )
        snap = self.snapshot()
        with open(path, "w") as f:
            json.dump(snap, f)
        return path


# -- module-level default recorder -------------------------------------
# built LAZILY at first use, so a malformed LORO_FLIGHT_CAP raises a
# typed ConfigError at the first record()/recorder() call (the repo's
# knob convention) instead of an untyped ValueError at package import
_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()
_auto_dump_dir: Optional[str] = os.environ.get("LORO_FLIGHT_DIR") or None
_auto_dump_counter = itertools.count(1)


def _env_cap() -> int:
    raw = os.environ.get("LORO_FLIGHT_CAP", "").strip()
    if not raw:
        return 1024
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError("must be positive")
    except ValueError:
        from ..errors import ConfigError

        raise ConfigError(
            "LORO_FLIGHT_CAP", raw, "a positive integer event capacity"
        ) from None
    return v


def recorder() -> FlightRecorder:
    global _default
    r = _default
    if r is None:
        with _default_lock:
            if _default is None:
                _default = FlightRecorder(capacity=_env_cap())
            r = _default
    return r


def record(kind: str, **fields) -> None:
    recorder().record(kind, **fields)


def events() -> List[Dict[str, Any]]:
    return recorder().events()


def tail(n: int = 200) -> List[Dict[str, Any]]:
    return recorder().tail(n)


def snapshot() -> dict:
    return recorder().snapshot()


def enable(capacity: Optional[int] = None) -> None:
    recorder().enable(capacity)


def disable() -> None:
    recorder().disable()


def is_on() -> bool:
    return recorder().on


def clear() -> None:
    recorder().clear()


def dump(path: Optional[str] = None) -> str:
    return recorder().dump(path)


def set_auto_dump(dir: Optional[str]) -> None:
    """Arm (or disarm with None) failure-path auto-dumping: while
    armed, ``dump_on(reason)`` writes a snapshot into ``dir``.  Off by
    default so fault-injection tests exercising degradations on
    purpose never write files."""
    global _auto_dump_dir
    _auto_dump_dir = dir


def dump_on(reason: str) -> Optional[str]:
    """Failure-path hook (supervisor degradations, probe wedge paths):
    record the trigger, then write a snapshot IF auto-dumping is armed
    (``LORO_FLIGHT_DIR`` / ``set_auto_dump``).  Returns the path or
    None."""
    from . import metrics as _m

    record("flight.trigger", reason=reason)
    _m.counter(
        "flight.triggers_total",
        "failure-path flight-dump triggers (degradations, wedge paths)",
    ).inc(reason=reason)
    if _auto_dump_dir is None:
        return None
    try:
        os.makedirs(_auto_dump_dir, exist_ok=True)
        # a process-monotonic counter, NOT recorded_total: the ring
        # may be disabled (recorded_total frozen), and two same-reason
        # dumps must never overwrite the black box they exist to keep
        path = recorder().dump(os.path.join(
            _auto_dump_dir,
            f"flight-{reason.replace('/', '_')}-{os.getpid()}-"
            f"{next(_auto_dump_counter)}.json",
        ))
    except OSError:
        return None  # advisory: a full disk must not break degradation
    _m.counter("flight.dumps_total", "flight snapshots written").inc(
        reason=reason
    )
    return path
