"""ctypes binding + on-demand build of the native wire->SoA decoder.

Builds codec.cpp with g++ on first use (cached as codec.so next to the
source; rebuilt when the source is newer).  Falls back gracefully: all
callers must handle `available() == False` (pure-Python paths exist for
everything — the native decoder is the throughput path for fleet
decode, reference-parity with loro's Rust block decode).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..errors import CodecDecodeError
from ..obs import metrics as _obs
from ..resilience import faultinject as _fi

_fi.register_site(
    "decode", "native explode entries: truncate/bit-flip the wire bytes "
    "before the C++ parser sees them (typed CodecDecodeError -> the "
    "caller's Python-decoder fallback)")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.cpp")
_SO = os.path.join(_DIR, "codec.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process: concurrent builds don't race
    _obs.counter("codec.native_build_total").inc()
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, OSError):
        _obs.counter("codec.native_build_failed_total").inc()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _obs_decode(fn: str, payload: bytes) -> bytes:
    """Per-call decode accounting (docs/OBSERVABILITY.md): which native
    explode entry ran and how many wire bytes it chewed.  Also the
    fault-injection choke point: an armed ``decode`` fault truncates or
    bit-flips the payload here, before the C++ parser sees it — the
    parser must answer with a typed CodecDecodeError, never a crash."""
    payload = _fi.mangle("decode", payload)
    _obs.counter("codec.native_decode_calls_total").inc(fn=fn)
    _obs.counter("codec.native_decode_bytes_total").inc(len(payload), fn=fn)
    return payload


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        need_build = not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        if need_build and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        lib.loro_count_seq_elements.restype = ctypes.c_longlong
        lib.loro_count_seq_elements.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ]
        lib.loro_set_rowtable_budget.restype = None
        lib.loro_set_rowtable_budget.argtypes = [ctypes.c_longlong]
        lib.loro_explode_seq.restype = ctypes.c_longlong
        lib.loro_explode_seq.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 6 + [ctypes.c_longlong]
        lib.loro_count_seq_deletes.restype = ctypes.c_longlong
        lib.loro_count_seq_deletes.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ]
        lib.loro_count_seq_delta_rows.restype = ctypes.c_longlong
        lib.loro_count_seq_delta_rows.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ]
        lib.loro_explode_seq_delta.restype = ctypes.c_longlong
        lib.loro_explode_seq_delta.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 7 + [ctypes.c_longlong] + [ctypes.c_void_p] * 3 + [
            ctypes.c_longlong,
            ctypes.c_void_p,
        ]
        lib.loro_explode_seq_anchor_meta.restype = ctypes.c_longlong
        lib.loro_explode_seq_anchor_meta.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 5 + [ctypes.c_longlong]
        lib.loro_count_map_ops.restype = ctypes.c_longlong
        lib.loro_count_map_ops.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
        lib.loro_explode_map.restype = ctypes.c_longlong
        lib.loro_explode_map.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
        ] + [ctypes.c_void_p] * 6 + [ctypes.c_longlong]
        lib.loro_count_tree_ops.restype = ctypes.c_longlong
        lib.loro_count_tree_ops.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ]
        lib.loro_explode_tree.restype = ctypes.c_longlong
        lib.loro_explode_tree.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 10 + [ctypes.c_longlong]
        lib.loro_count_movable.restype = ctypes.c_longlong
        lib.loro_count_movable.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 3
        lib.loro_explode_movable.restype = ctypes.c_longlong
        lib.loro_explode_movable.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 15 + [ctypes.c_longlong] * 3
        lib.loro_explode_movable_delta.restype = ctypes.c_longlong
        lib.loro_explode_movable_delta.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.c_int,
        ] + [ctypes.c_void_p] * 15 + [ctypes.c_longlong] * 3 + [ctypes.c_void_p] * 2
        lib.loro_order_new.restype = ctypes.c_void_p
        lib.loro_order_new.argtypes = []
        lib.loro_order_free.restype = None
        lib.loro_order_free.argtypes = [ctypes.c_void_p]
        lib.loro_order_nrows.restype = ctypes.c_longlong
        lib.loro_order_nrows.argtypes = [ctypes.c_void_p]
        lib.loro_order_renumbers.restype = ctypes.c_longlong
        lib.loro_order_renumbers.argtypes = [ctypes.c_void_p]
        lib.loro_order_all_keys.restype = None
        lib.loro_order_all_keys.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.loro_order_append.restype = ctypes.c_longlong
        lib.loro_order_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
        ] + [ctypes.c_void_p] * 4 + [ctypes.c_longlong, ctypes.c_void_p]
        lib.loro_idmap_new.restype = ctypes.c_void_p
        lib.loro_idmap_new.argtypes = []
        lib.loro_idmap_free.restype = None
        lib.loro_idmap_free.argtypes = [ctypes.c_void_p]
        lib.loro_idmap_len.restype = ctypes.c_longlong
        lib.loro_idmap_len.argtypes = [ctypes.c_void_p]
        lib.loro_idmap_insert.restype = None
        lib.loro_idmap_insert.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
        ] + [ctypes.c_void_p] * 3
        lib.loro_idmap_stage.restype = None
        lib.loro_idmap_stage.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int,
        ]
        lib.loro_idmap_commit.restype = None
        lib.loro_idmap_commit.argtypes = [ctypes.c_void_p]
        lib.loro_idmap_abort.restype = None
        lib.loro_idmap_abort.argtypes = [ctypes.c_void_p]
        lib.loro_idmap_lookup.restype = None
        lib.loro_idmap_lookup.argtypes = [
            ctypes.c_void_p,
            ctypes.c_longlong,
        ] + [ctypes.c_void_p] * 3
        lib.loro_idmap_get.restype = ctypes.c_longlong
        lib.loro_idmap_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_longlong,
        ]
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def explode_seq_payload(payload: bytes, target_cid_index: int):
    """Parse a binary updates payload and return the element table of
    the target sequence container as numpy columns
    (parent, side, peer_idx, counter, deleted, content) or None if the
    native decoder is unavailable.  Raises ValueError on malformed
    payloads or unresolvable references (caller falls back to Python).
    """
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("seq", payload)
    n = lib.loro_count_seq_elements(payload, len(payload), target_cid_index)
    if n < 0:
        raise CodecDecodeError("native decode failed (malformed payload?)")
    parent = np.empty(n, np.int32)
    side = np.empty(n, np.int32)
    peer = np.empty(n, np.int32)
    counter = np.empty(n, np.int32)
    deleted = np.zeros(n, np.uint8)
    content = np.empty(n, np.int32)
    wrote = lib.loro_explode_seq(
        payload,
        len(payload),
        target_cid_index,
        parent.ctypes.data_as(ctypes.c_void_p),
        side.ctypes.data_as(ctypes.c_void_p),
        peer.ctypes.data_as(ctypes.c_void_p),
        counter.ctypes.data_as(ctypes.c_void_p),
        deleted.ctypes.data_as(ctypes.c_void_p),
        content.ctypes.data_as(ctypes.c_void_p),
        n,
    )
    if wrote != n:
        raise CodecDecodeError("native decode failed (unresolvable refs or count mismatch)")
    return parent, side, peer, counter, deleted.astype(bool), content


def explode_seq_delta_payload(payload: bytes, target_cid_index: int):
    """Incremental decode: element rows whose cross-payload parents come
    back as (peer_idx, counter) for host resolution (out_parent == -2),
    plus raw delete spans.  Returns a dict of numpy arrays or None if
    the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("seq_delta", payload)
    n = lib.loro_count_seq_delta_rows(payload, len(payload), target_cid_index)
    nd = lib.loro_count_seq_deletes(payload, len(payload), target_cid_index)
    if n < 0 or nd < 0:
        raise CodecDecodeError("native decode failed (malformed payload?)")
    parent = np.empty(n, np.int32)
    side = np.empty(n, np.int32)
    peer = np.empty(n, np.int32)
    counter = np.empty(n, np.int32)
    content = np.empty(n, np.int32)
    ext_peer = np.empty(n, np.int32)
    ext_ctr = np.empty(n, np.int64)
    del_peer = np.empty(nd, np.int32)
    del_start = np.empty(nd, np.int64)
    del_end = np.empty(nd, np.int64)
    n_del_out = ctypes.c_longlong(0)
    wrote = lib.loro_explode_seq_delta(
        payload,
        len(payload),
        target_cid_index,
        parent.ctypes.data_as(ctypes.c_void_p),
        side.ctypes.data_as(ctypes.c_void_p),
        peer.ctypes.data_as(ctypes.c_void_p),
        counter.ctypes.data_as(ctypes.c_void_p),
        content.ctypes.data_as(ctypes.c_void_p),
        ext_peer.ctypes.data_as(ctypes.c_void_p),
        ext_ctr.ctypes.data_as(ctypes.c_void_p),
        n,
        del_peer.ctypes.data_as(ctypes.c_void_p),
        del_start.ctypes.data_as(ctypes.c_void_p),
        del_end.ctypes.data_as(ctypes.c_void_p),
        nd,
        ctypes.byref(n_del_out),
    )
    if wrote != n:
        raise CodecDecodeError("native delta decode failed")
    return {
        "parent": parent,
        "side": side,
        "peer_idx": peer,
        "counter": counter,
        "content": content,
        "ext_peer_idx": ext_peer,
        "ext_counter": ext_ctr,
        "del_peer_idx": del_peer[: n_del_out.value],
        "del_start": del_start[: n_del_out.value],
        "del_end": del_end[: n_del_out.value],
    }


def explode_seq_anchor_meta(payload: bytes, target_cid_index: int):
    """Style-anchor metadata in the same row numbering as
    explode_seq_delta_payload (host pairs anchors to device rows by the
    `row` ordinal).  Values stay encoded — `voffset` feeds
    decode_value_at.  Returns a dict of numpy columns or None when the
    native library is unavailable; raises ValueError on malformed
    payloads."""
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("seq_anchor", payload)
    n = lib.loro_explode_seq_anchor_meta(
        payload, len(payload), target_cid_index, None, None, None, None, None, 0
    )
    if n < 0:
        raise CodecDecodeError("native anchor decode failed (malformed payload?)")
    row = np.empty(n, np.int64)
    key = np.empty(n, np.int32)
    voff = np.empty(n, np.int64)
    lam = np.empty(n, np.int32)
    flags = np.empty(n, np.int32)
    wrote = lib.loro_explode_seq_anchor_meta(
        payload,
        len(payload),
        target_cid_index,
        row.ctypes.data_as(ctypes.c_void_p),
        key.ctypes.data_as(ctypes.c_void_p),
        voff.ctypes.data_as(ctypes.c_void_p),
        lam.ctypes.data_as(ctypes.c_void_p),
        flags.ctypes.data_as(ctypes.c_void_p),
        n,
    )
    if wrote != n:
        raise CodecDecodeError("native anchor decode failed")
    return {"row": row, "key_idx": key, "voffset": voff, "lamport": lam, "flags": flags}


def explode_map_payload(payload: bytes):
    """All MapSet/MapDel rows of a payload, or None when the native
    library is unavailable.  Returns a dict with numpy columns
    (cid_idx, key_idx, lamport, peer_rank, value_ordinal|-1) and the
    decoding tables (peers sorted-u64, keys, cids).  peer_rank follows
    the sorted-peer ordering the LWW kernels' (lamport, peer) tie-break
    contract requires — NOT wire registration order."""
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("map", payload)
    n = lib.loro_count_map_ops(payload, len(payload))
    if n < 0:
        raise CodecDecodeError("native decode failed (malformed payload?)")
    cid = np.empty(n, np.int32)
    key = np.empty(n, np.int32)
    lamport = np.empty(n, np.int32)
    peer = np.empty(n, np.int32)
    value = np.empty(n, np.int32)
    voffset = np.empty(n, np.int64)
    wrote = lib.loro_explode_map(
        payload,
        len(payload),
        cid.ctypes.data_as(ctypes.c_void_p),
        key.ctypes.data_as(ctypes.c_void_p),
        lamport.ctypes.data_as(ctypes.c_void_p),
        peer.ctypes.data_as(ctypes.c_void_p),
        value.ctypes.data_as(ctypes.c_void_p),
        voffset.ctypes.data_as(ctypes.c_void_p),
        n,
    )
    if wrote != n:
        raise CodecDecodeError("native decode failed (count mismatch)")
    # wire peer table is registration-ordered; remap to sorted ranks
    # (same contract handling as extract_seq_from_payload).  read_tables
    # raises a typed CodecDecodeError itself on truncated preludes.
    from ..codec.binary import read_tables

    peers_wire, keys, cids, _r = read_tables(payload)
    order = np.argsort(np.asarray(peers_wire, np.uint64), kind="stable")
    rank_of = np.empty(len(peers_wire), np.int32)
    rank_of[order] = np.arange(len(peers_wire), dtype=np.int32)
    peer_rank = rank_of[peer] if len(peers_wire) else peer
    return {
        "cid_idx": cid,
        "key_idx": key,
        "lamport": lamport,
        "peer_rank": peer_rank.astype(np.int32),
        "peer_u64": np.asarray([peers_wire[i] for i in peer], dtype=object),
        "value_ordinal": value,
        "value_offset": voffset,  # byte offset into the payload (-1 = delete)
        "peers": sorted(peers_wire),
        "keys": keys,
        "cids": cids,
    }


def decode_value_at(payload: bytes, offset: int, cids):
    """Decode one tagged value at a native-reported byte offset (lazy
    winner-only decoding for DeviceMapBatch)."""
    from ..codec.binary import Reader, _read_value

    r = Reader(payload)
    r.i = offset
    return _read_value(r, cids)


def explode_tree_payload(payload: bytes, target_cid_index: int):
    """All TreeMove rows of one container (wire order) as numpy
    columns, or None when the native library is unavailable.  Peer
    columns are WIRE indexes; positions are (offset, len) into the
    payload."""
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("tree", payload)
    n = lib.loro_count_tree_ops(payload, len(payload), target_cid_index)
    if n < 0:
        raise CodecDecodeError("native decode failed (malformed payload?)")
    cols = {
        "lamport": np.empty(n, np.int32),
        "peer_idx": np.empty(n, np.int32),
        "counter": np.empty(n, np.int32),
        "target_peer_idx": np.empty(n, np.int32),
        "target_ctr": np.empty(n, np.int32),
        "flags": np.empty(n, np.int32),
        "parent_peer_idx": np.empty(n, np.int32),
        "parent_ctr": np.empty(n, np.int32),
        "pos_off": np.empty(n, np.int64),
        "pos_len": np.empty(n, np.int32),
    }
    wrote = lib.loro_explode_tree(
        payload,
        len(payload),
        target_cid_index,
        *[a.ctypes.data_as(ctypes.c_void_p) for a in cols.values()],
        n,
    )
    if wrote != n:
        raise CodecDecodeError("native decode failed (count mismatch)")
    return cols


def explode_movable_payload(payload: bytes, target_cid_index: int):
    """Slots / sets / delete spans of one MovableList container, or
    None when unavailable.  Raises ValueError on malformed input or
    out-of-payload references (caller falls back to Python).  Value
    columns carry byte offsets; winners decode lazily."""
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("movable", payload)
    n_slots = ctypes.c_longlong()
    n_sets = ctypes.c_longlong()
    n_dels = ctypes.c_longlong()
    rc = lib.loro_count_movable(
        payload,
        len(payload),
        target_cid_index,
        ctypes.byref(n_slots),
        ctypes.byref(n_sets),
        ctypes.byref(n_dels),
    )
    if rc < 0:
        raise CodecDecodeError("native decode failed (malformed payload?)")
    ns, nv, nd = n_slots.value, n_sets.value, n_dels.value
    slots = {
        "parent": np.empty(ns, np.int32),
        "side": np.empty(ns, np.int32),
        "peer_idx": np.empty(ns, np.int32),
        "counter": np.empty(ns, np.int32),
        "lamport": np.empty(ns, np.int32),
        "elem_peer_idx": np.empty(ns, np.int32),
        "elem_ctr": np.empty(ns, np.int32),
    }
    sets = {
        "elem_peer_idx": np.empty(nv, np.int32),
        "elem_ctr": np.empty(nv, np.int32),
        "lamport": np.empty(nv, np.int32),
        "peer_idx": np.empty(nv, np.int32),
        "value_off": np.empty(nv, np.int64),
    }
    dels = {
        "peer_idx": np.empty(nd, np.int32),
        "start": np.empty(nd, np.int64),
        "end": np.empty(nd, np.int64),
    }
    wrote = lib.loro_explode_movable(
        payload,
        len(payload),
        target_cid_index,
        *[a.ctypes.data_as(ctypes.c_void_p) for a in slots.values()],
        *[a.ctypes.data_as(ctypes.c_void_p) for a in sets.values()],
        *[a.ctypes.data_as(ctypes.c_void_p) for a in dels.values()],
        ns,
        nv,
        nd,
    )
    if wrote != ns:
        raise CodecDecodeError("native decode failed (unresolvable refs or count mismatch)")
    return {"slots": slots, "sets": sets, "dels": dels}


def explode_movable_delta_payload(payload: bytes, target_cid_index: int):
    """Delta variant of explode_movable_payload: slot parents that don't
    resolve inside the payload come back as parent == -2 with
    (ext_peer_idx, ext_counter) pairs for host resolution against the
    resident batch's id map (DeviceMovableBatch.append_payloads)."""
    lib = _load()
    if lib is None:
        return None
    payload = _obs_decode("movable_delta", payload)
    n_slots = ctypes.c_longlong()
    n_sets = ctypes.c_longlong()
    n_dels = ctypes.c_longlong()
    rc = lib.loro_count_movable(
        payload,
        len(payload),
        target_cid_index,
        ctypes.byref(n_slots),
        ctypes.byref(n_sets),
        ctypes.byref(n_dels),
    )
    if rc < 0:
        raise CodecDecodeError("native decode failed (malformed payload?)")
    ns, nv, nd = n_slots.value, n_sets.value, n_dels.value
    slots = {
        "parent": np.empty(ns, np.int32),
        "side": np.empty(ns, np.int32),
        "peer_idx": np.empty(ns, np.int32),
        "counter": np.empty(ns, np.int32),
        "lamport": np.empty(ns, np.int32),
        "elem_peer_idx": np.empty(ns, np.int32),
        "elem_ctr": np.empty(ns, np.int32),
    }
    sets = {
        "elem_peer_idx": np.empty(nv, np.int32),
        "elem_ctr": np.empty(nv, np.int32),
        "lamport": np.empty(nv, np.int32),
        "peer_idx": np.empty(nv, np.int32),
        "value_off": np.empty(nv, np.int64),
    }
    dels = {
        "peer_idx": np.empty(nd, np.int32),
        "start": np.empty(nd, np.int64),
        "end": np.empty(nd, np.int64),
    }
    ext_peer = np.empty(ns, np.int32)
    ext_ctr = np.empty(ns, np.int64)
    wrote = lib.loro_explode_movable_delta(
        payload,
        len(payload),
        target_cid_index,
        *[a.ctypes.data_as(ctypes.c_void_p) for a in slots.values()],
        *[a.ctypes.data_as(ctypes.c_void_p) for a in sets.values()],
        *[a.ctypes.data_as(ctypes.c_void_p) for a in dels.values()],
        ns,
        nv,
        nd,
        ext_peer.ctypes.data_as(ctypes.c_void_p),
        ext_ctr.ctypes.data_as(ctypes.c_void_p),
    )
    if wrote != ns:
        raise CodecDecodeError("native delta decode failed")
    slots["ext_peer_idx"] = ext_peer
    slots["ext_counter"] = ext_ctr
    return {"slots": slots, "sets": sets, "dels": dels}


class NativeShadowOrder:
    """C++ twin of parallel.order_maintenance.ShadowOrder (same
    algorithm — keys are bit-identical; the Python engine is the
    differential oracle).  Construct via native_order() which returns
    None when the library is unavailable."""

    __slots__ = ("_lib", "_h")

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.loro_order_new()

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.loro_order_free(h)
            self._h = None

    @property
    def renumbers(self) -> int:
        return int(self._lib.loro_order_renumbers(self._h))

    @property
    def n(self) -> int:
        return int(self._lib.loro_order_nrows(self._h))

    def append_rows(self, rows, base_row: int):
        parent = np.asarray([r[0] for r in rows], np.int32)
        side = np.asarray([r[1] for r in rows], np.int32)
        peer = np.asarray([r[2] for r in rows], np.uint64)
        ctr = np.asarray([r[3] for r in rows], np.int64)
        return self.append_arrays(parent, side, peer, ctr, base_row)

    def append_arrays(self, parent, side, peer, ctr, base_row: int):
        """Columnar append (the hot resident-ingest path — no Python
        tuple round trip).  Same return contract as append_rows."""
        parent = np.ascontiguousarray(parent, np.int32)
        side = np.ascontiguousarray(side, np.int32)
        peer = np.ascontiguousarray(peer, np.uint64)
        ctr = np.ascontiguousarray(ctr, np.int64)
        out = np.empty(len(parent), np.int64)
        rc = self._lib.loro_order_append(
            self._h,
            len(parent),
            parent.ctypes.data_as(ctypes.c_void_p),
            side.ctypes.data_as(ctypes.c_void_p),
            peer.ctypes.data_as(ctypes.c_void_p),
            ctr.ctypes.data_as(ctypes.c_void_p),
            base_row,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        if rc < 0:
            raise ValueError("native order append: non-contiguous base row")
        if rc == 1:
            return None  # renumbered: caller re-uploads all_keys()
        return out  # int64 ndarray (split_keys consumes it directly)

    def all_keys(self) -> np.ndarray:
        n = self.n
        out = np.empty(n, np.int64)
        self._lib.loro_order_all_keys(self._h, out.ctypes.data_as(ctypes.c_void_p))
        return out


def native_order():
    lib = _load()
    if lib is None:
        return None
    return NativeShadowOrder(lib)


class NativeIdMap:
    """C++ (peer, counter) -> device-row map with the staging contract
    the resident batches need (stage / staged-aware lookup / commit |
    abort) plus the dict-like subset the Python fallback paths use.
    Bit-compatible drop-in for the per-doc id2row dicts — the per-row
    Python dict traffic was the r4 host-funnel cost center."""

    __slots__ = ("_lib", "_h")

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.loro_idmap_new()

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.loro_idmap_free(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.loro_idmap_len(self._h))

    def __bool__(self) -> bool:
        return len(self) > 0

    # -- dict-like subset (fallback walks, resolve_row) ---------------
    def get(self, key, default=None):
        r = self._lib.loro_idmap_get(
            self._h, ctypes.c_uint64(key[0]), ctypes.c_longlong(key[1])
        )
        return default if r < 0 else int(r)

    def __getitem__(self, key):
        r = self.get(key)
        if r is None:
            raise KeyError(key)
        return r

    def __contains__(self, key) -> bool:
        return self.get(key) is not None

    def update(self, d) -> None:
        """Committed bulk insert from a Python dict (fallback-path
        overlay commits)."""
        if not d:
            return
        n = len(d)
        peer = np.fromiter((k[0] for k in d), np.uint64, n)
        ctr = np.fromiter((k[1] for k in d), np.int64, n)
        rows = np.fromiter(d.values(), np.int32, n)
        self.insert_arrays(peer, ctr, rows)

    # -- columnar hot path --------------------------------------------
    def insert_arrays(self, peer, ctr, rows) -> None:
        peer = np.ascontiguousarray(peer, np.uint64)
        ctr = np.ascontiguousarray(ctr, np.int64)
        rows = np.ascontiguousarray(rows, np.int32)
        self._lib.loro_idmap_insert(
            self._h,
            len(peer),
            peer.ctypes.data_as(ctypes.c_void_p),
            ctr.ctypes.data_as(ctypes.c_void_p),
            rows.ctypes.data_as(ctypes.c_void_p),
        )

    def stage_base(self, peer, ctr, base_row: int) -> None:
        peer = np.ascontiguousarray(peer, np.uint64)
        ctr = np.ascontiguousarray(ctr, np.int64)
        self._lib.loro_idmap_stage(
            self._h,
            len(peer),
            peer.ctypes.data_as(ctypes.c_void_p),
            ctr.ctypes.data_as(ctypes.c_void_p),
            base_row,
        )

    def lookup(self, peer, ctr) -> np.ndarray:
        """Staged-first batch lookup; -1 = missing."""
        peer = np.ascontiguousarray(peer, np.uint64)
        ctr = np.ascontiguousarray(ctr, np.int64)
        out = np.empty(len(peer), np.int32)
        self._lib.loro_idmap_lookup(
            self._h,
            len(peer),
            peer.ctypes.data_as(ctypes.c_void_p),
            ctr.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out

    def commit(self) -> None:
        self._lib.loro_idmap_commit(self._h)

    def abort(self) -> None:
        self._lib.loro_idmap_abort(self._h)


def native_idmap():
    lib = _load()
    if lib is None:
        return None
    return NativeIdMap(lib)
