// Native wire->SoA decoder: parses the loro_tpu binary updates payload
// and explodes sequence-container ops straight into columnar element
// arrays (the host side of the fleet merge pipeline).
//
// Role parity: the reference's Rust block decode
// (crates/loro-internal/src/oplog/change_store/block_encode.rs) turns
// columnar wire blocks into ops; here the native decoder goes one step
// further and emits the padded element table the device kernels consume
// (SURVEY.md §2.4: "block decode (columnar RLE -> dense device arrays)
// overlapped with device merge").
//
// C ABI only (ctypes binding in loro_tpu/native/__init__.py).
// Format: see loro_tpu/codec/binary.py (LEB128/zigzag, dictionaries,
// change meta, per-op payloads).

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint8_t u8() {
    if (p >= end) { ok = false; return 0; }
    return *p++;
  }
  uint64_t varint() {
    uint64_t v = 0; int shift = 0;
    while (true) {
      if (p >= end || shift > 63) { ok = false; return 0; }
      uint8_t b = *p++;
      v |= (uint64_t)(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
  }
  int64_t zigzag() {
    uint64_t v = varint();
    return (v & 1) ? -(int64_t)((v + 1) >> 1) : (int64_t)(v >> 1);
  }
  uint64_t u64le() {
    if (end - p < 8) { ok = false; return 0; }
    uint64_t v; std::memcpy(&v, p, 8); p += 8; return v;
  }
  double f64() {
    if (end - p < 8) { ok = false; return 0; }
    double v; std::memcpy(&v, p, 8); p += 8; return v;
  }
  bool skip_bytes() {
    uint64_t n = varint();
    // compare against remaining length, never `p + n` (pointer overflow
    // on crafted huge lengths would wrap past `end`)
    if (!ok || n > (uint64_t)(end - p)) { ok = false; return false; }
    p += n; return true;
  }
  const uint8_t* bytes(uint64_t* n_out) {
    uint64_t n = varint();
    if (!ok || n > (uint64_t)(end - p)) { ok = false; return nullptr; }
    const uint8_t* q = p; p += n; *n_out = n; return q;
  }
};

// op kind tags (binary.py)
enum { K_MAP_SET = 0, K_MAP_DEL, K_INSERT_TEXT, K_INSERT_VALUES,
       K_INSERT_ANCHOR, K_DELETE, K_TREE, K_COUNTER, K_MSET, K_MMOVE,
       K_UNKNOWN };
// value tags
enum { VNULL = 0, VTRUE, VFALSE, VINT, VF64, VSTR, VBYTES, VLIST, VMAP, VCID };
enum { PT_NONE = 0, PT_ID = 1, PT_RUNCONT = 2 };

bool skip_value(Reader& r) {
  switch (r.u8()) {
    case VNULL: case VTRUE: case VFALSE: return r.ok;
    case VINT: r.zigzag(); return r.ok;
    case VF64: r.f64(); return r.ok;
    case VSTR: case VBYTES: return r.skip_bytes();
    case VLIST: {
      uint64_t n = r.varint();
      for (uint64_t i = 0; i < n && r.ok; i++) skip_value(r);
      return r.ok;
    }
    case VMAP: {
      uint64_t n = r.varint();
      for (uint64_t i = 0; i < n && r.ok; i++) { r.skip_bytes(); skip_value(r); }
      return r.ok;
    }
    case VCID: r.varint(); return r.ok;
    default: r.ok = false; return false;
  }
}

struct ChangeMeta;

// open-addressing hash map: (peer_idx, counter) -> element row
struct IdMap {
  std::vector<uint64_t> keys;
  std::vector<int32_t> vals;
  uint64_t mask;
  explicit IdMap(size_t n) {
    size_t cap = 16;
    while (cap < n * 2) cap <<= 1;
    keys.assign(cap, ~0ull);
    vals.assign(cap, -1);
    mask = cap - 1;
  }
  IdMap(uint64_t, const std::vector<ChangeMeta>&, size_t n)
      : IdMap(n > 16 ? n : 16) {}
  static uint64_t mix(uint64_t k) {
    k ^= k >> 33; k *= 0xff51afd7ed558ccdULL; k ^= k >> 33;
    k *= 0xc4ceb9fe1a85ec53ULL; k ^= k >> 33; return k;
  }
  void put(uint64_t k, int32_t v) {
    uint64_t i = mix(k) & mask;
    while (keys[i] != ~0ull && keys[i] != k) i = (i + 1) & mask;
    keys[i] = k; vals[i] = v;
  }
  int32_t get(uint64_t k) const {
    uint64_t i = mix(k) & mask;
    while (keys[i] != ~0ull) {
      if (keys[i] == k) return vals[i];
      i = (i + 1) & mask;
    }
    return -1;
  }
  bool overflow() const { return false; }
};

inline uint64_t idkey(uint32_t peer_idx, int64_t counter) {
  return ((uint64_t)peer_idx << 40) | (uint64_t)(counter & 0xffffffffffLL);
}

struct ChangeMeta {
  uint32_t peer_idx;
  int64_t ctr;
  int64_t lamport;
  uint64_t n_ops;
};

// Direct-address (peer, counter) -> row table: causal payloads have
// near-dense insert counters per peer, so idkey lookups become plain
// array loads (~2x on the 182k-row trace vs the open-addressing map,
// whose random probes miss cache).  Per-peer vectors grow on demand;
// a global entry budget guards against adversarial sparse counters
// (huge delete spans between inserts) — on overflow the caller falls
// back to the IdMap path, so behavior is identical on any input.
struct RowTable {
  std::vector<std::vector<int32_t>> t;
  std::vector<uint64_t> base;  // 40-bit masked, matching idkey()
  size_t total = 0, budget;
  bool over = false;
  RowTable(uint64_t n_peers, const std::vector<ChangeMeta>& metas,
           size_t n_elems);
  // index math in uint64: crafted payloads can carry counters anywhere
  // in the zigzag range, and signed subtraction would be UB; a wrapped
  // huge index simply trips the budget -> IdMap fallback
  void put(uint64_t key, int32_t row) {
    uint32_t p = (uint32_t)(key >> 40);
    uint64_t i = (key & 0xffffffffffULL) - base[p];
    auto& v = t[p];
    if (i >= v.size()) {
      if (i >= budget) { over = true; return; }
      size_t ns = (size_t)i + 1 + ((size_t)i >> 1) + 64;
      if (total + (ns - v.size()) > budget) { over = true; return; }
      total += ns - v.size();
      v.resize(ns, -1);
    }
    v[(size_t)i] = row;
  }
  int32_t get(uint64_t key) const {
    uint32_t p = (uint32_t)(key >> 40);
    if (p >= t.size()) return -1;
    uint64_t i = (key & 0xffffffffffULL) - base[p];
    if (i >= t[p].size()) return -1;
    return t[p][(size_t)i];
  }
  bool overflow() const { return over; }
};

// test hook: force a tiny budget so the IdMap fallback path is
// exercisable from the differential suite (0 = no override)
long long g_rowtable_budget_override = 0;

inline RowTable::RowTable(uint64_t n_peers,
                          const std::vector<ChangeMeta>& metas,
                          size_t n_elems)
    : budget(g_rowtable_budget_override > 0
                 ? (size_t)g_rowtable_budget_override
                 : n_elems * 8 + (1u << 20)) {
  t.resize(n_peers);
  base.assign(n_peers, ~0ull);
  for (auto& m : metas) {
    uint64_t c = (uint64_t)m.ctr & 0xffffffffffULL;
    if (c < base[m.peer_idx]) base[m.peer_idx] = c;
  }
}

// Strict UTF-8: validates continuation prefixes, rejects overlong
// encodings, surrogates, and > U+10FFFF (a corrupted-but-CRC-valid
// payload must fail decode, not produce wrong codepoints).  Returns
// bytes consumed, or -1 on malformed input.
inline int decode_utf8_cp(const uint8_t* s, uint64_t nb, uint64_t i, uint32_t* out) {
  uint8_t b0 = s[i];
  uint32_t cp; int extra;
  if (b0 < 0x80) { cp = b0; extra = 0; }
  else if ((b0 & 0xe0) == 0xc0) { cp = b0 & 0x1f; extra = 1; }
  else if ((b0 & 0xf0) == 0xe0) { cp = b0 & 0x0f; extra = 2; }
  else if ((b0 & 0xf8) == 0xf0) { cp = b0 & 0x07; extra = 3; }
  else return -1;
  if (i + (uint64_t)extra >= nb && extra > 0) return -1;
  for (int e = 1; e <= extra; e++) {
    if ((s[i + e] & 0xc0) != 0x80) return -1;
    cp = (cp << 6) | (s[i + e] & 0x3f);
  }
  static const uint32_t min_cp[4] = {0, 0x80, 0x800, 0x10000};
  if (extra > 0 && cp < min_cp[extra]) return -1;          // overlong
  if (cp >= 0xd800 && cp <= 0xdfff) return -1;             // surrogate
  if (cp > 0x10ffff) return -1;
  *out = cp;
  return extra + 1;
}

// Parse header tables + change meta.  Returns false on malformed input.
bool parse_prelude(Reader& r, uint64_t* n_peers, std::vector<int32_t>& cid_types,
                   std::vector<ChangeMeta>& metas, uint64_t* n_keys_out = nullptr) {
  *n_peers = r.varint();
  if (!r.ok || *n_peers > 1u << 24) return false;
  for (uint64_t i = 0; i < *n_peers; i++) r.u64le();
  uint64_t n_keys = r.varint();
  if (!r.ok || n_keys > 1u << 26) return false;
  if (n_keys_out) *n_keys_out = n_keys;
  for (uint64_t i = 0; i < n_keys; i++)
    if (!r.skip_bytes()) return false;
  uint64_t n_cids = r.varint();
  if (!r.ok || n_cids > 1u << 26) return false;
  cid_types.resize(n_cids);
  for (uint64_t i = 0; i < n_cids; i++) {
    uint8_t b = r.u8();
    cid_types[i] = b & 0x7f;
    if (b & 0x80) {
      if (!r.skip_bytes()) return false;  // root name
    } else {
      r.varint(); r.zigzag();  // peer idx + counter
    }
  }
  uint64_t n_changes = r.varint();
  if (!r.ok || n_changes > 1u << 28) return false;
  metas.resize(n_changes);
  for (uint64_t i = 0; i < n_changes; i++) {
    uint64_t pidx = r.varint();
    if (!r.ok || pidx >= *n_peers) return false;  // wire index must hit the peer table
    metas[i].peer_idx = (uint32_t)pidx;
    metas[i].ctr = r.zigzag();
    metas[i].lamport = r.zigzag();
    r.zigzag();  // timestamp delta
    uint64_t nd = r.varint();
    if (!r.ok || nd > 1u << 20) return false;
    for (uint64_t j = 0; j < nd; j++) { r.varint(); r.zigzag(); }
    if (r.u8()) { if (!r.skip_bytes()) return false; }  // message
    metas[i].n_ops = r.varint();
    if (!r.ok) return false;
  }
  return r.ok;
}

// Skip one op payload (after container idx + kind already consumed),
// for ops not on the target container.  `atoms` receives the counter
// span the op consumes.
bool skip_op(Reader& r, uint8_t kind, int64_t* atoms) {
  *atoms = 1;
  switch (kind) {
    case K_MAP_SET: r.varint(); return skip_value(r);
    case K_MAP_DEL: r.varint(); return r.ok;
    case K_INSERT_TEXT: {
      uint8_t tag = r.u8();
      if (tag == PT_ID) { r.varint(); r.zigzag(); }
      r.u8();  // side
      uint64_t n; const uint8_t* s = r.bytes(&n);
      if (!r.ok) return false;
      // count codepoints for atom length
      int64_t cp = 0;
      for (uint64_t i = 0; i < n; i++) if ((s[i] & 0xc0) != 0x80) cp++;
      *atoms = cp;
      return true;
    }
    case K_INSERT_VALUES: {
      uint8_t tag = r.u8();
      if (tag == PT_ID) { r.varint(); r.zigzag(); }
      r.u8();
      uint64_t n = r.varint();
      for (uint64_t i = 0; i < n && r.ok; i++) skip_value(r);
      *atoms = (int64_t)n;
      return r.ok;
    }
    case K_INSERT_ANCHOR: {
      uint8_t tag = r.u8();
      if (tag == PT_ID) { r.varint(); r.zigzag(); }
      r.u8();
      r.varint();  // key
      if (!skip_value(r)) return false;
      r.u8(); r.varint();
      return r.ok;
    }
    case K_DELETE: {
      uint64_t n = r.varint();
      for (uint64_t i = 0; i < n && r.ok; i++) { r.varint(); r.zigzag(); r.varint(); }
      return r.ok;
    }
    case K_TREE: {
      r.varint(); r.zigzag();
      uint8_t flags = r.u8();
      if (flags & 4) { r.varint(); r.zigzag(); }
      if (flags & 8) { if (!r.skip_bytes()) return false; }
      return r.ok;
    }
    case K_COUNTER: r.f64(); return r.ok;
    case K_MSET: r.varint(); r.zigzag(); return skip_value(r);
    case K_MMOVE: {
      r.varint(); r.zigzag();
      uint8_t tag = r.u8();
      if (tag == PT_ID) { r.varint(); r.zigzag(); }
      r.u8();
      return r.ok;
    }
    case K_UNKNOWN: r.varint(); return r.skip_bytes();
    default: return false;
  }
}

struct DelSpan { uint32_t peer_idx; int64_t start, end; };

}  // namespace

template <class MapT>
static long long explode_seq_impl(const uint8_t* buf, long long len,
                                  int target_cid,
                                  int32_t* out_parent, int32_t* out_side,
                                  int32_t* out_peer, int32_t* out_counter,
                                  uint8_t* out_deleted, int32_t* out_content,
                                  long long n_elems) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  MapT map(n_peers, metas, (size_t)(n_elems > 0 ? n_elems : 0));
  std::vector<DelSpan> dels;
  long long row = 0;
  int32_t value_base = 0;
  for (auto& m : metas) {
    int64_t ctr = m.ctr;
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if ((long long)cidx != target_cid) {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
        continue;
      }
      if (kind == K_INSERT_TEXT || kind == K_INSERT_VALUES) {
        uint8_t ptag = r.u8();
        uint32_t p_peer = 0; int64_t p_ctr = 0;
        if (ptag == PT_ID) {
          uint64_t pi = r.varint();
          if (!r.ok || pi >= n_peers) return -1;
          p_peer = (uint32_t)pi; p_ctr = r.zigzag();
        }
        uint8_t side = r.u8();
        // resolve first element's parent
        int32_t parent_row;
        if (ptag == PT_NONE) parent_row = -1;
        else if (ptag == PT_RUNCONT) {
          parent_row = map.get(idkey(m.peer_idx, ctr - 1));
          if (parent_row < 0) return map.overflow() ? -2 : -1;
        } else {
          parent_row = map.get(idkey(p_peer, p_ctr));
          if (parent_row < 0) return map.overflow() ? -2 : -1;
        }
        if (kind == K_INSERT_TEXT) {
          uint64_t nb; const uint8_t* s = r.bytes(&nb);
          if (!r.ok) return -1;
          // utf8 -> codepoints, one element per codepoint
          uint64_t i = 0; int64_t j = 0;
          while (i < nb) {
            uint32_t cp;
            int used = decode_utf8_cp(s, nb, i, &cp);
            if (used < 0) return -1;
            i += used;
            if (row >= n_elems) return -1;
            out_parent[row] = (j == 0) ? parent_row : (int32_t)(row - 1);
            out_side[row] = (j == 0) ? side : 1;
            out_peer[row] = (int32_t)m.peer_idx;
            out_counter[row] = (int32_t)(ctr + j);
            out_deleted[row] = 0;
            out_content[row] = (int32_t)cp;
            map.put(idkey(m.peer_idx, ctr + j), (int32_t)row);
            row++; j++;
          }
          ctr += j;
        } else {
          uint64_t n = r.varint();
          for (uint64_t j = 0; j < n; j++) {
            if (!skip_value(r)) return -1;
            if (row >= n_elems) return -1;
            out_parent[row] = (j == 0) ? parent_row : (int32_t)(row - 1);
            out_side[row] = (j == 0) ? side : 1;
            out_peer[row] = (int32_t)m.peer_idx;
            out_counter[row] = (int32_t)(ctr + (int64_t)j);
            out_deleted[row] = 0;
            out_content[row] = value_base++;
            map.put(idkey(m.peer_idx, ctr + (int64_t)j), (int32_t)row);
            row++;
          }
          ctr += (int64_t)n;
        }
      } else if (kind == K_DELETE) {
        uint64_t n = r.varint();
        for (uint64_t i = 0; i < n && r.ok; i++) {
          DelSpan d;
          uint64_t dpi = r.varint();
          if (!r.ok || dpi >= n_peers) return -1;
          d.peer_idx = (uint32_t)dpi;
          d.start = r.zigzag();
          d.end = d.start + (int64_t)r.varint();
          dels.push_back(d);
        }
        if (!r.ok) return -1;
        ctr += 1;
      } else {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
      }
    }
  }
  for (auto& d : dels) {
    for (int64_t c = d.start; c < d.end; c++) {
      int32_t i = map.get(idkey(d.peer_idx, c));
      if (i >= 0) out_deleted[i] = 1;
    }
  }
  if (map.overflow()) return -2;  // direct table blew its budget
  return row;
}


extern "C" {

// test-only: force a tiny RowTable budget (0 = default) so the
// IdMap fallback is exercisable from the differential suite
void loro_set_rowtable_budget(long long b) { g_rowtable_budget_override = b; }


// Pass 1: count elements of the target container (by cid index).
// Returns element count, or -1 on malformed input.
long long loro_count_seq_elements(const uint8_t* buf, long long len,
                                  int target_cid) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long total = 0;
  for (auto& m : metas) {
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      int64_t atoms = 1;
      if (!skip_op(r, kind, &atoms)) return -1;
      if ((long long)cidx == target_cid &&
          (kind == K_INSERT_TEXT || kind == K_INSERT_VALUES)) {
        total += atoms;
      }
    }
  }
  return total;
}

// Pass 2: fill element columns for the target container.
// out_* arrays must hold n_elems entries (from pass 1).
// out_content: codepoints for text inserts; value ops get ascending ids
// starting at `value_base` (caller resolves values Python-side).
// Returns number of elements written, or -1 on malformed input /
// unresolvable parent reference.
long long loro_explode_seq(const uint8_t* buf, long long len, int target_cid,
                           int32_t* out_parent, int32_t* out_side,
                           int32_t* out_peer, int32_t* out_counter,
                           uint8_t* out_deleted, int32_t* out_content,
                           long long n_elems) {
  long long rc = explode_seq_impl<RowTable>(
      buf, len, target_cid, out_parent, out_side, out_peer, out_counter,
      out_deleted, out_content, n_elems);
  if (rc != -2) return rc;
  // sparse-counter payload blew the direct table's budget: redo with
  // the open-addressing map — outputs are fully rewritten
  return explode_seq_impl<IdMap>(
      buf, len, target_cid, out_parent, out_side, out_peer, out_counter,
      out_deleted, out_content, n_elems);
}

// Count rows the DELTA explode will emit (chars/values AND style
// anchors — anchors are parentable Fugue nodes and must enter the
// resident id map).
long long loro_count_seq_delta_rows(const uint8_t* buf, long long len,
                                    int target_cid) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long total = 0;
  for (auto& m : metas) {
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      int64_t atoms = 1;
      if (!skip_op(r, kind, &atoms)) return -1;
      if ((long long)cidx == target_cid &&
          (kind == K_INSERT_TEXT || kind == K_INSERT_VALUES || kind == K_INSERT_ANCHOR)) {
        total += atoms;
      }
    }
  }
  return total;
}

// Pass 2 (incremental variant): like loro_explode_seq but parents that
// don't resolve inside this payload are reported as (peer_idx, counter)
// pairs with out_parent = -2, for host-side resolution against the
// resident batch's id map; deletes are returned as spans instead of
// folded, for the same reason; style anchors emit rows with
// out_content = -1.  out_del_* must hold n_del_max entries (from
// loro_count_seq_deletes).  Returns rows written or -1.
long long loro_explode_seq_delta(const uint8_t* buf, long long len, int target_cid,
                                 int32_t* out_parent, int32_t* out_side,
                                 int32_t* out_peer, int32_t* out_counter,
                                 int32_t* out_content,
                                 int32_t* out_ext_peer, int64_t* out_ext_ctr,
                                 long long n_elems,
                                 int32_t* out_del_peer, int64_t* out_del_start,
                                 int64_t* out_del_end, long long n_del_max,
                                 long long* n_del_out) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  IdMap map((size_t)(n_elems > 16 ? n_elems : 16));
  long long row = 0, n_del = 0;
  int32_t value_base = 0;
  for (auto& m : metas) {
    int64_t ctr = m.ctr;
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if ((long long)cidx != target_cid) {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
        continue;
      }
      if (kind == K_INSERT_TEXT || kind == K_INSERT_VALUES || kind == K_INSERT_ANCHOR) {
        uint8_t ptag = r.u8();
        uint32_t p_peer = 0; int64_t p_ctr = 0;
        if (ptag == PT_ID) {
          uint64_t pi = r.varint();
          if (!r.ok || pi >= n_peers) return -1;
          p_peer = (uint32_t)pi; p_ctr = r.zigzag();
        }
        uint8_t side = r.u8();
        int32_t parent_row;
        uint32_t ext_peer = 0; int64_t ext_ctr = -1;
        if (ptag == PT_NONE) parent_row = -1;
        else if (ptag == PT_RUNCONT) {
          parent_row = map.get(idkey(m.peer_idx, ctr - 1));
          if (parent_row < 0) { parent_row = -2; ext_peer = m.peer_idx; ext_ctr = ctr - 1; }
        } else {
          parent_row = map.get(idkey(p_peer, p_ctr));
          if (parent_row < 0) { parent_row = -2; ext_peer = p_peer; ext_ctr = p_ctr; }
        }
        auto emit = [&](int64_t j, uint32_t cp) -> bool {
          if (row >= n_elems) return false;
          out_parent[row] = (j == 0) ? parent_row : (int32_t)(row - 1);
          out_side[row] = (j == 0) ? side : 1;
          out_peer[row] = (int32_t)m.peer_idx;
          out_counter[row] = (int32_t)(ctr + j);
          out_content[row] = (int32_t)cp;
          out_ext_peer[row] = (j == 0 && parent_row == -2) ? (int32_t)ext_peer : -1;
          out_ext_ctr[row] = (j == 0 && parent_row == -2) ? ext_ctr : -1;
          map.put(idkey(m.peer_idx, ctr + j), (int32_t)row);
          row++;
          return true;
        };
        if (kind == K_INSERT_ANCHOR) {
          // key-idx, value, is_start, info — anchors are zero-width but
          // parentable: emit a content=-1 row (the order solve ignores
          // it; the id map needs it)
          r.varint();
          if (!skip_value(r)) return -1;
          r.u8(); r.varint();
          if (!r.ok) return -1;
          if (!emit(0, (uint32_t)-1)) return -1;
          ctr += 1;
        } else if (kind == K_INSERT_TEXT) {
          uint64_t nb; const uint8_t* s = r.bytes(&nb);
          if (!r.ok) return -1;
          uint64_t i = 0; int64_t j = 0;
          while (i < nb) {
            uint32_t cp;
            int used = decode_utf8_cp(s, nb, i, &cp);
            if (used < 0) return -1;
            i += used;
            if (!emit(j, cp)) return -1;
            j++;
          }
          ctr += j;
        } else {
          uint64_t n = r.varint();
          for (uint64_t j = 0; j < n; j++) {
            if (!skip_value(r)) return -1;
            if (!emit((int64_t)j, (uint32_t)value_base++)) return -1;
          }
          ctr += (int64_t)n;
        }
      } else if (kind == K_DELETE) {
        uint64_t n = r.varint();
        for (uint64_t i = 0; i < n && r.ok; i++) {
          uint64_t dpi = r.varint();
          if (!r.ok || dpi >= n_peers) return -1;
          uint32_t dp = (uint32_t)dpi;
          int64_t ds = r.zigzag();
          int64_t dl = (int64_t)r.varint();
          if (n_del >= n_del_max) return -1;
          out_del_peer[n_del] = (int32_t)dp;
          out_del_start[n_del] = ds;
          out_del_end[n_del] = ds + dl;
          n_del++;
        }
        if (!r.ok) return -1;
        ctr += 1;
      } else {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
      }
    }
  }
  *n_del_out = n_del;
  return row;
}

// Style-anchor metadata for a target container, in the SAME row
// numbering as loro_explode_seq_delta (the host pairs anchors to their
// device rows by ordinal).  Per anchor: row ordinal, wire key index,
// value BYTE OFFSET into the payload (decoded lazily host-side, like
// the map explode's winners), lamport, flags (bit0 = is_start).
// Returns anchors written, or -1 on malformed input / n_max overflow.
long long loro_explode_seq_anchor_meta(const uint8_t* buf, long long len,
                                       int target_cid,
                                       int64_t* out_row, int32_t* out_key,
                                       int64_t* out_voffset,
                                       int32_t* out_lamport,
                                       int32_t* out_flags,
                                       long long n_max) {
  Reader r{buf, buf + len};
  uint64_t n_peers, n_keys; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas, &n_keys)) return -1;
  long long row = 0, n_anchor = 0;
  for (auto& m : metas) {
    int64_t ctr = m.ctr;
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if ((long long)cidx != target_cid) {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
        continue;
      }
      if (kind == K_INSERT_ANCHOR) {
        uint8_t ptag = r.u8();
        if (ptag == PT_ID) { r.varint(); r.zigzag(); }
        r.u8();  // side
        uint64_t key = r.varint();
        if (!r.ok || key >= n_keys) return -1;
        int64_t voff = (int64_t)(r.p - buf);
        if (!skip_value(r)) return -1;
        uint8_t is_start = r.u8();
        r.varint();  // info (expand behavior rides anchor placement)
        if (!r.ok) return -1;
        if (out_row) {  // null outputs = counting pass
          if (n_anchor >= n_max) return -1;
          out_row[n_anchor] = row;
          out_key[n_anchor] = (int32_t)key;
          out_voffset[n_anchor] = voff;
          out_lamport[n_anchor] = (int32_t)(m.lamport + (ctr - m.ctr));
          out_flags[n_anchor] = is_start ? 1 : 0;
        }
        n_anchor++;
        row++;
        ctr += 1;
      } else {
        // every other kind: skip_op's atom count IS the row count for
        // insert kinds (one row per codepoint/value; the main explode
        // already strictly validated this same payload) and deletes
        // emit no rows
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        if (kind == K_INSERT_TEXT || kind == K_INSERT_VALUES) row += atoms;
        ctr += atoms;
      }
    }
  }
  return n_anchor;
}

// Count delete spans for a target container (sizing for the delta API).
long long loro_count_seq_deletes(const uint8_t* buf, long long len, int target_cid) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long total = 0;
  for (auto& m : metas) {
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if ((long long)cidx == target_cid && kind == K_DELETE) {
        // peek span count without consuming twice: parse spans
        uint64_t n = r.varint();
        for (uint64_t i = 0; i < n && r.ok; i++) { r.varint(); r.zigzag(); r.varint(); }
        if (!r.ok) return -1;
        total += (long long)n;
      } else {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
      }
    }
  }
  return total;
}

// Pass 1: count MapSet/MapDel rows in the payload.
long long loro_count_map_ops(const uint8_t* buf, long long len) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long total = 0;
  for (auto& m : metas) {
    for (uint64_t k = 0; k < m.n_ops; k++) {
      r.varint();  // container idx
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      int64_t atoms;
      if (!skip_op(r, kind, &atoms)) return -1;
      if (kind == K_MAP_SET || kind == K_MAP_DEL) total++;
    }
  }
  return total;
}

// Pass 2: fill map-op rows across ALL map containers:
// (cid_idx, key_idx, lamport, peer_idx, value ordinal or -1 for delete,
// value BYTE OFFSET into the payload or -1).  Values are not decoded
// natively — the offsets let Python decode only the LWW winners lazily
// (DeviceMapBatch ingests payloads without touching loser values).
long long loro_explode_map(const uint8_t* buf, long long len,
                           int32_t* out_cid, int32_t* out_key,
                           int32_t* out_lamport, int32_t* out_peer,
                           int32_t* out_value, int64_t* out_voffset,
                           long long n_rows) {
  Reader r{buf, buf + len};
  uint64_t n_peers, n_keys; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas, &n_keys)) return -1;
  long long row = 0;
  int32_t ordinal = 0;
  for (auto& m : metas) {
    int64_t ctr = m.ctr;
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if (kind == K_MAP_SET || kind == K_MAP_DEL) {
        uint64_t key = r.varint();
        if (!r.ok || cidx >= cid_types.size() || key >= n_keys) return -1;
        int32_t val = -1;
        int64_t voff = -1;
        if (kind == K_MAP_SET) {
          voff = (int64_t)(r.p - buf);
          if (!skip_value(r)) return -1;
          val = ordinal++;
        }
        if (row >= n_rows) return -1;
        out_cid[row] = (int32_t)cidx;
        out_key[row] = (int32_t)key;
        out_lamport[row] = (int32_t)(m.lamport + (ctr - m.ctr));
        out_peer[row] = (int32_t)m.peer_idx;
        out_value[row] = val;
        out_voffset[row] = voff;
        row++;
        ctr += 1;
      } else {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
      }
    }
  }
  return row;
}


// ---------------------------------------------------------------------------
// Tree explode: all TreeMove rows of one container, wire order.
// Columns: lamport, peer_idx (wire), counter, target (peer_idx, ctr),
// flags (1 create | 2 delete | 4 has-parent | 8 has-position), parent
// (peer_idx, ctr; valid when flags&4), position byte range into the
// payload.  Python sorts by (lamport, peer_u64, counter), builds the
// node dictionary, and feeds ops/tree_batch.tree_merge_batch without
// per-op Python objects.
long long loro_count_tree_ops(const uint8_t* buf, long long len, int target_cid) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long count = 0;
  for (auto& m : metas) {
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      int64_t atoms;
      if (kind == K_TREE && (long long)cidx == target_cid) count++;
      if (!skip_op(r, kind, &atoms)) return -1;
    }
  }
  return count;
}

long long loro_explode_tree(const uint8_t* buf, long long len, int target_cid,
                            int32_t* out_lamport, int32_t* out_peer,
                            int32_t* out_counter, int32_t* out_tpeer,
                            int32_t* out_tctr, int32_t* out_flags,
                            int32_t* out_ppeer, int32_t* out_pctr,
                            int64_t* out_pos_off, int32_t* out_pos_len,
                            long long n_rows) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long row = 0;
  for (auto& m : metas) {
    int64_t ctr = m.ctr;
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if (kind != K_TREE || (long long)cidx != target_cid) {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
        continue;
      }
      uint64_t tpi = r.varint();
      int64_t tctr = r.zigzag();
      uint8_t flags = r.u8();
      if (!r.ok || tpi >= n_peers) return -1;
      int32_t ppeer = -1; int64_t pctr = 0;
      if (flags & 4) {
        uint64_t ppi = r.varint();
        pctr = r.zigzag();
        if (!r.ok || ppi >= n_peers) return -1;
        ppeer = (int32_t)ppi;
      }
      int64_t pos_off = -1; int32_t pos_len = 0;
      if (flags & 8) {
        uint64_t nb;
        const uint8_t* pb = r.bytes(&nb);
        if (!r.ok) return -1;
        pos_off = (int64_t)(pb - buf);  // offset of the raw bytes
        pos_len = (int32_t)nb;
      }
      if (row >= n_rows) return -1;
      out_lamport[row] = (int32_t)(m.lamport + (ctr - m.ctr));
      out_peer[row] = (int32_t)m.peer_idx;
      out_counter[row] = (int32_t)ctr;
      out_tpeer[row] = (int32_t)tpi;
      out_tctr[row] = (int32_t)tctr;
      out_flags[row] = (int32_t)flags;
      out_ppeer[row] = ppeer;
      out_pctr[row] = (int32_t)pctr;
      out_pos_off[row] = pos_off;
      out_pos_len[row] = pos_len;
      row++;
      ctr += 1;
    }
  }
  return row;
}

// ---------------------------------------------------------------------------
// Movable-list explode: slots (inserts + moves, parent rows resolved
// through an in-payload id map like the seq explode), sets (creation
// values + MSET, value byte offsets — winners decode lazily in
// Python), delete spans.  Returns -1 on malformed input or an
// unresolvable in-payload reference (caller falls back to Python).
long long loro_count_movable(const uint8_t* buf, long long len, int target_cid,
                             long long* n_slots, long long* n_sets,
                             long long* n_dels) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  long long slots = 0, sets = 0, dels = 0;
  for (auto& m : metas) {
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      bool mine = (long long)cidx == target_cid;
      if (mine && kind == K_MMOVE) slots++;
      else if (mine && kind == K_MSET) sets++;
      else if (mine && kind == K_DELETE) {
        uint64_t n = r.varint();
        for (uint64_t i = 0; i < n && r.ok; i++) { r.varint(); r.zigzag(); r.varint(); }
        if (!r.ok) return -1;
        dels += (long long)n;
        continue;
      }
      int64_t atoms;
      if (!skip_op(r, kind, &atoms)) return -1;
      if (mine && kind == K_INSERT_VALUES) {
        slots += atoms;
        sets += atoms;  // creation values
      }
    }
  }
  *n_slots = slots; *n_sets = sets; *n_dels = dels;
  return 0;
}

static long long movable_walk(const uint8_t* buf, long long len, int target_cid,
                              int32_t* s_parent, int32_t* s_side,
                              int32_t* s_peer, int32_t* s_ctr,
                              int32_t* s_lamport, int32_t* s_epeer,
                              int32_t* s_ectr,
                              int32_t* v_epeer, int32_t* v_ectr,
                              int32_t* v_lamport, int32_t* v_peer,
                              int64_t* v_off,
                              int32_t* d_peer, int64_t* d_start, int64_t* d_end,
                              long long n_slots, long long n_sets,
                              long long n_dels,
                              int32_t* s_extpeer, int64_t* s_extctr) {
  Reader r{buf, buf + len};
  uint64_t n_peers; std::vector<int32_t> cid_types; std::vector<ChangeMeta> metas;
  if (!parse_prelude(r, &n_peers, cid_types, metas)) return -1;
  IdMap map((size_t)(n_slots > 16 ? n_slots : 16));
  long long srow = 0, vrow = 0, drow = 0;
  for (auto& m : metas) {
    int64_t ctr = m.ctr;
    for (uint64_t k = 0; k < m.n_ops; k++) {
      uint64_t cidx = r.varint();
      uint8_t kind = r.u8();
      if (!r.ok) return -1;
      if ((long long)cidx != target_cid) {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
        continue;
      }
      if (kind == K_INSERT_VALUES) {
        uint8_t ptag = r.u8();
        uint32_t p_peer = 0; int64_t p_ctr = 0;
        if (ptag == PT_ID) {
          uint64_t pi = r.varint();
          if (!r.ok || pi >= n_peers) return -1;
          p_peer = (uint32_t)pi; p_ctr = r.zigzag();
        }
        uint8_t side = r.u8();
        uint64_t n = r.varint();
        if (!r.ok) return -1;
        int32_t parent_row;
        uint32_t ext_peer = 0; int64_t ext_ctr = -1; bool ext = false;
        if (ptag == PT_NONE) parent_row = -1;
        else if (ptag == PT_RUNCONT) {
          parent_row = map.get(idkey(m.peer_idx, ctr - 1));
          if (parent_row < 0) {
            if (!s_extpeer) return -1;  // one-shot mode: must resolve
            parent_row = -2; ext = true; ext_peer = m.peer_idx; ext_ctr = ctr - 1;
          }
        } else {
          parent_row = map.get(idkey(p_peer, p_ctr));
          if (parent_row < 0) {
            if (!s_extpeer) return -1;
            parent_row = -2; ext = true; ext_peer = p_peer; ext_ctr = p_ctr;
          }
        }
        for (uint64_t j = 0; j < n; j++) {
          int64_t voff = (int64_t)(r.p - buf);
          if (!skip_value(r)) return -1;
          if (srow >= n_slots || vrow >= n_sets) return -1;
          s_parent[srow] = (j == 0) ? parent_row : (int32_t)(srow - 1);
          s_side[srow] = (j == 0) ? (int32_t)side : 1;
          s_peer[srow] = (int32_t)m.peer_idx;
          s_ctr[srow] = (int32_t)(ctr + (int64_t)j);
          s_lamport[srow] = (int32_t)(m.lamport + (ctr - m.ctr) + (int64_t)j);
          s_epeer[srow] = (int32_t)m.peer_idx;  // insert: elem id == own id
          s_ectr[srow] = (int32_t)(ctr + (int64_t)j);
          if (s_extpeer) {
            s_extpeer[srow] = (ext && j == 0) ? (int32_t)ext_peer : -1;
            s_extctr[srow] = (ext && j == 0) ? ext_ctr : -1;
          }
          map.put(idkey(m.peer_idx, ctr + (int64_t)j), (int32_t)srow);
          v_epeer[vrow] = (int32_t)m.peer_idx;
          v_ectr[vrow] = (int32_t)(ctr + (int64_t)j);
          v_lamport[vrow] = (int32_t)(m.lamport + (ctr - m.ctr) + (int64_t)j);
          v_peer[vrow] = (int32_t)m.peer_idx;
          v_off[vrow] = voff;
          srow++; vrow++;
        }
        ctr += (int64_t)n;
      } else if (kind == K_MMOVE) {
        uint64_t epi = r.varint();
        int64_t ectr = r.zigzag();
        if (!r.ok || epi >= n_peers) return -1;
        uint8_t ptag = r.u8();
        uint32_t p_peer = 0; int64_t p_ctr = 0;
        if (ptag == PT_ID) {
          uint64_t pi = r.varint();
          if (!r.ok || pi >= n_peers) return -1;
          p_peer = (uint32_t)pi; p_ctr = r.zigzag();
        }
        uint8_t side = r.u8();
        if (!r.ok) return -1;
        int32_t parent_row;
        uint32_t ext_peer = 0; int64_t ext_ctr = -1; bool ext = false;
        if (ptag == PT_NONE) parent_row = -1;
        else if (ptag == PT_RUNCONT) {
          parent_row = map.get(idkey(m.peer_idx, ctr - 1));
          if (parent_row < 0) {
            if (!s_extpeer) return -1;  // one-shot mode: must resolve
            parent_row = -2; ext = true; ext_peer = m.peer_idx; ext_ctr = ctr - 1;
          }
        } else {
          parent_row = map.get(idkey(p_peer, p_ctr));
          if (parent_row < 0) {
            if (!s_extpeer) return -1;
            parent_row = -2; ext = true; ext_peer = p_peer; ext_ctr = p_ctr;
          }
        }
        if (srow >= n_slots) return -1;
        s_parent[srow] = parent_row;
        s_side[srow] = (int32_t)side;
        s_peer[srow] = (int32_t)m.peer_idx;
        s_ctr[srow] = (int32_t)ctr;
        s_lamport[srow] = (int32_t)(m.lamport + (ctr - m.ctr));
        s_epeer[srow] = (int32_t)epi;
        s_ectr[srow] = (int32_t)ectr;
        if (s_extpeer) {
          s_extpeer[srow] = ext ? (int32_t)ext_peer : -1;
          s_extctr[srow] = ext ? ext_ctr : -1;
        }
        map.put(idkey(m.peer_idx, ctr), (int32_t)srow);
        srow++;
        ctr += 1;
      } else if (kind == K_MSET) {
        uint64_t epi = r.varint();
        int64_t ectr = r.zigzag();
        if (!r.ok || epi >= n_peers) return -1;
        int64_t voff = (int64_t)(r.p - buf);
        if (!skip_value(r)) return -1;
        if (vrow >= n_sets) return -1;
        v_epeer[vrow] = (int32_t)epi;
        v_ectr[vrow] = (int32_t)ectr;
        v_lamport[vrow] = (int32_t)(m.lamport + (ctr - m.ctr));
        v_peer[vrow] = (int32_t)m.peer_idx;
        v_off[vrow] = voff;
        vrow++;
        ctr += 1;
      } else if (kind == K_DELETE) {
        uint64_t n = r.varint();
        for (uint64_t i = 0; i < n && r.ok; i++) {
          uint64_t dpi = r.varint();
          if (!r.ok || dpi >= n_peers) return -1;
          int64_t ds = r.zigzag();
          int64_t dl = (int64_t)r.varint();
          if (drow >= n_dels) return -1;
          d_peer[drow] = (int32_t)dpi;
          d_start[drow] = ds;
          d_end[drow] = ds + dl;
          drow++;
        }
        if (!r.ok) return -1;
        ctr += 1;
      } else {
        int64_t atoms;
        if (!skip_op(r, kind, &atoms)) return -1;
        ctr += atoms;
      }
    }
  }
  return srow;
}

long long loro_explode_movable(const uint8_t* buf, long long len, int target_cid,
                               int32_t* s_parent, int32_t* s_side,
                               int32_t* s_peer, int32_t* s_ctr,
                               int32_t* s_lamport, int32_t* s_epeer,
                               int32_t* s_ectr,
                               int32_t* v_epeer, int32_t* v_ectr,
                               int32_t* v_lamport, int32_t* v_peer,
                               int64_t* v_off,
                               int32_t* d_peer, int64_t* d_start, int64_t* d_end,
                               long long n_slots, long long n_sets,
                               long long n_dels) {
  return movable_walk(buf, len, target_cid, s_parent, s_side, s_peer, s_ctr,
                      s_lamport, s_epeer, s_ectr, v_epeer, v_ectr, v_lamport,
                      v_peer, v_off, d_peer, d_start, d_end, n_slots, n_sets,
                      n_dels, nullptr, nullptr);
}

// Delta variant: parents that don't resolve inside this payload come
// back as s_parent == -2 with (s_extpeer, s_extctr) pairs for host
// resolution against the resident batch's id map (the movable analog
// of loro_explode_seq_delta's ext-ref protocol).
long long loro_explode_movable_delta(const uint8_t* buf, long long len, int target_cid,
                                     int32_t* s_parent, int32_t* s_side,
                                     int32_t* s_peer, int32_t* s_ctr,
                                     int32_t* s_lamport, int32_t* s_epeer,
                                     int32_t* s_ectr,
                                     int32_t* v_epeer, int32_t* v_ectr,
                                     int32_t* v_lamport, int32_t* v_peer,
                                     int64_t* v_off,
                                     int32_t* d_peer, int64_t* d_start, int64_t* d_end,
                                     long long n_slots, long long n_sets,
                                     long long n_dels,
                                     int32_t* s_extpeer, int64_t* s_extctr) {
  return movable_walk(buf, len, target_cid, s_parent, s_side, s_peer, s_ctr,
                      s_lamport, s_epeer, s_ectr, v_epeer, v_ectr, v_lamport,
                      v_peer, v_off, d_peer, d_start, d_end, n_slots, n_sets,
                      n_dels, s_extpeer, s_extctr);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native ShadowOrder: incremental Fugue order maintenance (the exact
// algorithm of parallel/order_maintenance.py, so keys are bit-identical
// — the Python engine is the differential oracle).  State lives behind
// an opaque handle; DeviceDocBatch calls append per sync with the delta
// rows and gets 64-bit order keys back in O(delta).

namespace order {

constexpr int64_t KEY_STEP = 1ll << 20;
// Run continuations take a small low-biased step instead of the gap
// midpoint (mirrors order_maintenance.py RUN_STEP — the two engines
// must stay bit-identical): a typing run consumes L*RUN_STEP of the
// gap instead of halving it L times.
constexpr int64_t RUN_STEP = 1ll << 8;
constexpr int32_t HEAD = -2;

struct Doc {
  std::vector<uint64_t> peer;
  std::vector<int64_t> ctr;
  std::vector<int32_t> prev, next, spine;
  std::vector<int64_t> key;
  int32_t first_row = -1;
  // (row << 1 | side) -> children sorted by (peer, ctr)
  std::unordered_map<uint64_t, std::vector<int32_t>> branches;
  std::vector<int32_t> root_children;
  int64_t renumbers = 0;

  int64_t n() const { return (int64_t)peer.size(); }

  bool sib_less(int32_t a, uint64_t bp, int64_t bc) const {
    return peer[a] != bp ? peer[a] < bp : ctr[a] < bc;
  }

  int32_t last_r_child(int32_t row) const {
    auto it = branches.find(((uint64_t)row << 1) | 1);
    if (it != branches.end() && !it->second.empty()) return it->second.back();
    return spine[row];
  }

  int32_t subtree_last(int32_t row) const {
    int32_t x = row;
    while (true) {
      int32_t nxt = last_r_child(x);
      if (nxt < 0) return x;
      x = nxt;
    }
  }

  int32_t subtree_first(int32_t row) const {
    int32_t x = row;
    while (true) {
      auto it = branches.find(((uint64_t)x << 1) | 0);
      if (it == branches.end() || it->second.empty()) return x;
      x = it->second.front();
    }
  }

  void splice_after(int32_t pred, int32_t row) {
    int32_t succ;
    if (pred == HEAD) {
      succ = first_row;
      first_row = row;
    } else {
      succ = next[pred];
      next[pred] = row;
    }
    prev[row] = pred;
    next[row] = succ;
    if (succ >= 0) prev[succ] = row;
  }

  bool assign_key(int32_t row, bool run) {
    int32_t p = prev[row], s = next[row];
    if (p < 0 && s < 0) key[row] = 0;
    else if (p < 0) key[row] = key[s] - KEY_STEP;
    else if (s < 0) key[row] = key[p] + KEY_STEP;
    else {
      int64_t lo = key[p], hi = key[s];
      if (hi - lo < 2) return false;
      int64_t step = (hi - lo) / 2;
      if (run && step > RUN_STEP) step = RUN_STEP;
      key[row] = lo + step;
    }
    return true;
  }

  void renumber() {
    renumbers++;
    int64_t k = 0;
    int32_t x = first_row;
    while (x >= 0) {
      key[x] = k;
      k += KEY_STEP;
      x = next[x];
    }
  }

  std::vector<int32_t>& sibling_list(int32_t parent_row, int32_t side) {
    if (parent_row < 0) return root_children;
    uint64_t bk = ((uint64_t)parent_row << 1) | (uint64_t)side;
    auto it = branches.find(bk);
    if (it == branches.end()) {
      auto& lst = branches[bk];
      if (side == 1) {
        int32_t sp = spine[parent_row];
        if (sp >= 0) {
          lst.push_back(sp);
          spine[parent_row] = -1;  // now tracked in branches
        }
      }
      return lst;  // node-stable reference
    }
    return it->second;
  }

  // Returns true on the run-continuation fast path (caller assigns a
  // low-biased key so runs don't bisect the gap).
  bool place(int32_t parent_row, int32_t side, int32_t row) {
    // run-continuation fast path
    if (parent_row >= 0 && side == 1 && spine[parent_row] < 0 &&
        branches.find(((uint64_t)parent_row << 1) | 1) == branches.end() &&
        peer[parent_row] == peer[row] && ctr[parent_row] == ctr[row] - 1) {
      spine[parent_row] = row;
      splice_after(parent_row, row);
      return true;
    }
    auto& sibs = sibling_list(parent_row, side);
    uint64_t mp = peer[row];
    int64_t mc = ctr[row];
    size_t i = 0;
    while (i < sibs.size() && sib_less(sibs[i], mp, mc)) i++;
    sibs.insert(sibs.begin() + i, row);
    if (side == 1 || parent_row < 0) {
      int32_t pred;
      if (i == 0) pred = parent_row >= 0 ? parent_row : HEAD;
      else pred = subtree_last(sibs[i - 1]);
      splice_after(pred, row);
    } else {
      if (i > 0) {
        splice_after(subtree_last(sibs[i - 1]), row);
      } else {
        int32_t nxt = sibs.size() > i + 1 ? sibs[i + 1] : -1;
        int32_t old_first = nxt >= 0 ? subtree_first(nxt) : parent_row;
        splice_after(prev[old_first], row);
      }
    }
    return false;
  }
};

}  // namespace order

extern "C" {

void* loro_order_new() { return new order::Doc(); }

void loro_order_free(void* h) { delete (order::Doc*)h; }

long long loro_order_nrows(void* h) { return ((order::Doc*)h)->n(); }

long long loro_order_renumbers(void* h) { return ((order::Doc*)h)->renumbers; }

void loro_order_all_keys(void* h, int64_t* out) {
  auto* d = (order::Doc*)h;
  for (int64_t i = 0; i < d->n(); i++) out[i] = d->key[i];
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native id map: per-doc (peer u64, counter i64) -> device row.  The
// resident batches resolve cross-epoch parents/deletes and register
// every ingested row here; doing it per-row in Python dicts was the
// host-funnel cost center (r4 verdict #5).  Staging mirrors the
// Python-side contract: stage -> lookup (staged shadows main) ->
// commit | abort, so a capacity error leaves the map untouched.

namespace idmap {

struct Key {
  uint64_t peer;
  int64_t ctr;
  bool operator==(const Key& o) const { return peer == o.peer && ctr == o.ctr; }
};

struct KeyHash {
  size_t operator()(const Key& k) const {
    uint64_t x = k.peer ^ (uint64_t)k.ctr * 0x9E3779B97F4A7C15ull;
    x ^= x >> 30; x *= 0xBF58476D1CE4E5B9ull;
    x ^= x >> 27; x *= 0x94D049BB133111EBull;
    return (size_t)(x ^ (x >> 31));
  }
};

struct Map {
  std::unordered_map<Key, int32_t, KeyHash> main, staged;
};

}  // namespace idmap

extern "C" {

void* loro_idmap_new() { return new idmap::Map(); }
void loro_idmap_free(void* h) { delete (idmap::Map*)h; }

long long loro_idmap_len(void* h) {
  return (long long)((idmap::Map*)h)->main.size();
}

// Committed inserts with explicit rows (import_state, fallback-path
// overlay commits).
void loro_idmap_insert(void* h, long long n, const uint64_t* peer,
                       const int64_t* ctr, const int32_t* rows) {
  auto* m = (idmap::Map*)h;
  m->main.reserve(m->main.size() + (size_t)n);
  for (long long i = 0; i < n; i++) m->main[{peer[i], ctr[i]}] = rows[i];
}

// Stage n new rows at base_row..base_row+n-1 (visible to lookups,
// not committed).
void loro_idmap_stage(void* h, long long n, const uint64_t* peer,
                      const int64_t* ctr, int32_t base_row) {
  auto* m = (idmap::Map*)h;
  m->staged.reserve(m->staged.size() + (size_t)n);
  for (long long i = 0; i < n; i++)
    m->staged[{peer[i], ctr[i]}] = base_row + (int32_t)i;
}

void loro_idmap_commit(void* h) {
  auto* m = (idmap::Map*)h;
  m->main.reserve(m->main.size() + m->staged.size());
  for (auto& kv : m->staged) m->main[kv.first] = kv.second;
  m->staged.clear();
}

void loro_idmap_abort(void* h) { ((idmap::Map*)h)->staged.clear(); }

// Batch lookup, staged-first (matches the overlay-then-idmap order of
// the Python paths); -1 = missing.
void loro_idmap_lookup(void* h, long long n, const uint64_t* peer,
                       const int64_t* ctr, int32_t* out) {
  auto* m = (idmap::Map*)h;
  for (long long i = 0; i < n; i++) {
    idmap::Key k{peer[i], ctr[i]};
    auto it = m->staged.find(k);
    if (it == m->staged.end()) {
      it = m->main.find(k);
      if (it == m->main.end()) { out[i] = -1; continue; }
    }
    out[i] = it->second;
  }
}

long long loro_idmap_get(void* h, uint64_t peer, int64_t ctr) {
  auto* m = (idmap::Map*)h;
  idmap::Key k{peer, ctr};
  auto it = m->staged.find(k);
  if (it == m->staged.end()) {
    it = m->main.find(k);
    if (it == m->main.end()) return -1;
  }
  return it->second;
}

}  // extern "C"

extern "C" {

// Place k rows (parent_row, side, peer, ctr) at indexes base_row..;
// fills out_keys.  Returns 0, 1 when a renumber happened (caller
// re-uploads all keys), or -1 on a non-contiguous base.
long long loro_order_append(void* h, long long k, const int32_t* parent,
                            const int32_t* side, const uint64_t* peer,
                            const int64_t* ctr, long long base_row,
                            int64_t* out_keys) {
  auto* d = (order::Doc*)h;
  if (base_row != d->n()) return -1;
  bool renumbered = false;
  for (long long j = 0; j < k; j++) {
    int32_t row = (int32_t)(base_row + j);
    d->peer.push_back(peer[j]);
    d->ctr.push_back(ctr[j]);
    d->prev.push_back(order::HEAD);
    d->next.push_back(-1);
    d->spine.push_back(-1);
    d->key.push_back(0);
    bool run = d->place(parent[j], side[j], row);
    if (!d->assign_key(row, run)) {
      d->renumber();
      renumbered = true;
    }
    out_keys[j] = d->key[row];
  }
  return renumbered ? 1 : 0;
}

}  // extern "C"
