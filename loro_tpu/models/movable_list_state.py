"""MovableList container state.

reference: crates/loro-internal/src/state/movable_list_state.rs +
MovableListDiffCalculator (diff_calc.rs:1669-2020).  Model: the Fugue
sequence holds *position slots*; each element owns the set of slots
created for it (its insert op + every move op).  Per element:

- winning slot  = slot with max (lamport, peer)  (last move wins)
- winning value = set op with max (lamport, peer) (or creation value)
- element is visible iff its winning slot is not tombstoned — so a move
  that is newer (LWW) than a concurrent delete revives the element at
  the destination, matching the reference's move/delete resolution.

Device equivalent: two scatter-max passes (slot winner, value winner)
over (doc, elem) keys + the shared Fugue order kernel for slot order.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.change import MovableMove, MovableSet, Op, SeqDelete, SeqInsert
from ..core.ids import ContainerID, ID
from ..event import Delta, Diff
from .base import ContainerState
from .list_state import _resolve_run_cont
from .seq_crdt import FugueSeq, SeqElem


class ElemEntry:
    __slots__ = ("value", "value_key", "pos_key", "slot", "deleted", "slots", "sets")

    def __init__(self, value: Any, value_key: Tuple[int, int], pos_key: Tuple[int, int], slot: ID):
        self.value = value
        self.value_key = value_key  # (lamport, peer) of winning set
        self.pos_key = pos_key  # (lamport, peer) of winning slot
        self.slot = slot  # winning slot id
        self.deleted = False
        # full histories for version-diff evaluation:
        self.slots: List[ID] = [slot]  # every position slot ever created
        self.sets: List[Tuple[int, int, ID, Any]] = []  # (lamport, peer, op id, value)


class MovableListState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.seq = FugueSeq()  # slots; content = elem ID
        self.elems: Dict[ID, ElemEntry] = {}

    # ------------------------------------------------------------------
    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        if isinstance(c, SeqInsert):
            return self._apply_insert(op, c, peer, lamport, record)
        if isinstance(c, SeqDelete):
            return self._apply_delete(c, record, ID(peer, op.counter))
        if isinstance(c, MovableSet):
            return self._apply_set(c, peer, lamport, record, ID(peer, op.counter))
        assert isinstance(c, MovableMove)
        return self._apply_move(op, c, peer, lamport, record)

    def _apply_insert(
        self, op: Op, c: SeqInsert, peer: int, lamport: int, record: bool
    ) -> Optional[Diff]:
        parent = _resolve_run_cont(c.parent, peer, op.counter)
        elem_ids = [ID(peer, op.counter + j) for j in range(len(c.content))]
        pos, slots = self.seq.integrate_insert(
            peer, op.counter, parent, c.side, elem_ids, lamport, compute_pos=record
        )
        for j, (eid, v) in enumerate(zip(elem_ids, c.content)):
            key = (lamport + j, peer)
            entry = ElemEntry(v, key, key, eid)
            entry.sets.append((lamport + j, peer, eid, v))  # creation value
            self.elems[eid] = entry
        if not record:
            return None
        return Delta().retain(pos).insert(tuple(c.content))

    def _apply_delete(self, c: SeqDelete, record: bool, op_id: ID) -> Optional[Diff]:
        out = Delta()
        changed = False
        for span in c.spans:
            for ctr in range(span.start, span.end):
                slot = self.seq.by_id.get((span.peer, ctr))
                if slot is None:
                    continue
                # record the deleter even on already-dead slots so
                # version diffs can evaluate visibility at any vv
                slot.deleted_by.append(op_id)
                if slot.deleted:
                    continue
                was_visible = slot.vis_w > 0
                pos = self.seq.treap.visible_rank(slot) if (record and was_visible) else 0
                slot.deleted = True
                self.seq.set_visible(slot, 0)
                eid: ID = slot.content
                entry = self.elems.get(eid)
                if entry is not None and entry.slot == ID(span.peer, ctr):
                    entry.deleted = True
                if record and was_visible:
                    out = out.compose(Delta().retain(pos).delete(1))
                    changed = True
        return out if changed else None

    def _apply_set(
        self, c: MovableSet, peer: int, lamport: int, record: bool, op_id: ID
    ) -> Optional[Diff]:
        entry = self.elems.get(c.elem)
        if entry is None:
            return None  # element unknown (trimmed history)
        entry.sets.append((lamport, peer, op_id, c.value))
        if entry.value_key >= (lamport, peer):
            return None
        entry.value = c.value
        entry.value_key = (lamport, peer)
        if not record or entry.deleted:
            return None
        pos = self.seq.visible_index_of(entry.slot)
        if pos is None:
            return None
        return Delta().retain(pos).delete(1).compose(Delta().retain(pos).insert((c.value,)))

    def _apply_move(
        self, op: Op, c: MovableMove, peer: int, lamport: int, record: bool
    ) -> Optional[Diff]:
        entry = self.elems.get(c.elem)
        parent = _resolve_run_cont(c.parent, peer, op.counter)
        _, slots = self.seq.integrate_insert(
            peer, op.counter, parent, c.side, [c.elem], lamport, compute_pos=False
        )
        new_slot = slots[0]
        # hide immediately: event positions below must be computed on a
        # state that does NOT yet contain the destination slot (the diff
        # is delete-then-insert; the winner case re-shows it)
        self.seq.set_visible(new_slot, 0)
        if entry is None:
            return None  # unknown element (trimmed history)
        entry.slots.append(ID(peer, op.counter))
        new_key = (lamport, peer)
        if new_key <= entry.pos_key:
            return None  # stale move: slot stays invisible
        d = Delta()
        # hide old winning slot
        old = self.seq.by_id.get((entry.slot.peer, entry.slot.counter))
        was_visible = old is not None and old.vis_w > 0
        if was_visible:
            if record:
                old_pos = self.seq.treap.visible_rank(old)
                d = d.compose(Delta().retain(old_pos).delete(1))
            self.seq.set_visible(old, 0)
        entry.pos_key = new_key
        entry.slot = ID(peer, op.counter)
        revived = entry.deleted and not new_slot.deleted
        entry.deleted = new_slot.deleted
        if not new_slot.deleted:
            # the new slot becomes visible (move destination)
            self.seq.set_visible(new_slot, 1)
            if record:
                new_pos = self.seq.treap.visible_rank(new_slot)
                d = d.compose(Delta().retain(new_pos).insert((entry.value,)))
        if not record:
            return None
        return d if (was_visible or revived or not new_slot.deleted) else None

    # -- version diffs -------------------------------------------------
    def _winner_at(self, elem_id: ID, v, cache: Dict[ID, Optional[SeqElem]]) -> Optional[SeqElem]:
        """LWW-winning slot of an element within version v (memoized per
        diff so an element moved M times costs O(M) once, not per slot)."""
        if elem_id in cache:
            return cache[elem_id]
        entry = self.elems.get(elem_id)
        best = None
        if entry is not None:
            for sid in entry.slots:
                if not v.includes(sid):
                    continue
                se = self.seq.by_id.get((sid.peer, sid.counter))
                if se is None:
                    continue
                k = (se.lamport, se.peer)
                if best is None or k > best[0]:
                    best = (k, se)
        win = best[1] if best is not None else None
        cache[elem_id] = win
        return win

    def _slot_visible_at(self, slot: SeqElem, v, cache: Dict[ID, Optional[SeqElem]]) -> bool:
        """Slot shows the element at version v iff it exists, isn't
        deleted, and is the LWW winner among the element's slots in v."""
        if not v.includes(slot.id) or any(v.includes(x) for x in slot.deleted_by):
            return False
        return self._winner_at(slot.content, v, cache) is slot

    def _value_at(self, elem_id: ID, v) -> Any:
        entry = self.elems.get(elem_id)
        best = None
        if entry is not None:
            for lam, peer, oid, val in entry.sets:
                if v.includes(oid) and (best is None or (lam, peer) > best[0]):
                    best = ((lam, peer), val)
        return best[1] if best else None

    def delta_between(self, va, vb) -> Delta:
        """Exact delta turning the list at va into the list at vb
        (element/slot identity aware; value changes become replace)."""
        d = Delta()
        cache_a: Dict[ID, Optional[SeqElem]] = {}
        cache_b: Dict[ID, Optional[SeqElem]] = {}
        for slot in self.seq.all_elems():
            a_vis = self._slot_visible_at(slot, va, cache_a)
            b_vis = self._slot_visible_at(slot, vb, cache_b)
            if a_vis and b_vis:
                a_val = self._value_at(slot.content, va)
                b_val = self._value_at(slot.content, vb)
                if a_val == b_val:
                    d.retain(1)
                else:
                    d.delete(1)
                    d.insert((b_val,))
            elif a_vis:
                d.delete(1)
            elif b_vis:
                d.insert((self._value_at(slot.content, vb),))
        return d.chop()

    # -- queries ------------------------------------------------------
    def get_value(self) -> List[Any]:
        out = []
        for slot in self.seq.visible_elems():
            entry = self.elems.get(slot.content)
            out.append(entry.value if entry is not None else None)
        return out

    def __len__(self) -> int:
        return self.seq.visible_len

    def get(self, index: int) -> Any:
        slot = self.seq.elem_at(index)
        if slot is None:
            return None
        entry = self.elems.get(slot.content)
        return entry.value if entry is not None else None

    def elem_id_at(self, index: int) -> Optional[ID]:
        slot = self.seq.elem_at(index)
        return slot.content if slot is not None else None

    def slot_id_at(self, index: int) -> Optional[ID]:
        slot = self.seq.elem_at(index)
        return slot.id if slot is not None else None

    def to_diff(self) -> Diff:
        v = tuple(self.get_value())
        d = Delta()
        if v:
            d.insert(v)
        return d
