"""ContainerState interface — the gating boundary of the framework.

reference: crates/loro-internal/src/state.rs:238-277 (`ContainerState`
trait).  Device merge kernels produce diffs/states behind this same
boundary: a container state can be host-materialized (these classes) or
batch-resident on device (loro_tpu/parallel/fleet.py), with identical
observable behavior.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Optional, TYPE_CHECKING

from ..core.change import Op
from ..core.ids import ContainerID
from ..event import Diff

if TYPE_CHECKING:  # pragma: no cover
    from ..core.change import Change


class ContainerState(ABC):
    """Materialized state of one container.

    `materialized` is False for states that exist only because a handler
    READ them (reads must not make containers spring into existence in
    doc values — reference: should_avoid_initialize_new_container_
    accidentally); it flips True when an op applies or a snapshot
    hydrates the state."""

    materialized = False

    def __init__(self, cid: ContainerID):
        self.cid = cid

    @abstractmethod
    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        """Integrate one op (local or remote, causally ordered) and return
        the event diff it produced (None if no observable change).
        `peer` is the authoring peer; `lamport` is the lamport of the
        op's first atom.  With record=False the integration happens but
        no diff is built (positions/rank queries skipped — the fast
        path when nothing consumes events)."""

    @abstractmethod
    def get_value(self) -> Any:
        """Shallow value (child containers appear as ContainerID)."""

    @abstractmethod
    def to_diff(self) -> Diff:
        """Diff from empty to the current state (for initial subscription
        snapshots and checkout events)."""

    def is_empty_state(self) -> bool:
        v = self.get_value()
        return not v
