"""Movable tree container state (Kleppmann-style movable tree).

reference: crates/loro-internal/src/state/tree_state.rs +
diff_calc/tree.rs.  Semantics: all moves are applied in global
(lamport, peer, counter) order; a move whose new parent lies inside the
target's own subtree at that moment is a no-op (`effected = false`,
tree.rs:499-508).  Deletion is a move under the TRASH sentinel.
Sibling order is (fractional_index, (lamport, peer)) — tree.rs:592-595.

Out-of-(lamport)-order arrivals trigger a replay of the move log — the
same sorted-replay the batched device kernel performs with a
pointer-doubling ancestor check (loro_tpu/ops/tree_batch.py).
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..core.change import Op, TreeMove
from ..core.ids import ContainerID, ContainerType, TreeID
from ..event import Diff, TreeDiff, TreeDiffAction, TreeDiffItem
from .base import ContainerState

TRASH = TreeID(0xFFFF_FFFF_FFFF_FFFF, -1)  # deleted-subtree sentinel parent


class TreeNode:
    __slots__ = ("parent", "position", "move_key")

    def __init__(self, parent: Optional[TreeID], position: Optional[bytes], move_key: Tuple):
        self.parent = parent  # None = root child, TRASH = deleted
        self.position = position
        self.move_key = move_key  # (lamport, peer, counter) of effective move


class TreeState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.nodes: Dict[TreeID, TreeNode] = {}
        # full move log sorted by (lamport, peer, counter); replayed on
        # out-of-order arrivals (rare) and by the device kernel (always)
        self.moves: List[Tuple[Tuple[int, int, int], TreeMove]] = []

    # ------------------------------------------------------------------
    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        assert isinstance(c, TreeMove)
        key = (lamport, peer, op.counter)
        entry = (key, c)
        if not self.moves or self.moves[-1][0] < key:
            self.moves.append(entry)
            return self._apply_move(key, c, record)
        # out-of-order in lamport: insert into log and replay
        i = bisect.bisect_left(self.moves, key, key=lambda e: e[0])
        if i < len(self.moves) and self.moves[i][0] == key:
            return None  # duplicate
        self.moves.insert(i, entry)
        return self._replay_all(record)

    def _apply_move(self, key: Tuple, c: TreeMove, record: bool = True) -> Optional[Diff]:
        target = c.target
        parent = TRASH if c.is_delete else c.parent
        if parent is not None and parent != TRASH and self._creates_cycle(target, parent):
            return None  # not effected
        was = self.nodes.get(target)
        was_alive = record and was is not None and not self._is_deleted(target)
        self.nodes[target] = TreeNode(parent, c.position, key)
        if not record:
            return None
        now_alive = not self._is_deleted(target)
        d = TreeDiff()
        if was_alive and not now_alive:
            d.items.append(TreeDiffItem(target, TreeDiffAction.Delete))
        elif now_alive and not was_alive:
            d.items.append(
                TreeDiffItem(target, TreeDiffAction.Create, parent, self.index_of(target), c.position)
            )
        elif was_alive and now_alive:
            d.items.append(
                TreeDiffItem(target, TreeDiffAction.Move, parent, self.index_of(target), c.position)
            )
        else:
            return None  # dead -> dead: invisible
        return d

    def _replay_all(self, record: bool = True) -> Optional[Diff]:
        """Rebuild node table by replaying the sorted move log, then diff
        old vs new tables (reference retreat/forward, tree.rs:230-396)."""
        old = (
            {t: (n.parent, n.position) for t, n in self.nodes.items() if not self._is_deleted(t)}
            if record
            else {}
        )
        self.nodes = {}
        for key, c in self.moves:
            target = c.target
            parent = TRASH if c.is_delete else c.parent
            if parent is not None and parent != TRASH and self._creates_cycle(target, parent):
                continue
            self.nodes[target] = TreeNode(parent, c.position, key)
        if not record:
            return None
        d = TreeDiff()
        new_alive = {t for t in self.nodes if not self._is_deleted(t)}
        for t in old:
            if t not in new_alive:
                d.items.append(TreeDiffItem(t, TreeDiffAction.Delete))
        for t in sorted(new_alive, key=self._depth):
            n = self.nodes[t]
            if t not in old:
                d.items.append(
                    TreeDiffItem(t, TreeDiffAction.Create, n.parent, self.index_of(t), n.position)
                )
            elif old[t] != (n.parent, n.position):
                d.items.append(
                    TreeDiffItem(t, TreeDiffAction.Move, n.parent, self.index_of(t), n.position)
                )
        return d if d.items else None

    # ------------------------------------------------------------------
    def _creates_cycle(self, target: TreeID, new_parent: TreeID) -> bool:
        """True if target is an ancestor of new_parent (or equal)."""
        cur: Optional[TreeID] = new_parent
        seen = 0
        while cur is not None and cur != TRASH:
            if cur == target:
                return True
            node = self.nodes.get(cur)
            cur = node.parent if node else None
            seen += 1
            if seen > len(self.nodes) + 1:  # corrupted cycle guard
                return True
        return False

    def _is_deleted_parent(self, parent: Optional[TreeID]) -> bool:
        return parent == TRASH or (parent is not None and self._is_deleted(parent))

    def _is_deleted(self, t: TreeID) -> bool:
        cur: Optional[TreeID] = t
        while cur is not None:
            if cur == TRASH:
                return True
            node = self.nodes.get(cur)
            if node is None:
                return False
            cur = node.parent
        return False

    def _depth(self, t: TreeID) -> int:
        d = 0
        node = self.nodes.get(t)
        while node is not None and node.parent is not None and node.parent != TRASH:
            d += 1
            node = self.nodes.get(node.parent)
        return d

    # -- queries ------------------------------------------------------
    def children_of(self, parent: Optional[TreeID]) -> List[TreeID]:
        kids = [
            (n.position or b"", n.move_key, t)
            for t, n in self.nodes.items()
            if n.parent == parent and not self._is_deleted(t)
        ]
        kids.sort(key=lambda x: (x[0], x[1]))
        return [t for _, _, t in kids]

    def index_of(self, t: TreeID) -> int:
        n = self.nodes.get(t)
        if n is None or self._is_deleted(t):
            return -1
        sibs = self.children_of(n.parent)
        return sibs.index(t)

    def parent_of(self, t: TreeID) -> Optional[TreeID]:
        n = self.nodes.get(t)
        return n.parent if n else None

    def contains(self, t: TreeID) -> bool:
        return t in self.nodes and not self._is_deleted(t)

    def roots(self) -> List[TreeID]:
        return self.children_of(None)

    def meta_cid(self, t: TreeID) -> ContainerID:
        """Every tree node owns a meta map container keyed by its id
        (reference: tree node `meta` handler)."""
        return ContainerID.normal(t.peer, t.counter, ContainerType.Map)

    def get_value(self) -> List[dict]:
        """Flat node list (id, parent, index, fractional_index, meta cid),
        matching the reference's tree value shape."""
        out = []
        queue: List[Optional[TreeID]] = [None]
        while queue:
            parent = queue.pop(0)
            for i, t in enumerate(self.children_of(parent)):
                n = self.nodes[t]
                out.append(
                    {
                        "id": str(t),
                        "parent": str(parent) if parent is not None else None,
                        "index": i,
                        "fractional_index": (n.position or b"").hex(),
                        "meta": self.meta_cid(t),
                    }
                )
                queue.append(t)
        return out

    def to_diff(self) -> Diff:
        d = TreeDiff()
        stack = [(None, t) for t in reversed(self.roots())]
        while stack:
            parent, t = stack.pop()
            n = self.nodes[t]
            d.items.append(
                TreeDiffItem(t, TreeDiffAction.Create, parent, self.index_of(t), n.position)
            )
            for c in reversed(self.children_of(t)):
                stack.append((t, c))
        return d
