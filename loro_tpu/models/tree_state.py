"""Movable tree container state (Kleppmann-style movable tree).

reference: crates/loro-internal/src/state/tree_state.rs +
diff_calc/tree.rs.  Semantics: all moves are applied in global
(lamport, peer, counter) order; a move whose new parent lies inside the
target's own subtree at that moment is a no-op (`effected = false`,
tree.rs:499-508).  Deletion is a move under the TRASH sentinel.
Sibling order is (fractional_index, (lamport, peer)) — tree.rs:592-595.

Out-of-(lamport)-order arrivals trigger a replay of the move log — the
same sorted-replay the batched device kernel performs with a
pointer-doubling ancestor check (loro_tpu/ops/tree_batch.py).
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..core.change import Op, TreeMove
from ..core.ids import ContainerID, ContainerType, TreeID
from ..event import Diff, TreeDiff, TreeDiffAction, TreeDiffItem
from .base import ContainerState

TRASH = TreeID(0xFFFF_FFFF_FFFF_FFFF, -1)  # deleted-subtree sentinel parent


# -- helpers over a bare node table (shared by live state + version
#    reconstructions in delta_between) ---------------------------------
def _deleted_in(nodes: Dict[TreeID, "TreeNode"], t: TreeID) -> bool:
    cur: Optional[TreeID] = t
    while cur is not None:
        if cur == TRASH:
            return True
        node = nodes.get(cur)
        if node is None:
            return False
        cur = node.parent
    return False


def _cycle_in(nodes: Dict[TreeID, "TreeNode"], target: TreeID, new_parent: TreeID) -> bool:
    cur: Optional[TreeID] = new_parent
    seen = 0
    while cur is not None and cur != TRASH:
        if cur == target:
            return True
        node = nodes.get(cur)
        cur = node.parent if node else None
        seen += 1
        if seen > len(nodes) + 1:
            return True
    return False


def _depth_in(nodes: Dict[TreeID, "TreeNode"], t: TreeID) -> int:
    d = 0
    node = nodes.get(t)
    while node is not None and node.parent is not None and node.parent != TRASH:
        d += 1
        node = nodes.get(node.parent)
    return d


def _children_in(nodes: Dict[TreeID, "TreeNode"], parent: Optional[TreeID]) -> List[TreeID]:
    kids = [
        (n.position or b"", n.move_key, t)
        for t, n in nodes.items()
        if n.parent == parent and not _deleted_in(nodes, t)
    ]
    kids.sort(key=lambda x: (x[0], x[1]))
    return [t for _, _, t in kids]


def _index_in(nodes: Dict[TreeID, "TreeNode"], t: TreeID) -> int:
    n = nodes.get(t)
    if n is None or _deleted_in(nodes, t):
        return -1
    sibs = _children_in(nodes, n.parent)
    return sibs.index(t)


def _table_views(nodes: Dict[TreeID, "TreeNode"]):
    """One-pass (alive set, children-by-parent, index-by-node) views so
    version diffs don't pay per-item sibling sorts."""
    alive = {t for t in nodes if not _deleted_in(nodes, t)}
    kids: Dict[Optional[TreeID], List[TreeID]] = {}
    for t in alive:
        kids.setdefault(nodes[t].parent, []).append(t)
    for lst in kids.values():
        lst.sort(key=lambda t: (nodes[t].position or b"", nodes[t].move_key))
    index = {t: i for lst in kids.values() for i, t in enumerate(lst)}
    return alive, kids, index


class TreeNode:
    __slots__ = ("parent", "position", "move_key")

    def __init__(self, parent: Optional[TreeID], position: Optional[bytes], move_key: Tuple):
        self.parent = parent  # None = root child, TRASH = deleted
        self.position = position
        self.move_key = move_key  # (lamport, peer, counter) of effective move


class TreeState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.nodes: Dict[TreeID, TreeNode] = {}
        # full move log sorted by (lamport, peer, counter); replayed on
        # out-of-order arrivals (rare) and by the device kernel (always)
        self.moves: List[Tuple[Tuple[int, int, int], TreeMove]] = []

    # ------------------------------------------------------------------
    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        assert isinstance(c, TreeMove)
        key = (lamport, peer, op.counter)
        entry = (key, c)
        if not self.moves or self.moves[-1][0] < key:
            self.moves.append(entry)
            return self._apply_move(key, c, record)
        # out-of-order in lamport: insert into log and replay
        i = bisect.bisect_left(self.moves, key, key=lambda e: e[0])
        if i < len(self.moves) and self.moves[i][0] == key:
            return None  # duplicate
        self.moves.insert(i, entry)
        return self._replay_all(record)

    def _apply_move(self, key: Tuple, c: TreeMove, record: bool = True) -> Optional[Diff]:
        target = c.target
        parent = TRASH if c.is_delete else c.parent
        if parent is not None and parent != TRASH and self._creates_cycle(target, parent):
            return None  # not effected
        was = self.nodes.get(target)
        was_alive = record and was is not None and not self._is_deleted(target)
        old_spot = (
            (was.parent, _index_in(self.nodes, target)) if was_alive else (None, None)
        )
        # the target is about to die if its new parent chain is trashed;
        # only then collect the live subtree (delete events are emitted
        # per node, children first — see the Delete branch below)
        will_die = parent == TRASH or (parent is not None and self._is_deleted(parent))
        doomed: List[TreeID] = []
        old_spots = {}
        if was_alive and will_die:
            _alive, kids, index = _table_views(self.nodes)
            queue = [target]
            while queue:
                p = queue.pop(0)
                doomed.append(p)
                queue.extend(kids.get(p, ()))
            old_spots = {
                t: (self.nodes[t].parent, index.get(t, -1)) for t in doomed
            }
        self.nodes[target] = TreeNode(parent, c.position, key)
        if not record:
            return None
        now_alive = not self._is_deleted(target)
        d = TreeDiff()
        if was_alive and not now_alive:
            # per-node deletes, children first: the event contract is
            # by-id (every consumer removal is explicit; no implicit
            # subtree semantics), matching delta_between
            for t in reversed(doomed):
                op, oi = old_spots[t]
                d.items.append(
                    TreeDiffItem(t, TreeDiffAction.Delete, old_parent=op, old_index=oi)
                )
        elif now_alive and not was_alive:
            d.items.append(
                TreeDiffItem(target, TreeDiffAction.Create, parent, self.index_of(target), c.position)
            )
            # recursive revival: undeleting target (e.g. moving it out
            # of a trashed subtree) brings its whole live subtree back;
            # consumers saw those nodes deleted with the subtree root,
            # so they must be re-created parents-first (reference:
            # diff_calc/tree.rs subtree revival)
            queue = [target]
            while queue:
                p = queue.pop(0)
                for ch in self.children_of(p):
                    if ch == target:
                        continue
                    n = self.nodes[ch]
                    d.items.append(
                        TreeDiffItem(
                            ch, TreeDiffAction.Create, n.parent, self.index_of(ch), n.position
                        )
                    )
                    queue.append(ch)
        elif was_alive and now_alive:
            d.items.append(
                TreeDiffItem(
                    target,
                    TreeDiffAction.Move,
                    parent,
                    self.index_of(target),
                    c.position,
                    old_parent=old_spot[0],
                    old_index=old_spot[1],
                )
            )
        else:
            return None  # dead -> dead: invisible
        return d

    def _replay_all(self, record: bool = True) -> Optional[Diff]:
        """Rebuild node table by replaying the sorted move log, then diff
        old vs new tables (reference retreat/forward, tree.rs:230-396)."""
        old_nodes = dict(self.nodes) if record else {}
        if record:
            old_alive, _old_kids, old_index = _table_views(old_nodes)
            old = {t: (old_nodes[t].parent, old_nodes[t].position) for t in old_alive}
        else:
            old = {}
        self.nodes = {}
        for key, c in self.moves:
            target = c.target
            parent = TRASH if c.is_delete else c.parent
            if parent is not None and parent != TRASH and self._creates_cycle(target, parent):
                continue
            self.nodes[target] = TreeNode(parent, c.position, key)
        if not record:
            return None
        d = TreeDiff()
        new_alive, _new_kids, new_index = _table_views(self.nodes)
        gone = [t for t in old if t not in new_alive]
        for t in sorted(gone, key=lambda t: -_depth_in(old_nodes, t)):
            d.items.append(
                TreeDiffItem(
                    t,
                    TreeDiffAction.Delete,
                    old_parent=old[t][0],
                    old_index=old_index.get(t, -1),
                )
            )
        for t in sorted(new_alive, key=self._depth):
            n = self.nodes[t]
            if t not in old:
                d.items.append(
                    TreeDiffItem(t, TreeDiffAction.Create, n.parent, new_index.get(t, -1), n.position)
                )
            elif old[t] != (n.parent, n.position):
                d.items.append(
                    TreeDiffItem(
                        t,
                        TreeDiffAction.Move,
                        n.parent,
                        new_index.get(t, -1),
                        n.position,
                        old_parent=old[t][0],
                        old_index=old_index.get(t, -1),
                    )
                )
        return d if d.items else None

    # ------------------------------------------------------------------
    def _creates_cycle(self, target: TreeID, new_parent: TreeID) -> bool:
        """True if target is an ancestor of new_parent (or equal)."""
        return _cycle_in(self.nodes, target, new_parent)

    def _is_deleted_parent(self, parent: Optional[TreeID]) -> bool:
        return parent == TRASH or (parent is not None and self._is_deleted(parent))

    def _is_deleted(self, t: TreeID) -> bool:
        return _deleted_in(self.nodes, t)

    def _depth(self, t: TreeID) -> int:
        return _depth_in(self.nodes, t)

    # -- exact version diffs (element identity over the move log) ------
    def _nodes_at(self, vv) -> Dict[TreeID, TreeNode]:
        """Node table at an arbitrary version: replay the move log
        filtered to ops included in `vv` (reference: diff_calc/tree.rs
        :230-396 reaches the same states via retreat/forward on its
        per-container history cache).  Small memo keyed on (version,
        log length) so checkout scrubs / repeated diffs near the same
        versions don't re-replay."""
        from ..core.ids import ID

        memo_key = (tuple(sorted(vv.items())), len(self.moves))
        cache = getattr(self, "_nodes_at_memo", None)
        if cache is None:
            cache = self._nodes_at_memo = {}
        if memo_key in cache:
            return cache[memo_key]
        nodes: Dict[TreeID, TreeNode] = {}
        for key, c in self.moves:
            lam, peer, ctr = key
            if not vv.includes(ID(peer, ctr)):
                continue
            target = c.target
            parent = TRASH if c.is_delete else c.parent
            if parent is not None and parent != TRASH and _cycle_in(nodes, target, parent):
                continue
            nodes[target] = TreeNode(parent, c.position, key)
        if len(cache) >= 8:
            cache.pop(next(iter(cache)))
        cache[memo_key] = nodes
        return nodes

    def delta_between(self, va, vb) -> TreeDiff:
        """Exact TreeDiff turning state(va) into state(vb), by move-op
        identity: per-node Create (incl. every node of a revived
        subtree, parents first), Move (with old_parent/old_index), and
        Delete (children first).  reference: tree.rs:230-396."""
        old = self._nodes_at(va)
        new = self._nodes_at(vb)
        alive_old, _kids_old, idx_old = _table_views(old)
        alive_new, kids_new, idx_new = _table_views(new)
        d = TreeDiff()
        # deletes children-first so consumers never orphan a live child
        gone = alive_old - alive_new
        for t in sorted(gone, key=lambda t: -_depth_in(old, t)):
            d.items.append(
                TreeDiffItem(
                    t,
                    TreeDiffAction.Delete,
                    old_parent=old[t].parent,
                    old_index=idx_old.get(t, -1),
                )
            )
        # creates + moves parents-first in the NEW tree (BFS): a parent
        # is always placed before its children, which also makes the
        # item sequence safe to apply move-by-move (no transient cycles)
        order: List[TreeID] = []
        queue: List[Optional[TreeID]] = [None]
        while queue:
            p = queue.pop(0)
            for t in kids_new.get(p, ()):
                order.append(t)
                queue.append(t)
        for t in order:
            n = new[t]
            if t not in alive_old:
                d.items.append(
                    TreeDiffItem(
                        t,
                        TreeDiffAction.Create,
                        n.parent,
                        idx_new.get(t, -1),
                        n.position,
                    )
                )
            else:
                o = old[t]
                if (o.parent, o.position) != (n.parent, n.position):
                    d.items.append(
                        TreeDiffItem(
                            t,
                            TreeDiffAction.Move,
                            n.parent,
                            idx_new.get(t, -1),
                            n.position,
                            old_parent=o.parent,
                            old_index=idx_old.get(t, -1),
                        )
                    )
        return d

    # -- queries ------------------------------------------------------
    def children_of(self, parent: Optional[TreeID]) -> List[TreeID]:
        return _children_in(self.nodes, parent)

    def index_of(self, t: TreeID) -> int:
        return _index_in(self.nodes, t)

    def parent_of(self, t: TreeID) -> Optional[TreeID]:
        n = self.nodes.get(t)
        return n.parent if n else None

    def contains(self, t: TreeID) -> bool:
        return t in self.nodes and not self._is_deleted(t)

    def roots(self) -> List[TreeID]:
        return self.children_of(None)

    def meta_cid(self, t: TreeID) -> ContainerID:
        """Every tree node owns a meta map container keyed by its id
        (reference: tree node `meta` handler)."""
        return ContainerID.normal(t.peer, t.counter, ContainerType.Map)

    def get_value(self) -> List[dict]:
        """Flat node list (id, parent, index, fractional_index, meta cid),
        matching the reference's tree value shape."""
        out = []
        queue: List[Optional[TreeID]] = [None]
        while queue:
            parent = queue.pop(0)
            for i, t in enumerate(self.children_of(parent)):
                n = self.nodes[t]
                out.append(
                    {
                        "id": str(t),
                        "parent": str(parent) if parent is not None else None,
                        "index": i,
                        "fractional_index": (n.position or b"").hex(),
                        "meta": self.meta_cid(t),
                    }
                )
                queue.append(t)
        return out

    def to_diff(self) -> Diff:
        d = TreeDiff()
        stack = [(None, t) for t in reversed(self.roots())]
        while stack:
            parent, t = stack.pop()
            n = self.nodes[t]
            d.items.append(
                TreeDiffItem(t, TreeDiffAction.Create, parent, self.index_of(t), n.position)
            )
            for c in reversed(self.children_of(t)):
                stack.append((t, c))
        return d
