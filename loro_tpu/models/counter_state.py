"""Counter container state (reference: state/counter_state.rs).

A counter is a PN-counter specialization: the value is the sum of all
increment deltas, which is order-independent — the device equivalent is
a segment-sum over (doc, container) slots (loro_tpu/ops/lww.py)."""
from __future__ import annotations

from typing import Optional

from ..core.change import CounterIncr, Op
from ..core.ids import ContainerID
from ..event import CounterDiff, Diff
from .base import ContainerState


class CounterState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.value: float = 0.0

    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        assert isinstance(c, CounterIncr)
        self.value += c.delta
        return CounterDiff(c.delta)

    def get_value(self) -> float:
        return self.value

    def to_diff(self) -> Diff:
        return CounterDiff(self.value)
