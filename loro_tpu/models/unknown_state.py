"""Unknown container state: forward-compat passthrough that retains ops
without interpreting them (reference: state/unknown_state.rs)."""
from __future__ import annotations

from typing import List, Optional

from ..core.change import Op
from ..core.ids import ContainerID
from ..event import Diff, MapDiff
from .base import ContainerState


class UnknownState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.ops: List[Op] = []

    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        self.ops.append(op)
        return None

    def get_value(self) -> None:
        return None

    def to_diff(self) -> Diff:
        return MapDiff()
