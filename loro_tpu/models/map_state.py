"""LWW-Map container state.

reference: crates/loro-internal/src/state/map_state.rs +
MapDiffCalculator (diff_calc.rs:488-616): per key, the winner is the op
with max (lamport, peer).  Deleted keys keep a tombstone entry so later
LWW comparisons stay correct.  The batched device equivalent is a
scatter-max over (doc, container, key) slots (loro_tpu/ops/lww.py).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..core.change import MapSet, Op
from ..core.ids import ContainerID, PeerID
from ..event import Diff, MapDiff
from .base import ContainerState


class MapEntry:
    __slots__ = ("value", "lamport", "peer", "counter", "deleted")

    def __init__(self, value: Any, lamport: int, peer: PeerID, counter: int, deleted: bool):
        self.value = value
        self.lamport = lamport
        self.peer = peer
        self.counter = counter
        self.deleted = deleted

    @property
    def ord(self) -> Tuple[int, PeerID]:
        return (self.lamport, self.peer)


class MapState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.entries: Dict[str, MapEntry] = {}

    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        assert isinstance(c, MapSet)
        cur = self.entries.get(c.key)
        if cur is not None and cur.ord >= (lamport, peer):
            return None  # LWW: existing entry wins
        self.entries[c.key] = MapEntry(c.value, lamport, peer, op.counter, c.deleted)
        d = MapDiff()
        if c.deleted:
            if cur is None or cur.deleted:
                return None  # no observable change
            d.deleted.add(c.key)
        else:
            d.updated[c.key] = c.value
        return d

    def get_value(self) -> Dict[str, Any]:
        return {k: e.value for k, e in self.entries.items() if not e.deleted}

    def get_entry(self, key: str) -> Optional[MapEntry]:
        e = self.entries.get(key)
        return e if e is not None and not e.deleted else None

    def to_diff(self) -> Diff:
        d = MapDiff()
        for k, e in self.entries.items():
            if not e.deleted:
                d.updated[k] = e.value
        return d
