"""Rich-text container state over FugueSeq.

reference: crates/loro-internal/src/state/richtext_state.rs +
container/richtext/ (Fugue tracker, style_range_map).  Characters and
Peritext-style anchors live in one Fugue sequence; a style anchor pair
(start at id (p,c), end at id (p,c+1) — handler invariant) spans the
elements between them, and per style key the winning pair covering a
char is the one with max (lamport, peer).  Unmark = a pair with value
None.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.change import Op, SeqDelete, SeqInsert, StyleAnchor
from ..core.ids import ContainerID, ID
from ..event import Delta, Diff
from .base import ContainerState
from .list_state import _resolve_run_cont
from .seq_crdt import FugueSeq, SeqElem


class TextState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.seq = FugueSeq()
        self.n_anchors = 0  # fast path: style scans skipped when 0

    # -- op application ----------------------------------------------
    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        if isinstance(c, SeqInsert):
            parent = _resolve_run_cont(c.parent, peer, op.counter)
            if isinstance(c.content, StyleAnchor):
                self.seq.integrate_insert(
                    peer, op.counter, parent, c.side, [c.content], lamport, compute_pos=False
                )
                self.n_anchors += 1
                # anchors are invisible; the style change event is the
                # attribute delta over the covered visible range
                return self._style_event_for_anchor(peer, op.counter) if record else None
            pos, _ = self.seq.integrate_insert(
                peer, op.counter, parent, c.side, c.content, lamport, compute_pos=record
            )
            if not record:
                return None
            attrs = (
                self._styles_at_elem(self.seq.by_id[(peer, op.counter)]) if self.n_anchors else {}
            )
            return Delta().retain(pos).insert(c.content, attrs or None)
        assert isinstance(c, SeqDelete)
        removed = self.seq.integrate_delete(
            c.spans, deleter=ID(peer, op.counter), compute_pos=record
        )
        if not removed:
            return None
        out = Delta()
        for pos, ln in removed:
            out = out.compose(Delta().retain(pos).delete(ln))
        return out

    # -- queries ------------------------------------------------------
    def get_value(self) -> str:
        return "".join(e.content for e in self.seq.visible_elems())

    def __len__(self) -> int:
        return self.seq.visible_len

    def _iter_char_attrs(self, anchor_live, char_live):
        """Single shared anchor walk (pairing via the (peer, counter+1)
        invariant): yields (elem, attrs) for every char passing
        `char_live`, with `anchor_live` filtering which anchors count.
        Backs both the live render and version-filtered diffs."""
        active: Dict[str, list] = {}
        for e in self.seq.all_elems():
            if isinstance(e.content, StyleAnchor):
                if not anchor_live(e):
                    continue
                a: StyleAnchor = e.content
                if a.is_start:
                    active.setdefault(a.key, []).append((e.lamport, e.peer, a.value, e.counter))
                else:
                    lst = active.get(a.key)
                    if lst:
                        # remove the entry whose start anchor is (peer, counter-1)
                        for i, ent in enumerate(lst):
                            if ent[1] == e.peer and ent[3] == e.counter - 1:
                                lst.pop(i)
                                break
                continue
            if char_live(e):
                yield e, _resolve_attrs(active)

    def get_richtext_value(self) -> List[dict]:
        """Quill-style segments [{insert, attributes?}] with resolved
        styles (reference: richtext_state get_richtext_value)."""
        segs: List[dict] = []
        for e, attrs in self._iter_char_attrs(
            lambda a: not a.deleted, lambda c: bool(c.vis_w)
        ):
            attrs = attrs or None
            if segs and segs[-1].get("attributes") == attrs:
                segs[-1]["insert"] += e.content
            else:
                seg: dict = {"insert": e.content}
                if attrs:
                    seg["attributes"] = attrs
                segs.append(seg)
        return segs

    def _styles_at_elem(self, elem: SeqElem) -> Dict[str, Any]:
        """Resolved style attributes covering `elem` (scan; fine for host
        path — bulk style resolution is a device kernel)."""
        active: Dict[str, List[Tuple[int, int, Any, int]]] = {}
        for e in self.seq.all_elems():
            if e is elem:
                break
            if isinstance(e.content, StyleAnchor) and not e.deleted:
                a: StyleAnchor = e.content
                if a.is_start:
                    active.setdefault(a.key, []).append((e.lamport, e.peer, a.value, e.counter))
                else:
                    lst = active.get(a.key)
                    if lst:
                        for i, ent in enumerate(lst):
                            if ent[1] == e.peer and ent[3] == e.counter - 1:
                                lst.pop(i)
                                break
        return _resolve_attrs(active)

    def _style_event_for_anchor(self, peer: int, counter: int) -> Optional[Diff]:
        """Attribute-retain delta for the range covered by the anchor pair
        whose start or end is (peer, counter)."""
        e = self.seq.by_id.get((peer, counter))
        if e is None or not isinstance(e.content, StyleAnchor):
            return None
        a: StyleAnchor = e.content
        if a.is_start:
            start_e = e
            end_e = self.seq.by_id.get((peer, counter + 1))
        else:
            end_e = e
            start_e = self.seq.by_id.get((peer, counter - 1))
        if start_e is None or end_e is None:
            return None  # pair incomplete (end arrives next op)
        s = self.seq.treap.visible_rank(start_e)
        t = self.seq.treap.visible_rank(end_e)
        if t <= s:
            return None
        return Delta().retain(s).retain(t - s, {a.key: a.value})

    def to_diff(self) -> Diff:
        d = Delta()
        for seg in self.get_richtext_value():
            d.insert(seg["insert"], seg.get("attributes"))
        return d

    # -- style-aware version diffs -------------------------------------
    def _attrs_stream_at(self, v):
        """(elem, attrs) for every char VISIBLE at version v — the
        shared walk with version-filtered liveness predicates."""

        from .seq_crdt import visible_at

        def live(e):
            return visible_at(e, v)

        return self._iter_char_attrs(live, live)

    def styled_delta_between(self, va, vb) -> Delta:
        """Exact element-identity delta INCLUDING attribute changes:
        chars kept in both versions whose resolved styles differ emit
        attribute retains ({key: new-or-None}); inserts carry their
        vb-side attributes."""
        a_attrs = {(e.peer, e.counter): attrs for e, attrs in self._attrs_stream_at(va)}
        b_attrs = {(e.peer, e.counter): attrs for e, attrs in self._attrs_stream_at(vb)}
        d = Delta()
        for e in self.seq.all_elems():
            if isinstance(e.content, StyleAnchor):
                continue
            key = (e.peer, e.counter)
            in_a = key in a_attrs
            in_b = key in b_attrs
            if in_a and in_b:
                aa = a_attrs[key]
                bb = b_attrs[key]
                if aa == bb:
                    d.retain(1)
                else:
                    change = {k: bb.get(k) for k in set(aa) | set(bb) if aa.get(k) != bb.get(k)}
                    d.retain(1, change)
            elif in_a:
                d.delete(1)
            elif in_b:
                d.insert(e.content, b_attrs[key] or None)
        return d.chop()


def _resolve_attrs(active: Dict[str, List[Tuple]]) -> Dict[str, Any]:
    """Per key: LWW winner among active pairs; None value = unstyled."""
    out: Dict[str, Any] = {}
    for k, lst in active.items():
        if not lst:
            continue
        win = max(lst, key=lambda t: (t[0], t[1]))
        if win[2] is not None:
            out[k] = win[2]
    return out
