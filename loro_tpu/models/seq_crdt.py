"""FugueSeq: the shared sequence CRDT for Text / List / MovableList.

Mirrors the role of the reference's Fugue tracker
(crates/loro-internal/src/container/richtext/tracker.rs +
tracker/crdt_rope.rs) but with a different, TPU-first formulation:

* Ops ship the Fugue **tree placement** `(parent, side)` decided at the
  source replica (see core/change.py).  Integration is then pure tree
  insertion with deterministic sibling order `(peer, counter)` — no
  origin-scan — so a batch of inserts integrates on device by sorting
  `(parent, side, peer, counter)` keys + list ranking
  (loro_tpu/ops/fugue_batch.py).  This host class is the sequential
  engine and the differential oracle for those kernels.

* Local placement rule (Fugue, Weidner & Kleppmann "The Art of the
  Fugue"): inserting after visible element `a`:
    - `a` has no right children  -> (a, Right)
    - else                       -> (succ(a), Left)
  where succ(a) is a's immediate tree-traversal successor (tombstones
  included).  succ(a) necessarily has no left children yet, so the new
  element lands exactly at the intended position; concurrent same-spot
  inserts become siblings ordered by id.

Order maintenance is an order-statistic treap (utils/treap.py), the
analog of the reference's generic-btree rope.
"""
from __future__ import annotations

import bisect
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.change import Side, StyleAnchor
from ..core.ids import ID, Counter, IdSpan, PeerID
from ..utils.treap import Treap, TreapNode

ROOT = None  # fugue-parent sentinel for root children


def visible_at(e: "SeqElem", v) -> bool:
    """Element visibility at version v: inserted (id in v) and not
    deleted by any delete-op in v.  THE visibility predicate — all
    version-filtered walks (diffs, styled diffs) must share it."""
    return v.includes(e.id) and not any(v.includes(x) for x in e.deleted_by)


class SeqElem(TreapNode):
    """One sequence element (char / list value / anchor / position)."""

    __slots__ = (
        "peer",
        "counter",
        "content",
        "deleted",
        "deleted_by",  # List[ID] of delete-op atoms (for version diffs)
        "fparent",  # Optional[SeqElem]; None = root child
        "fside",  # Side
        "l_children",  # List[SeqElem] sorted by (peer, counter)
        "r_children",
        "lamport",
    )

    def __init__(
        self,
        peer: PeerID,
        counter: Counter,
        content: Any,
        fparent: Optional["SeqElem"],
        fside: Side,
        lamport: int = 0,
    ):
        self.peer = peer
        self.counter = counter
        self.content = content
        self.deleted = False
        self.deleted_by: List[ID] = []
        self.fparent = fparent
        self.fside = fside
        self.l_children: List[SeqElem] = []
        self.r_children: List[SeqElem] = []
        self.lamport = lamport
        is_anchor = isinstance(content, StyleAnchor)
        self.init_treap(0 if is_anchor else 1)

    @property
    def id(self) -> ID:
        return ID(self.peer, self.counter)

    @property
    def sib_key(self) -> Tuple[PeerID, Counter]:
        return (self.peer, self.counter)

    @property
    def is_anchor(self) -> bool:
        return isinstance(self.content, StyleAnchor)

    def base_width(self) -> int:
        return 0 if self.is_anchor else 1

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{self.counter}@{self.peer} {self.content!r}{' DEL' if self.deleted else ''}>"


class FugueSeq:
    """The sequence CRDT.  All mutation goes through local_* (source
    replica) or integrate_* (both local apply and remote merge)."""

    def __init__(self) -> None:
        self.treap = Treap()
        self.by_id: Dict[Tuple[PeerID, Counter], SeqElem] = {}
        self.root_children: List[SeqElem] = []  # sorted by sib_key; side=Right
        # delete-op id -> elements it tombstoned (inverse of deleted_by;
        # lets version diffs find visibility flips by id range)
        self.deleter_index: Dict[Tuple[PeerID, Counter], List[SeqElem]] = {}

    # ------------------------------------------------------------------
    # tree navigation
    # ------------------------------------------------------------------
    @staticmethod
    def _subtree_last(x: SeqElem) -> SeqElem:
        while x.r_children:
            x = x.r_children[-1]
        return x

    @staticmethod
    def _subtree_first(x: SeqElem) -> SeqElem:
        while x.l_children:
            x = x.l_children[0]
        return x

    # ------------------------------------------------------------------
    # local placement (source replica)
    # ------------------------------------------------------------------
    def placement_for_visible_pos(self, k: int) -> Tuple[Optional[ID], Side]:
        """Compute the Fugue (parent, side) for a local insert at visible
        position k (0..visible_len)."""
        if k == 0:
            f = self.treap.first()
            if f is None:
                return None, Side.Right
            return f.id, Side.Left  # type: ignore[union-attr]
        a = self.treap.find_visible(k - 1)
        assert a is not None, f"insert pos {k} out of range"
        return self._placement_after(a)

    def _placement_after(self, a: SeqElem) -> Tuple[Optional[ID], Side]:
        if not a.r_children:
            return a.id, Side.Right
        succ = Treap.successor(a)
        assert succ is not None and not succ.l_children  # immediate successor
        return succ.id, Side.Left  # type: ignore[union-attr]

    def placement_after_elem(self, elem_id: ID) -> Tuple[Optional[ID], Side]:
        """Placement immediately after a known element (used by
        MovableList move and style-anchor insertion)."""
        return self._placement_after(self.by_id[(elem_id.peer, elem_id.counter)])

    # ------------------------------------------------------------------
    # integration (local + remote)
    # ------------------------------------------------------------------
    def integrate_insert(
        self,
        peer: PeerID,
        counter: Counter,
        parent: Optional[ID],
        side: Side,
        contents: Sequence[Any],
        lamport: int = 0,
        compute_pos: bool = True,
    ) -> Tuple[int, List[SeqElem]]:
        """Insert a run of elements with ids (peer, counter+j).  Element 0
        is placed per (parent, side); element j>0 chains as Right child of
        element j-1 (RLE right-spine, like the reference's FugueSpan runs).
        Returns (visible position of first element — -1 when
        compute_pos=False — and the created elems)."""
        first = SeqElem(peer, counter, contents[0], None, side, lamport)
        self._place(first, parent, side)
        elems = [first]
        prev = first
        for j in range(1, len(contents)):
            e = SeqElem(peer, counter + j, contents[j], prev, Side.Right, lamport + j)
            # prev was just created: appending keeps (peer,counter) order
            prev.r_children.append(e)
            self.treap.insert_after(prev, e)
            self.by_id[(peer, counter + j)] = e
            elems.append(e)
            prev = e
        pos = self.treap.visible_rank(first) if compute_pos else -1
        return pos, elems

    def _place(self, n: SeqElem, parent: Optional[ID], side: Side) -> None:
        """Fugue tree insertion with sibling order (peer, counter)."""
        if parent is None:
            sibs = self.root_children
            parent_elem = None
        else:
            parent_elem = self.by_id[(parent.peer, parent.counter)]
            sibs = parent_elem.r_children if side == Side.Right else parent_elem.l_children
        n.fparent = parent_elem
        n.fside = side
        i = bisect.bisect_left(sibs, n.sib_key, key=lambda e: e.sib_key)
        sibs.insert(i, n)
        if side == Side.Right:
            if i == 0:
                pred = parent_elem  # may be None (root): insert at beginning
                if parent is None:
                    # root children: first sibling -> very beginning unless
                    # there are smaller siblings (i==0 means none)
                    pred = None
            else:
                pred = self._subtree_last(sibs[i - 1])
            self.treap.insert_after(pred, n)
        else:
            if i > 0:
                pred = self._subtree_last(sibs[i - 1])
                self.treap.insert_after(pred, n)
            else:
                # new leftmost of parent's subtree: before old subtree-first
                assert parent_elem is not None
                old_first = parent_elem
                # subtree-first along remaining l_children (excluding n)
                cur = parent_elem
                while True:
                    lc = [c for c in cur.l_children if c is not n]
                    if not lc:
                        break
                    cur = lc[0]
                old_first = cur
                pred = Treap.predecessor(old_first)
                self.treap.insert_after(pred, n)
        self.by_id[(n.peer, n.counter)] = n

    def integrate_delete(
        self, spans: Iterable[IdSpan], deleter: Optional[ID] = None, compute_pos: bool = True
    ) -> List[Tuple[int, int]]:
        """Tombstone elements by id.  Returns visible (pos, len) ranges
        that disappeared (merged, descending-safe order of single units;
        empty when compute_pos=False).  `deleter` (the delete op's id)
        is recorded per element so version diffs can evaluate visibility
        at any vv."""
        removed: List[Tuple[int, int]] = []
        for span in spans:
            for c in range(span.start, span.end):
                e = self.by_id.get((span.peer, c))
                if e is None:
                    continue
                if deleter is not None:
                    e.deleted_by.append(deleter)
                    self.deleter_index.setdefault(
                        (deleter.peer, deleter.counter), []
                    ).append(e)
                if e.deleted:
                    continue
                if compute_pos:
                    pos = self.treap.visible_rank(e)
                    if e.vis_w:
                        removed.append((pos, 1))
                e.deleted = True
                self.treap.set_visible(e, 0)
        return _merge_removed(removed)

    def delta_between(self, va, vb, as_text: bool, vc=None):
        """Exact delta turning the visible sequence at version `va` into
        the one at `vb` (both must be within this seq's history).
        Element visibility at V: inserted (id in V) and not deleted by
        any delete-op in V.

        When `vc` — the version this structure's treap CURRENTLY
        reflects — is given, the scan is O(delta): only elements whose
        visibility can differ among {va, vb, vc} (derived from the
        per-peer counter ranges of the symmetric differences va^vc and
        vb^vc, resolved through by_id / deleter_index) are evaluated;
        every other element has vis_va == vis_vb == its live treap
        width, so the retain gaps between affected elements come from
        visible-rank arithmetic instead of a full walk.  Reference
        extracts diffs by walking only changed subtrees
        (crates/loro-internal/src/container/richtext/tracker/
        crdt_rope.rs:383-451); this is the rank-query analog.
        """
        from ..event import Delta

        d = Delta()
        if vc is None:
            for e in self.all_elems():
                if e.is_anchor:
                    continue
                in_a = visible_at(e, va)
                in_b = visible_at(e, vb)
                if in_a and in_b:
                    d.retain(1)
                elif in_a:
                    d.delete(1)
                elif in_b:
                    d.insert(e.content if as_text else (e.content,))
            return d.chop()

        cand: Dict[int, SeqElem] = {}
        for hi, lo in ((va, vc), (vc, va), (vb, vc), (vc, vb)):
            for span in hi.diff_spans(lo):
                for c in range(span.start, span.end):
                    e = self.by_id.get((span.peer, c))
                    if e is not None and not e.is_anchor:
                        cand[id(e)] = e
                    hit = self.deleter_index.get((span.peer, c))
                    if hit:
                        for e2 in hit:
                            if not e2.is_anchor:
                                cand[id(e2)] = e2
        elems = sorted(cand.values(), key=self.treap.total_rank)
        pending = 0  # retains accumulated since the last emitted op
        prev = 0  # live-visible rank consumed so far
        for e in elems:
            r = self.treap.visible_rank(e)
            pending += r - prev
            prev = r + e.vis_w  # skip e's own live width; handled below
            in_a = visible_at(e, va)
            in_b = visible_at(e, vb)
            if in_a and in_b:
                pending += 1
            elif in_a:
                if pending:
                    d.retain(pending)
                    pending = 0
                d.delete(1)
            elif in_b:
                if pending:
                    d.retain(pending)
                    pending = 0
                d.insert(e.content if as_text else (e.content,))
        return d.chop()

    def set_visible(self, elem: SeqElem, vis_w: int) -> None:
        """Directly control an element's visible width (MovableList uses
        this for slot-winner bookkeeping)."""
        self.treap.set_visible(elem, vis_w)

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    @property
    def visible_len(self) -> int:
        return self.treap.visible_len

    @property
    def total_len(self) -> int:
        return self.treap.total_len

    def visible_elems(self) -> Iterable[SeqElem]:
        for e in self.treap:
            if e.vis_w:
                yield e

    def all_elems(self) -> Iterable[SeqElem]:
        return iter(self.treap)

    def elem_at(self, k: int) -> Optional[SeqElem]:
        n = self.treap.find_visible(k)
        return n  # type: ignore[return-value]

    def id_range_of_visible(self, k: int, length: int) -> List[IdSpan]:
        """Ids of the visible elements in [k, k+length) as RLE spans —
        the payload of a SeqDelete op."""
        spans: List[IdSpan] = []
        e = self.treap.find_visible(k)
        n = 0
        while e is not None and n < length:
            if e.vis_w:
                if spans and spans[-1].peer == e.peer and spans[-1].end == e.counter:
                    spans[-1] = IdSpan(e.peer, spans[-1].start, e.counter + 1)
                else:
                    spans.append(IdSpan(e.peer, e.counter, e.counter + 1))
                n += 1
            e = Treap.successor(e)  # type: ignore[assignment]
        return spans

    def check_invariants(self) -> None:
        """Slow structural self-check (fuzzer oracle; reference:
        check_state_correctness_slow).  Raises AssertionError on any
        violated invariant."""
        n_total = 0
        n_vis = 0
        for e in self.all_elems():
            n_total += 1
            if e.vis_w:
                n_vis += 1
            assert self.by_id.get((e.peer, e.counter)) is e, "by_id out of sync"
            for side_list, side in ((e.l_children, Side.Left), (e.r_children, Side.Right)):
                keys = [c.sib_key for c in side_list]
                assert keys == sorted(keys), "children unsorted"
                for c in side_list:
                    assert c.fparent is e and c.fside == side, "child link broken"
            if e.deleted or e.is_anchor:
                assert e.vis_w == 0, "tombstone/anchor with visible width"
        assert n_total == self.treap.total_len, "treap count out of sync"
        assert n_vis == self.treap.visible_len, "treap visible count out of sync"
        rk = [c.sib_key for c in self.root_children]
        assert rk == sorted(rk), "root children unsorted"
        # rank/select agreement on a few positions
        for k in range(0, n_vis, max(1, n_vis // 7)):
            e = self.treap.find_visible(k)
            assert e is not None and self.treap.visible_rank(e) == k, "rank/select mismatch"

    def visible_index_of(self, elem_id: ID) -> Optional[int]:
        e = self.by_id.get((elem_id.peer, elem_id.counter))
        if e is None or not e.vis_w:
            return None
        return self.treap.visible_rank(e)


def _merge_removed(removed: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Merge unit removals into ranges.  Successive deletions at the same
    visible position (forward sweep) collapse into one range."""
    out: List[Tuple[int, int]] = []
    for pos, ln in removed:
        if out and out[-1][0] == pos:
            out[-1] = (pos, out[-1][1] + ln)
        else:
            out.append((pos, ln))
    return out
