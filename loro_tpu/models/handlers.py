"""Typed container handlers — the user-facing mutation API.

reference: crates/loro-internal/src/handler.rs (TextHandler, MapHandler,
ListHandler, MovableListHandler, TreeHandler + loro/src counter.rs) and
handler/text_update.rs (diff-based `update`).
"""
from __future__ import annotations

import difflib
from typing import Any, List, Optional, TYPE_CHECKING

from ..core.change import (
    CounterIncr,
    MapSet,
    MovableMove,
    MovableSet,
    SeqDelete,
    SeqInsert,
    Side,
    StyleAnchor,
    TreeMove,
)
from ..core.ids import ContainerID, ContainerType, ID, TreeID
from ..errors import LoroError
from ..utils.fractional_index import key_between
from ..core.value import validate_value

if TYPE_CHECKING:  # pragma: no cover
    from ..doc import LoroDoc


class Handler:
    CT: ContainerType

    def __init__(self, doc: "LoroDoc", cid: ContainerID):
        assert cid.ctype == self.CT, f"{cid} is not a {self.CT.name}"
        self.doc = doc
        self.cid = cid

    @property
    def id(self) -> ContainerID:
        return self.cid

    @property
    def _state(self):
        return self.doc.state.get_or_create(self.cid)

    def _apply(self, content) -> int:
        return self.doc._txn_apply(self.cid, content)

    def get_value(self):
        return self._state.get_value()

    def get_deep_value(self):
        return self.doc.state._deep(self._state)

    def is_attached(self) -> bool:
        return True

    def get_type(self) -> ContainerType:
        """reference: Handler::get_type / ContainerTrait."""
        return self.cid.ctype

    def is_deleted(self) -> bool:
        """True when this container is no longer reachable from a root
        (its parent entry was overwritten/deleted, its list slot removed,
        or its tree node trashed); reference: ContainerTrait::is_deleted."""
        return not self.doc.state.is_alive(self.cid)

    def get_cursor(self, pos: int, side=None):
        """Stable cursor at pos (reference: Handler::get_cursor)."""
        from ..cursor import get_cursor as _get_cursor

        if side is None:
            return _get_cursor(self.doc, self, pos)
        return _get_cursor(self.doc, self, pos, side)

    def _child_handler(self, cid: ContainerID) -> "Handler":
        return make_handler(self.doc, cid)

    def subscribe(self, cb):
        return self.doc.subscribe(self.cid, cb)


class TextHandler(Handler):
    CT = ContainerType.Text

    # -- reads --------------------------------------------------------
    def to_string(self) -> str:
        return self._state.get_value()

    def get_richtext_value(self) -> List[dict]:
        return self._state.get_richtext_value()

    def __len__(self) -> int:
        return len(self._state)

    @property
    def length(self) -> int:
        return len(self._state)

    def char_at(self, pos: int) -> str:
        e = self._state.seq.elem_at(pos)
        if e is None:
            raise IndexError(pos)
        return e.content

    def slice(self, start: int, end: int) -> str:
        return self.to_string()[start:end]

    # -- utf16 index space (JS interop; reference tracks unicode/utf16/
    # utf8/entity lengths per rope node) ------------------------------
    @staticmethod
    def _w16(ch: str) -> int:
        return 1 + (ord(ch) > 0xFFFF)

    @staticmethod
    def _w8(ch: str) -> int:
        return len(ch.encode())

    def _width_len(self, width) -> int:
        return sum(width(e.content) for e in self._state.seq.visible_elems())

    def _offset_to_unicode(self, off: int, width, space: str) -> int:
        """Convert a unit offset in a variable-width index space to a
        codepoint position.  Offsets landing inside a unit (surrogate
        pair / multi-byte codepoint) are rejected — the reference errors
        on non-boundary indices rather than silently snapping (a JS
        peer's bug must not become data loss)."""
        acc = 0
        for i, e in enumerate(self._state.seq.visible_elems()):
            if acc == off:
                return i
            if acc > off:
                raise IndexError(f"{space} pos {off} is inside a unit boundary")
            acc += width(e.content)
        if acc < off:
            raise IndexError(f"{space} pos {off} > len {acc}")
        if acc > off:
            raise IndexError(f"{space} pos {off} is inside a unit boundary")
        return len(self._state)

    def len_utf16(self) -> int:
        return self._width_len(self._w16)

    def utf16_to_unicode(self, u16: int) -> int:
        return self._offset_to_unicode(u16, self._w16, "utf16")

    def unicode_to_utf16(self, pos: int) -> int:
        acc = 0
        for i, e in enumerate(self._state.seq.visible_elems()):
            if i >= pos:
                return acc
            acc += self._w16(e.content)
        if pos > len(self._state):
            raise IndexError(pos)
        return acc

    def insert_utf16(self, u16_pos: int, s: str) -> None:
        self.insert(self.utf16_to_unicode(u16_pos), s)

    def delete_utf16(self, u16_pos: int, u16_len: int) -> None:
        start = self.utf16_to_unicode(u16_pos)
        end = self.utf16_to_unicode(u16_pos + u16_len)
        self.delete(start, end - start)

    # -- writes -------------------------------------------------------
    def insert(self, pos: int, s: str) -> None:
        if not s:
            return
        st = self._state
        if pos > len(st):
            raise IndexError(f"insert pos {pos} > len {len(st)}")
        if st.n_anchors:
            parent, side = self._placement_with_expand(pos)
        else:
            parent, side = st.seq.placement_for_visible_pos(pos)
        self._apply(SeqInsert(parent, side, s))

    def _placement_with_expand(self, pos: int):
        """Anchor-aware placement: text typed at a mark boundary inherits
        the style iff the style's expand behavior says so (reference:
        ExpandType — "after" (default) grows past the end anchor,
        "none"/"before" does not; "before"/"both" grow before the start
        anchor).  Implemented by choosing which boundary anchors the new
        text lands after."""
        from ..utils.treap import Treap

        st = self._state
        styles = self.doc.config.text_style_config
        if pos == 0:
            a = None
            cur = st.seq.treap.first()
        else:
            a = st.seq.elem_at(pos - 1)
            assert a is not None
            cur = Treap.successor(a)
        # walk the invisible window (tombstoned chars + anchors) after the
        # left neighbor: tombstones are style-neutral and stepped over so
        # anchors beyond them still govern placement (deleting a char at
        # a mark boundary must not change expand behavior)
        while cur is not None and cur.vis_w == 0:
            if getattr(cur, "is_anchor", False) and not cur.deleted:
                anch: StyleAnchor = cur.content
                exp = styles.get(anch.key, self.doc.config.default_text_style)
                if anch.is_start:
                    # range starts here: typing before it inherits only
                    # for expand "before"/"both" -> step inside
                    advance = exp in ("before", "both")
                else:
                    # range ends here: typing after inherits for
                    # "after"/"both" -> stay inside (before the anchor)
                    advance = exp in ("none", "before")
                if not advance:
                    break
            a = cur
            cur = Treap.successor(cur)
        if a is None:
            f = st.seq.treap.first()
            if f is None:
                return None, Side.Right
            return f.id, Side.Left
        return st.seq._placement_after(a)

    def delete(self, pos: int, length: int) -> None:
        if length <= 0:
            return
        if pos + length > len(self._state):
            raise IndexError(f"delete [{pos},{pos+length}) > len {len(self._state)}")
        spans = self._state.seq.id_range_of_visible(pos, length)
        self._apply(SeqDelete(tuple(spans)))

    def push(self, s: str) -> None:
        self.insert(len(self._state), s)

    def mark(self, start: int, end: int, key: str, value: Any) -> None:
        """Style [start, end) with key=value.  Emits a start anchor at
        `start` and an end anchor after `end-1` as two consecutive ops
        (ids (p,c) and (p,c+1) — the pairing invariant TextState relies
        on)."""
        if end <= start:
            return
        st = self._state
        if end > len(st):
            raise IndexError(f"mark [{start},{end}) > len {len(st)}")
        parent, side = st.seq.placement_for_visible_pos(start)
        c1 = self._apply(SeqInsert(parent, side, StyleAnchor(key, value, True)))
        last_char = st.seq.elem_at(end - 1)
        assert last_char is not None
        parent2, side2 = st.seq.placement_after_elem(last_char.id)
        self._apply(SeqInsert(parent2, side2, StyleAnchor(key, value, False)))

    def unmark(self, start: int, end: int, key: str) -> None:
        self.mark(start, end, key, None)

    def splice(self, pos: int, length: int, replacement: str = "") -> str:
        """Delete [pos, pos+length) and insert `replacement` there;
        returns the removed text (reference: Text::splice)."""
        removed = self.to_string()[pos : pos + length]
        if length:
            self.delete(pos, length)
        if replacement:
            self.insert(pos, replacement)
        return removed

    def is_empty(self) -> bool:
        return len(self._state) == 0

    def update(self, new_text: str) -> None:
        """Minimal-diff update (reference: handler/text_update.rs Myers)."""
        old = self.to_string()
        if old == new_text:
            return
        sm = difflib.SequenceMatcher(a=old, b=new_text, autojunk=False)
        # apply right-to-left so positions stay valid
        ops = [op for op in sm.get_opcodes() if op[0] != "equal"]
        for tag, i1, i2, j1, j2 in reversed(ops):
            if tag in ("replace", "delete"):
                self.delete(i1, i2 - i1)
            if tag in ("replace", "insert"):
                self.insert(i1, new_text[j1:j2])

    def update_by_line(self, new_text: str) -> None:
        """Line-granular minimal-diff update (reference:
        Text::update_by_line) — cheaper than char-level Myers on large
        texts and keeps whole-line edits as single splices."""
        old_lines = self.to_string().splitlines(keepends=True)
        new_lines = new_text.splitlines(keepends=True)
        if old_lines == new_lines:
            return
        starts = [0]
        for ln in old_lines:
            starts.append(starts[-1] + len(ln))
        sm = difflib.SequenceMatcher(a=old_lines, b=new_lines, autojunk=False)
        ops = [op for op in sm.get_opcodes() if op[0] != "equal"]
        for tag, i1, i2, j1, j2 in reversed(ops):
            if tag in ("replace", "delete"):
                self.delete(starts[i1], starts[i2] - starts[i1])
            if tag in ("replace", "insert"):
                self.insert(starts[i1], "".join(new_lines[j1:j2]))

    # -- quill-style deltas (reference: Text::to_delta / apply_delta /
    # slice_delta) ----------------------------------------------------
    def to_delta(self) -> List[dict]:
        """Styled segments as quill-style ops: [{"insert": str,
        "attributes": {...}?}, ...]."""
        out = []
        for seg in self.get_richtext_value():
            item = {"insert": seg["insert"]}
            if seg.get("attributes"):
                item["attributes"] = dict(seg["attributes"])
            out.append(item)
        return out

    def slice_delta(self, start: int, end: int) -> List[dict]:
        """to_delta() restricted to the unicode range [start, end)."""
        out: List[dict] = []
        pos = 0
        for seg in self.to_delta():
            s = seg["insert"]
            seg_start, seg_end = pos, pos + len(s)
            pos = seg_end
            lo, hi = max(seg_start, start), min(seg_end, end)
            if lo >= hi:
                continue
            item = {"insert": s[lo - seg_start : hi - seg_start]}
            if seg.get("attributes"):
                item["attributes"] = dict(seg["attributes"])
            out.append(item)
        return out

    def apply_delta(self, items: List[dict]) -> None:
        """Apply a quill-style delta: [{"retain": n, "attributes"?},
        {"insert": s, "attributes"?}, {"delete": n}] (reference:
        Text::apply_delta)."""
        pos = 0
        for it in items:
            if "retain" in it:
                n = it["retain"]
                attrs = it.get("attributes") or {}
                for k, v in attrs.items():
                    if v is None:
                        self.unmark(pos, pos + n, k)
                    else:
                        self.mark(pos, pos + n, k, v)
                pos += n
            elif "insert" in it:
                s = it["insert"]
                self.insert(pos, s)
                # the delta's attributes are authoritative for inserted
                # text: neutralize styles inherited from surrounding
                # anchor pairs too (same contract as doc.apply_diff)
                st = self._state
                elem = st.seq.elem_at(pos)
                inherited = (
                    st._styles_at_elem(elem)
                    if (st.n_anchors and elem is not None)
                    else {}
                )
                target = {
                    k: v for k, v in (it.get("attributes") or {}).items() if v is not None
                }
                for k in set(inherited) | set(target):
                    tv = target.get(k)
                    if tv is None:
                        self.unmark(pos, pos + len(s), k)
                    elif inherited.get(k) != tv:
                        self.mark(pos, pos + len(s), k, tv)
                pos += len(s)
            elif "delete" in it:
                self.delete(pos, it["delete"])

    # -- utf8 index space (reference tracks unicode/utf16/utf8 lengths
    # per rope node) ---------------------------------------------------
    def len_utf8(self) -> int:
        return self._width_len(self._w8)

    def utf8_to_unicode(self, b: int) -> int:
        return self._offset_to_unicode(b, self._w8, "utf8")

    def insert_utf8(self, b_pos: int, s: str) -> None:
        self.insert(self.utf8_to_unicode(b_pos), s)

    def delete_utf8(self, b_pos: int, b_len: int) -> None:
        start = self.utf8_to_unicode(b_pos)
        end = self.utf8_to_unicode(b_pos + b_len)
        self.delete(start, end - start)

    def mark_utf8(self, b_start: int, b_end: int, key: str, value: Any) -> None:
        self.mark(self.utf8_to_unicode(b_start), self.utf8_to_unicode(b_end), key, value)

    # -- utf16 mark/slice/splice (JS interop) -------------------------
    def mark_utf16(self, u_start: int, u_end: int, key: str, value: Any) -> None:
        self.mark(self.utf16_to_unicode(u_start), self.utf16_to_unicode(u_end), key, value)

    def unmark_utf16(self, u_start: int, u_end: int, key: str) -> None:
        self.mark_utf16(u_start, u_end, key, None)

    def slice_utf16(self, u_start: int, u_end: int) -> str:
        return self.slice(self.utf16_to_unicode(u_start), self.utf16_to_unicode(u_end))

    def splice_utf16(self, u_pos: int, u_len: int, replacement: str = "") -> str:
        start = self.utf16_to_unicode(u_pos)
        end = self.utf16_to_unicode(u_pos + u_len)
        return self.splice(start, end - start, replacement)

    def get_id_at(self, pos: int) -> Optional[ID]:
        """Op id of the character at unicode position `pos` (reference:
        Text::get_id_at / get_editor_at_unicode_pos)."""
        e = self._state.seq.elem_at(pos)
        return e.id if e is not None else None

    def get_editor_at_unicode_pos(self, pos: int) -> Optional[int]:
        e = self._state.seq.elem_at(pos)
        return e.peer if e is not None else None

    @property
    def len_unicode(self) -> int:
        """reference: LoroText::len_unicode."""
        return len(self)

    def push_str(self, s: str) -> None:
        """reference: LoroText::push_str."""
        self.push(s)

    def convert_pos(self, index: int, from_type: str, to_type: str) -> Optional[int]:
        """Convert a position between coordinate systems ("unicode",
        "utf16", "bytes", "event"); None when out of bounds (reference:
        LoroText::convert_pos / cursor::PosType — Event == Unicode
        without the wasm feature)."""

        def norm(t: str) -> str:
            t = t.lower()
            if t == "event":
                return "unicode"
            if t not in ("unicode", "utf16", "bytes"):
                raise LoroError(f"unsupported position type {t!r}")
            return t

        from_type, to_type = norm(from_type), norm(to_type)
        if index < 0:
            return None
        try:
            if from_type == "unicode":
                uni = index
            elif from_type == "utf16":
                uni = self.utf16_to_unicode(index)
            else:
                uni = self.utf8_to_unicode(index)
            if uni > len(self):
                return None
            if to_type == "unicode":
                return uni
            if to_type == "utf16":
                return self.unicode_to_utf16(uni)
            return len(self.slice(0, uni).encode("utf-8"))
        except (IndexError, ValueError):
            return None


class ListHandler(Handler):
    CT = ContainerType.List

    def __len__(self) -> int:
        return len(self._state)

    @property
    def length(self) -> int:
        return len(self._state)

    def get(self, index: int):
        v = self._state.get(index)
        if isinstance(v, ContainerID):
            return self._child_handler(v)
        return v

    def insert(self, pos: int, *values: Any) -> None:
        if not values:
            return
        if pos > len(self._state):
            raise IndexError(f"insert pos {pos} > len {len(self._state)}")
        for v in values:
            validate_value(v)
        parent, side = self._state.seq.placement_for_visible_pos(pos)
        self._apply(SeqInsert(parent, side, tuple(values)))

    def push(self, *values: Any) -> None:
        self.insert(len(self._state), *values)

    def delete(self, pos: int, length: int) -> None:
        if length <= 0:
            return
        if pos + length > len(self._state):
            raise IndexError(f"delete [{pos},{pos+length}) > len {len(self._state)}")
        spans = self._state.seq.id_range_of_visible(pos, length)
        self._apply(SeqDelete(tuple(spans)))

    def insert_container(self, pos: int, ctype: ContainerType) -> Handler:
        parent, side = self._state.seq.placement_for_visible_pos(pos)
        # op counter == element id == child container id
        marker = _ChildMarker(ctype)
        counter = self._apply(SeqInsert(parent, side, (marker,)))
        cid = marker.cid
        assert cid is not None
        return self._child_handler(cid)

    def push_container(self, ctype: ContainerType) -> Handler:
        return self.insert_container(len(self._state), ctype)

    def pop(self):
        """Remove and return the last value (reference: List::pop)."""
        n = len(self._state)
        if n == 0:
            return None
        v = self._state.get(n - 1)
        self.delete(n - 1, 1)
        return v

    def clear(self) -> None:
        if len(self._state):
            self.delete(0, len(self._state))

    def is_empty(self) -> bool:
        return len(self._state) == 0

    def to_vec(self) -> List[Any]:
        return self.get_value()

    def get_id_at(self, pos: int) -> Optional[ID]:
        """Op id of the element at `pos` (reference: LoroList::get_id_at)."""
        e = self._state.seq.elem_at(pos)
        return e.id if e is not None else None

    def get_creator_at(self, pos: int) -> Optional[int]:
        """Peer that inserted the element at `pos` (reference:
        LoroList::get_creator_at semantics via the op id)."""
        e = self._state.seq.elem_at(pos)
        return e.peer if e is not None else None

    def __iter__(self):
        for i in range(len(self)):
            yield self.get(i)


class _ChildMarker:
    """Placeholder replaced by the real child ContainerID at txn apply
    time (the id needs the op counter, which only the txn knows)."""

    __slots__ = ("ctype", "cid")

    def __init__(self, ctype: ContainerType):
        self.ctype = ctype
        self.cid: Optional[ContainerID] = None


class MapHandler(Handler):
    CT = ContainerType.Map

    def get(self, key: str):
        entry = self._state.get_entry(key)
        if entry is None:
            return None
        if isinstance(entry.value, ContainerID):
            return self._child_handler(entry.value)
        return entry.value

    def set(self, key: str, value: Any) -> None:
        validate_value(value)
        self._apply(MapSet(key, value))

    def delete(self, key: str) -> None:
        self._apply(MapSet(key, None, deleted=True))

    def keys(self) -> List[str]:
        return sorted(self._state.get_value().keys())

    def values(self) -> List[Any]:
        v = self._state.get_value()
        return [v[k] for k in sorted(v)]

    def __len__(self) -> int:
        return len(self._state.get_value())

    def __contains__(self, key: str) -> bool:
        return self._state.get_entry(key) is not None

    def set_container(self, key: str, ctype: ContainerType) -> Handler:
        marker = _ChildMarker(ctype)
        self._apply(MapSet(key, marker))
        assert marker.cid is not None
        return self._child_handler(marker.cid)

    # -- mergeable child containers (reference: ensure_mergeable_*,
    # state/mergeable.rs) ---------------------------------------------
    def _ensure_mergeable(self, key: str, ctype: ContainerType) -> Handler:
        """Child container with a DETERMINISTIC id derived from
        (this map, key, type): concurrent first creation on different
        replicas yields the same container, so their edits merge
        instead of forking (unlike set_container, whose op-id child
        forks under concurrency).  Raises LoroError if the key already
        holds a non-mergeable value (the existing value is kept)."""
        from ..core.ids import mergeable_root_name

        cid = ContainerID.root(mergeable_root_name(self.cid, key, ctype), ctype)
        cur = self._state.entries.get(key)
        if cur is not None and not cur.deleted:
            if cur.value == cid:
                return self._child_handler(cid)
            from ..errors import LoroError

            raise LoroError(
                f"map key {key!r} already holds a non-mergeable value"
            )
        self._apply(MapSet(key, cid))
        return self._child_handler(cid)

    def ensure_mergeable_text(self, key: str):
        return self._ensure_mergeable(key, ContainerType.Text)

    def ensure_mergeable_map(self, key: str):
        return self._ensure_mergeable(key, ContainerType.Map)

    def ensure_mergeable_list(self, key: str):
        return self._ensure_mergeable(key, ContainerType.List)

    def ensure_mergeable_movable_list(self, key: str):
        return self._ensure_mergeable(key, ContainerType.MovableList)

    def ensure_mergeable_tree(self, key: str):
        return self._ensure_mergeable(key, ContainerType.Tree)

    def ensure_mergeable_counter(self, key: str):
        return self._ensure_mergeable(key, ContainerType.Counter)

    def clear(self) -> None:
        for k in self.keys():
            self.delete(k)

    def is_empty(self) -> bool:
        return len(self._state.get_value()) == 0

    def get_last_editor(self, key: str) -> Optional[int]:
        """Peer of the winning (LWW) write to `key`, including deletes;
        None for never-written keys (reference: LoroMap::get_last_editor)."""
        e = self._state.entries.get(key)
        return e.peer if e is not None else None

    def keys_iter(self):
        return iter(self.keys())

    def __iter__(self):
        return iter(self.keys())

    def get_or_create_container(self, key: str, ctype: ContainerType) -> Handler:
        """Existing child or a fresh one (reference: get_or_create)."""
        entry = self._state.get_entry(key)
        if entry is not None and isinstance(entry.value, ContainerID):
            if entry.value.ctype == ctype:
                return self._child_handler(entry.value)
        return self.set_container(key, ctype)


class MovableListHandler(Handler):
    CT = ContainerType.MovableList

    def __len__(self) -> int:
        return len(self._state)

    @property
    def length(self) -> int:
        return len(self._state)

    def get(self, index: int):
        v = self._state.get(index)
        if isinstance(v, ContainerID):
            return self._child_handler(v)
        return v

    def insert(self, pos: int, *values: Any) -> None:
        if not values:
            return
        if pos > len(self._state):
            raise IndexError(f"insert pos {pos} > len {len(self._state)}")
        for v in values:
            validate_value(v)
        parent, side = self._state.seq.placement_for_visible_pos(pos)
        self._apply(SeqInsert(parent, side, tuple(values)))

    def push(self, *values: Any) -> None:
        self.insert(len(self._state), *values)

    def delete(self, pos: int, length: int) -> None:
        if length <= 0:
            return
        if pos + length > len(self._state):
            raise IndexError(f"delete [{pos},{pos+length}) > len {len(self._state)}")
        st = self._state
        spans = []
        for i in range(pos, pos + length):
            sid = st.slot_id_at(i)
            assert sid is not None
            spans.append(sid)
        from ..core.ids import IdSpan

        rle = []
        for sid in spans:
            if rle and rle[-1].peer == sid.peer and rle[-1].end == sid.counter:
                rle[-1] = IdSpan(sid.peer, rle[-1].start, sid.counter + 1)
            else:
                rle.append(IdSpan(sid.peer, sid.counter, sid.counter + 1))
        self._apply(SeqDelete(tuple(rle)))

    def set(self, pos: int, value: Any) -> None:
        validate_value(value)
        eid = self._state.elem_id_at(pos)
        if eid is None:
            raise IndexError(pos)
        self._apply(MovableSet(eid, value))

    def move(self, from_pos: int, to_pos: int) -> None:
        """Move the element at from_pos so it ends up at to_pos
        (reference: MovableListHandler::mov)."""
        if from_pos == to_pos:
            return
        st = self._state
        eid = st.elem_id_at(from_pos)
        if eid is None:
            raise IndexError(from_pos)
        # placement computed against the list *without* the moved element:
        # target index in the post-move list maps to a boundary in the
        # current list skipping the source slot
        anchor = to_pos if to_pos < from_pos else to_pos + 1
        parent, side = st.seq.placement_for_visible_pos(anchor)
        self._apply(MovableMove(eid, parent, side))

    def to_vec(self) -> List[Any]:
        return self.get_value()

    def mov(self, from_pos: int, to_pos: int) -> None:
        self.move(from_pos, to_pos)

    def push_container(self, ctype: ContainerType) -> Handler:
        return self.insert_container(len(self._state), ctype)

    # -- element attribution (reference: MovableList::get_creator_at /
    # get_last_editor_at / get_last_mover_at) -------------------------
    def _entry_at(self, pos: int):
        slot = self._state.seq.elem_at(pos)
        if slot is None:
            return None, None
        eid = slot.content
        return eid, self._state.elems.get(eid)

    def get_creator_at(self, pos: int) -> Optional[int]:
        eid, entry = self._entry_at(pos)
        return eid.peer if eid is not None else None

    def get_last_editor_at(self, pos: int) -> Optional[int]:
        """Peer of the winning set op (or the creator when never set)."""
        eid, entry = self._entry_at(pos)
        if entry is None:
            return eid.peer if eid is not None else None
        return entry.value_key[1]

    def get_last_mover_at(self, pos: int) -> Optional[int]:
        """Peer of the winning position slot."""
        eid, entry = self._entry_at(pos)
        if entry is None:
            return None
        return entry.slot.peer

    def set_container(self, pos: int, ctype: ContainerType) -> Handler:
        eid = self._state.elem_id_at(pos)
        if eid is None:
            raise IndexError(pos)
        marker = _ChildMarker(ctype)
        self._apply(MovableSet(eid, marker))
        assert marker.cid is not None
        return self._child_handler(marker.cid)

    def insert_container(self, pos: int, ctype: ContainerType) -> Handler:
        parent, side = self._state.seq.placement_for_visible_pos(pos)
        marker = _ChildMarker(ctype)
        self._apply(SeqInsert(parent, side, (marker,)))
        assert marker.cid is not None
        return self._child_handler(marker.cid)

    def pop(self):
        n = len(self._state)
        if n == 0:
            return None
        v = self._state.get(n - 1)
        self.delete(n - 1, 1)
        return v

    def clear(self) -> None:
        if len(self._state):
            self.delete(0, len(self._state))

    def is_empty(self) -> bool:
        return len(self._state) == 0


class TreeHandler(Handler):
    CT = ContainerType.Tree

    def create(self, parent: Optional[TreeID] = None, index: Optional[int] = None) -> TreeID:
        pos = self._position_for(parent, index)
        marker = _TreeTargetMarker()
        counter = self._apply(TreeMove(marker, parent, pos, is_create=True))  # type: ignore[arg-type]
        return TreeID(self.doc.peer, counter)

    def move(self, target: TreeID, parent: Optional[TreeID], index: Optional[int] = None) -> None:
        if parent is not None and not self._state.contains(parent):
            raise ValueError(f"parent {parent} not in tree")
        pos = self._position_for(parent, index, moving=target)
        self._apply(TreeMove(target, parent, pos))

    def mov_to_root(self, target: TreeID) -> None:
        self.move(target, None)

    def delete(self, target: TreeID) -> None:
        self._apply(TreeMove(target, None, None, is_delete=True))

    def _position_for(
        self, parent: Optional[TreeID], index: Optional[int], moving: Optional[TreeID] = None
    ) -> Optional[bytes]:
        if not self.doc.config.fractional_index_enabled:
            return None
        key = self._position_key(parent, index, moving)
        jitter = self.doc.config.fractional_index_jitter
        if jitter:
            import random as _random

            key += bytes(_random.getrandbits(8) for _ in range(jitter))
        return key

    def _position_key(
        self, parent: Optional[TreeID], index: Optional[int], moving: Optional[TreeID] = None
    ) -> bytes:
        sibs = [t for t in self._state.children_of(parent) if t != moving]
        positions = [self._state.nodes[t].position for t in sibs]
        if index is None or index >= len(sibs):
            lo = positions[-1] if positions else None
            return key_between(lo, None)
        hi = positions[index]
        lo = positions[index - 1] if index > 0 else None
        if lo is not None and hi is not None and lo >= hi:
            # degenerate duplicate keys (concurrent same-position): nudge
            return key_between(lo, None)
        return key_between(lo, hi)

    # -- reads --------------------------------------------------------
    # reference aliases / sibling-relative moves ----------------------
    def create_at(self, parent: Optional[TreeID] = None, index: int = 0) -> TreeID:
        return self.create(parent, index)

    def mov(self, target: TreeID, parent: Optional[TreeID], index: Optional[int] = None) -> None:
        self.move(target, parent, index)

    def mov_to(self, target: TreeID, parent: Optional[TreeID], index: int) -> None:
        self.move(target, parent, index)

    def mov_after(self, target: TreeID, after: TreeID) -> None:
        """Place `target` as the next sibling after `after`."""
        p = self._state.parent_of(after)
        sibs = [t for t in self._state.children_of(p) if t != target]
        self.move(target, p, sibs.index(after) + 1)

    def mov_before(self, target: TreeID, before: TreeID) -> None:
        p = self._state.parent_of(before)
        sibs = [t for t in self._state.children_of(p) if t != target]
        self.move(target, p, sibs.index(before))

    def children_num(self, parent: Optional[TreeID] = None) -> int:
        return len(self._state.children_of(parent))

    def is_node_deleted(self, target: TreeID) -> bool:
        """True when the node exists but is trash-reachable (reference:
        Tree::is_node_deleted; unknown nodes raise)."""
        if target not in self._state.nodes:
            raise ValueError(f"unknown tree node {target}")
        return self._state._is_deleted(target)

    def enable_fractional_index(self, jitter: int = 0) -> None:
        """Generate fractional indexes on create/move (on by default;
        reference: Tree::enable_fractional_index).  With jitter > 0,
        keys get that many random suffix bytes so concurrent peers
        inserting into the same gap rarely collide."""
        self.doc.config.fractional_index_enabled = True
        self.doc.config.fractional_index_jitter = jitter

    def disable_fractional_index(self) -> None:
        """New moves ship no position: sibling order falls back to the
        move-key tiebreak (reference: Tree::disable_fractional_index)."""
        self.doc.config.fractional_index_enabled = False

    def is_fractional_index_enabled(self) -> bool:
        return self.doc.config.fractional_index_enabled

    def contains(self, target: TreeID) -> bool:
        return self._state.contains(target)

    def children(self, parent: Optional[TreeID] = None) -> List[TreeID]:
        return self._state.children_of(parent)

    def roots(self) -> List[TreeID]:
        return self._state.roots()

    def parent(self, target: TreeID) -> Optional[TreeID]:
        return self._state.parent_of(target)

    def get_meta(self, target: TreeID) -> MapHandler:
        if not self._state.contains(target) and target not in self._state.nodes:
            raise ValueError(f"{target} not in tree")
        return self._child_handler(self._state.meta_cid(target))  # type: ignore[return-value]

    def nodes(self) -> List[TreeID]:
        return [t for t in self._state.nodes if self._state.contains(t)]

    def fractional_index(self, target: TreeID) -> Optional[bytes]:
        n = self._state.nodes.get(target)
        return n.position if n else None

    def get_last_move_id(self, target: TreeID) -> Optional[ID]:
        """Op id of the effective (winning) move of `target`; None for
        unknown nodes (reference: LoroTree::get_last_move_id)."""
        n = self._state.nodes.get(target)
        if n is None:
            return None
        _lamport, peer, counter = n.move_key
        return ID(peer, counter)

    def get_nodes(self, with_deleted: bool = False) -> List[dict]:
        """Flat node records {id, parent, index, fractional_index}
        (reference: LoroTree::get_nodes; deleted nodes get parent=None,
        index=None)."""
        st = self._state
        out = []
        for t in st.nodes:
            alive = st.contains(t)
            if not alive and not with_deleted:
                continue
            out.append(
                {
                    "id": t,
                    "parent": st.parent_of(t) if alive else None,
                    "index": st.index_of(t) if alive else None,
                    "fractional_index": st.nodes[t].position,
                    "deleted": not alive,
                }
            )
        return out

    def get_value_with_meta(self) -> List[dict]:
        """Hierarchy values with each node's meta map resolved
        (reference: LoroTree::get_value_with_meta == deep value)."""
        return self.get_deep_value()


class _TreeTargetMarker:
    """Placeholder for a tree-create target (id = the op's own id)."""

    __slots__ = ()


class CounterHandler(Handler):
    CT = ContainerType.Counter

    def increment(self, delta: float = 1.0) -> None:
        self._apply(CounterIncr(float(delta)))

    def decrement(self, delta: float = 1.0) -> None:
        self._apply(CounterIncr(-float(delta)))

    @property
    def value(self) -> float:
        return self._state.get_value()


_HANDLER_BY_TYPE = {
    ContainerType.Text: TextHandler,
    ContainerType.List: ListHandler,
    ContainerType.Map: MapHandler,
    ContainerType.MovableList: MovableListHandler,
    ContainerType.Tree: TreeHandler,
    ContainerType.Counter: CounterHandler,
}


def make_handler(doc: "LoroDoc", cid: ContainerID) -> Handler:
    return _HANDLER_BY_TYPE[cid.ctype](doc, cid)
