"""List container state over FugueSeq.

reference: crates/loro-internal/src/state/list_state.rs (state) +
ListDiffCalculator (diff_calc.rs:620-867, merge).  Values are arbitrary
LoroValues; child containers appear as ContainerID values.
"""
from __future__ import annotations

from typing import Any, List, Optional

from ..core.change import Op, SeqDelete, SeqInsert
from ..core.ids import ContainerID, ID
from ..event import Delta, Diff
from .base import ContainerState
from .seq_crdt import FugueSeq


class ListState(ContainerState):
    def __init__(self, cid: ContainerID):
        super().__init__(cid)
        self.seq = FugueSeq()

    def apply_op(self, op: Op, peer: int, lamport: int, record: bool = True) -> Optional[Diff]:
        c = op.content
        if isinstance(c, SeqInsert):
            parent = _resolve_run_cont(c.parent, peer, op.counter)
            pos, _ = self.seq.integrate_insert(
                peer, op.counter, parent, c.side, list(c.content), lamport, compute_pos=record
            )
            if not record:
                return None
            return Delta().retain(pos).insert(tuple(c.content))
        assert isinstance(c, SeqDelete)
        removed = self.seq.integrate_delete(
            c.spans, deleter=ID(peer, op.counter), compute_pos=record
        )
        if not removed:
            return None
        # each removal's position is relative to the state after the
        # previous removals — compose folds them into one delta
        out = Delta()
        for pos, ln in removed:
            out = out.compose(Delta().retain(pos).delete(ln))
        return out

    def get_value(self) -> List[Any]:
        return [e.content for e in self.seq.visible_elems()]

    def __len__(self) -> int:
        return self.seq.visible_len

    def get(self, index: int) -> Any:
        e = self.seq.elem_at(index)
        return e.content if e is not None else None

    def elem_id_at(self, index: int) -> Optional[ID]:
        e = self.seq.elem_at(index)
        return e.id if e is not None else None

    def to_diff(self) -> Diff:
        v = tuple(self.get_value())
        d = Delta()
        if v:
            d.insert(v)
        return d


def _resolve_run_cont(parent, peer: int, counter: int):
    """Resolve the run-continuation sentinel left by change slicing: the
    implicit parent of a sliced run's first element is the previous
    element of the same peer (see oplog.oplog._slice_run)."""
    from ..oplog.oplog import _RunCont

    if isinstance(parent, _RunCont):
        return ID(peer, counter - 1)
    return parent
