"""Binary wire protocol for the network edge (docs/NET.md).

Every frame on the wire is::

    u32le body_len | u32le crc32(body) | body
    body = u8 msg_type | type-specific fields

— the codec-harden envelope pattern (persist/wal.py frames, codec
``strip_envelope``): the length prefix bounds the read, the crc32
rejects truncation and bit-flips BEFORE any field decoding, and a
declared length above the negotiated maximum is refused without
reading the body.  Violations raise typed ``errors.CodecDecodeError``
(damaged bytes) or ``errors.NetProtocolError`` (oversized frame,
unknown type, wrong HELLO magic/version) — never a silent skip, never
an untyped crash of the connection loop.

Field primitives: ``uvarint`` (LEB128, the codec/binary idiom),
length-prefixed UTF-8 strings, length-prefixed byte blobs, and
``VersionVector.encode()`` for frontiers.  The PUSH/DELTA payloads are
the existing columnar-updates bytes VERBATIM — the wire layer never
re-encodes CRDT data, so a pulled delta is byte-identical to the
in-process ``Session.pull`` (the differential gate in
tests/test_net_wire.py).

Message catalogue (client → server unless noted):

- ``HELLO``     magic ``LTNT`` + protocol version + family + client id
                + per-doc frontier VVs (the RESUME TOKEN: the server
                holds no session state across disconnects — a
                reconnect IS a pull-since-frontier)
- ``HELLO_OK``  (server) version + family + n_docs + committed epoch +
                session id + how many frontier docs resumed
- ``PUSH``      request id + doc + updates blob (verbatim)
- ``PUSH_ACK``  (server) request id + visible epoch + durable
                watermark + the server-side trace id
- ``PULL``      request id + doc + optional min_epoch (read-your-
                writes gate, docs/REPLICATION.md)
- ``DELTA``     (server) request id + doc + payload (byte-identical to
                ``Session.pull``) + the new client frontier + a
                first-sync flag
- ``POLL``      request id + timeout_ms (long-poll registration)
- ``EVENT``     (server) request id + dirty ``{doc: epoch}`` map +
                presence blobs (drop-oldest coalesced like ``poll()``)
- ``PRESENCE``  a client Awareness/EphemeralStore blob to broadcast
- ``ERROR``     (server) request id (0 = connection-level) + typed
                code + message + leader address (NOT_LEADER redirect)
- ``BYE``       graceful close (either side)
- ``STATUS``    request id — admin probe for the aggregated health
                verdict (docs/OBSERVABILITY.md "Health & heat")
- ``STATUS_OK`` (server) request id + JSON status payload blob (the
                same object ``/status.json`` serves, plus the server's
                own ``net`` section)
"""
from __future__ import annotations

import struct
import zlib
from typing import Dict, Optional, Tuple

from ..core.version import VersionVector
from ..errors import CodecDecodeError, NetProtocolError

PROTO_MAGIC = b"LTNT"
PROTO_VERSION = 1

HEADER_LEN = 8  # u32le body_len | u32le crc32

# message types (u8)
HELLO = 0x01
HELLO_OK = 0x02
PUSH = 0x03
PUSH_ACK = 0x04
PULL = 0x05
DELTA = 0x06
POLL = 0x07
EVENT = 0x08
PRESENCE = 0x09
ERROR = 0x0A
BYE = 0x0B
STATUS = 0x0C
STATUS_OK = 0x0D

TYPE_NAMES = {
    HELLO: "HELLO", HELLO_OK: "HELLO_OK", PUSH: "PUSH",
    PUSH_ACK: "PUSH_ACK", PULL: "PULL", DELTA: "DELTA", POLL: "POLL",
    EVENT: "EVENT", PRESENCE: "PRESENCE", ERROR: "ERROR", BYE: "BYE",
    STATUS: "STATUS", STATUS_OK: "STATUS_OK",
}

# typed error codes carried by ERROR frames; the client re-raises the
# matching loro_tpu.errors type (map_error / raise_error below)
E_BAD_FRAME = 1
E_BAD_VERSION = 2
E_PUSH_REJECTED = 3
E_STALE_FRONTIER = 4
E_NOT_LEADER = 5
E_REPLICA_LAG = 6
E_SESSION_CLOSED = 7
E_UNAVAILABLE = 8
E_INTERNAL = 9

CODE_NAMES = {
    E_BAD_FRAME: "BAD_FRAME", E_BAD_VERSION: "BAD_VERSION",
    E_PUSH_REJECTED: "PUSH_REJECTED", E_STALE_FRONTIER: "STALE_FRONTIER",
    E_NOT_LEADER: "NOT_LEADER", E_REPLICA_LAG: "REPLICA_LAG",
    E_SESSION_CLOSED: "SESSION_CLOSED", E_UNAVAILABLE: "UNAVAILABLE",
    E_INTERNAL: "INTERNAL",
}


# -- primitives --------------------------------------------------------
def _uvarint(out: bytearray, v: int) -> None:
    if v < 0:
        raise ValueError(f"uvarint cannot encode negative {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_uvarint(data: bytes, pos: list) -> int:
    shift = 0
    result = 0
    while True:
        if pos[0] >= len(data):
            raise CodecDecodeError("net frame truncated inside a varint")
        b = data[pos[0]]
        pos[0] += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result
        shift += 7
        if shift > 63:
            raise CodecDecodeError("net frame varint overruns 64 bits")


def _put_bytes(out: bytearray, b: bytes) -> None:
    _uvarint(out, len(b))
    out += b


def _read_bytes(data: bytes, pos: list) -> bytes:
    n = _read_uvarint(data, pos)
    if pos[0] + n > len(data):
        raise CodecDecodeError(
            f"net frame truncated: field wants {n} bytes, "
            f"{len(data) - pos[0]} remain")
    b = data[pos[0]:pos[0] + n]
    pos[0] += n
    return b


def _put_str(out: bytearray, s: str) -> None:
    _put_bytes(out, s.encode("utf-8"))


def _read_str(data: bytes, pos: list) -> str:
    try:
        return _read_bytes(data, pos).decode("utf-8")
    except UnicodeDecodeError as e:
        raise CodecDecodeError(f"net frame string is not UTF-8: {e}") from e


# -- framing -----------------------------------------------------------
def frame(body: bytes, max_frame: Optional[int] = None) -> bytes:
    """Wrap one message body in the length+crc envelope."""
    if max_frame is not None and len(body) > max_frame:
        raise NetProtocolError(
            f"frame body {len(body)}B exceeds the {max_frame}B maximum "
            "— split the payload or raise LORO_NET_MAX_FRAME")
    return struct.pack("<II", len(body), zlib.crc32(body)) + body


def parse_header(header: bytes, max_frame: int) -> Tuple[int, int]:
    """``(body_len, crc)`` from the 8-byte header; typed refusal of
    oversized declarations BEFORE any body byte is read."""
    if len(header) != HEADER_LEN:
        raise CodecDecodeError(
            f"net frame header truncated: {len(header)}/{HEADER_LEN} bytes")
    body_len, crc = struct.unpack("<II", header)
    if body_len > max_frame:
        raise NetProtocolError(
            f"peer declared a {body_len}B frame; the negotiated maximum "
            f"is {max_frame}B — refusing before the body is read")
    if body_len == 0:
        raise CodecDecodeError("net frame with empty body")
    return body_len, crc


def check_body(body: bytes, crc: int) -> bytes:
    """crc32 gate — truncation and bit-flips fail here, typed, before
    any field decodes."""
    if zlib.crc32(body) != crc:
        raise CodecDecodeError(
            f"net frame crc mismatch over {len(body)} body bytes "
            "(truncated or bit-flipped on the wire)")
    return body


# -- encoders ----------------------------------------------------------
def encode_hello(family: str, client_id: str,
                 frontiers: Optional[Dict[int, VersionVector]] = None,
                 version: int = PROTO_VERSION) -> bytes:
    out = bytearray()
    out.append(HELLO)
    out += PROTO_MAGIC
    out.append(version)
    _put_str(out, family)
    _put_str(out, client_id)
    frontiers = frontiers or {}
    _uvarint(out, len(frontiers))
    for di in sorted(frontiers):
        _uvarint(out, di)
        _put_bytes(out, frontiers[di].encode())
    return bytes(out)


def encode_hello_ok(family: str, n_docs: int, epoch: int, sid: str,
                    resumed: int, version: int = PROTO_VERSION) -> bytes:
    out = bytearray()
    out.append(HELLO_OK)
    out.append(version)
    _put_str(out, family)
    _uvarint(out, n_docs)
    _uvarint(out, epoch)
    _put_str(out, sid)
    _uvarint(out, resumed)
    return bytes(out)


def encode_push(rid: int, di: int, payload: bytes) -> bytes:
    out = bytearray()
    out.append(PUSH)
    _uvarint(out, rid)
    _uvarint(out, di)
    _put_bytes(out, payload)
    return bytes(out)


def encode_push_ack(rid: int, epoch: int, durable_epoch: Optional[int],
                    trace_id: str) -> bytes:
    out = bytearray()
    out.append(PUSH_ACK)
    _uvarint(out, rid)
    _uvarint(out, epoch)
    # durable watermark: 0 = not a durable server; else epoch + 1
    _uvarint(out, 0 if durable_epoch is None else durable_epoch + 1)
    _put_str(out, trace_id or "")
    return bytes(out)


def encode_pull(rid: int, di: int, min_epoch: Optional[int] = None) -> bytes:
    out = bytearray()
    out.append(PULL)
    _uvarint(out, rid)
    _uvarint(out, di)
    _uvarint(out, 0 if min_epoch is None else min_epoch + 1)
    return bytes(out)


def encode_delta(rid: int, di: int, payload: bytes, new_vv: VersionVector,
                 first_sync: bool) -> bytes:
    out = bytearray()
    out.append(DELTA)
    _uvarint(out, rid)
    _uvarint(out, di)
    _put_bytes(out, payload)
    _put_bytes(out, new_vv.encode())
    out.append(1 if first_sync else 0)
    return bytes(out)


def encode_poll(rid: int, timeout_ms: int) -> bytes:
    out = bytearray()
    out.append(POLL)
    _uvarint(out, rid)
    _uvarint(out, max(0, int(timeout_ms)))
    return bytes(out)


def encode_event(rid: int, docs: Dict[int, int], presence) -> bytes:
    out = bytearray()
    out.append(EVENT)
    _uvarint(out, rid)
    _uvarint(out, len(docs))
    for di in sorted(docs):
        _uvarint(out, di)
        _uvarint(out, docs[di])
    presence = list(presence or ())
    _uvarint(out, len(presence))
    for blob in presence:
        _put_bytes(out, bytes(blob))
    return bytes(out)


def encode_presence(blob: bytes) -> bytes:
    out = bytearray()
    out.append(PRESENCE)
    _put_bytes(out, bytes(blob))
    return bytes(out)


def encode_error(rid: int, code: int, message: str,
                 leader: str = "") -> bytes:
    out = bytearray()
    out.append(ERROR)
    _uvarint(out, rid)
    _uvarint(out, code)
    _put_str(out, message)
    _put_str(out, leader or "")
    return bytes(out)


def encode_bye() -> bytes:
    return bytes([BYE])


def encode_status(rid: int) -> bytes:
    out = bytearray()
    out.append(STATUS)
    _uvarint(out, rid)
    return bytes(out)


def encode_status_ok(rid: int, payload: bytes) -> bytes:
    out = bytearray()
    out.append(STATUS_OK)
    _uvarint(out, rid)
    _put_bytes(out, bytes(payload))
    return bytes(out)


# -- decoder -----------------------------------------------------------
def decode(body: bytes) -> Tuple[int, dict]:
    """``(msg_type, fields)`` for one crc-checked body.  Unknown types
    raise ``NetProtocolError``; short/damaged bodies raise
    ``CodecDecodeError`` (both typed — the connection loop maps them to
    an ERROR frame, never dies silently)."""
    if not body:
        raise CodecDecodeError("net frame with empty body")
    t = body[0]
    pos = [1]
    if t == HELLO:
        if body[1:5] != PROTO_MAGIC:
            raise NetProtocolError(
                f"HELLO magic {body[1:5]!r} is not {PROTO_MAGIC!r} — "
                "the peer is not speaking the loro-tpu net protocol")
        pos = [5]
        if pos[0] >= len(body):
            raise CodecDecodeError("HELLO truncated before the version")
        version = body[pos[0]]
        pos[0] += 1
        family = _read_str(body, pos)
        client_id = _read_str(body, pos)
        n = _read_uvarint(body, pos)
        frontiers: Dict[int, VersionVector] = {}
        for _ in range(n):
            di = _read_uvarint(body, pos)
            frontiers[di] = VersionVector.decode(_read_bytes(body, pos))
        return t, {"version": version, "family": family,
                   "client_id": client_id, "frontiers": frontiers}
    if t == HELLO_OK:
        if pos[0] >= len(body):
            raise CodecDecodeError("HELLO_OK truncated before the version")
        version = body[pos[0]]
        pos[0] += 1
        return t, {
            "version": version,
            "family": _read_str(body, pos),
            "n_docs": _read_uvarint(body, pos),
            "epoch": _read_uvarint(body, pos),
            "sid": _read_str(body, pos),
            "resumed": _read_uvarint(body, pos),
        }
    if t == PUSH:
        return t, {"rid": _read_uvarint(body, pos),
                   "di": _read_uvarint(body, pos),
                   "payload": _read_bytes(body, pos)}
    if t == PUSH_ACK:
        rid = _read_uvarint(body, pos)
        epoch = _read_uvarint(body, pos)
        dur = _read_uvarint(body, pos)
        return t, {"rid": rid, "epoch": epoch,
                   "durable_epoch": None if dur == 0 else dur - 1,
                   "trace_id": _read_str(body, pos)}
    if t == PULL:
        rid = _read_uvarint(body, pos)
        di = _read_uvarint(body, pos)
        me = _read_uvarint(body, pos)
        return t, {"rid": rid, "di": di,
                   "min_epoch": None if me == 0 else me - 1}
    if t == DELTA:
        rid = _read_uvarint(body, pos)
        di = _read_uvarint(body, pos)
        payload = _read_bytes(body, pos)
        vv = VersionVector.decode(_read_bytes(body, pos))
        if pos[0] >= len(body):
            raise CodecDecodeError("DELTA truncated before the sync flag")
        return t, {"rid": rid, "di": di, "payload": payload,
                   "new_vv": vv, "first_sync": bool(body[pos[0]])}
    if t == POLL:
        return t, {"rid": _read_uvarint(body, pos),
                   "timeout_ms": _read_uvarint(body, pos)}
    if t == EVENT:
        rid = _read_uvarint(body, pos)
        n = _read_uvarint(body, pos)
        docs = {}
        for _ in range(n):
            di = _read_uvarint(body, pos)
            docs[di] = _read_uvarint(body, pos)
        np = _read_uvarint(body, pos)
        presence = [_read_bytes(body, pos) for _ in range(np)]
        return t, {"rid": rid, "docs": docs, "presence": presence}
    if t == PRESENCE:
        return t, {"blob": _read_bytes(body, pos)}
    if t == ERROR:
        return t, {"rid": _read_uvarint(body, pos),
                   "code": _read_uvarint(body, pos),
                   "message": _read_str(body, pos),
                   "leader": _read_str(body, pos) or None}
    if t == BYE:
        return t, {}
    if t == STATUS:
        return t, {"rid": _read_uvarint(body, pos)}
    if t == STATUS_OK:
        return t, {"rid": _read_uvarint(body, pos),
                   "payload": _read_bytes(body, pos)}
    raise NetProtocolError(f"unknown net message type 0x{t:02x}")


# -- ERROR code <-> typed exception mapping ----------------------------
def error_code_for(exc: BaseException) -> Tuple[int, str]:
    """``(code, leader)`` an ERROR frame should carry for a sync-layer
    exception crossing the wire."""
    from ..errors import (
        NotLeader, PushRejected, ReplicaLag, SessionClosed, StaleFrontier,
    )

    if isinstance(exc, PushRejected):
        return E_PUSH_REJECTED, ""
    if isinstance(exc, StaleFrontier):
        return E_STALE_FRONTIER, ""
    if isinstance(exc, NotLeader):
        return E_NOT_LEADER, str(exc.leader or "")
    if isinstance(exc, ReplicaLag):
        return E_REPLICA_LAG, ""
    if isinstance(exc, SessionClosed):
        return E_SESSION_CLOSED, ""
    if isinstance(exc, (CodecDecodeError, NetProtocolError)):
        return E_BAD_FRAME, ""
    return E_INTERNAL, ""


def raise_error(fields: dict) -> None:
    """Re-raise a received ERROR frame as its typed exception — the
    client sees the SAME error types the in-process Session raises."""
    from ..errors import (
        NetError, NotLeader, PushRejected, ReplicaLag, SessionClosed,
        StaleFrontier,
    )

    code = fields.get("code")
    msg = fields.get("message", "")
    if code == E_PUSH_REJECTED:
        raise PushRejected(msg)
    if code == E_STALE_FRONTIER:
        raise StaleFrontier(msg)
    if code == E_NOT_LEADER:
        raise NotLeader(msg, leader=fields.get("leader"))
    if code == E_REPLICA_LAG:
        raise ReplicaLag(msg)
    if code == E_SESSION_CLOSED:
        raise SessionClosed(msg)
    if code == E_BAD_VERSION:
        raise NetProtocolError(msg)
    if code == E_BAD_FRAME:
        raise CodecDecodeError(msg)
    raise NetError(f"{CODE_NAMES.get(code, code)}: {msg}")
