"""NetClient: blocking TCP client for the net edge (docs/NET.md).

Single-threaded and synchronous on purpose — it is the test/bench/
soak-side half of the wire contract, one thread per simulated client
replica.  The client's ``frontiers`` (doc -> VersionVector) are its
COMPLETE resume token: ``connect()`` ships them in HELLO, so after any
disconnect — graceful ``close()``, an abrupt ``kill()`` (the simulated
SIGKILL), or a real process death — ``reconnect()`` is just a new
socket + the same HELLO, and the first ``pull()`` per doc is exactly
the delta since what this client already holds (eg-walker updates-
since-frontier; the server keeps NO session state across disconnects).

Keep ``frontiers`` honest and resume loses nothing: ``pull()`` merges
the DELTA frontier in automatically; after importing your own pushes
into your local doc, call ``set_frontier(di, doc.oplog_vv())`` (or
just pull once) so the server does not re-serve your own ops — though
re-serving is SAFE (CRDT import is idempotent), it is wasted bytes.

Typed errors cross the wire: an ERROR frame re-raises the same
exception types the in-process ``Session`` raises (``PushRejected``,
``StaleFrontier``, ``NotLeader`` carrying the leader address for
redirect, ``ReplicaLag``, ...); transport failures raise ``NetError``;
damaged frames raise ``CodecDecodeError``.
"""
from __future__ import annotations

import socket
from typing import Dict, Optional

from ..core.version import VersionVector
from ..errors import CodecDecodeError, NetError
from . import config as netcfg
from . import wire


class NetClient:
    def __init__(self, host: str, port: int, family: str,
                 client_id: str = "", *, max_frame: Optional[int] = None,
                 timeout: float = 30.0):
        self.host = host
        self.port = port
        self.family = family
        self.client_id = client_id
        self.max_frame = netcfg.resolve_max_frame(max_frame)
        self.timeout = timeout
        self.frontiers: Dict[int, VersionVector] = {}
        self.hello_info: Optional[dict] = None
        self.last_push: Optional[dict] = None
        self.last_pull: Optional[dict] = None
        self._sock: Optional[socket.socket] = None
        self._rid = 0
        self._events = []  # unsolicited EVENT payloads between rpcs

    # -- connection lifecycle -------------------------------------------
    def connect(self) -> dict:
        """Dial + HELLO (with the current frontiers as the resume
        token).  Returns the HELLO_OK info dict."""
        if self._sock is not None:
            raise NetError("already connected; close() or kill() first")
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send(wire.encode_hello(
            self.family, self.client_id, self.frontiers))
        t, fields = self._expect(wire.HELLO_OK)
        self.hello_info = fields
        return fields

    def reconnect(self) -> dict:
        """Resume: fresh socket, HELLO with the frontiers this client
        already holds.  Safe after ``kill()`` or a server-side close."""
        if self._sock is not None:
            self.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill is a socket close on a CPU-only TCP client — no process, no device work)
        return self.connect()

    def close(self) -> None:
        """Graceful: BYE, then close."""
        if self._sock is None:
            return
        try:
            self._send(wire.encode_bye())
        except (NetError, OSError):
            pass
        self.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill is a socket close on a CPU-only TCP client — no process, no device work)

    def kill(self) -> None:
        """Abrupt close — the in-process stand-in for a SIGKILLed
        client process: no BYE, no drain, the server finds out from
        the dead socket."""
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    @property
    def connected(self) -> bool:
        return self._sock is not None

    # -- operations -----------------------------------------------------
    def push(self, di: int, data: bytes,
             timeout: Optional[float] = None) -> dict:
        """Push one updates blob; blocks for PUSH_ACK.  Returns
        ``{"epoch", "durable_epoch", "trace_id"}`` — ``durable_epoch``
        is the server's acked-fsync watermark (None on a non-durable
        server): everything at or below it survives a server crash."""
        rid = self._next_rid()
        self._send(wire.encode_push(rid, di, bytes(data)))
        t, fields = self._expect(wire.PUSH_ACK, rid=rid, timeout=timeout)
        self.last_push = fields
        return fields

    def pull(self, di: int, min_epoch: Optional[int] = None) -> bytes:
        """Delta since this client's frontier (byte-identical to the
        in-process ``Session.pull``).  Merges the served frontier into
        ``self.frontiers[di]``; ``self.last_pull["first_sync"]`` tells
        a fresh doc to import a snapshot."""
        rid = self._next_rid()
        self._send(wire.encode_pull(rid, di, min_epoch))
        t, fields = self._expect(wire.DELTA, rid=rid)
        vv = self.frontiers.get(di)
        if vv is None:
            self.frontiers[di] = fields["new_vv"].copy()
        else:
            vv.merge(fields["new_vv"])
        self.last_pull = {"di": di, "first_sync": fields["first_sync"],
                          "bytes": len(fields["payload"])}
        return fields["payload"]

    def poll(self, timeout_s: float = 0.0) -> dict:
        """Long-poll for activity: ``{"docs": {di: epoch}, "presence":
        [blobs]}`` (empty members = nothing before the deadline).
        Pending unsolicited events drained between rpcs merge in."""
        rid = self._next_rid()
        self._send(wire.encode_poll(rid, int(timeout_s * 1000)))
        t, fields = self._expect(
            wire.EVENT, rid=rid, timeout=self.timeout + timeout_s)
        out = {"docs": dict(fields["docs"]),
               "presence": list(fields["presence"])}
        for ev in self._events:
            for di, ep in ev["docs"].items():
                if out["docs"].get(di, -1) < ep:
                    out["docs"][di] = ep
            out["presence"].extend(ev["presence"])
        self._events.clear()
        return out

    def broadcast_presence(self, blob: bytes) -> None:
        """Fire-and-forget presence relay (no acknowledgement)."""
        self._send(wire.encode_presence(bytes(blob)))

    def status(self, timeout: Optional[float] = None) -> dict:
        """Admin probe: the server's aggregated health verdict (the
        ``/status.json`` object plus the server's ``net`` section —
        docs/OBSERVABILITY.md "Health & heat").  A server with no
        health plane installed answers ``{"verdict": "unknown", ...}``
        rather than an error."""
        import json

        rid = self._next_rid()
        self._send(wire.encode_status(rid))
        t, fields = self._expect(wire.STATUS_OK, rid=rid, timeout=timeout)
        return json.loads(fields["payload"].decode("utf-8"))

    def set_frontier(self, di: int, vv: VersionVector) -> None:
        """Install/advance the resume frontier for one doc (merge —
        never regresses)."""
        cur = self.frontiers.get(di)
        if cur is None:
            self.frontiers[di] = vv.copy()
        else:
            cur.merge(vv)

    # -- wire plumbing --------------------------------------------------
    def _next_rid(self) -> int:
        self._rid += 1
        return self._rid

    def _require_sock(self) -> socket.socket:
        if self._sock is None:
            raise NetError("not connected (connect()/reconnect() first)")
        return self._sock

    def _send(self, body: bytes) -> None:
        s = self._require_sock()
        try:
            s.sendall(wire.frame(body, self.max_frame))
        except OSError as e:
            self.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill is a socket close on a CPU-only TCP client — no process, no device work)
            raise NetError(f"send failed: {e}") from e

    def _recv_exact(self, n: int) -> bytes:
        s = self._require_sock()
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = s.recv(n - len(buf))
            except socket.timeout as e:
                raise NetError(
                    f"timed out waiting for {n - len(buf)} more bytes "
                    f"after {self.timeout}s") from e
            except OSError as e:
                self.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill is a socket close on a CPU-only TCP client — no process, no device work)
                raise NetError(f"recv failed: {e}") from e
            if not chunk:
                self.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill is a socket close on a CPU-only TCP client — no process, no device work)
                raise NetError("connection closed by the server")
            buf += chunk
        return bytes(buf)

    def _recv_frame(self):
        header = self._recv_exact(wire.HEADER_LEN)
        body_len, crc = wire.parse_header(header, self.max_frame)
        body = wire.check_body(self._recv_exact(body_len), crc)
        return wire.decode(body)

    def _expect(self, want_type: int, rid: Optional[int] = None,
                timeout: Optional[float] = None):
        """Read frames until the wanted (type, rid) answer.  ERROR
        frames for this rid (or connection-level rid 0) re-raise
        typed; unsolicited EVENTs stash for the next ``poll()``."""
        s = self._require_sock()
        if timeout is not None:
            s.settimeout(timeout)
        try:
            while True:
                t, fields = self._recv_frame()
                if t == wire.ERROR:
                    if rid is None or fields["rid"] in (0, rid):
                        wire.raise_error(fields)
                    continue  # a stale request's error: not ours
                if t == wire.BYE:
                    self.kill()  # tpulint: disable=LT-TUNNEL(NetClient.kill is a socket close on a CPU-only TCP client — no process, no device work)
                    raise NetError("server said BYE (shutting down)")
                if t == wire.EVENT and (rid is None
                                        or fields.get("rid") != rid):
                    self._events.append(fields)
                    continue
                if t == want_type and (rid is None
                                       or fields.get("rid") == rid):
                    return t, fields
                raise CodecDecodeError(
                    f"unexpected {wire.TYPE_NAMES.get(t, t)} frame "
                    f"(wanted {wire.TYPE_NAMES.get(want_type)})")
        finally:
            if timeout is not None and self._sock is not None:
                self._sock.settimeout(self.timeout)

    def __enter__(self) -> "NetClient":
        if self._sock is None:
            self.connect()
        return self

    def __exit__(self, *exc) -> None:
        self.close()
