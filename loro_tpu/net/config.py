"""Net-edge tuning knobs with typed first-use validation (docs/NET.md).

Every knob resolves explicit-argument-first, then the environment,
then the documented default — and a malformed environment value raises
``errors.ConfigError`` AT FIRST USE with the accepted range spelled
out (the ``LORO_SHARDS`` pattern: never a silent fall-back to the
default you were not actually running with).

- ``LORO_NET_PORT``      listen port (0 = ephemeral, the test/bench
                         default; the bound port is ``server.port``)
- ``LORO_NET_MAX_FRAME`` maximum frame body bytes either side will
                         send or accept (default 8 MiB; a declared
                         length above it is refused typed BEFORE the
                         body is read)
- ``LORO_NET_BACKLOG``   listen(2) backlog (default 128)
- ``LORO_NET_MAX_CONNS`` concurrent-connection cap — the accept loop
                         refuses (counted, typed) above it instead of
                         queueing unbounded sessions (default 1024)
- ``LORO_NET_IDLE_S``    idle-connection timeout seconds (0 = never;
                         default 0 — the SyncServer session TTL is the
                         authoritative idleness policy, this one just
                         reclaims dead sockets sooner)
"""
from __future__ import annotations

import os
from typing import Optional

from ..errors import ConfigError

DEFAULT_MAX_FRAME = 8 * 1024 * 1024
DEFAULT_BACKLOG = 128
DEFAULT_MAX_CONNS = 1024
DEFAULT_IDLE_S = 0.0


def _env_int(knob: str, default: int, lo: int, hi: int,
             accepted: str) -> int:
    env = os.environ.get(knob)
    if env is None:
        return default
    try:
        v = int(env)
    except ValueError:
        raise ConfigError(knob, env, accepted) from None
    if not (lo <= v <= hi):
        raise ConfigError(knob, env, accepted)
    return v


def resolve_port(port: Optional[int] = None) -> int:
    if port is None:
        return _env_int("LORO_NET_PORT", 0, 0, 65535,
                        "TCP port 0..65535 (0 = ephemeral)")
    if not (0 <= int(port) <= 65535):
        raise ConfigError("LORO_NET_PORT", port,
                          "TCP port 0..65535 (0 = ephemeral)")
    return int(port)


def resolve_max_frame(max_frame: Optional[int] = None) -> int:
    if max_frame is None:
        return _env_int(
            "LORO_NET_MAX_FRAME", DEFAULT_MAX_FRAME, 1024, 1 << 31,
            "frame byte cap 1024..2**31")
    if not (1024 <= int(max_frame) <= 1 << 31):
        raise ConfigError("LORO_NET_MAX_FRAME", max_frame,
                          "frame byte cap 1024..2**31")
    return int(max_frame)


def resolve_backlog(backlog: Optional[int] = None) -> int:
    if backlog is None:
        return _env_int("LORO_NET_BACKLOG", DEFAULT_BACKLOG, 1, 65535,
                        "listen backlog 1..65535")
    if not (1 <= int(backlog) <= 65535):
        raise ConfigError("LORO_NET_BACKLOG", backlog,
                          "listen backlog 1..65535")
    return int(backlog)


def resolve_max_conns(max_connections: Optional[int] = None) -> int:
    if max_connections is None:
        return _env_int(
            "LORO_NET_MAX_CONNS", DEFAULT_MAX_CONNS, 1, 1 << 20,
            "concurrent-connection cap 1..2**20")
    if not (1 <= int(max_connections) <= 1 << 20):
        raise ConfigError("LORO_NET_MAX_CONNS", max_connections,
                          "concurrent-connection cap 1..2**20")
    return int(max_connections)


def resolve_idle_s(idle_timeout: Optional[float] = None) -> float:
    if idle_timeout is None:
        env = os.environ.get("LORO_NET_IDLE_S")
        if env is None:
            return DEFAULT_IDLE_S
        try:
            v = float(env)
        except ValueError:
            raise ConfigError(
                "LORO_NET_IDLE_S", env,
                "idle seconds >= 0 (0 = never)") from None
        if v < 0:
            raise ConfigError("LORO_NET_IDLE_S", env,
                              "idle seconds >= 0 (0 = never)")
        return v
    if float(idle_timeout) < 0:
        raise ConfigError("LORO_NET_IDLE_S", idle_timeout,
                          "idle seconds >= 0 (0 = never)")
    return float(idle_timeout)
