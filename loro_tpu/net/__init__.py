"""Network edge: binary wire protocol + TCP session transport
(docs/NET.md).

``NetServer`` fronts a ``sync.SyncServer`` (or a follower's
``ReadOnlySyncServer``) over real TCP sockets — one connection = one
``Session``, length-prefixed crc32-enveloped frames carrying the
existing columnar-updates bytes VERBATIM (a socket pull is
byte-identical to the in-process ``Session.pull``), bounded
backpressure mapped onto the FanIn bound, and typed errors crossing
the wire as ERROR frames.

``NetClient`` is the blocking test/bench client; its per-doc version
vectors are a complete resume token — reconnect = HELLO with your
frontiers, first pull = delta-since-frontier (the server holds no
session state across disconnects).

Typed errors live in ``loro_tpu.errors``: ``NetError``,
``NetProtocolError`` (plus the sync/replication types the wire
re-raises).  Knobs: ``LORO_NET_PORT`` / ``LORO_NET_MAX_FRAME`` /
``LORO_NET_BACKLOG`` / ``LORO_NET_MAX_CONNS`` / ``LORO_NET_IDLE_S``
(typed ``ConfigError`` at first use, ``net/config.py``).
"""
from ..errors import NetError, NetProtocolError
from . import wire
from .client import NetClient
from .server import NetServer

__all__ = [
    "NetServer",
    "NetClient",
    "NetError",
    "NetProtocolError",
    "wire",
]
