"""NetServer: asyncio TCP front over a SyncServer (docs/NET.md).

One accepted connection = one ``sync.Session``.  The event loop runs
in a dedicated thread ("loro-net-loop") beside the threaded resident
planes; blocking session calls (push backpressure, pulls, presence)
run on a small thread pool so the loop never blocks, and the per-
connection dispatch is SERIAL — a push stalled on the bounded FanIn
suspends that connection's reader, which stops draining its socket,
which is TCP backpressure to exactly the client that caused it.
Pushes are never dropped.

Fan-out maps onto the existing ``poll()`` coalescing: a connection
holds at most ONE pending long-poll; a newer POLL answers the
superseded one empty (drop-oldest, like the presence inbox), and the
notifier thread waits on the SyncServer wakeup condition to answer
polls the moment commits land.  The per-connection send queue is
bounded: a reader too slow to drain even the coalesced stream fails
typed (``NetError``, counted) instead of growing an unbounded buffer.

Acks ride a dedicated acker thread: it blocks on each ``PushTicket``
in the connection's FIFO order, appends the ``net.ack`` / ``net.send``
stage marks (the breakdown keeps telescoping to the total — the chaos
``attribution`` invariant), and enqueues PUSH_ACK carrying the visible
epoch, the durable watermark, and the server trace id.

Failure contract: a damaged frame (crc / truncation / the ``net_frame``
fault) fails ONLY that connection, typed; an armed ``net_accept``
fault refuses new connections while live sessions keep serving;
``conn_stall`` delays one connection's writer (a slow reader socket)
or tears it down typed.  Sync-layer outcomes (``PushRejected``,
``StaleFrontier``, ``NotLeader`` with the leader address, ...) cross
the wire as ERROR frames and the connection keeps serving.

Fault sites: ``net_accept`` / ``net_frame`` / ``conn_stall``
(docs/RESILIENCE.md).
"""
from __future__ import annotations

import asyncio
import functools
import queue as _queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ..analysis.lockwitness import named_lock
from ..errors import (
    CodecDecodeError, NetError, NetProtocolError, NotLeader, PushRejected,
    ReplicaLag, SessionClosed, StaleFrontier, SyncError,
)
from ..obs import flight
from ..obs import metrics as obs
from ..resilience import faultinject
from . import config as netcfg
from . import wire

faultinject.register_site(
    "net_accept", "net.NetServer accept path: refuse the next accepted "
    "connection(s) typed — live connections and sessions unaffected")
faultinject.register_site(
    "net_frame", "net.NetServer frame reader: mangle one received "
    "frame's bytes on their way to the crc gate (truncate/bitflip -> "
    "typed CodecDecodeError failing ONLY that connection)")
faultinject.register_site(
    "conn_stall", "net.NetServer per-connection writer: delay = a "
    "stalled/slow reader socket (bounded send-queue backpressure); "
    "raise = typed teardown of that one connection")

_ACK_TIMEOUT_S = 120.0
_SEND_QUEUE_CAP = 256
_NOTIFY_TICK_S = 0.05


class _Conn:
    """Per-connection state (owned by the loop thread; ``pending_poll``
    and registry membership are shared under the ``net.accept`` lock)."""

    __slots__ = (
        "cid", "reader", "writer", "session", "sendq", "writer_task",
        "reader_task", "last_activity", "client_id", "closing",
        "pending_poll", "peer",
    )

    def __init__(self, cid: int, reader, writer):
        self.cid = cid
        self.reader = reader
        self.writer = writer
        self.session = None
        self.sendq: Optional[asyncio.Queue] = None
        self.writer_task = None
        self.reader_task = None
        self.last_activity = 0.0
        self.client_id = ""
        self.closing = False
        self.pending_poll = None  # (rid, deadline) under the net lock
        self.peer = ""


class NetServer:
    """TCP front for one ``SyncServer`` (or ``ReadOnlySyncServer`` on a
    follower — pushes then answer typed NOT_LEADER carrying the leader
    address so clients redirect instead of guessing).

    ``NetServer(sync)`` binds ``127.0.0.1`` on an ephemeral port (see
    ``server.port``); knobs default from the environment with typed
    first-use validation (``net/config.py``).  ``clock=`` injects the
    idle/deadline clock (tests); stage marks use ``time.perf_counter``
    like the tickets they extend.  The server does NOT own the
    SyncServer's lifecycle — ``close()`` drains and detaches only the
    network edge.
    """

    def __init__(self, sync, host: str = "127.0.0.1",
                 port: Optional[int] = None, *,
                 max_frame: Optional[int] = None,
                 backlog: Optional[int] = None,
                 max_connections: Optional[int] = None,
                 idle_timeout: Optional[float] = None,
                 leader_addr: Optional[str] = None,
                 clock=None, health=None):
        self._sync = sync
        self._health = health  # explicit plane beats health.active()
        self.host = host
        self.max_frame = netcfg.resolve_max_frame(max_frame)
        self._backlog = netcfg.resolve_backlog(backlog)
        self.max_connections = netcfg.resolve_max_conns(max_connections)
        self.idle_timeout = netcfg.resolve_idle_s(idle_timeout)
        self.leader_addr = leader_addr
        self._clock = clock if clock is not None else time.monotonic
        self._lock = named_lock("net.accept")
        self._conns: Dict[int, _Conn] = {}
        self._next_cid = 1
        self._next_sid = 1
        self._closed = False
        self._stopping = False
        # counters mirrored into report() (obs counters are process-
        # global; these are THIS server's numbers for the net sidecar)
        self._accepted = 0
        self._refused = 0
        self._frame_errors = 0
        self._resumes = 0
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="loro-net-io")
        self._ackq: _queue.SimpleQueue = _queue.SimpleQueue()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run_loop, name="loro-net-loop", daemon=True)
        self._thread.start()
        want_port = netcfg.resolve_port(port)
        try:
            self.port = asyncio.run_coroutine_threadsafe(
                self._start(want_port), self._loop).result(timeout=30.0)
        except BaseException:
            self._stop_loop()
            raise
        self._acker = threading.Thread(
            target=self._ack_loop, name="loro-net-acker", daemon=True)
        self._acker.start()
        self._notifier = threading.Thread(
            target=self._notify_loop, name="loro-net-notify", daemon=True)
        self._notifier.start()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- loop lifecycle -------------------------------------------------
    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def _stop_loop(self) -> None:
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10.0)

    async def _start(self, port: int) -> int:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, port, backlog=self._backlog)
        if self.idle_timeout > 0:
            self._idle_task = asyncio.ensure_future(self._idle_loop())
        else:
            self._idle_task = None
        return self._server.sockets[0].getsockname()[1]

    # -- accept path ----------------------------------------------------
    async def _handle_conn(self, reader, writer) -> None:
        with self._lock:
            n_live = len(self._conns)
        refuse = None
        if self._stopping:
            refuse = "closing"
        elif n_live >= self.max_connections:
            refuse = f"at the {self.max_connections}-connection cap"
        else:
            try:
                await self._loop.run_in_executor(
                    self._pool,
                    functools.partial(faultinject.check, "net_accept"))
            except Exception as e:  # noqa: BLE001 — tpulint: disable=LT-EXC(any armed net_accept fault refuses exactly this connection; the accept loop itself keeps serving)
                refuse = f"injected accept fault: {type(e).__name__}: {e}"
        if refuse is not None:
            with self._lock:
                self._refused += 1
            obs.counter(
                "net.accept_refusals_total",
                "connections refused at accept (cap / fault / closing)",
            ).inc(family=self._sync.family)
            flight.record("net.error", family=self._sync.family,
                          err="accept_refused", detail=refuse)
            try:
                writer.close()
            except OSError:
                pass
            return
        with self._lock:
            cid = self._next_cid
            self._next_cid += 1
            conn = _Conn(cid, reader, writer)
            conn.last_activity = self._clock()
            try:
                conn.peer = "%s:%s" % writer.get_extra_info(
                    "peername", ("?", "?"))[:2]
            except (TypeError, IndexError):
                conn.peer = "?"
            self._conns[cid] = conn
            self._accepted += 1
            n_live = len(self._conns)
        conn.sendq = asyncio.Queue(maxsize=_SEND_QUEUE_CAP)
        conn.writer_task = asyncio.ensure_future(self._writer_loop(conn))
        conn.reader_task = asyncio.current_task()
        obs.counter("net.connections_total",
                    "connections accepted").inc(family=self._sync.family)
        obs.gauge("net.connections", "live net connections").set(
            n_live, family=self._sync.family)
        flight.record("net.accept", family=self._sync.family, conn=cid,
                      peer=conn.peer)
        try:
            await self._serve(conn)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer went away (incl. SIGKILLed clients): clean close
        except (NetError, CodecDecodeError) as e:
            # frame-layer violation: fail ONLY this connection, typed
            with self._lock:
                self._frame_errors += 1
            obs.counter(
                "net.frame_errors_total",
                "connections failed on a damaged/protocol-violating frame",
            ).inc(family=self._sync.family)
            flight.record("net.error", family=self._sync.family, conn=cid,
                          err=type(e).__name__, detail=str(e)[:200])
            code = (wire.E_BAD_VERSION if isinstance(e, NetProtocolError)
                    else wire.E_BAD_FRAME)
            await self._try_send_error(conn, 0, code, str(e))
        except Exception as e:  # noqa: BLE001 — tpulint: disable=LT-EXC(last-resort isolation: an unexpected dispatch error must fail one connection typed, never the accept loop)
            obs.counter(
                "net.internal_errors_total",
                "connections failed on an unexpected server-side error",
            ).inc(family=self._sync.family)
            flight.record("net.error", family=self._sync.family, conn=cid,
                          err=type(e).__name__, detail=str(e)[:200])
            await self._try_send_error(conn, 0, wire.E_INTERNAL, str(e))
        finally:
            await self._close_conn(conn)

    async def _serve(self, conn: _Conn) -> None:
        body = await self._read_frame(conn)
        t, fields = wire.decode(body)
        if t != wire.HELLO:
            raise NetProtocolError(
                f"first frame must be HELLO, got {wire.TYPE_NAMES.get(t, t)}")
        await self._handle_hello(conn, fields)
        while not conn.closing:
            body = await self._read_frame(conn)
            t, fields = wire.decode(body)
            if t == wire.BYE:
                return
            await self._dispatch(conn, t, fields)

    async def _read_frame(self, conn: _Conn) -> bytes:
        header = await conn.reader.readexactly(wire.HEADER_LEN)
        body_len, crc = wire.parse_header(header, self.max_frame)
        body = await conn.reader.readexactly(body_len)
        conn.last_activity = self._clock()
        obs.counter("net.frames_total", "frames on the wire").inc(
            family=self._sync.family, dir="in")
        obs.counter("net.bytes_total", "bytes on the wire").inc(
            body_len + wire.HEADER_LEN, family=self._sync.family, dir="in")
        body = faultinject.mangle("net_frame", body)
        return wire.check_body(body, crc)

    async def _handle_hello(self, conn: _Conn, fields: dict) -> None:
        sync = self._sync
        if fields["version"] != wire.PROTO_VERSION:
            await self._try_send_error(
                conn, 0, wire.E_BAD_VERSION,
                f"protocol version {fields['version']} unsupported "
                f"(server speaks {wire.PROTO_VERSION})")
            raise NetProtocolError(
                f"client protocol version {fields['version']} != "
                f"{wire.PROTO_VERSION}")
        if fields["family"] != sync.family:
            await self._try_send_error(
                conn, 0, wire.E_BAD_VERSION,
                f"server serves family {sync.family!r}, "
                f"not {fields['family']!r}")
            raise NetProtocolError(
                f"family mismatch: client {fields['family']!r}, "
                f"server {sync.family!r}")
        conn.client_id = fields["client_id"]
        with self._lock:
            sid = f"net-{conn.client_id or 'anon'}-{self._next_sid}"
            self._next_sid += 1
        frontiers = fields["frontiers"]

        def _connect():
            s = sync.connect(sid=sid)
            resumed = 0
            # the HELLO frontiers ARE the session state a disconnect
            # dropped: install them so the first pull is exactly a
            # delta-since-frontier (eg-walker resume; docs/NET.md)
            with sync._lock:
                for di, vv in frontiers.items():
                    if 0 <= di < sync.n_docs and len(vv):
                        s._vv[di] = vv.copy()
                        resumed += 1
            return s, resumed

        conn.session, resumed = await self._loop.run_in_executor(
            self._pool, _connect)
        if conn.closing:
            return
        if resumed:
            with self._lock:
                self._resumes += 1
            obs.counter(
                "net.resumes_total",
                "connections that resumed with a non-empty HELLO frontier",
            ).inc(family=sync.family)
            flight.record("net.resume", family=sync.family, conn=conn.cid,
                          client=conn.client_id, docs=resumed)
        self._enqueue(conn, wire.encode_hello_ok(
            sync.family, sync.n_docs, sync.epoch, sid, resumed))

    # -- dispatch -------------------------------------------------------
    async def _dispatch(self, conn: _Conn, t: int, fields: dict) -> None:
        if conn.session is None or conn.session.closed:
            raise SessionClosed("connection has no live session")
        rid = fields.get("rid", 0)
        try:
            if t == wire.PUSH:
                await self._handle_push(conn, fields)
            elif t == wire.PULL:
                await self._handle_pull(conn, fields)
            elif t == wire.POLL:
                await self._handle_poll(conn, fields)
            elif t == wire.PRESENCE:
                await self._loop.run_in_executor(
                    self._pool, conn.session.broadcast_presence,
                    fields["blob"])
            elif t == wire.STATUS:
                body = await self._loop.run_in_executor(
                    self._pool, self._status_payload)
                self._enqueue(conn, wire.encode_status_ok(rid, body))
            elif t == wire.HELLO:
                raise NetProtocolError("HELLO after the handshake")
            else:
                raise NetProtocolError(
                    f"unexpected {wire.TYPE_NAMES.get(t, t)} frame "
                    "from a client")
        except (PushRejected, StaleFrontier, NotLeader, ReplicaLag,
                SessionClosed, SyncError, ValueError) as e:
            # sync-layer outcome: typed over the wire, connection LIVES
            code, leader = wire.error_code_for(e)
            if code == wire.E_NOT_LEADER and not leader:
                leader = self.leader_addr or ""
            obs.counter(
                "net.request_errors_total",
                "requests answered with a typed ERROR frame",
            ).inc(family=self._sync.family, code=wire.CODE_NAMES.get(
                code, str(code)))
            self._enqueue(conn, wire.encode_error(
                rid, code, str(e), leader))

    async def _handle_push(self, conn: _Conn, fields: dict) -> None:
        # session.push blocks on FanIn backpressure: running it on the
        # pool and awaiting suspends THIS connection's reader only —
        # its socket fills, TCP pushes back on the client (never drop)
        tk = await self._loop.run_in_executor(
            self._pool, conn.session.push, fields["di"], fields["payload"])
        self._ackq.put((conn, fields["rid"], tk))

    async def _handle_pull(self, conn: _Conn, fields: dict) -> None:
        di = fields["di"]
        sess = conn.session

        def _pull():
            data = sess.pull(di, min_epoch=fields["min_epoch"])
            lp = sess.last_pull or {}
            return data, sess.frontier(di), lp.get("path") == "snapshot"

        data, new_vv, first_sync = await self._loop.run_in_executor(
            self._pool, _pull)
        self._enqueue(conn, wire.encode_delta(
            fields["rid"], di, data, new_vv, first_sync))

    async def _handle_poll(self, conn: _Conn, fields: dict) -> None:
        rid = fields["rid"]
        timeout_ms = fields["timeout_ms"]
        deadline = self._clock() + timeout_ms / 1000.0
        with self._lock:
            old = conn.pending_poll
            conn.pending_poll = (rid, deadline)
        if old is not None:
            # drop-oldest: the superseded long-poll answers empty (the
            # newer one owns whatever activity lands), mirroring the
            # session poll()'s self-coalescing contract
            self._enqueue(conn, wire.encode_event(old[0], {}, []))
        if timeout_ms == 0:
            # non-blocking drain: answer inline instead of waiting for
            # the notifier tick
            out = await self._loop.run_in_executor(
                self._pool, functools.partial(
                    conn.session.poll, timeout=0))
            self._answer_poll(conn, out, force=True)

    # -- send path ------------------------------------------------------
    def _enqueue(self, conn: _Conn, data: bytes) -> None:
        """Queue one frame on the connection's bounded send queue (loop
        thread only — threads go through ``_send_from_thread``)."""
        if conn.closing or conn.sendq is None:
            return
        try:
            conn.sendq.put_nowait(data)
        except asyncio.QueueFull:
            obs.counter(
                "net.send_overflows_total",
                "connections failed typed: reader too slow for even the "
                "coalesced stream (bounded send queue)",
            ).inc(family=self._sync.family)
            flight.record("net.error", family=self._sync.family,
                          conn=conn.cid, err="send_overflow")
            self._fail_conn(conn, NetError(
                f"connection {conn.cid}: send queue overflow "
                f"({_SEND_QUEUE_CAP} frames queued) — the reader is not "
                "draining its socket"))

    def _send_from_thread(self, conn: _Conn, data: bytes) -> None:
        if self._closed:
            return
        try:
            self._loop.call_soon_threadsafe(self._enqueue, conn, data)
        except RuntimeError:
            pass  # loop already stopped: the connection is gone anyway

    async def _writer_loop(self, conn: _Conn) -> None:
        sync = self._sync
        try:
            while True:
                data = await conn.sendq.get()
                if data is None:
                    return
                # armed-only fast path: the stall fault runs on the
                # pool (a delay must stall THIS writer, not the loop).
                # active() is registry state; the reader's per-frame
                # mangle() already forced the LORO_FAULT env parse.
                if faultinject.active().get("conn_stall"):
                    await self._loop.run_in_executor(
                        self._pool,
                        functools.partial(faultinject.check, "conn_stall"))
                conn.writer.write(wire.frame(data, self.max_frame))
                await conn.writer.drain()
                obs.counter("net.frames_total", "frames on the wire").inc(
                    family=sync.family, dir="out")
                obs.counter("net.bytes_total", "bytes on the wire").inc(
                    len(data) + wire.HEADER_LEN, family=sync.family,
                    dir="out")
        except (ConnectionError, OSError):
            self._fail_conn(conn, None)
        except Exception as e:  # noqa: BLE001 — tpulint: disable=LT-EXC(an injected conn_stall raise or writer failure tears down exactly this connection, typed and counted)
            flight.record("net.error", family=sync.family, conn=conn.cid,
                          err=type(e).__name__, detail=str(e)[:200])
            self._fail_conn(conn, NetError(
                f"connection {conn.cid}: writer failed: "
                f"{type(e).__name__}: {e}"))

    def _fail_conn(self, conn: _Conn, _exc) -> None:
        """Tear one connection down from the loop thread (typed —
        the accept loop and every other connection keep serving)."""
        if not conn.closing:
            asyncio.ensure_future(self._close_conn(conn))

    async def _try_send_error(self, conn: _Conn, rid: int, code: int,
                              msg: str) -> None:
        """Best-effort direct ERROR write (bypasses the queue: used on
        paths that close the connection right after)."""
        try:
            conn.writer.write(wire.frame(
                wire.encode_error(rid, code, msg[:512]), self.max_frame))
            await asyncio.wait_for(conn.writer.drain(), timeout=1.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass

    async def _close_conn(self, conn: _Conn) -> None:
        with self._lock:
            if conn.closing:
                return
            conn.closing = True
            self._conns.pop(conn.cid, None)
            conn.pending_poll = None
            n_live = len(self._conns)
        if conn.writer_task is not None:
            try:
                conn.sendq.put_nowait(None)
            except asyncio.QueueFull:
                conn.writer_task.cancel()
            try:
                await asyncio.wait_for(conn.writer_task, timeout=5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass
        try:
            conn.writer.close()
        except OSError:
            pass
        sess = conn.session
        if sess is not None and not sess.closed:
            # disconnect drops replica floors + presence; it takes the
            # sync lock, so keep it off the loop thread
            await self._loop.run_in_executor(self._pool, sess.close)
        obs.gauge("net.connections", "live net connections").set(
            n_live, family=self._sync.family)
        flight.record("net.close", family=self._sync.family, conn=conn.cid)

    # -- acker thread (PUSH_ACK + net.* stage attribution) --------------
    def _ack_loop(self) -> None:
        sync = self._sync
        resident = sync.resident
        stage_h = obs.histogram(
            "trace.push_stage_seconds",
            "per-stage push latency attribution (stages telescope to "
            "sync.push_to_visible_seconds)")
        ack_h = obs.histogram(
            "net.push_to_ack_seconds",
            "push submit -> PUSH_ACK enqueued on the wire")
        while True:
            item = self._ackq.get()
            if item is None:
                return
            conn, rid, tk = item
            try:
                ep = tk.epoch(timeout=_ACK_TIMEOUT_S)
            except Exception as e:  # noqa: BLE001 — tpulint: disable=LT-EXC(every ticket failure maps to ONE typed ERROR frame for its request; the acker itself must outlive any of them)
                code, leader = wire.error_code_for(e)
                if isinstance(e, TimeoutError):
                    code = wire.E_UNAVAILABLE
                if code == wire.E_NOT_LEADER and not leader:
                    leader = self.leader_addr or ""
                self._send_from_thread(conn, wire.encode_error(
                    rid, code, str(e), leader))
                continue
            # net.* stage marks EXTEND the ticket's breakdown: net.ack
            # closes fanout -> acker dequeue, net.send closes the ack's
            # hop onto the send queue; sum(stages) == total still holds
            prev = tk.marks[-1][1] if tk.marks else tk.t0
            tk.mark("net.ack")
            t_ack = tk.marks[-1][1]
            stage_h.observe(t_ack - prev, family=sync.family,
                            stage="net.ack", exemplar=tk.trace_id)
            dur = (resident.durable_epoch
                   if getattr(resident, "_durable", None) is not None
                   else None)
            self._send_from_thread(conn, wire.encode_push_ack(
                rid, ep, dur, tk.trace_id or ""))
            tk.mark("net.send")
            t_send = tk.marks[-1][1]
            stage_h.observe(t_send - t_ack, family=sync.family,
                            stage="net.send", exemplar=tk.trace_id)
            ack_h.observe(t_send - tk.t0, family=sync.family,
                          exemplar=tk.trace_id)
            obs.counter("net.push_acks_total", "PUSH_ACK frames sent").inc(
                family=sync.family)

    # -- notifier thread (long-poll fan-out) ----------------------------
    def _notify_loop(self) -> None:
        sync = self._sync
        while not self._stopping:
            with sync._lock:
                sync._wakeup.wait(_NOTIFY_TICK_S)
            if self._stopping:
                return
            now = self._clock()
            with self._lock:
                conns = [c for c in self._conns.values()
                         if c.pending_poll is not None and not c.closing]
            for c in conns:
                with self._lock:
                    pp = c.pending_poll
                if pp is None:
                    continue
                sess = c.session
                if sess is None or sess.closed:
                    continue
                try:
                    out = sess.poll(timeout=0)
                except SessionClosed:
                    continue
                if out["docs"] or out["presence"]:
                    self._answer_poll(c, out)
                elif now >= pp[1]:
                    self._answer_poll(c, out)  # deadline: answer empty

    def _answer_poll(self, conn: _Conn, out: dict,
                     force: bool = False) -> None:
        """Answer the connection's CURRENT pending poll with a drained
        activity set (drained events always ride the newest rid — a
        replace between drain and answer can never lose them)."""
        with self._lock:
            pp = conn.pending_poll
            if pp is None:
                if not (force or out["docs"] or out["presence"]):
                    return
                rid = 0  # unsolicited (answered-then-drained races)
            else:
                rid = pp[0]
                conn.pending_poll = None
        obs.counter("net.events_total", "EVENT frames fanned out").inc(
            family=self._sync.family)
        self._send_from_thread(conn, wire.encode_event(
            rid, out["docs"], out["presence"]))

    # -- idle housekeeping ----------------------------------------------
    async def _idle_loop(self) -> None:
        tick = max(0.25, self.idle_timeout / 4.0)
        while not self._stopping:
            await asyncio.sleep(tick)
            cutoff = self._clock() - self.idle_timeout
            with self._lock:
                stale = [c for c in self._conns.values()
                         if c.last_activity < cutoff
                         and c.pending_poll is None and not c.closing]
            for c in stale:
                obs.counter(
                    "net.idle_closes_total",
                    "connections closed by the idle timeout",
                ).inc(family=self._sync.family)
                flight.record("net.close", family=self._sync.family,
                              conn=c.cid, reason="idle")
                await self._close_conn(c)

    # -- lifecycle ------------------------------------------------------
    def report(self) -> dict:
        """This server's connection-plane numbers (the bench ``net``
        sidecar core)."""
        with self._lock:
            return {
                "addr": self.addr,
                "connections": len(self._conns),
                "accepted": self._accepted,
                "refused": self._refused,
                "frame_errors": self._frame_errors,
                "resumes": self._resumes,
                "max_frame": self.max_frame,
                "max_connections": self.max_connections,
            }

    def _status_payload(self) -> bytes:
        """JSON bytes for a STATUS_OK frame: the aggregated health
        verdict (explicit ``health=`` plane, else the process-installed
        one, else the typed "unknown" stub) with THIS server's ``net``
        section merged in — the same object ``/status.json`` serves."""
        import json

        from ..obs import health as _health

        plane = self._health if self._health is not None else _health.active()
        payload = (plane.status() if plane is not None
                   else _health.status_payload())
        payload["net"] = self.report()
        return json.dumps(payload).encode()

    async def _shutdown(self) -> None:
        self._server.close()
        await self._server.wait_closed()
        if self._idle_task is not None:
            self._idle_task.cancel()
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            self._enqueue(c, wire.encode_bye())
            await self._close_conn(c)

    def close(self) -> None:
        """Graceful drain: stop accepting, BYE + close every
        connection (their sessions disconnect), stop the worker
        threads and the loop.  Idempotent; never touches the
        SyncServer's own lifecycle."""
        if self._closed:
            return
        self._closed = True
        self._stopping = True
        try:
            asyncio.run_coroutine_threadsafe(
                self._shutdown(), self._loop).result(timeout=30.0)
        except (RuntimeError, TimeoutError):
            pass
        self._ackq.put(None)
        self._acker.join(timeout=10.0)
        self._notifier.join(timeout=10.0)
        self._stop_loop()
        self._pool.shutdown(wait=False)
        obs.gauge("net.connections", "live net connections").set(
            0, family=self._sync.family)

    def __enter__(self) -> "NetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
