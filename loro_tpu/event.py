"""Events, diffs and the Delta algebra.

reference: crates/loro-internal/src/event.rs (+ the loro-delta crate).
Diffs are the currency of the whole framework: container states emit
them on merge, subscribers receive them, undo inverts them, checkout
produces them.  Sequence diffs are Quill-style deltas with O(n) compose
(the reference uses a B-tree DeltaRope for O(log n); host diffs here are
small — bulk merge work happens on device).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from .core.ids import ContainerID, TreeID
from .core.version import Frontiers


class EventTriggerKind(enum.Enum):
    Local = "local"
    Import = "import"
    Checkout = "checkout"


# ---------------------------------------------------------------------------
# Delta (retain / insert / delete runs)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Retain:
    n: int
    attributes: Optional[dict] = None


@dataclass(frozen=True)
class Insert:
    # str for text, tuple of values for lists
    value: Union[str, Tuple[Any, ...]]
    attributes: Optional[dict] = None

    def __len__(self) -> int:
        return len(self.value)


@dataclass(frozen=True)
class Delete:
    n: int


DeltaItem = Union[Retain, Insert, Delete]


def _concat(a: Union[str, Tuple], b: Union[str, Tuple]):
    return a + b


class Delta:
    """A list of delta items with normalization and compose."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[Sequence[DeltaItem]] = None):
        self.items: List[DeltaItem] = []
        if items:
            for it in items:
                self.push(it)

    # -- builders -----------------------------------------------------
    def retain(self, n: int, attributes: Optional[dict] = None) -> "Delta":
        if n > 0:
            self.push(Retain(n, attributes))
        return self

    def insert(self, value, attributes: Optional[dict] = None) -> "Delta":
        if len(value) > 0:
            self.push(Insert(value, attributes))
        return self

    def delete(self, n: int) -> "Delta":
        if n > 0:
            self.push(Delete(n))
        return self

    def push(self, it: DeltaItem) -> None:
        if isinstance(it, Retain) and it.n == 0:
            return
        if isinstance(it, Insert) and len(it.value) == 0:
            return
        if isinstance(it, Delete) and it.n == 0:
            return
        if self.items:
            last = self.items[-1]
            if isinstance(last, Retain) and isinstance(it, Retain) and last.attributes == it.attributes:
                self.items[-1] = Retain(last.n + it.n, last.attributes)
                return
            if (
                isinstance(last, Insert)
                and isinstance(it, Insert)
                and last.attributes == it.attributes
                and type(last.value) is type(it.value)
            ):
                self.items[-1] = Insert(_concat(last.value, it.value), last.attributes)
                return
            if isinstance(last, Delete) and isinstance(it, Delete):
                self.items[-1] = Delete(last.n + it.n)
                return
        self.items.append(it)

    def chop(self) -> "Delta":
        """Drop a trailing attribute-less retain."""
        while self.items and isinstance(self.items[-1], Retain) and self.items[-1].attributes is None:
            self.items.pop()
        return self

    def is_empty(self) -> bool:
        return not self.items

    # -- application --------------------------------------------------
    def apply_to_text(self, s: str) -> str:
        out: List[str] = []
        i = 0
        for it in self.items:
            if isinstance(it, Retain):
                out.append(s[i : i + it.n])
                i += it.n
            elif isinstance(it, Insert):
                out.append(it.value)  # type: ignore[arg-type]
            else:
                i += it.n
        out.append(s[i:])
        return "".join(out)

    def apply_to_list(self, xs: List[Any]) -> List[Any]:
        out: List[Any] = []
        i = 0
        for it in self.items:
            if isinstance(it, Retain):
                out.extend(xs[i : i + it.n])
                i += it.n
            elif isinstance(it, Insert):
                out.extend(it.value)
            else:
                i += it.n
        out.extend(xs[i:])
        return out

    # -- algebra ------------------------------------------------------
    def compose(self, other: "Delta") -> "Delta":
        """self then other, as one delta (standard Quill compose)."""
        out = Delta()
        a = _Cursor(self.items)
        b = _Cursor(other.items)
        while a.has() or b.has():
            if b.peek_type() is Insert:
                out.push(b.take_insert())
                continue
            if not a.has():
                it = b.take(b.remaining())
                out.push(it)
                continue
            if not b.has():
                out.push(a.take(a.remaining()))
                continue
            if a.peek_type() is Delete:
                out.push(a.take(a.remaining()))
                continue
            n = min(a.remaining(), b.remaining())
            ai = a.take(n)
            bi = b.take(n)
            if isinstance(bi, Delete):
                if isinstance(ai, Retain):
                    out.push(Delete(n))
                # insert+delete annihilate
            else:  # bi is Retain
                battr = bi.attributes
                if isinstance(ai, Insert):
                    out.push(Insert(ai.value, _merge_attr(ai.attributes, battr)))
                else:
                    out.push(Retain(n, _merge_attr(ai.attributes, battr)))
        return out.chop()

    def transform(self, other: "Delta", priority_left: bool) -> "Delta":
        """Transform `other` against self (OT; used by undo's remote-op
        transform, reference undo.rs DiffBatch::transform)."""
        out = Delta()
        a = _Cursor(self.items)
        b = _Cursor(other.items)
        while a.has() or b.has():
            if a.peek_type() is Insert and (priority_left or b.peek_type() is not Insert):
                out.retain(len(a.take_insert().value))
                continue
            if b.peek_type() is Insert:
                out.push(b.take_insert())
                continue
            if not a.has():
                out.push(b.take(b.remaining()))
                continue
            if not b.has():
                break
            n = min(a.remaining(), b.remaining())
            ai = a.take(n)
            bi = b.take(n)
            if isinstance(ai, Delete):
                continue  # ai deleted the region `bi` acted on
            if isinstance(bi, Delete):
                out.push(Delete(n))
            else:
                out.push(Retain(n, bi.attributes))
        return out.chop()

    def insert_len(self) -> int:
        return sum(len(it.value) for it in self.items if isinstance(it, Insert))

    def delete_len(self) -> int:
        return sum(it.n for it in self.items if isinstance(it, Delete))

    def __eq__(self, other) -> bool:
        return isinstance(other, Delta) and self.items == other.items

    def __repr__(self) -> str:
        return f"Delta({self.items!r})"

    def to_json(self) -> List[dict]:
        out = []
        for it in self.items:
            if isinstance(it, Retain):
                d: dict = {"retain": it.n}
                if it.attributes is not None:
                    d["attributes"] = it.attributes
            elif isinstance(it, Insert):
                d = {"insert": it.value if isinstance(it.value, str) else list(it.value)}
                if it.attributes is not None:
                    d["attributes"] = it.attributes
            else:
                d = {"delete": it.n}
            out.append(d)
        return out


def _merge_attr(a: Optional[dict], b: Optional[dict]) -> Optional[dict]:
    if a is None:
        return b
    if b is None:
        return a
    out = dict(a)
    out.update(b)
    return out or None


class _Cursor:
    """Iterates delta items with partial consumption."""

    __slots__ = ("items", "i", "off")

    def __init__(self, items: List[DeltaItem]):
        self.items = items
        self.i = 0
        self.off = 0

    def has(self) -> bool:
        return self.i < len(self.items)

    def peek_type(self):
        return type(self.items[self.i]) if self.has() else None

    def remaining(self) -> int:
        it = self.items[self.i]
        if isinstance(it, Insert):
            return len(it.value) - self.off
        return it.n - self.off

    def take(self, n: int) -> DeltaItem:
        it = self.items[self.i]
        if isinstance(it, Insert):
            v = it.value[self.off : self.off + n]
            self._adv(n, len(it.value))
            return Insert(v, it.attributes)
        if isinstance(it, Retain):
            self._adv(n, it.n)
            return Retain(n, it.attributes)
        self._adv(n, it.n)
        return Delete(n)

    def take_insert(self) -> Insert:
        it = self.items[self.i]
        assert isinstance(it, Insert)
        v = it.value[self.off :]
        self.i += 1
        self.off = 0
        return Insert(v, it.attributes)

    def _adv(self, n: int, total: int) -> None:
        self.off += n
        if self.off >= total:
            self.i += 1
            self.off = 0


# ---------------------------------------------------------------------------
# Container diffs
# ---------------------------------------------------------------------------


@dataclass
class MapDiff:
    """key -> new value (None + key in `deleted` means removal)."""

    updated: Dict[str, Any] = field(default_factory=dict)
    deleted: set = field(default_factory=set)

    def compose(self, other: "MapDiff") -> "MapDiff":
        out = MapDiff(dict(self.updated), set(self.deleted))
        for k, v in other.updated.items():
            out.updated[k] = v
            out.deleted.discard(k)
        for k in other.deleted:
            out.updated.pop(k, None)
            out.deleted.add(k)
        return out

    def is_empty(self) -> bool:
        return not self.updated and not self.deleted


class TreeDiffAction(enum.Enum):
    Create = "create"
    Move = "move"
    Delete = "delete"


@dataclass(frozen=True)
class TreeDiffItem:
    target: TreeID
    action: TreeDiffAction
    parent: Optional[TreeID] = None  # None = root (for Create/Move)
    index: int = 0
    position: Optional[bytes] = None  # fractional index
    # where the node came from, for Move/Delete consumers (reference:
    # TreeExternalDiff::Move { old_parent, old_index })
    old_parent: Optional[TreeID] = None
    old_index: Optional[int] = None


@dataclass
class TreeDiff:
    items: List[TreeDiffItem] = field(default_factory=list)

    def compose(self, other: "TreeDiff") -> "TreeDiff":
        return TreeDiff(self.items + other.items)

    def is_empty(self) -> bool:
        return not self.items


@dataclass
class CounterDiff:
    delta: float = 0.0

    def compose(self, other: "CounterDiff") -> "CounterDiff":
        return CounterDiff(self.delta + other.delta)

    def is_empty(self) -> bool:
        return self.delta == 0.0


Diff = Union[Delta, MapDiff, TreeDiff, CounterDiff]


def compose_diff(a: Optional[Diff], b: Diff) -> Diff:
    if a is None:
        return b
    return a.compose(b)  # type: ignore[union-attr]


# ---------------------------------------------------------------------------
# Doc-level events
# ---------------------------------------------------------------------------


@dataclass
class ContainerDiff:
    id: ContainerID
    path: Tuple[Union[str, int], ...]  # key / index path from root
    diff: Diff


@dataclass
class DocDiff:
    """reference: event.rs DocDiff."""

    origin: str
    by: EventTriggerKind
    from_frontiers: Frontiers
    to_frontiers: Frontiers
    diffs: List[ContainerDiff] = field(default_factory=list)


Subscriber = Callable[[DocDiff], None]


class Observer:
    """Subscription registry (reference: subscription.rs)."""

    def __init__(self) -> None:
        self._root: Dict[int, Subscriber] = {}
        self._by_container: Dict[ContainerID, Dict[int, Subscriber]] = {}
        self._next = 0

    def subscribe_root(self, cb: Subscriber) -> Callable[[], None]:
        sid = self._next
        self._next += 1
        self._root[sid] = cb

        def unsub() -> None:
            self._root.pop(sid, None)

        return unsub

    def subscribe(self, cid: ContainerID, cb: Subscriber) -> Callable[[], None]:
        sid = self._next
        self._next += 1
        self._by_container.setdefault(cid, {})[sid] = cb

        def unsub() -> None:
            subs = self._by_container.get(cid)
            if subs:
                subs.pop(sid, None)
                if not subs:
                    self._by_container.pop(cid, None)

        return unsub

    def has_subscribers(self) -> bool:
        return bool(self._root) or bool(self._by_container)

    def emit(self, ev: DocDiff) -> None:
        for cb in list(self._root.values()):
            cb(ev)
        if not self._by_container:
            return
        for cd in ev.diffs:
            subs = self._by_container.get(cd.id)
            if subs:
                scoped = DocDiff(ev.origin, ev.by, ev.from_frontiers, ev.to_frontiers, [cd])
                for cb in list(subs.values()):
                    cb(scoped)
