"""Transaction: collects local ops, applies them to state immediately,
and packs them into one Change on commit.

reference: crates/loro-internal/src/txn.rs (single active txn per doc,
contiguous (peer, counter, lamport) assignment, txn.rs:548-650).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, TYPE_CHECKING

from .core.change import Change, MapSet, MovableSet, Op, OpContent, SeqInsert, TreeMove
from .core.ids import ContainerID, ID, TreeID
from .core.version import Frontiers
from .event import Diff
from .models.handlers import _ChildMarker, _TreeTargetMarker

if TYPE_CHECKING:  # pragma: no cover
    from .doc import LoroDoc


class Transaction:
    def __init__(self, doc: "LoroDoc", origin: str = ""):
        self.doc = doc
        self.origin = origin
        self.peer = doc.peer
        self.start_counter = doc.oplog.next_counter(doc.peer)
        self.next_counter = self.start_counter
        self.start_lamport = doc.oplog.next_lamport
        # detached-editable docs branch from the *state* version, not the
        # oplog head (reference: editable_detached_mode forks history)
        self.deps: Frontiers = (
            doc.state.frontiers if doc.is_detached() else doc.oplog.frontiers
        )
        self.start_frontiers: Frontiers = doc.state.frontiers
        self.ops: List[Op] = []
        self.diffs: Dict[ContainerID, List[Diff]] = {}
        self.message: Optional[str] = None
        # pre-commit subscribers may override (reference: ChangeModifier
        # sets commit message and timestamp)
        self.timestamp_override: Optional[int] = None

    # ------------------------------------------------------------------
    def apply(self, cid: ContainerID, content: OpContent) -> int:
        """Allocate ids for one op, apply it to state, buffer for commit.
        Returns the op's first counter (callers use it to derive child
        container ids / tree node ids)."""
        counter = self.next_counter
        content = self._resolve_markers(content, counter)
        op = Op(counter, cid, content)
        lamport = self.start_lamport + (counter - self.start_counter)
        self.doc.state._register_children(op, self.peer)
        st = self.doc.state.get_or_create(cid)
        st.materialized = True
        record = self.doc.observer.has_subscribers()
        d = st.apply_op(op, self.peer, lamport, record=record)
        # diff objects are only kept when someone will consume them
        # (reference skips event building with no subscribers)
        if d is not None and record:
            self.diffs.setdefault(cid, []).append(d)
        self.ops.append(op)
        self.next_counter += op.atom_len()
        return counter

    def is_empty(self) -> bool:
        return not self.ops

    def atom_len(self) -> int:
        return sum(op.ctr_end - op.counter for op in self.ops)

    def _resolve_markers(self, content: OpContent, counter: int) -> OpContent:
        """Replace handler-side child/tree markers with real ids — the
        child container id / tree node id is the op's own (peer, counter)."""
        if isinstance(content, MapSet) and isinstance(content.value, _ChildMarker):
            cid = ContainerID.normal(self.peer, counter, content.value.ctype)
            content.value.cid = cid
            return MapSet(content.key, cid, content.deleted)
        if isinstance(content, MovableSet) and isinstance(content.value, _ChildMarker):
            cid = ContainerID.normal(self.peer, counter, content.value.ctype)
            content.value.cid = cid
            return MovableSet(content.elem, cid)
        if isinstance(content, SeqInsert) and isinstance(content.content, tuple):
            if any(isinstance(v, _ChildMarker) for v in content.content):
                vals = []
                for j, v in enumerate(content.content):
                    if isinstance(v, _ChildMarker):
                        cid = ContainerID.normal(self.peer, counter + j, v.ctype)
                        v.cid = cid
                        vals.append(cid)
                    else:
                        vals.append(v)
                return SeqInsert(content.parent, content.side, tuple(vals))
        if isinstance(content, TreeMove) and isinstance(content.target, _TreeTargetMarker):
            return TreeMove(
                TreeID(self.peer, counter),
                content.parent,
                content.position,
                content.is_create,
                content.is_delete,
            )
        return content

    # ------------------------------------------------------------------
    def build_change(self) -> Optional[Change]:
        if not self.ops:
            return None
        if self.timestamp_override is not None:
            ts = self.timestamp_override
        else:
            # op timestamps are WIRE DATA (record_timestamp), not logic
            ts = int(time.time()) if self.doc.config.record_timestamp else 0  # tpulint: disable=LT-TIME(change timestamps are wire metadata, not scheduling logic)
        return Change(
            id=ID(self.peer, self.start_counter),
            lamport=self.start_lamport,
            deps=self.deps,
            ops=self.ops,
            timestamp=ts,
            message=self.message,
        )
