"""Presence plane: Awareness/EphemeralStore served over session fan-out.

`loro_tpu/awareness.py` ports the reference's presence types (peer ->
LWW state outside the CRDT history) but nothing *served* them — this
module is the serving side, riding the same session fan-out as delta
notifications while never touching the oplog or the device fleet:

- the server keeps ONE aggregated ``Awareness`` (every session's
  latest state) and ONE ``EphemeralStore`` (shared key->LWW values);
- a session publishes via ``set_state`` (server-encoded) or relays a
  client-encoded blob via ``broadcast``; either way the blob lands in
  every OTHER subscribed session's presence inbox verbatim — apply
  order does not matter (counter/timestamp LWW, the apply-order
  independence tests in tests/test_sync.py);
- **TTL expiry**: a departed session (closed, or idle past
  ``session_ttl``) has its peer dropped from the aggregated view and a
  departure blob (bumped counter, ``None`` state) fanned out so client
  views converge on the departure without waiting out their own local
  Awareness timeout.

Blob wire formats are `awareness.py`'s (magic ``LTAW`` / ``LTEP``);
a malformed relay raises the ValueError to the RELAYING session and is
never fanned out.
"""
from __future__ import annotations

import time
from typing import List, Optional

from ..awareness import Awareness, EphemeralStore
from ..obs import metrics as obs
from ..resilience import faultinject

faultinject.register_site(
    "session_stall", "presence fan-out delivery: delay one session's "
    "presence slot (shared with the delta fan-out site)")


class PresencePlane:
    """Owned by a SyncServer; all methods take the server lock.
    ``clock`` is the injectable presence wall clock, threaded into the
    Awareness/EphemeralStore LWW timestamps and TTL expiry (fake-clock
    tests drive expiry without sleeping)."""

    def __init__(self, server, ttl_s: float = 30.0, clock=None):
        self._server = server
        self.ttl_s = ttl_s
        self.clock = clock if clock is not None else time.time
        # the aggregated view: peer 0 is the server itself (it never
        # publishes state, so it never appears in the peers map)
        self.awareness = Awareness(peer=0, timeout_s=ttl_s, clock=self.clock)
        self.ephemeral = EphemeralStore(timeout_ms=int(ttl_s * 1000),
                                        clock=self.clock)

    # -- publishing ----------------------------------------------------
    def set_state(self, session, state) -> None:
        """Record ``state`` for the session's presence peer and fan the
        encoded single-peer blob out to the other subscribed sessions."""
        srv = self._server
        with srv._lock:
            session._touch()
            aw = self.awareness
            cur = aw.peers.get(session.peer)
            counter = (cur.counter + 1) if cur else 1
            from ..awareness import PeerInfo

            aw.peers[session.peer] = PeerInfo(state, counter, self.clock())
            blob = aw.encode([session.peer])
        self._fan_out(blob, origin=session)

    def broadcast(self, session, blob: bytes) -> None:
        """Relay a client-encoded blob: validate + apply it to the
        aggregated view (malformed -> ValueError to the relayer, no
        fan-out), then deliver verbatim to the other sessions."""
        srv = self._server
        with srv._lock:
            session._touch()
            if blob[:4] == b"LTEP":
                self.ephemeral.apply(bytes(blob))
            else:
                self.awareness.apply(bytes(blob))  # raises on bad magic
        self._fan_out(blob, origin=session)

    def _fan_out(self, blob: bytes, origin=None,
                 sessions: Optional[list] = None) -> None:
        srv = self._server
        with srv._lock:
            targets = sessions if sessions is not None else [
                s for s in srv._sessions.values()
                if s.subscribed and s is not origin and not s.closed
            ]
        n = 0
        for s in targets:
            # a stalled session delays only its own delivery slot
            faultinject.check("session_stall")
            with srv._lock:
                if not s.closed:
                    s._push_presence(blob)
                    n += 1
        with srv._lock:
            srv._wakeup.notify_all()
        obs.counter(
            "sync.presence_broadcasts_total",
            "presence blobs fanned out (per receiving session)",
        ).inc(n, family=srv.family)

    # -- departure / expiry --------------------------------------------
    def drop_peer(self, peer: int) -> None:
        """Forget a departed session's presence and fan out a departure
        blob (bumped counter, None state) so remote views converge."""
        srv = self._server
        with srv._lock:
            aw = self.awareness
            cur = aw.peers.pop(peer, None)
            if cur is None:
                return
            from ..awareness import PeerInfo

            # transient re-insert at a bumped counter so the encoded
            # departure wins LWW against the peer's last real state
            aw.peers[peer] = PeerInfo(None, cur.counter + 1, self.clock())
            blob = aw.encode([peer])
            del aw.peers[peer]
        self._fan_out(blob)

    def expire(self) -> List[int]:
        """Drop aggregated entries older than the TTL (sessions that
        died without disconnecting keep their last blob forever
        otherwise).  Returns the dropped peers.  Session-level expiry
        (replica floors etc.) is ``SyncServer.expire_sessions``."""
        with self._server._lock:
            dead = self.awareness.remove_outdated()
            self.ephemeral.remove_outdated()
        for p in dead:
            obs.counter(
                "sync.presence_expired_total",
                "presence peers dropped by TTL expiry",
            ).inc(family=self._server.family)
        return dead

    # -- reads ---------------------------------------------------------
    def states(self) -> dict:
        with self._server._lock:
            return self.awareness.get_all_states()

    def ephemeral_states(self) -> dict:
        with self._server._lock:
            return self.ephemeral.get_all_states()
