"""Bounded fan-in: many sessions' pushes -> batched ingest rounds.

Concurrent client sessions push single-doc update payloads at arbitrary
times; the resident path wants wide per-doc ROUNDS (one device launch
covers the whole fleet) and the pipeline executor wants several rounds
per coalesced group.  ``FanIn`` is the funnel between the two shapes:

- ``submit(di, payload, ...)`` enqueues one push and returns a
  ``PushTicket`` whose ``epoch()`` resolves once the push's round is
  committed (and, on a ``durable_fsync="group"`` server, fsync'd — an
  acked push is never lost to a crash);
- a single worker thread drains the queue into *batches*; the commit
  callback (``SyncServer._commit_batch``) packs a batch into rounds —
  one entry per doc per round, same-doc pushes spilling to the next
  round in FIFO order — and feeds them to the resident pipeline;
- the queue is BOUNDED: ``submit`` blocks at ``max_queue`` queued
  pushes (``sync.backpressure_waits_total``), so a stalled device
  propagates backpressure to the pushing sessions instead of
  accumulating unbounded staged work.  Nothing is ever dropped.

Failure contract mirrors ``parallel/pipeline.py``: a commit-callback
error fails every waiting ticket and closes the intake typed; per-push
data errors (poison payloads) are the commit callback's business — it
fails only the offending ticket (``errors.PushRejected``) and the rest
of the batch lands.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

from ..analysis.lockwitness import named_lock
from ..obs import metrics as obs


class PushTicket:
    """Handle for one submitted push: ``epoch()`` blocks until the
    push's round committed and returns the visible epoch to ack.

    Request tracing (docs/OBSERVABILITY.md): ``trace_id`` is minted at
    push entry and carried through every stage; ``marks`` accumulates
    ``(stage_name, perf_counter)`` pairs at the stage BOUNDARIES the
    push crosses (fan-in dequeue, pipeline stage/commit, fsync,
    visibility), so ``breakdown()`` telescopes them into per-stage
    durations that sum EXACTLY to the push-to-visible total."""

    __slots__ = ("_ev", "_epoch", "_error", "t0", "trace_id", "marks")

    def __init__(self, trace_id: Optional[str] = None):
        self._ev = threading.Event()
        self._epoch: Optional[int] = None
        self._error: Optional[BaseException] = None
        self.t0 = time.perf_counter()  # push-to-visible clock start
        self.trace_id = trace_id
        self.marks: List[tuple] = []   # (stage_name, t) in crossing order

    def mark(self, stage: str, t: Optional[float] = None) -> None:
        self.marks.append((stage, time.perf_counter() if t is None else t))

    def _resolve(self, epoch: int) -> None:
        self._epoch = epoch
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    @property
    def done(self) -> bool:
        return self._ev.is_set()

    def epoch(self, timeout: Optional[float] = None) -> int:
        if not self._ev.wait(timeout):
            raise TimeoutError("push not committed yet")
        if self._error is not None:
            raise self._error
        return self._epoch

    def breakdown(self) -> dict:
        """Per-stage timing attribution (milliseconds): the durations
        between consecutive marks, named by the stage each mark closes,
        plus ``total_ms`` (creation -> last mark).  Telescoping by
        construction: ``sum(stages) == total_ms`` exactly (the chaos
        ``attribution`` invariant gates this).  Stages a path skipped
        (e.g. no pipeline -> no stage/coalesce split) are absent."""
        out: dict = {"trace_id": self.trace_id}
        prev = self.t0
        for name, t in self.marks:
            out[f"{name}_ms"] = (t - prev) * 1e3
            prev = t
        out["total_ms"] = (prev - self.t0) * 1e3
        return out


class FanIn:
    """Bounded push queue + single drain worker.

    ``commit``: callable taking a list of ``(di, payload, ticket,
    session)`` items (one drained batch, FIFO); it must resolve or fail
    every ticket it is handed.  ``max_queue``: backpressure bound;
    ``max_batch``: most items handed to one commit call (default: the
    queue bound, so one drain can cover a full queue).
    """

    def __init__(self, commit, max_queue: int = 64,
                 max_batch: Optional[int] = None, family: str = ""):
        self._commit = commit
        self._max_queue = max(1, int(max_queue))
        self._max_batch = (
            self._max_queue if max_batch is None else max(1, int(max_batch))
        )
        self._family = family
        self._lock = named_lock("fanin.queue")
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()  # (di, payload, ticket, session)
        self._busy = False        # worker inside a commit call
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        # count-based report (the bench `sync` sidecar + test guards)
        self._pushes = 0
        self._batches = 0
        self._max_batch_seen = 0
        self._max_queue_seen = 0
        self._backpressure_waits = 0

    # -- producer side -------------------------------------------------
    def submit(self, di: int, payload, ticket: PushTicket, session=None) -> None:
        with self._cv:
            self._check_open()
            if len(self._q) >= self._max_queue:
                self._backpressure_waits += 1
                obs.counter(
                    "sync.backpressure_waits_total",
                    "pushes that blocked on the bounded fan-in queue",
                ).inc(family=self._family)
            while len(self._q) >= self._max_queue and self._error is None \
                    and not self._stop:
                self._cv.wait()
            self._check_open()
            self._q.append((di, payload, ticket, session))
            self._pushes += 1
            self._max_queue_seen = max(self._max_queue_seen, len(self._q))
            obs.gauge(
                "sync.fanin_depth", "pushes queued behind the fan-in worker"
            ).set(len(self._q), family=self._family)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="loro-sync-fanin", daemon=True
                )
                self._thread.start()
            self._cv.notify_all()

    def _check_open(self) -> None:
        if self._stop:
            raise RuntimeError("sync fan-in is closed")
        if self._error is not None:
            raise RuntimeError(
                "sync fan-in failed; no further pushes accepted"
            ) from self._error

    def flush(self) -> None:
        """Block until every submitted push has been committed (its
        ticket resolved or failed).  Re-raises the worker error."""
        if threading.current_thread() is self._thread:
            return
        with self._cv:
            while (self._q or self._busy) and self._error is None:
                self._cv.wait()
            if self._error is not None:
                raise RuntimeError("sync fan-in failed") from self._error

    def close(self) -> None:
        """Drain, then stop the worker.  Idempotent."""
        err = None
        try:
            self.flush()
        except RuntimeError as e:
            err = e
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and threading.current_thread() is not t:
            t.join(timeout=30.0)
        if err is not None:
            raise err

    @property
    def closed(self) -> bool:
        return self._stop

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop and self._error is None:
                    self._cv.notify_all()  # wake flushers: idle
                    self._cv.wait()
                if (self._stop and not self._q) or self._error is not None:
                    self._cv.notify_all()
                    return
                batch: List[tuple] = []
                while self._q and len(batch) < self._max_batch:
                    batch.append(self._q.popleft())
                now = time.perf_counter()
                for _di, _pl, tk, _s in batch:
                    # attribution: time queued behind the fan-in worker
                    tk.mark("queue_wait", now)
                self._busy = True
                self._batches += 1
                self._max_batch_seen = max(self._max_batch_seen, len(batch))
                obs.gauge(
                    "sync.fanin_depth",
                    "pushes queued behind the fan-in worker",
                ).set(len(self._q), family=self._family)
                self._cv.notify_all()  # backpressured producers refill
            try:
                self._commit(batch)
            except BaseException as e:  # noqa: BLE001 — fail every waiter
                with self._cv:
                    self._error = e
                    self._busy = False
                    for _di, _pl, tk, _s in batch:
                        if not tk.done:
                            tk._fail(e)
                    while self._q:
                        _di, _pl, tk, _s = self._q.popleft()
                        tk._fail(e)
                    self._cv.notify_all()
                obs.counter(
                    "sync.fanin_errors_total",
                    "fan-in commit batches that raised (intake closed)",
                ).inc(family=self._family)
                return
            with self._cv:
                self._busy = False
                self._cv.notify_all()

    # -- reporting -----------------------------------------------------
    def report(self) -> dict:
        with self._lock:
            return {
                "pushes": self._pushes,
                "batches": self._batches,
                "max_batch": self._max_batch_seen,
                "queue_bound": self._max_queue,
                "max_queue_seen": self._max_queue_seen,
                "backpressure_waits": self._backpressure_waits,
            }
