"""Session-oriented sync front-end (docs/SYNC.md).

``SyncServer`` fronts a ``ResidentServer`` with many concurrent client
sessions: per-session version vectors, delta export since the client
frontier (``Session.pull``), batched fan-in of pushes into pipelined
ingest rounds with backpressure (``fanin.FanIn``), fan-out of committed
epochs as delta notifications, and an ephemeral presence plane
(``presence.PresencePlane`` over ``loro_tpu.awareness``).

Reads ride the batched device read plane by default
(``readbatch.ReadBatcher`` — concurrent ``Session.pull``s coalesce
into one vmapped export launch, byte-identical to the oracle export;
``read_batch=False`` keeps every pull on the per-doc oracle).

Typed errors live in ``loro_tpu.errors``: ``SyncError``,
``PushRejected``, ``StaleFrontier``, ``SessionClosed``.
"""
from ..errors import PushRejected, SessionClosed, StaleFrontier, SyncError
from .fanin import FanIn, PushTicket
from .presence import PresencePlane
from .readbatch import PullTicket, ReadBatcher
from .server import SyncServer
from .session import Session

__all__ = [
    "SyncServer",
    "Session",
    "FanIn",
    "PushTicket",
    "PullTicket",
    "ReadBatcher",
    "PresencePlane",
    "SyncError",
    "PushRejected",
    "StaleFrontier",
    "SessionClosed",
]
