"""Session-oriented sync front-end (docs/SYNC.md).

``SyncServer`` fronts a ``ResidentServer`` with many concurrent client
sessions: per-session version vectors, delta export since the client
frontier (``Session.pull``), batched fan-in of pushes into pipelined
ingest rounds with backpressure (``fanin.FanIn``), fan-out of committed
epochs as delta notifications, and an ephemeral presence plane
(``presence.PresencePlane`` over ``loro_tpu.awareness``).

Typed errors live in ``loro_tpu.errors``: ``SyncError``,
``PushRejected``, ``StaleFrontier``, ``SessionClosed``.
"""
from ..errors import PushRejected, SessionClosed, StaleFrontier, SyncError
from .fanin import FanIn, PushTicket
from .presence import PresencePlane
from .server import SyncServer
from .session import Session

__all__ = [
    "SyncServer",
    "Session",
    "FanIn",
    "PushTicket",
    "PresencePlane",
    "SyncError",
    "PushRejected",
    "StaleFrontier",
    "SessionClosed",
]
