"""One client session of a SyncServer (docs/SYNC.md).

A session is the server-side half of one connected client replica:

- a per-doc **client version vector** — what the client is known to
  hold.  ``pull(di)`` exports only the delta since that frontier
  (``ExportMode.Updates`` on the per-doc oracle — columnar-updates
  bytes a stock client ``import_()``s), then advances the frontier and
  acks the covered epoch into the resident compaction floors;
- ``push(di, data)`` feeds the client's own update bytes through the
  server's bounded fan-in (``fanin.PushTicket`` resolves at commit);
- a **delta-notification plane**: committed epochs mark the session's
  dirty-doc set (self-coalescing — a slow reader accumulates one flag
  per doc, never an unbounded event log) and ``poll()`` waits on it;
- a **presence inbox**: Awareness/EphemeralStore blobs broadcast by
  other sessions (bounded, drop-oldest — presence is ephemeral by
  definition, docs/SYNC.md "Presence plane").

First-sync contract: when the server oracle is *shallow* (its history
floor was trimmed by the checkpoint ladder — every recovered server is)
and the client frontier sits below that floor, a delta cannot exist.
An EMPTY client gets the documented first-sync path instead: ``pull``
returns a full snapshot (the oracle's shallow base rides along, a
fresh ``LoroDoc`` imports it directly).  A NON-empty client below the
floor raises typed ``errors.StaleFrontier`` — it must resync from a
fresh doc.  (Before this path existed, ``_export_shallow`` raised a
bare ``LoroError`` at the caller.)
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from ..core.version import VersionVector
from ..errors import SessionClosed, StaleFrontier
from ..obs import metrics as obs
from ..resilience import faultinject

# presence inbox bound: a session that never polls drops its OLDEST
# presence blobs (counted) — presence is last-writer-wins ephemeral
# state, so the newest blobs are the ones that matter
PRESENCE_INBOX_CAP = 256


class Session:
    """Construct via ``SyncServer.connect()`` (never directly): the
    server owns the registry, replica registration and presence
    lifecycle this object participates in."""

    def __init__(self, server, sid: str, peer: int, subscribe: bool = True):
        self._server = server
        self.sid = sid
        self.peer = peer  # presence-plane peer id (never a CRDT peer)
        self.subscribed = subscribe
        self.closed = False
        self.last_seen = time.monotonic()
        self._polling = 0  # threads blocked in poll(): never TTL-idle
        # di -> VersionVector the client is known to hold
        self._vv: Dict[int, VersionVector] = {}
        # committed docs the client has not pulled yet (self-coalescing)
        self._dirty: Dict[int, int] = {}  # di -> newest committed epoch
        self._presence: deque = deque()   # encoded presence blobs
        self._dropped_presence = 0

    # -- internal (called by the server under its lock) ----------------
    def _touch(self) -> None:
        self.last_seen = time.monotonic()

    def _check_open(self) -> None:
        if self.closed:
            raise SessionClosed(f"session {self.sid!r} is closed")

    def _mark_dirty(self, di: int, epoch: int) -> None:
        if self._dirty.get(di, -1) < epoch:
            self._dirty[di] = epoch

    def _push_presence(self, blob: bytes) -> None:
        if len(self._presence) >= PRESENCE_INBOX_CAP:
            self._presence.popleft()
            self._dropped_presence += 1
            obs.counter(
                "sync.presence_dropped_total",
                "presence blobs dropped from slow sessions' inboxes",
            ).inc(family=self._server.family)
        self._presence.append(blob)

    # -- sync ----------------------------------------------------------
    def push(self, di: int, data: bytes):
        """Queue the client's update bytes (a ``doc.export_updates``
        blob) for doc ``di``.  Returns a ``fanin.PushTicket``; blocks
        only on fan-in backpressure.  Malformed envelopes raise typed
        ``errors.PushRejected`` here, before anything is queued."""
        self._check_open()
        return self._server._push(self, di, data)

    def pull(self, di: int, to_frontiers=None) -> bytes:
        """Delta since this client's frontier for doc ``di`` as
        columnar-updates bytes (``client_doc.import_()`` them), or the
        first-sync snapshot when the oracle is shallow and the client
        is empty.  ``to_frontiers`` bounds the delta
        (``ExportMode.UpdatesInRange``) — e.g. replaying up to a known
        stable point; default is everything the server holds.  Advances
        the client frontier and acks the covered epoch."""
        from ..doc import ExportMode

        self._check_open()
        faultinject.check("sync_pull", doc=di)
        srv = self._server
        with srv._lock:
            self._touch()
            d = srv._oracle.docs[di]
            from_vv = self._vv.get(di) or VersionVector()
            first_sync = False
            if d.is_shallow() and not (d.shallow_since_vv() <= from_vv):
                if len(from_vv) == 0:
                    # documented first-sync path: full snapshot (the
                    # shallow base rides along; a fresh doc imports it)
                    first_sync = True
                    data = d.export(ExportMode.Snapshot)
                    new_vv = d.oplog_vv()
                    obs.counter(
                        "sync.first_sync_snapshots_total",
                        "pulls served as snapshots (client below the "
                        "oracle's shallow root)",
                    ).inc(family=srv.family)
                else:
                    raise StaleFrontier(
                        f"doc {di}: client frontier {from_vv.to_json()} is "
                        "below the server oracle's shallow root "
                        f"{d.shallow_since_vv().to_json()} — history there "
                        "was trimmed; resync from a fresh doc (empty "
                        "frontier pulls take the first-sync snapshot path)"
                    )
            elif to_frontiers is not None:
                to_vv = d.oplog.dag.frontiers_to_vv(to_frontiers)
                data = d.export(ExportMode.UpdatesInRange(from_vv, to_vv))
                new_vv = from_vv.copy()
                for peer, end in to_vv.items():
                    if end > new_vv.get(peer):
                        new_vv.set_end(peer, end)
            else:
                data = d.export(ExportMode.Updates(from_vv))
                new_vv = d.oplog_vv()
            self._vv[di] = new_vv
            if to_frontiers is None:
                self._dirty.pop(di, None)
                # a FULL pull covers everything committed: ack it into
                # the compaction floors.  A bounded pull integrates
                # strictly less — acking the committed epoch for it
                # would let compact() reclaim rows this client still
                # needs (ResidentServer.ack's contract), so it never
                # acks and the dirty flag survives for the catch-up
                srv._ack(self, di)
        obs.counter("sync.pulls_total").inc(
            family=srv.family, kind="snapshot" if first_sync else "delta"
        )
        obs.histogram(
            "sync.pull_bytes", "bytes served per pull",
            buckets=(64, 256, 1024, 4096, 16384, 65536, 262144, 1048576),
        ).observe(len(data), family=srv.family)
        return data

    def frontier(self, di: int) -> VersionVector:
        """The client's known frontier for doc ``di`` (copy)."""
        vv = self._vv.get(di)
        return vv.copy() if vv is not None else VersionVector()

    # -- notifications -------------------------------------------------
    def poll(self, timeout: Optional[float] = None) -> dict:
        """Wait up to ``timeout`` for activity, then drain it:
        ``{"docs": {di: newest_epoch, ...}, "presence": [blobs...]}``.
        Empty dict members mean nothing happened (timeout).  The docs
        map is self-coalesced: however many epochs landed since the
        last poll, the client does ONE pull per dirty doc."""
        self._check_open()
        srv = self._server
        deadline = None if timeout is None else time.monotonic() + timeout
        with srv._lock:
            self._touch()
            # a BLOCKED poller is not idle: TTL expiry skips sessions
            # with a live poll (expire_sessions), so a quiet reader is
            # never disconnected mid-wait
            self._polling += 1
            try:
                while not self._dirty and not self._presence:
                    if deadline is None:
                        srv._wakeup.wait()
                    else:
                        left = deadline - time.monotonic()
                        if left <= 0 or not srv._wakeup.wait(left):
                            break
                    self._check_open()
            finally:
                self._polling -= 1
                self._touch()
            docs = dict(self._dirty)
            self._dirty.clear()
            presence = list(self._presence)
            self._presence.clear()
        return {"docs": docs, "presence": presence}

    def dirty_docs(self) -> Dict[int, int]:
        """Non-blocking view of docs with unpulled commits."""
        with self._server._lock:
            return dict(self._dirty)

    # -- presence ------------------------------------------------------
    def set_presence(self, state) -> None:
        """Publish this session's presence state (cursor, name, ...) to
        every other subscribed session.  Never touches the oplog."""
        self._check_open()
        self._server.presence.set_state(self, state)

    def broadcast_presence(self, blob: bytes) -> None:
        """Relay a client-encoded Awareness or EphemeralStore blob."""
        self._check_open()
        self._server.presence.broadcast(self, blob)

    def presence_states(self) -> dict:
        """The server's aggregated presence view (peer -> state)."""
        return self._server.presence.states()

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._server.disconnect(self)

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
