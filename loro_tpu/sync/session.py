"""One client session of a SyncServer (docs/SYNC.md).

A session is the server-side half of one connected client replica:

- a per-doc **client version vector** — what the client is known to
  hold.  ``pull(di)`` exports only the delta since that frontier
  (``ExportMode.Updates`` on the per-doc oracle — columnar-updates
  bytes a stock client ``import_()``s), then advances the frontier and
  acks the covered epoch into the resident compaction floors;
- ``push(di, data)`` feeds the client's own update bytes through the
  server's bounded fan-in (``fanin.PushTicket`` resolves at commit);
- a **delta-notification plane**: committed epochs mark the session's
  dirty-doc set (self-coalescing — a slow reader accumulates one flag
  per doc, never an unbounded event log) and ``poll()`` waits on it;
- a **presence inbox**: Awareness/EphemeralStore blobs broadcast by
  other sessions (bounded, drop-oldest — presence is ephemeral by
  definition, docs/SYNC.md "Presence plane").

First-sync contract: when the server oracle is *shallow* (its history
floor was trimmed by the checkpoint ladder — every recovered server is)
and the client frontier sits below that floor, a delta cannot exist.
An EMPTY client gets the documented first-sync path instead: ``pull``
returns a full snapshot (the oracle's shallow base rides along, a
fresh ``LoroDoc`` imports it directly).  A NON-empty client below the
floor raises typed ``errors.StaleFrontier`` — it must resync from a
fresh doc.  (Before this path existed, ``_export_shallow`` raised a
bare ``LoroError`` at the caller.)
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from ..core.version import VersionVector
from ..errors import SessionClosed
from ..obs import flight
from ..obs import heat as heat_acct
from ..obs import metrics as obs
from ..resilience import faultinject
from ..utils import tracing

faultinject.register_site(
    "sync_pull", "Session.pull: raise/delay before the delta export "
    "(client-visible read-path failures)")

# presence inbox bound: a session that never polls drops its OLDEST
# presence blobs (counted) — presence is last-writer-wins ephemeral
# state, so the newest blobs are the ones that matter
PRESENCE_INBOX_CAP = 256


class Session:
    """Construct via ``SyncServer.connect()`` (never directly): the
    server owns the registry, replica registration and presence
    lifecycle this object participates in."""

    def __init__(self, server, sid: str, peer: int, subscribe: bool = True):
        self._server = server
        self.sid = sid
        self.peer = peer  # presence-plane peer id (never a CRDT peer)
        self.subscribed = subscribe
        self.closed = False
        self.last_seen = time.monotonic()
        self._polling = 0  # threads blocked in poll(): never TTL-idle
        # di -> VersionVector the client is known to hold
        self._vv: Dict[int, VersionVector] = {}
        # committed docs the client has not pulled yet (self-coalescing)
        self._dirty: Dict[int, int] = {}  # di -> newest committed epoch
        self._presence: deque = deque()   # encoded presence blobs
        self._dropped_presence = 0
        # attribution of the session's most recent pull (trace id,
        # serving path, per-stage ms — docs/OBSERVABILITY.md "Request
        # tracing"); None until the first pull
        self.last_pull: Optional[dict] = None

    # -- internal (called by the server under its lock) ----------------
    def _touch(self) -> None:
        self.last_seen = time.monotonic()

    def _check_open(self) -> None:
        if self.closed:
            raise SessionClosed(f"session {self.sid!r} is closed")

    def _mark_dirty(self, di: int, epoch: int) -> None:
        if self._dirty.get(di, -1) < epoch:
            self._dirty[di] = epoch

    def _push_presence(self, blob: bytes) -> None:
        if len(self._presence) >= PRESENCE_INBOX_CAP:
            self._presence.popleft()
            self._dropped_presence += 1
            obs.counter(
                "sync.presence_dropped_total",
                "presence blobs dropped from slow sessions' inboxes",
            ).inc(family=self._server.family)
        self._presence.append(blob)

    # -- sync ----------------------------------------------------------
    def push(self, di: int, data: bytes):
        """Queue the client's update bytes (a ``doc.export_updates``
        blob) for doc ``di``.  Returns a ``fanin.PushTicket``; blocks
        only on fan-in backpressure.  Malformed envelopes raise typed
        ``errors.PushRejected`` here, before anything is queued."""
        self._check_open()
        return self._server._push(self, di, data)

    def pull(self, di: int, to_frontiers=None, min_epoch=None,
             wait_s: float = 5.0) -> bytes:
        """Delta since this client's frontier for doc ``di`` as
        columnar-updates bytes (``client_doc.import_()`` them), or the
        first-sync snapshot when the oracle is shallow and the client
        is empty.  ``to_frontiers`` bounds the delta
        (``ExportMode.UpdatesInRange``) — e.g. replaying up to a known
        stable point; default is everything the server holds.  Advances
        the client frontier and acks the covered epoch.

        ``min_epoch=`` is the read-your-writes gate (docs/REPLICATION.md):
        block up to ``wait_s`` until the server's committed epoch
        reaches it — pass a push ticket's epoch to read your own write
        from a replication follower; typed ``errors.ReplicaLag`` on
        timeout.  Trivial on a leader (tickets resolve at/after the
        committed epoch).

        Batchable pulls (unbounded, frontier at/above the read-plane
        floor, not a shallow first-sync case) coalesce with concurrent
        pulls into one device export launch through the server's
        ``ReadBatcher`` — byte-identical to the oracle export, served
        off the oracle transparently on device failure (docs/SYNC.md
        "Read plane").  Everything else stays on the per-doc oracle."""
        self._check_open()
        faultinject.check("sync_pull", doc=di)
        srv = self._server
        trace_id = tracing.new_trace_id("g")
        t_pull0 = time.perf_counter()
        if min_epoch is not None:
            self._wait_min_epoch(di, int(min_epoch), wait_s)
        tk = hit = None
        with srv._lock:
            self._touch()
            from_vv = self._vv.get(di) or VersionVector()
            if to_frontiers is None and srv._route_device(di, from_vv):
                # inline fast path first: a frame already cut at this
                # (doc, frontier) since the doc's last commit serves
                # without a window round-trip (the reader fan-out case)
                hit = srv._readbatch.try_cached(di, from_vv)
                if hit is None:
                    from ..errors import SyncError

                    try:
                        # enqueue under the lock (frontier snapshot is
                        # atomic with the routing decision); the window
                        # drive runs OUTSIDE it
                        tk = srv._readbatch.submit(
                            di, from_vv.copy(), trace=trace_id
                        )
                    except SyncError:
                        tk = None  # closed under us: oracle path below
        if tk is not None or hit is not None:
            data, new_vv, epoch = (
                hit if hit is not None else srv._readbatch.drive(tk)
            )
            stages = dict(tk.stages) if tk is not None and tk.stages \
                else {"cache_hit": True}
            if hit is not None or stages.get("cache_hit"):
                path = "cache"
            elif stages.get("degraded"):
                path = "oracle_degraded"
            elif stages.get("rerouted"):
                path = "oracle_reroute"
            else:
                path = "device"
            stages.update(
                trace_id=trace_id, path=path,
                total_ms=(time.perf_counter() - t_pull0) * 1e3,
            )
            self.last_pull = stages
            flight.record("sync.pull", family=srv.family, doc=di,
                          trace=trace_id, path=path, bytes=len(data))
            with srv._lock:
                self._touch()
                cur = self._vv.get(di)
                if cur is not None:
                    # never regress: a push of ours may have committed
                    # (and advanced the frontier) while the window ran
                    new_vv.merge(cur)
                self._vv[di] = new_vv
                # the window covers `epoch`; a commit landing after its
                # snapshot re-marked the doc — keep that flag alive
                if self._dirty.get(di, -1) <= epoch:
                    self._dirty.pop(di, None)
                srv._ack_at(self, di, epoch)
            heat_acct.tick_doc(di, "pull")
            obs.counter("sync.pulls_total").inc(family=srv.family, kind="delta")
            obs.counter(
                "sync.pulls_batched_total",
                "pulls served by the batched device read plane",
            ).inc(family=srv.family)
            obs.histogram(
                "sync.pull_bytes", "bytes served per pull",
                buckets=(64, 256, 1024, 4096, 16384, 65536, 262144, 1048576),
            ).observe(len(data), family=srv.family)
            return data
        t_o0 = time.perf_counter()
        with srv._lock:
            self._touch()
            from_vv = self._vv.get(di) or VersionVector()
            data, new_vv, first_sync = srv._oracle_pull(
                di, from_vv, to_frontiers
            )
            self._vv[di] = new_vv
            if to_frontiers is None:
                self._dirty.pop(di, None)
                # a FULL pull covers everything committed: ack it into
                # the compaction floors.  A bounded pull integrates
                # strictly less — acking the committed epoch for it
                # would let compact() reclaim rows this client still
                # needs (ResidentServer.ack's contract), so it never
                # acks and the dirty flag survives for the catch-up
                srv._ack(self, di)
        now = time.perf_counter()
        self.last_pull = {
            "trace_id": trace_id,
            "path": "snapshot" if first_sync else "oracle",
            "oracle_ms": (now - t_o0) * 1e3,
            "total_ms": (now - t_pull0) * 1e3,
        }
        flight.record("sync.pull", family=srv.family, doc=di,
                      trace=trace_id, path=self.last_pull["path"],
                      bytes=len(data))
        heat_acct.tick_doc(di, "pull")
        obs.counter("sync.pulls_total").inc(
            family=srv.family, kind="snapshot" if first_sync else "delta"
        )
        obs.histogram(
            "sync.pull_bytes", "bytes served per pull",
            buckets=(64, 256, 1024, 4096, 16384, 65536, 262144, 1048576),
        ).observe(len(data), family=srv.family)
        return data

    def _wait_min_epoch(self, di: int, min_epoch: int,
                        wait_s: float) -> None:
        """Block until the server's committed epoch reaches
        ``min_epoch`` (replicated applies and local commits both
        notify the wakeup condition); typed ``ReplicaLag`` on
        timeout."""
        srv = self._server
        deadline = time.monotonic() + max(0.0, wait_s)
        with srv._lock:
            while srv._committed_epoch < min_epoch:
                left = deadline - time.monotonic()
                if left <= 0:
                    from ..errors import ReplicaLag

                    obs.counter(
                        "repl.min_epoch_timeouts_total",
                        "pull(min_epoch=) gates that timed out lagging",
                    ).inc(family=srv.family)
                    raise ReplicaLag(
                        f"doc {di}: committed epoch "
                        f"{srv._committed_epoch} < min_epoch "
                        f"{min_epoch} after {wait_s}s — the replica is "
                        "lagging; retry, or pull from the leader"
                    )
                srv._wakeup.wait(left)
                self._check_open()

    def frontier(self, di: int) -> VersionVector:
        """The client's known frontier for doc ``di`` (copy)."""
        vv = self._vv.get(di)
        return vv.copy() if vv is not None else VersionVector()

    # -- notifications -------------------------------------------------
    def poll(self, timeout: Optional[float] = None) -> dict:
        """Wait up to ``timeout`` for activity, then drain it:
        ``{"docs": {di: newest_epoch, ...}, "presence": [blobs...]}``.
        Empty dict members mean nothing happened (timeout).  The docs
        map is self-coalesced: however many epochs landed since the
        last poll, the client does ONE pull per dirty doc."""
        self._check_open()
        srv = self._server
        deadline = None if timeout is None else time.monotonic() + timeout
        with srv._lock:
            self._touch()
            # a BLOCKED poller is not idle: TTL expiry skips sessions
            # with a live poll (expire_sessions), so a quiet reader is
            # never disconnected mid-wait
            self._polling += 1
            try:
                while not self._dirty and not self._presence:
                    if deadline is None:
                        srv._wakeup.wait()
                    else:
                        left = deadline - time.monotonic()
                        if left <= 0 or not srv._wakeup.wait(left):
                            break
                    self._check_open()
            finally:
                self._polling -= 1
                self._touch()
            docs = dict(self._dirty)
            self._dirty.clear()
            presence = list(self._presence)
            self._presence.clear()
        return {"docs": docs, "presence": presence}

    def dirty_docs(self) -> Dict[int, int]:
        """Non-blocking view of docs with unpulled commits."""
        with self._server._lock:
            return dict(self._dirty)

    # -- presence ------------------------------------------------------
    def set_presence(self, state) -> None:
        """Publish this session's presence state (cursor, name, ...) to
        every other subscribed session.  Never touches the oplog."""
        self._check_open()
        self._server.presence.set_state(self, state)

    def broadcast_presence(self, blob: bytes) -> None:
        """Relay a client-encoded Awareness or EphemeralStore blob."""
        self._check_open()
        self._server.presence.broadcast(self, blob)

    def presence_states(self) -> dict:
        """The server's aggregated presence view (peer -> state)."""
        return self._server.presence.states()

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._server.disconnect(self)

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
