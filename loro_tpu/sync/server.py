"""SyncServer: session-oriented sync front-end over a ResidentServer.

This is the L6 serving layer (SURVEY §1) the resident stack was built
for: many concurrent client sessions speaking the existing columnar
updates wire format to one resident fleet.  Three planes:

- **fan-in** (``fanin.FanIn``): sessions push single-doc update bytes;
  a bounded queue batches concurrent pushes into per-doc ingest rounds
  (one entry per doc per round, same-doc pushes spill FIFO to the next
  round) and feeds them to ``ResidentServer.pipeline()`` as coalesced
  groups — one device launch per group, backpressure to the pushers
  when the queue is full.  Each push's ``PushTicket`` resolves with the
  round's visible epoch at commit, and never before the WAL fsync
  covering it on a ``durable_fsync="group"`` server (an acked push is
  never lost to a crash).
- **fan-out**: committed epochs mark every subscribed session's
  dirty-doc set (self-coalescing) and wake ``poll()``ers; sessions
  then ``pull()`` the delta since their own frontier from the per-doc
  **oracle** — host ``LoroDoc`` mirrors fed the exact same rounds the
  device batch ingests (byte-identical by the differential-fuzz
  contract), seeded from the resident's mirror anchor + journal so a
  ``persist.recover_server`` reopen serves deltas immediately.
- **presence** (``presence.PresencePlane``): Awareness/EphemeralStore
  blobs broadcast through the same session fan-out with TTL expiry,
  never touching the oplog.

Degradation composes: a DeviceFailure inside resident ingest degrades
the epoch to the resident's host mirror transparently (pushes keep
committing, pulls keep serving); a poison push fails only ITS ticket
(typed ``errors.PushRejected``); fault sites ``sync_push`` /
``sync_pull`` / ``session_stall`` inject at the new choke points
(docs/SYNC.md, docs/RESILIENCE.md).

The paper anchor: serving OT/CRDT merges to many sessions at arbitrary
scale and latency (Operational Concurrency Control..., PAPERS.md); the
delta-since-frontier export is eg-walker's version-vector machinery
(PAPERS.md) as implemented by the oplog.
"""
from __future__ import annotations

import struct
import threading
import time
from typing import Dict, List, Optional

from ..errors import DecodeError, PushRejected, StaleFrontier, SyncError
from ..analysis.lockwitness import named_rlock
from ..obs import flight
from ..obs import heat as heat_acct
from ..obs import metrics as obs
from ..resilience import faultinject
from ..utils import tracing
from .fanin import FanIn, PushTicket

faultinject.register_site(
    "sync_push", "SyncServer push entry: raise/delay before the fan-in "
    "queue, or mangle the client's update bytes (typed PushRejected)")
faultinject.register_site(
    "session_stall", "sync fan-out delivery: delay one session's "
    "notification slot (slow-consumer backpressure)")
from .presence import PresencePlane
from .session import Session

_DATA_ERRORS = (ValueError, TypeError, KeyError, IndexError, struct.error)


class SyncServer:
    """Session front-end over one resident family.

    ``SyncServer(family, n_docs, cid=..., **caps)`` builds and owns a
    fresh ``ResidentServer`` (capacity/durability kwargs pass through);
    ``SyncServer.over(resident)`` fronts an existing one — e.g. the
    server ``persist.recover_server`` returns — without owning its
    lifecycle.  ``cid`` is the served container id (required for the
    positional families, same contract as ``ResidentServer.ingest``;
    map/counter need none; a recovered server already knows its cid).

    ``pipeline=True`` routes fan-in batches through a
    ``PipelinedIngest`` executor (round coalescing + host/device
    overlap); ``False`` falls back to ``ingest_coalesced`` — byte-
    identical state either way.  ``max_queue`` bounds the fan-in
    (backpressure); ``session_ttl`` seconds of idleness expires a
    session (replica floors dropped, presence departure fanned out).

    Thread contract: any number of session threads may push/pull/poll
    concurrently; reads (``texts()``...) flush the fan-in first.
    """

    def __init__(self, family: Optional[str] = None,
                 n_docs: Optional[int] = None, mesh=None, cid=None,
                 resident=None, pipeline: bool = True, coalesce: int = 8,
                 depth: int = 2, max_queue: int = 64,
                 session_ttl: float = 30.0, read_batch: bool = True,
                 **caps):
        if resident is None:
            from ..parallel.server import ResidentServer

            if family is None or n_docs is None:
                raise ValueError(
                    "SyncServer needs (family, n_docs) to build a resident "
                    "server, or resident=/.over() to front an existing one"
                )
            resident = ResidentServer(family, n_docs, mesh=mesh, **caps)
            self._own_resident = True
        else:
            if caps:
                raise ValueError(
                    "capacity kwargs only apply when SyncServer builds the "
                    f"resident server itself (got {sorted(caps)})"
                )
            self._own_resident = False
        self.resident = resident
        self.family = resident.family
        self.n_docs = resident.n_docs
        self.cid = cid if cid is not None else resident._cid
        if self.family not in ("map", "counter") and self.cid is None:
            raise ValueError(
                f"{self.family} SyncServer needs the served container id "
                "(cid=), same contract as ResidentServer.ingest"
            )
        self._lock = named_rlock("sync.server")
        self._wakeup = threading.Condition(self._lock)
        self._oracle = self._seed_oracle()
        # newest epoch the ORACLE reflects (pulls/acks key on this; the
        # resident's own clock may run ahead mid-batch)
        self._committed_epoch = resident.epoch
        # per-doc oracle head VV, cached per committed epoch (rebuilt
        # lazily, invalidated per dirty doc in _commit_batch) — the
        # oracle pull path stops rebuilding from_vv/to_vv objects per
        # pull, so the host-fallback line in the read A/B is honest
        self._head_vv: Dict[int, object] = {}
        # batched device read plane (docs/SYNC.md "Read plane"): pulls
        # coalesce into one vmapped export launch; the oracle above is
        # demoted to the differential-fuzz oracle + typed degradation
        # fallback.  read_batch=False keeps every pull on the oracle
        # (the bench A/B's host line).
        if read_batch:
            from .readbatch import ReadBatcher

            self._readbatch = ReadBatcher(self)
        else:
            self._readbatch = None
        self._sessions: Dict[str, Session] = {}
        self._next_peer = 1
        self.session_ttl = session_ttl
        self.presence = PresencePlane(self, ttl_s=session_ttl)
        self._pipe = (
            resident.pipeline(cid=self.cid, coalesce=coalesce, depth=depth)
            if pipeline else None
        )
        self._fanin = FanIn(
            self._commit_batch, max_queue=max_queue, family=self.family
        )
        self._rounds = 0
        self._unsub_epochs = resident.subscribe_epochs(self._on_epoch)
        self._closed = False

    @classmethod
    def over(cls, resident, cid=None, **kw) -> "SyncServer":
        """Front an existing ResidentServer (typically the one
        ``persist.recover_server`` returned).  The oracle seeds from
        the resident's mirror anchor + journal tail, so recovered
        history is servable immediately — as shallow docs, which is
        what makes the first-sync snapshot path in ``Session.pull``
        load-bearing after a reopen."""
        return cls(resident=resident, cid=cid, **kw)

    def _seed_oracle(self):
        """Per-doc LoroDoc mirrors at the resident's current state —
        ``ResidentServer.seed_mirror_engine()``, the same anchor+journal
        replay the degradation path uses, reused as the delta-export
        oracle."""
        srv = self.resident
        if not (srv._host_fallback
                and (srv._history_complete or srv._anchor is not None)):
            raise SyncError(
                "SyncServer needs a resident server with a host-mirror "
                "journal (host_fallback=True; pre-v3 restores lack one) — "
                "the per-doc oracle that serves deltas is seeded from it"
            )
        return srv.seed_mirror_engine()

    def oracle_doc(self, di: int):
        """The per-doc oracle LoroDoc (read-only by contract: mutating
        it diverges pulls from the resident state)."""
        return self._oracle.docs[di]

    # -- epoch-commit hook (ResidentServer.subscribe_epochs) -----------
    def _on_epoch(self, epoch: int) -> None:
        # fires on the committing thread BEFORE pipeline futures
        # resolve; lock-free on purpose (a slow subscriber here would
        # sit inside the resident ingest path).  Session fan-out itself
        # rides _commit_batch — every served round flows through the
        # fan-in, so this hook's job is the observability watermark
        # (and it is the subscription point external consumers, e.g. a
        # future WAL-shipping follower, attach to).
        obs.gauge(
            "sync.committed_epoch",
            "newest resident-visible epoch (epoch-commit hook)",
        ).set(epoch, family=self.family)

    # -- sessions ------------------------------------------------------
    def connect(self, sid: Optional[str] = None, subscribe: bool = True,
                register_replica: bool = True) -> Session:
        """Open a session.  ``register_replica=True`` (default) enters
        the session into every doc's replica set, so its pull-acks
        drive the compaction floors — and an abandoned session pins
        them until TTL expiry drops it (the documented trade)."""
        with self._lock:
            if self._closed:
                raise SyncError("sync server is closed")
            peer = self._next_peer
            self._next_peer += 1
            if sid is None:
                sid = f"s{peer}"
            if sid in self._sessions:
                raise ValueError(f"session id {sid!r} already connected")
            s = Session(self, sid, peer, subscribe=subscribe)
            s._registered = register_replica
            self._sessions[sid] = s
            if register_replica:
                for di in range(self.n_docs):
                    self.resident.register_replica(di, sid)
            obs.gauge(
                "sync.sessions", "connected sessions"
            ).set(len(self._sessions), family=self.family)
        obs.counter("sync.sessions_opened_total").inc(family=self.family)
        return s

    def disconnect(self, session: Session) -> None:
        """Close a session: drop its replica registrations (so it stops
        pinning compaction floors) and fan out its presence departure.
        Idempotent."""
        with self._lock:
            if session.closed:
                return
            session.closed = True
            self._sessions.pop(session.sid, None)
            if session._registered:
                for di in range(self.n_docs):
                    self.resident.drop_replica(di, session.sid)
            obs.gauge(
                "sync.sessions", "connected sessions"
            ).set(len(self._sessions), family=self.family)
            self._wakeup.notify_all()  # unblock its poll()ers (typed)
        self.presence.drop_peer(session.peer)
        obs.counter("sync.sessions_closed_total").inc(family=self.family)

    def sessions(self) -> List[Session]:
        with self._lock:
            return list(self._sessions.values())

    def expire_sessions(self, ttl: Optional[float] = None) -> List[str]:
        """Disconnect sessions idle longer than ``ttl`` (default: the
        server's ``session_ttl``).  Runs opportunistically after every
        fan-in batch; call it from a housekeeping loop too if traffic
        is sparse.  Returns the expired session ids."""
        ttl = self.session_ttl if ttl is None else ttl
        if ttl is None:
            return []
        cutoff = time.monotonic() - ttl
        with self._lock:
            stale = [
                s for s in self._sessions.values()
                if s.last_seen < cutoff and s._polling == 0
            ]
        out = []
        for s in stale:
            obs.counter(
                "sync.sessions_expired_total",
                "sessions dropped by TTL idleness expiry",
            ).inc(family=self.family)
            self.disconnect(s)
            out.append(s.sid)
        if out:
            self.presence.expire()
        return out

    # -- push path -----------------------------------------------------
    def _push(self, session: Session, di: int, data: bytes) -> PushTicket:
        # one armed fault covers every action: raise/delay fire here,
        # truncate/bitflip corrupt the wire bytes (-> typed reject)
        data = faultinject.mangle("sync_push", bytes(data), doc=di)
        if not (0 <= di < self.n_docs):
            raise ValueError(f"doc index {di} out of range [0, {self.n_docs})")
        from ..doc import strip_envelope

        try:
            payload = strip_envelope(bytes(data))
        except (DecodeError,) + _DATA_ERRORS as e:
            obs.counter(
                "sync.push_rejects_total",
                "pushes rejected typed (bad envelope / undecodable payload)",
            ).inc(family=self.family, reason="envelope")
            raise PushRejected(
                f"doc {di}: push is not a valid updates blob: "
                f"{type(e).__name__}: {e}"
            ) from e
        tk = PushTicket(trace_id=tracing.new_trace_id("p"))
        with self._lock:
            session._touch()
        obs.counter("sync.pushes_total").inc(family=self.family)
        flight.record("sync.push", family=self.family, doc=di,
                      trace=tk.trace_id, bytes=len(data))
        self._fanin.submit(di, payload, tk, session)
        return tk

    def _commit_batch(self, items) -> None:
        """Fan-in worker entry: pack one drained batch into ingest
        rounds, commit them through the pipeline (or coalesced ingest),
        honor the durable watermark, apply to the oracle, resolve
        tickets, fan out delta notifications."""
        from ..codec.binary import decode_changes

        rounds: List[list] = []        # per-doc payload lists
        metas: List[dict] = []         # di -> (ticket, changes, session)
        # tentative per-doc frontier: oracle head + every change this
        # batch already accepted for the doc (the causality gate below)
        tentative: Dict[int, object] = {}
        for di, payload, tk, sess in items:
            try:
                chs = decode_changes(bytes(payload))
            except _DATA_ERRORS as e:
                # poison push: fail ITS ticket typed; the resident
                # fleet never sees the payload, nothing half-applies
                tk._fail(PushRejected(
                    f"doc {di}: push payload does not decode: "
                    f"{type(e).__name__}: {e}"
                ))
                obs.counter(
                    "sync.push_rejects_total",
                    "pushes rejected typed (bad envelope / undecodable "
                    "payload)",
                ).inc(family=self.family, reason="decode")
                continue
            # causality gate BEFORE any plane applies it: a push whose
            # deps the server does not hold (a client pushing over a
            # stale export mark) would apply on the device columnar
            # path but not on the oracle — reject it typed here so the
            # two planes can never diverge
            vvt = tentative.get(di)
            if vvt is None:
                with self._lock:
                    vvt = tentative[di] = self._oracle.docs[di].oplog_vv()
            gap = None
            for ch in chs:
                if ch.ctr_start > vvt.get(ch.peer):
                    gap = f"peer {ch.peer} counter {vvt.get(ch.peer)}" \
                          f"..{ch.ctr_start} missing"
                    break
                missing = [d for d in ch.deps if not vvt.includes(d)]
                if missing:
                    gap = f"deps {missing} not held"
                    break
                vvt.extend_to_include(ch.id_span())
            if gap is not None:
                tk._fail(PushRejected(
                    f"doc {di}: push depends on history the server does "
                    f"not hold ({gap}) — re-export from a frontier the "
                    "server has (pull first, or resync)"
                ))
                obs.counter(
                    "sync.push_rejects_total",
                    "pushes rejected typed (bad envelope / undecodable "
                    "payload)",
                ).inc(family=self.family, reason="causality")
                continue
            for r, m in zip(rounds, metas):
                if r[di] is None:
                    r[di] = payload
                    m[di] = (tk, chs, sess)
                    break
            else:
                rounds.append([None] * self.n_docs)
                metas.append({di: (tk, chs, sess)})
                rounds[-1][di] = payload
        if not rounds:
            return
        self._rounds += len(rounds)
        if self._pipe is not None and not self._pipe.closed:
            # each round rides the trace of its FIRST push (the round
            # leader) into the pipeline and the WAL stamp
            prs = [
                self._pipe.submit(list(r), trace=next(
                    (tk.trace_id for tk, _c, _s in m.values()), None
                ))
                for r, m in zip(rounds, metas)
            ]
            epochs = [pr.epoch() for pr in prs]
            # attribution: fold the round's stage-boundary marks into
            # every push ticket the round carried
            for pr, m in zip(prs, metas):
                marks = list(pr.marks)
                for tk, _chs, _sess in m.values():
                    tk.marks.extend(marks)
        else:
            with tracing.ambient(next(
                (tk.trace_id for m in metas
                 for tk, _c, _s in m.values() if tk.trace_id), None
            )):
                epochs = self.resident.ingest_coalesced(
                    [list(r) for r in rounds], self.cid
                )
            t_commit = time.perf_counter()
            for m in metas:
                for tk, _chs, _sess in m.values():
                    tk.mark("commit", t_commit)
        # durable watermark: a resolved ticket is an ACK — it must
        # never outrun the fsync covering its round (group mode defers
        # them; pipeline groups flush at commit, serial singles do not)
        srv = self.resident
        if srv._durable is not None:
            if srv.durable_epoch < epochs[-1]:
                srv.flush_durable()
            t_fsync = time.perf_counter()
            for m in metas:
                for tk, _chs, _sess in m.values():
                    tk.mark("fsync", t_fsync)
        p2v = obs.histogram(
            "sync.push_to_visible_seconds",
            "push submit -> committed + oracle-visible + ticket resolved",
        )
        stage_h = obs.histogram(
            "trace.push_stage_seconds",
            "per-stage push latency attribution (stages telescope to "
            "sync.push_to_visible_seconds)",
        )
        dirty: Dict[int, int] = {}
        resolved: List[tuple] = []
        with self._lock:
            for m, ep in zip(metas, epochs):
                for di, (tk, chs, sess) in m.items():
                    try:
                        # mirror HostEngine.apply per doc: seen-cid
                        # scoping + direct change import
                        for ch in chs:
                            for op in ch.ops:
                                self._oracle._seen_cids[di].setdefault(
                                    op.container
                                )
                        self._oracle.docs[di]._import_changes(
                            list(chs), origin="sync"
                        )
                    except Exception as e:  # noqa: BLE001 — tpulint: disable=LT-EXC(typed reject: the ticket fails PushRejected and the counter below is the reseed signal)
                        # should be unreachable: the causality gate
                        # above rejects dep-gap pushes before ANY plane
                        # applies them.  If something still slips
                        # through, fail the ticket typed and count it —
                        # the counter alerting is the signal the oracle
                        # needs reseeding (close + SyncServer.over)
                        tk._fail(PushRejected(
                            f"doc {di}: oracle apply failed: "
                            f"{type(e).__name__}: {e}"
                        ))
                        obs.counter(
                            "sync.oracle_apply_errors_total",
                            "committed pushes the oracle could not apply "
                            "(client protocol violation)",
                        ).inc(family=self.family)
                        continue
                    # read plane: feed the device change-span index the
                    # SAME accepted changes, BEFORE the committed-epoch
                    # bump below — the window worker's epoch snapshot
                    # relies on feed-then-bump (readbatch._process_device)
                    self._head_vv.pop(di, None)
                    if self._readbatch is not None:
                        self._readbatch.plane.note_changes(di, chs)
                    # the pusher holds its own ops: advance its pull
                    # frontier past them so pulls don't echo them back
                    if sess is not None and not sess.closed:
                        vv = sess._vv.get(di)
                        if vv is None:
                            from ..core.version import VersionVector

                            vv = sess._vv[di] = VersionVector()
                        for ch in chs:
                            vv.extend_to_include(ch.id_span())
                    dirty[di] = ep
                    resolved.append((tk, ep))
            if epochs and epochs[-1] > self._committed_epoch:
                self._committed_epoch = epochs[-1]
            self._oracle.epoch = self._committed_epoch
        now = time.perf_counter()
        for tk, ep in resolved:
            if not tk.done:
                # the fanout mark and the p2v observation share `now`,
                # so breakdown() stages sum EXACTLY to the histogram's
                # end-to-end sample (the chaos attribution invariant)
                tk.mark("fanout", now)
                tk._resolve(ep)
                p2v.observe(now - tk.t0, family=self.family,
                            exemplar=tk.trace_id)
                prev = tk.t0
                for name, t in tk.marks:
                    stage_h.observe(t - prev, family=self.family,
                                    stage=name, exemplar=tk.trace_id)
                    prev = t
        if epochs:
            flight.record("sync.commit", family=self.family,
                          epoch=epochs[-1], rounds=len(rounds),
                          pushes=len(resolved))
        # per-doc push heat (docs/OBSERVABILITY.md "Health & heat"):
        # one tick per resolved push, fed to the rebalancer accountant
        for m in metas:
            for di in m:
                heat_acct.tick_doc(di, "push")
        self._fan_out_deltas(dirty)
        self.expire_sessions()

    def _fan_out_deltas(self, dirty: Dict[int, int]) -> None:
        if not dirty:
            return
        with self._lock:
            targets = [
                s for s in self._sessions.values()
                if s.subscribed and not s.closed
            ]
        n = 0
        for s in targets:
            # a stalled session delays only its own delivery slot
            faultinject.check("session_stall")
            with self._lock:
                if not s.closed:
                    for di, ep in dirty.items():
                        s._mark_dirty(di, ep)
                    n += 1
        with self._lock:
            self._wakeup.notify_all()
        obs.counter(
            "sync.fanout_notifications_total",
            "delta notifications delivered (per receiving session)",
        ).inc(n, family=self.family)

    def _ack(self, session: Session, di: int) -> None:
        """Pull-time ack into the resident compaction floors (caller
        holds the lock)."""
        self._ack_at(session, di, self._committed_epoch)

    def _ack_at(self, session: Session, di: int, epoch: int) -> None:
        """Ack a specific covered epoch (batched device pulls ack the
        window's snapshot epoch, which may trail the live committed
        epoch; resident.ack is monotone either way)."""
        if session._registered:
            self.resident.ack(di, session.sid, epoch)

    # -- pull serving (oracle path + device routing) --------------------
    def _oracle_head_vv(self, di: int):
        """The oracle's head VV for doc ``di`` (cached copy) — caller
        holds the lock.  Invalidated per dirty doc at commit."""
        vv = self._head_vv.get(di)
        if vv is None:
            vv = self._head_vv[di] = self._oracle.docs[di].oplog_vv()
        return vv.copy()

    def _oracle_pull(self, di: int, from_vv, to_frontiers):
        """Serve one pull off the per-doc oracle (caller holds the
        lock).  Returns ``(data, new_vv, first_sync)``; raises typed
        ``StaleFrontier`` below a shallow root.  The ONE oracle export
        rule — Session.pull's host path and the read batcher's
        degraded-window fallback both route here."""
        from ..doc import ExportMode

        d = self._oracle.docs[di]
        first_sync = False
        if d.is_shallow() and not (d.shallow_since_vv() <= from_vv):
            if len(from_vv) == 0:
                # documented first-sync path: full snapshot (the
                # shallow base rides along; a fresh doc imports it)
                first_sync = True
                data = d.export(ExportMode.Snapshot)
                new_vv = self._oracle_head_vv(di)
                obs.counter(
                    "sync.first_sync_snapshots_total",
                    "pulls served as snapshots (client below the "
                    "oracle's shallow root)",
                ).inc(family=self.family)
            else:
                raise StaleFrontier(
                    f"doc {di}: client frontier {from_vv.to_json()} is "
                    "below the server oracle's shallow root "
                    f"{d.shallow_since_vv().to_json()} — history there "
                    "was trimmed; resync from a fresh doc (empty "
                    "frontier pulls take the first-sync snapshot path)"
                )
        elif to_frontiers is not None:
            to_vv = d.oplog.dag.frontiers_to_vv(to_frontiers)
            data = d.export(ExportMode.UpdatesInRange(from_vv, to_vv))
            new_vv = from_vv.copy()
            for peer, end in to_vv.items():
                if end > new_vv.get(peer):
                    new_vv.set_end(peer, end)
        else:
            data = d.export(ExportMode.Updates(from_vv))
            new_vv = self._oracle_head_vv(di)
        return data, new_vv, first_sync

    def _route_device(self, di: int, from_vv) -> bool:
        """Whether this pull is batchable onto the device read plane
        (caller holds the lock).  Oracle-only: bounded pulls (checked
        by the caller), shallow first-sync / StaleFrontier cases, and
        frontiers below the index floor — docs/SYNC.md "Read plane"."""
        rb = self._readbatch
        if rb is None or rb.closed:
            return False
        d = self._oracle.docs[di]
        if d.is_shallow() and not (d.shallow_since_vv() <= from_vv):
            return False
        return rb.plane.covers(di, from_vv)

    # -- reads (flush fan-in, then the resident batch) ------------------
    def flush(self) -> None:
        """Block until every accepted push is committed, oracle-visible
        and its ticket resolved."""
        self._fanin.flush()

    def _read(self, name: str, *args):
        self.flush()
        return getattr(self.resident, name)(*args)

    def texts(self):
        return self._read("texts")

    def richtexts(self):
        return self._read("richtexts")

    def values(self):
        return self._read("values")

    def value_maps(self):
        return self._read("value_maps")

    def root_value_maps(self, name: str):
        return self._read("root_value_maps", name)

    def parent_maps(self):
        return self._read("parent_maps")

    def children_maps(self):
        return self._read("children_maps")

    def value_lists(self):
        return self._read("value_lists")

    @property
    def epoch(self) -> int:
        """Newest oracle-visible epoch (what pulls/acks cover)."""
        return self._committed_epoch

    # -- compaction (resident rows + read-plane index retention) --------
    def compact(self) -> int:
        """Housekeeping pass: flush the fan-in, reclaim resident rows
        under the replica ack floors (``ResidentServer.compact``), and
        prune the device change-span index below the connected
        sessions' pull frontiers (the ISSUE 11 retention follow-up).
        Returns resident rows reclaimed."""
        self.flush()
        n = self.resident.compact()
        self._compact_read_plane()
        return n

    def _compact_read_plane(self) -> int:
        """Advance the read-plane index floors to the pointwise MEET of
        every registered session's pull frontier per doc and drop the
        rows below it: every connected client already holds them, so
        only a NEW (or unregistered) client could need them — and its
        below-floor frontier re-routes to the oracle through the
        existing ``covers`` path.  Docs some session never pulled keep
        their floor (an empty frontier meets everything to zero)."""
        rb = self._readbatch
        if rb is None or rb.closed:
            return 0
        with self._lock:
            sessions = [
                s for s in self._sessions.values()
                if s._registered and not s.closed
            ]
            if not sessions:
                return 0
            floors: Dict[int, object] = {}
            for di in range(self.n_docs):
                vvs = [s._vv.get(di) for s in sessions]
                if any(v is None or not len(v) for v in vvs):
                    continue
                floor = vvs[0].copy()
                for v in vvs[1:]:
                    floor = floor.meet(v)
                if len(floor):
                    floors[di] = floor
        pruned = 0
        with rb.plane._lock:
            for di, floor in floors.items():
                pruned += rb.plane.index.prune_below(di, floor)
        return pruned

    # -- lifecycle -----------------------------------------------------
    def report(self) -> dict:
        """Compact outcome dict (the bench ``sync`` sidecar core).
        Fronting a tiered resident (hot_slots=, docs/RESIDENCY.md)
        adds the residency report: pushes/pulls on warm/cold docs
        revive them transparently — a push's ticket simply resolves
        after the revived round commits — so the hit rate here is the
        serving-path cache behavior clients actually saw."""
        with self._lock:
            n_sessions = len(self._sessions)
        out = self._fanin.report()
        out.update(
            sessions=n_sessions,
            rounds=self._rounds,
            committed_epoch=self._committed_epoch,
            pipeline=self._pipe is not None,
        )
        if self._readbatch is not None:
            out["readbatch"] = self._readbatch.report()
        res = getattr(self.resident, "residency", None)
        if res is not None:
            out["residency"] = res.report()
        return out

    def warm_read_plane(self, max_window: Optional[int] = None,
                        max_peers: int = 4) -> int:
        """Pre-compile the read plane's selection shapes (one per
        window-size bucket up to ``max_window``; ``max_peers`` bounds
        the frontier-width bucket) so first-pull windows never bank an
        XLA compile as serving latency; returns the shape count, 0
        when the read plane is disabled."""
        if self._readbatch is None:
            return 0
        return self._readbatch.warmup(max_window, max_peers)

    def close(self) -> None:
        """Drain the fan-in, close every session, detach from the
        resident server (and close it when this SyncServer built it —
        durable WAL release included)."""
        err = None
        try:
            self._fanin.close()
        except RuntimeError as e:
            err = e
        if self._readbatch is not None:
            # after the fan-in drain (late pushes committed) and
            # WITHOUT the server lock (a degraded window's oracle
            # fallback needs it): queued pulls serve, then the worker
            # stops and Session.pull routes oracle-only
            self._readbatch.close()
        with self._lock:
            self._closed = True
            sessions = list(self._sessions.values())
        for s in sessions:
            self.disconnect(s)
        try:
            self._unsub_epochs()
        except ValueError:
            pass
        if self._own_resident:
            self.resident.close()
        elif self._pipe is not None and not self._pipe.closed:
            self._pipe.close()
        if err is not None:
            raise err

    def __enter__(self) -> "SyncServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
