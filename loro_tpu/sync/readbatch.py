"""ReadBatcher: many sessions' pulls -> one batched delta-export launch.

The read-side dual of ``fanin.FanIn`` (docs/SYNC.md "Read plane").
Writers got fleet shape in PRs 5-10 — batched, pipelined, sharded,
tiered ingest — while every ``Session.pull()`` still walked a per-doc
host ``LoroDoc`` oracle: single-doc, GIL-shaped, exactly inverted from
the vmap-across-docs thesis where production traffic dominates
(readers outnumber writers ~100x).  This module lifts the pull path
onto the device:

- concurrent ``pull()``s on a window coalesce into ONE vmapped
  selection launch over the device-resident change-span index
  (``ops/export_batch.py``) — the count guard in the tests: launches
  per window == 1, however many sessions pulled;
- identical ``(doc, frontier)`` requests in a window FRAME ONCE and
  share the wire bytes (a fan-out of readers at the same frontier —
  the common case after a notification — pays one encode, not N);
- framing rides the exact oracle code path
  (``doc.frame_columnar_updates`` over the stored changes, trimmed by
  ``oplog.trim_known_prefix``), so batched device pulls are
  byte-identical to ``ExportMode.Updates`` oracle exports — the
  differential gate in tests/test_read_plane.py;
- the launch routes through the ``DeviceSupervisor`` via the family
  batch's ``export_select`` entry; a ``DeviceFailure`` (or an armed
  ``read_batch``/``export_launch`` fault) degrades ONLY that window to
  per-request oracle pulls — typed, counted, invisible to sessions;
- the host oracle stays authoritative for everything the index cannot
  serve: first-sync snapshots, ``StaleFrontier``, bounded
  ``UpdatesInRange`` pulls, and frontiers below the index floor
  (pre-SyncServer history on a recovered/restored resident).

The queue is UNBOUNDED on purpose (unlike the fan-in): a pull request
is O(frontier) bytes with no staged payload, the window drain is one
launch regardless of depth, and a bounded queue here could deadlock a
session submitting under the server lock against a degraded window
re-entering the oracle under that same lock.

There is NO dedicated read thread: pulls are leader-driven.  The
first missing puller becomes the window leader (``ReadBatcher.drive``)
— it sleeps one short gather beat so racing pulls pile into its
window, then drains, launches, frames and resolves every ticket;
followers block on their tickets.  Repeat ``(doc, frontier)`` pulls
against an unchanged doc skip the window entirely: the **frame cache**
(invalidated per doc at commit) serves them inline.

Locks (analysis/lockorder.py): ``sync.readbatch`` (queue/cv) and
``sync.readplane`` (index + changelog + frame cache) sit between
``sync.server`` and ``fanin.queue`` — the commit path feeds the plane
while holding the server lock; the window leader takes the plane lock
with the queue lock released and takes the SERVER lock only on the
degraded path, with the plane lock released.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..analysis.lockwitness import named_lock
from ..core.version import VersionVector
from ..errors import DeviceFailure, SyncError
from ..obs import metrics as obs
from ..resilience import faultinject

faultinject.register_site(
    "read_batch", "ReadBatcher window worker: fires before any device "
    "work on a drained pull window — the whole window degrades to "
    "per-doc oracle pulls (typed, counted, invisible to sessions)")
faultinject.register_site(
    "export_launch", "batched delta-export selection launch (shared "
    "with parallel.fleet's export_select site)")


class PullTicket:
    """Handle for one batched pull: ``result()`` blocks until the
    window serving it resolves, then returns ``(data, new_vv, epoch)``
    — the wire bytes, the client's advanced frontier (a private copy),
    and the committed epoch the pull covers (the ack watermark).

    ``trace_id`` and ``stages`` carry the read-side attribution
    (window-wait / launch / frame, or the cache-hit and degraded
    paths) the serving window fills in before resolving — the pull
    dual of ``fanin.PushTicket.breakdown()``."""

    __slots__ = ("_ev", "_data", "_vv", "_epoch", "_error", "t0",
                 "trace_id", "stages")

    def __init__(self, trace_id: Optional[str] = None):
        self._ev = threading.Event()
        self._data: Optional[bytes] = None
        self._vv: Optional[VersionVector] = None
        self._epoch = 0
        self._error: Optional[BaseException] = None
        self.t0 = time.perf_counter()
        self.trace_id = trace_id
        self.stages: Optional[dict] = None

    def _resolve(self, data: bytes, vv: VersionVector, epoch: int) -> None:
        self._data, self._vv, self._epoch = data, vv, epoch
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    @property
    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None
               ) -> Tuple[bytes, VersionVector, int]:
        if not self._ev.wait(timeout):
            raise TimeoutError("batched pull not served yet")
        if self._error is not None:
            raise self._error
        return self._data, self._vv, self._epoch


class ReadPlane:
    """The device-resident read state: one ``ops.export_batch.
    ExportIndex`` fed from the sync commit path (the same decoded
    changes the oracle imports, after the causality gate — so index
    rows ARE the oracle's stored changes) under ``sync.readplane``.

    Plus the **frame cache**: per doc, the last few framed ``(frontier
    -> wire bytes)`` exports.  A doc's delta-since-frontier is
    deterministic between commits, so the cache is exact until the
    next feed invalidates the doc — and a reader fan-out (many
    sessions at the same frontier after one notification) serves
    inline off it, no window, no launch, no re-encode."""

    FRAME_CACHE_PER_DOC = 8

    def __init__(self, server):
        from ..ops.export_batch import ExportIndex

        self._lock = named_lock("sync.readplane")
        # index floor = the oracle head at read-plane birth: pulls
        # whose frontier does not dominate it need pre-index history
        # and stay on the oracle path (recovered servers etc.)
        floors = [
            server._oracle.docs[i].oplog_vv() for i in range(server.n_docs)
        ]
        self.index = ExportIndex(
            server.n_docs, family=server.family, floor_vvs=floors
        )
        # di -> {frontier_key: (data, head_vv, epoch)} (FIFO-bounded)
        self._frames: List[Dict[tuple, tuple]] = [
            {} for _ in range(server.n_docs)
        ]

    def note_changes(self, di: int, chs) -> None:
        """Commit-path feed (caller holds the server lock; this nests
        ``sync.readplane`` under it — the declared order).  Invalidates
        the doc's frame cache: its head moved."""
        with self._lock:
            self.index.note_changes(di, chs)
            self._frames[di].clear()

    def covers(self, di: int, from_vv: VersionVector) -> bool:
        # floor VVs only ever advance, by whole-object swap
        # (prune_below), so this read is safe lock-free — but a pull
        # that passed here may still see its rows pruned before its
        # window processes; _process_device re-checks under the plane
        # lock and re-routes casualties to the oracle
        return self.index.covers(di, from_vv)

    # -- frame cache (caller holds sync.readplane) ---------------------
    @staticmethod
    def frame_key(from_vv: VersionVector) -> tuple:
        return tuple(sorted(from_vv.items()))

    def cached_frame(self, di: int, key: tuple):
        return self._frames[di].get(key)

    def store_frame(self, di: int, key: tuple, data: bytes,
                    head_vv: VersionVector, epoch: int) -> None:
        cache = self._frames[di]
        if len(cache) >= self.FRAME_CACHE_PER_DOC:
            cache.pop(next(iter(cache)))  # FIFO: oldest frontier out
        cache[key] = (data, head_vv, epoch)

    def report(self) -> dict:
        with self._lock:
            return self.index.report()


class ReadBatcher:
    """Unbounded pull queue + leader-elected window processing.

    No dedicated worker thread: the first pulling session to find no
    leader BECOMES the window leader (``drive``) — it waits one short
    gather beat so concurrent pulls pile into its window, drains the
    queue, runs the one selection launch, frames, and resolves every
    ticket including its own.  Followers just block on their tickets.
    Under a reader storm this keeps the whole window on a thread that
    already holds the GIL instead of paying a scheduler handoff per
    window (measured 2-3x on the 64-reader CPU-mesh A/B)."""

    def __init__(self, server, max_window: int = 256,
                 gather_s: float = 0.002, sleep=None):
        self._server = server
        self.plane = ReadPlane(server)
        self._max_window = max(1, int(max_window))
        # the coalescing beat: the leader sleeps this long before
        # draining, letting racing pulls join its window (one launch
        # instead of N); bounded, so a solo pull pays at most
        # gather_s extra latency.  `sleep` is injectable (fake-clock
        # tests), defaulting to time.sleep.
        self._gather_s = max(0.0, float(gather_s))
        self._sleep = sleep if sleep is not None else time.sleep
        self._lock = named_lock("sync.readbatch")
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()  # (di, from_vv, ticket)
        self._busy = False  # a leader is gathering/processing
        self._stop = False
        # count-based report (the bench `readplane` sidecar + the
        # one-launch-per-window test guard)
        self._pulls = 0
        self._queued = 0
        self._cache_hits = 0
        self._windows = 0
        self._max_window_seen = 0
        self._frames = 0
        self._frames_shared = 0
        self._degraded_windows = 0
        self._degraded_pulls = 0

    # -- producer side (sessions; may hold the server lock) ------------
    def try_cached(self, di: int, from_vv: VersionVector):
        """Inline fast path: serve this pull straight off the frame
        cache — no queue, no worker round-trip, no launch.  Returns
        ``(data, new_vv, epoch)`` or None on a miss.  Exact by the
        cache's invalidate-on-feed contract (the bytes were framed
        from a device selection at the same doc head)."""
        if self._stop:
            return None
        key = ReadPlane.frame_key(from_vv)
        with self.plane._lock:
            hit = self.plane.cached_frame(di, key)
            if hit is None:
                return None
            data, head_vv, epoch = hit
        with self._lock:
            self._pulls += 1
            self._cache_hits += 1
        obs.counter(
            "readbatch.frame_cache_hits_total",
            "pulls served inline off the read-plane frame cache",
        ).inc(family=self._server.family)
        return data, head_vv.copy(), epoch

    def submit(self, di: int, from_vv: VersionVector,
               trace: Optional[str] = None) -> PullTicket:
        """Enqueue one pull (cheap — callers may hold the server
        lock).  The caller must then ``drive()`` the ticket OUTSIDE
        the server lock: leadership can run the degraded-window
        fallback, which re-enters the oracle under that lock."""
        tk = PullTicket(trace_id=trace)
        with self._cv:
            if self._stop:
                raise SyncError("read batcher is closed")
            self._q.append((di, from_vv, tk))
            self._pulls += 1
            self._queued += 1
            obs.gauge(
                "readbatch.depth", "pulls queued behind the window leader"
            ).set(len(self._q), family=self._server.family)
        return tk

    def drive(self, tk: PullTicket) -> Tuple[bytes, VersionVector, int]:
        """Serve until ``tk`` resolves: become the window leader when
        none is active (gather beat -> drain -> one launch -> frame ->
        resolve), else wait as a follower.  Hold NO locks on entry."""
        while not tk.done:
            with self._cv:
                if tk.done:
                    break
                if self._busy:
                    # follower: the live leader's window (or a later
                    # one we lead ourselves) will resolve us
                    self._cv.wait(0.1)
                    continue
                self._busy = True
            self._lead_once(gather=True)
        return tk.result()

    def _lead_once(self, gather: bool) -> None:
        """One leadership turn (caller set ``_busy``): optional gather
        beat, drain a window, process it, release leadership."""
        try:
            if gather and self._gather_s > 0.0:
                # coalescing beat OUTSIDE the queue lock: racing
                # pulls enqueue into this window meanwhile
                self._sleep(self._gather_s)
            with self._cv:
                window: List[tuple] = []
                while self._q and len(window) < self._max_window:
                    window.append(self._q.popleft())
                if window:
                    self._windows += 1
                    self._max_window_seen = max(
                        self._max_window_seen, len(window)
                    )
            if window:
                self._process_guarded(window)
        finally:
            with self._cv:
                self._busy = False
                self._cv.notify_all()

    def _process_guarded(self, window: List[tuple]) -> None:
        try:
            self._process(window)
        except BaseException as e:  # noqa: BLE001 — fail the window's waiters typed, stay serving
            # a window-level logic error fails ITS tickets (pull
            # raises at the caller) and the batcher keeps serving:
            # selection is a pure read, the next window is
            # independent state
            for _di, _vv, tk in window:
                if not tk.done:
                    tk._fail(e)
            obs.counter(
                "readbatch.window_errors_total",
                "read windows that raised outside the degradation "
                "contract (tickets failed typed)",
            ).inc(family=self._server.family)

    def warmup(self, max_window: Optional[int] = None,
               max_peers: int = 4) -> int:
        """Pre-compile the selection launch for every window-size
        bucket this batcher can form (up to ``max_window``, capped by
        the batcher's own window cap; ``max_peers`` bounds the
        frontier-width bucket — pass the widest per-doc writer count
        expected), so the first reader storm never pays an XLA compile
        inside a pull (``ExportIndex.warm``).  Warm launches run
        against throwaway arrays of the live shapes, so the plane lock
        is NOT held across the compiles (commits and cached pulls
        never stall behind a warm), but they ride the same device
        routing real windows use — the batch device lock plus the
        ``DeviceSupervisor`` — so warm fetches never interleave with a
        buffer-donating grow/evict on the device queue and a dead
        device surfaces as typed ``DeviceFailure``.  Returns the
        number of shapes compiled; no-op once closed."""
        if self._stop:
            return 0
        n = self._max_window if max_window is None else min(
            int(max_window), self._max_window
        )

        def thunk():
            return self.plane.index.warm(max(1, n), max_peers)

        sup = self._supervisor()
        batch = getattr(self._server.resident, "batch", None)
        # tiered resident: the hot-set inner batch owns the device
        # queue (the same resolution TieredBatch.export_select does)
        batch = getattr(batch, "inner", batch)
        lock = getattr(batch, "_dev_lock", None)
        if lock is not None:
            with lock:
                done = sup.launch(
                    thunk, label=f"sync.read_warm.{self._server.family}"
                )
        else:
            done = sup.launch(
                thunk, label=f"sync.read_warm.{self._server.family}"
            )
        if done:
            obs.counter(
                "readbatch.warm_launches_total",
                "selection-kernel shapes pre-compiled by warmup()",
            ).inc(done, family=self._server.family)
        return done

    def flush(self) -> None:
        """Block until every submitted pull has been served (pulls are
        leader-driven, so an empty idle queue means done)."""
        with self._cv:
            while self._q or self._busy:
                self._cv.wait(0.05)

    def close(self) -> None:
        """Refuse new submits, then serve anything still queued
        OURSELVES — pulls are leader-driven, and a ticket whose
        submitter died between submit() and drive() (async exception,
        or an external caller that abandoned ``result(timeout)``) has
        no leader coming; waiting on one would hang this close (and
        ``SyncServer.close`` with it).  Idempotent; late pulls route
        to the oracle path (``closed`` gates the Session.pull
        routing)."""
        with self._cv:
            self._stop = True
        while True:
            with self._cv:
                if self._busy:
                    self._cv.wait(0.05)
                    continue
                if not self._q:
                    self._cv.notify_all()
                    return
                self._busy = True
            self._lead_once(gather=False)

    @property
    def closed(self) -> bool:
        return self._stop

    def _process(self, window: List[tuple]) -> None:
        srv = self._server
        obs.histogram(
            "readbatch.window_pulls", "pulls coalesced per read window",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
        ).observe(len(window), family=srv.family)
        try:
            # mid-batch failure choke point #1: before any device work
            faultinject.check("read_batch")
            resolved = self._process_device(window)
        except (DeviceFailure, faultinject.InjectedFault) as e:
            self._degrade_window(window, e)
            return
        now = time.perf_counter()
        wait = obs.histogram(
            "sync.pull_wait_seconds",
            "pull submit -> batched window served (device path)",
        )
        stage_h = obs.histogram(
            "trace.pull_stage_seconds",
            "per-stage pull latency attribution (read plane)",
        )
        for tk, data, vv, ep in resolved:
            tk._resolve(data, vv, ep)
            wait.observe(now - tk.t0, family=srv.family,
                         exemplar=tk.trace_id)
            for name, ms in (tk.stages or {}).items():
                if name.endswith("_ms"):
                    stage_h.observe(ms * 1e-3, family=srv.family,
                                    stage=name[:-3],
                                    exemplar=tk.trace_id)

    def _process_device(self, window: List[tuple]) -> List[tuple]:
        """One launch for the whole window; frames deduped by (doc,
        frontier).  Returns ``(ticket, data, new_vv, epoch)`` rows."""
        from ..doc import frame_columnar_updates
        from ..oplog.oplog import trim_known_prefix

        srv = self._server
        t_win = time.perf_counter()  # attribution: window drain time
        groups: Dict[tuple, list] = {}
        order: List[tuple] = []
        for di, vv, tk in window:
            key = (di, tuple(sorted(vv.items())))
            g = groups.get(key)
            if g is None:
                groups[key] = g = [di, vv, []]
                order.append(g)
            g[2].append(tk)
        out: List[tuple] = []
        stale: List[list] = []
        win_hits = win_shared = 0
        with self.plane._lock:
            # epoch snapshot: reads BEFORE any ticket resolves, while
            # holding the plane lock — the commit path feeds the plane
            # before bumping the epoch (both under the server lock), so
            # the index always covers at least this watermark
            epoch = srv._committed_epoch
            # frame-cache pass: groups framed since the doc's last
            # commit serve without re-selection; only misses launch
            # (zero misses -> zero launches for this window)
            misses: List[list] = []
            for g in order:
                di, from_vv, _tks = g
                key = ReadPlane.frame_key(from_vv)
                hit = self.plane.cached_frame(di, key)
                if hit is None:
                    # covers re-check under the plane lock: a compact()
                    # may have pruned index rows this frontier needs
                    # AFTER the routing check passed (the submit ran
                    # under the server lock with the old floor) — a
                    # below-floor selection would silently drop the
                    # pruned changes, so these pulls re-route to the
                    # oracle outside the plane lock instead
                    if not self.plane.index.covers(di, from_vv):
                        stale.append(g)
                        continue
                    g.append(key)
                    misses.append(g)
                else:
                    win_hits += len(g[2])
                    data, head, ep0 = hit
                    for tk in g[2]:
                        tk.stages = {
                            "window_wait_ms": (t_win - tk.t0) * 1e3,
                            "cache_hit": True,
                        }
                        out.append((tk, data, head.copy(), ep0))
            sel = self._launch(
                [(g[0], g[1]) for g in misses]
            ) if misses else []
            t_sel = time.perf_counter()
            for g, idx in zip(misses, sel):
                di, from_vv, tks, key = g
                t_f0 = time.perf_counter()
                log = self.plane.index.changes[di]
                picked = []
                for i in idx:
                    ch = log[int(i)]
                    start = from_vv.get(ch.peer)
                    if ch.ctr_start < start:
                        ch = trim_known_prefix(ch, start)
                    picked.append(ch)
                data = frame_columnar_updates(picked)
                head = self.plane.index.head_vv(di)
                self._frames += 1
                win_shared += len(tks) - 1
                self.plane.store_frame(di, key, data, head, epoch)
                t_f1 = time.perf_counter()
                for tk in tks:
                    tk.stages = {
                        "window_wait_ms": (t_win - tk.t0) * 1e3,
                        "launch_ms": (t_sel - t_win) * 1e3,
                        "frame_ms": (t_f1 - t_f0) * 1e3,
                    }
                    # per-ticket VV copy: sessions mutate their
                    # frontier in place on later pushes
                    out.append((tk, data, head.copy(), epoch))
        # pruned-from-under-us pulls: serve off the oracle, outside the
        # plane lock (the server lock must never nest under readplane)
        for g in stale:
            di, from_vv, tks = g[0], g[1], g[2]
            t_o0 = time.perf_counter()
            with srv._lock:
                data, new_vv, _first = srv._oracle_pull(di, from_vv, None)
                ep1 = srv._committed_epoch
            t_o1 = time.perf_counter()
            obs.counter(
                "readbatch.floor_reroutes_total",
                "window pulls re-routed to the oracle because "
                "compaction pruned their index rows mid-flight",
            ).inc(len(tks), family=srv.family)
            for tk in tks:
                tk.stages = {
                    "window_wait_ms": (t_win - tk.t0) * 1e3,
                    "oracle_ms": (t_o1 - t_o0) * 1e3,
                    "rerouted": True,
                }
                out.append((tk, data, new_vv.copy(), ep1))
        # counter updates AFTER the plane lock (readbatch < readplane
        # in the declared order, so never nest the queue lock under it)
        if win_hits:
            with self._lock:
                self._cache_hits += win_hits
            obs.counter(
                "readbatch.frame_cache_hits_total",
                "pulls served inline off the read-plane frame cache",
            ).inc(win_hits, family=srv.family)
        if win_shared:
            self._frames_shared += win_shared
            obs.counter(
                "readbatch.frames_shared_total",
                "pulls served off another request's frame "
                "(same doc+frontier in the window)",
            ).inc(win_shared, family=srv.family)
        return out

    def _supervisor(self):
        """The resident's DeviceSupervisor, or the process one when the
        resident has no single supervisor (the sharded fleet runs one
        per shard; the read plane's index is fleet-wide)."""
        sup = getattr(self._server.resident, "_sup", None)
        if sup is not None:
            return sup()
        from ..resilience import get_supervisor

        return get_supervisor()

    def _launch(self, requests):
        """Route the selection launch through the family batch's
        ``export_select`` entry (device lock + supervisor + fault
        site); a resident with no single batch (the sharded fleet)
        launches the index directly under the supervisor."""
        resident = self._server.resident
        entry = getattr(getattr(resident, "batch", None), "export_select", None)
        if entry is not None:
            return entry(self.plane.index, requests, sup=self._supervisor())

        def thunk():
            faultinject.check("export_launch")
            return self.plane.index.select(requests)

        return self._supervisor().launch(
            thunk, label=f"sync.read_batch.{self._server.family}"
        )

    # -- typed degradation: this window only ---------------------------
    def _degrade_window(self, window: List[tuple], cause) -> None:
        """Serve every pull of the failed window off the per-doc
        oracle — sessions see bytes, never the failure.  The NEXT
        window tries the device again (selection is stateless; a dead
        device keeps degrading per window until the resident
        recovers)."""
        srv = self._server
        self._degraded_windows += 1
        obs.counter(
            "readbatch.degraded_windows_total",
            "read windows degraded whole to per-doc oracle pulls "
            "(DeviceFailure / injected fault)",
        ).inc(family=srv.family)
        self._supervisor().note_degradation(f"sync.read_batch.{srv.family}")
        for di, from_vv, tk in window:
            try:
                t_o0 = time.perf_counter()
                with srv._lock:
                    data, new_vv, _first = srv._oracle_pull(di, from_vv, None)
                    epoch = srv._committed_epoch
                self._degraded_pulls += 1
                obs.counter(
                    "readbatch.degraded_pulls_total",
                    "pulls served by the oracle inside degraded windows",
                ).inc(family=srv.family)
                tk.stages = {
                    "oracle_ms": (time.perf_counter() - t_o0) * 1e3,
                    "degraded": True,
                }
                tk._resolve(data, new_vv, epoch)
            except BaseException as e:  # noqa: BLE001 — per-ticket isolation on the fallback path
                tk._fail(e)

    # -- reporting -----------------------------------------------------
    def report(self) -> dict:
        with self._lock:
            out = {
                "pulls": self._pulls,
                "queued": self._queued,
                "cache_hits": self._cache_hits,
                "windows": self._windows,
                "max_window": self._max_window_seen,
                "frames": self._frames,
                "frames_shared": self._frames_shared,
                "degraded_windows": self._degraded_windows,
                "degraded_pulls": self._degraded_pulls,
            }
        out.update(self.plane.report())
        return out
