"""Op and Change model.

reference: crates/loro-internal/src/{op.rs,op/content.rs,change.rs}.

Design departure from the reference (deliberate, TPU-first): sequence
(Text/List/MovableList) inserts ship the Fugue tree placement
`(parent_id, side)` computed at the source replica, instead of
origin_left/origin_right pairs.  Integration then needs no sequential
origin-scan: a batch of inserts is placed by sorting `(parent, side,
peer, counter)` keys — which maps directly onto device sort + list-rank
kernels (loro_tpu/ops/fugue_batch.py).  Semantics are the Fugue tree
algorithm (Weidner & Kleppmann), matching the reference's Fugue text
CRDT behavior (crates/loro-internal/src/container/richtext/tracker.rs).

Each op consumes a contiguous counter range of the change:
- SeqInsert of n items consumes n counters (one id per element, RLE run)
- all other ops consume 1 counter.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple, Union

from .ids import ID, ContainerID, Counter, IdSpan, Lamport, PeerID, TreeID
from .version import Frontiers


class Side(enum.IntEnum):
    Left = 0
    Right = 1


@dataclass(frozen=True)
class StyleAnchor:
    """A rich-text style anchor element (Peritext-style, mirroring the
    reference's StyleStart/StyleEnd rope anchors in
    container/richtext/fugue_span.rs RichtextChunk::StyleAnchor)."""

    key: str
    value: Any
    is_start: bool
    # expand behavior: whether text inserted at the boundary inherits the
    # style ("before"/"after"/"both"/"none" — reference: ExpandType)
    info: int = 0


# ---------------------------------------------------------------------------
# Op contents
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MapSet:
    key: str
    value: Any  # LoroValue; None+deleted=True encodes key deletion
    deleted: bool = False


@dataclass(frozen=True)
class SeqInsert:
    """Insert `len(content)` elements as a Fugue run.

    parent=None means root (beginning of sequence); side is the Fugue
    child side relative to parent.  Element j of the run has id
    (peer, op_counter + j); for j>0 its implicit parent is element j-1,
    side Right (runs are right-spines, identical to the reference's RLE
    FugueSpan runs)."""

    parent: Optional[ID]
    side: Side
    content: Union[str, Tuple[Any, ...], StyleAnchor]

    def n_elems(self) -> int:
        if isinstance(self.content, StyleAnchor):
            return 1
        return len(self.content)


@dataclass(frozen=True)
class SeqDelete:
    """Tombstone the elements in `spans` (ids of elements, not positions)."""

    spans: Tuple[IdSpan, ...]


@dataclass(frozen=True)
class TreeMove:
    """Create/move/delete a tree node.  parent semantics:
    None = root child; DELETED_TREE_PARENT sentinel = trash.
    reference: diff_calc/tree.rs MoveLamportAndID."""

    target: TreeID
    parent: Optional[TreeID]
    position: Optional[bytes]  # fractional index among siblings
    is_create: bool = False
    is_delete: bool = False


@dataclass(frozen=True)
class CounterIncr:
    delta: float


@dataclass(frozen=True)
class MovableSet:
    elem: ID  # element id (id of the insert op element)
    value: Any


@dataclass(frozen=True)
class MovableMove:
    """Move element `elem` to a new Fugue position (this op's id becomes
    the new position element's id)."""

    elem: ID
    parent: Optional[ID]
    side: Side


@dataclass(frozen=True)
class UnknownContent:
    """Forward-compatibility payload (reference ContainerType::Unknown)."""

    kind: int
    data: bytes


OpContent = Union[
    MapSet, SeqInsert, SeqDelete, TreeMove, CounterIncr, MovableSet, MovableMove, UnknownContent
]


@dataclass(frozen=True)
class Op:
    """One operation inside a change.  `counter` is absolute (peer-wide)."""

    counter: Counter
    container: ContainerID
    content: OpContent

    def atom_len(self) -> int:
        c = self.content
        if isinstance(c, SeqInsert):
            return c.n_elems()
        return 1

    @property
    def ctr_end(self) -> Counter:
        return self.counter + self.atom_len()


@dataclass
class Change:
    """A batch of causally-contiguous ops by one peer.
    reference: change.rs:28-39."""

    id: ID  # (peer, first counter)
    lamport: Lamport
    deps: Frontiers
    ops: List[Op]
    timestamp: int = 0
    message: Optional[str] = None

    @property
    def peer(self) -> PeerID:
        return self.id.peer

    @property
    def ctr_start(self) -> Counter:
        return self.id.counter

    @property
    def ctr_end(self) -> Counter:
        return self.ops[-1].ctr_end if self.ops else self.id.counter

    def atom_len(self) -> int:
        return self.ctr_end - self.ctr_start

    @property
    def lamport_end(self) -> Lamport:
        return self.lamport + self.atom_len()

    def id_span(self) -> IdSpan:
        return IdSpan(self.peer, self.ctr_start, self.ctr_end)

    def last_id(self) -> ID:
        return ID(self.peer, self.ctr_end - 1)

    def can_merge_right(self, other: "Change", merge_interval_s: int) -> bool:
        """Whether `other` can be RLE-merged onto self (same peer,
        contiguous counters, dep-on-self, close timestamps, equal
        commit messages — reference change.rs can_merge_right)."""
        return (
            other.peer == self.peer
            and other.ctr_start == self.ctr_end
            and other.lamport == self.lamport_end
            and len(other.deps) == 1
            and next(iter(other.deps)) == self.last_id()
            and abs(other.timestamp - self.timestamp) <= merge_interval_s
            and other.message == self.message
        )
