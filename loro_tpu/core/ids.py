"""Primitive identifier types.

TPU-native re-design of the reference's `loro-common` id types
(reference: crates/loro-common/src/lib.rs — `ID`, `IdLp`, `IdFull`,
`ContainerID`, `ContainerType`, `TreeID`).  Host-side these are light
Python values; device-side ids are split into (peer_index, counter)
i32 columns with a per-batch peer dictionary (see loro_tpu/ops/columnar.py).
"""
from __future__ import annotations

import enum
from typing import NamedTuple, Optional, Tuple, Union

PeerID = int  # u64 semantics; Python int holds it natively
Counter = int  # i32 semantics
Lamport = int  # u32 semantics

# Sentinel used for "no id" in columnar encodings.
NONE_PEER = 0xFFFF_FFFF_FFFF_FFFF


class ID(NamedTuple):
    """An op id: (peer, counter).  reference: loro-common/src/id.rs."""

    peer: PeerID
    counter: Counter

    def inc(self, delta: int) -> "ID":
        return ID(self.peer, self.counter + delta)

    def __str__(self) -> str:  # e.g. "12@7" mirrors the reference's display
        return f"{self.counter}@{self.peer}"

    @staticmethod
    def parse(s: str) -> "ID":
        c, p = s.split("@")
        return ID(int(p), int(c))


class IdLp(NamedTuple):
    """Lamport-keyed id used for LWW ordering (reference: lib.rs:525)."""

    lamport: Lamport
    peer: PeerID

    def __str__(self) -> str:
        return f"L{self.lamport}@{self.peer}"


class IdFull(NamedTuple):
    """Id with both counter and lamport (reference: lib.rs:573)."""

    peer: PeerID
    counter: Counter
    lamport: Lamport

    @property
    def id(self) -> ID:
        return ID(self.peer, self.counter)

    @property
    def idlp(self) -> IdLp:
        return IdLp(self.lamport, self.peer)


class IdSpan(NamedTuple):
    """A contiguous counter span on one peer: [start, end).

    reference: loro-common/src/span.rs.
    """

    peer: PeerID
    start: Counter
    end: Counter

    def __len__(self) -> int:
        return max(0, self.end - self.start)

    def contains(self, id: ID) -> bool:
        return id.peer == self.peer and self.start <= id.counter < self.end


# Reserved root-name namespace for mergeable child containers
# (MapHandler.ensure_mergeable_*): the name deterministically encodes
# (parent cid, key, type), so concurrent creation on different replicas
# yields the SAME container and edits merge (reference:
# state/mergeable.rs ContainerID::new_mergeable).  The \x00 prefix
# keeps user root names from colliding.
MERGEABLE_PREFIX = "\x00m:"


def mergeable_root_name(parent_cid: "ContainerID", key: str, ctype: "ContainerType") -> str:
    return f"{MERGEABLE_PREFIX}{parent_cid}\x00{key}\x00{int(ctype)}"


def is_internal_root_name(name: str) -> bool:
    return name.startswith(MERGEABLE_PREFIX)


def parse_mergeable_root_name(name: str):
    """(parent ContainerID, key) of a mergeable root name, or None."""
    if not name.startswith(MERGEABLE_PREFIX):
        return None
    body = name[len(MERGEABLE_PREFIX) :]
    try:
        # rsplit: the parent cid string may itself embed \x00 (nested
        # mergeable containers)
        parent_s, key, _t = body.rsplit("\x00", 2)
        return ContainerID.parse(parent_s), key
    except (ValueError, KeyError):
        return None


class ContainerType(enum.IntEnum):
    """The seven container kinds (reference: loro-common/src/lib.rs:737)."""

    Map = 0
    List = 1
    Text = 2
    Tree = 3
    MovableList = 4
    Counter = 5
    Unknown = 6

    @staticmethod
    def from_name(name: str) -> "ContainerType":
        return _CT_BY_NAME[name]


_CT_BY_NAME = {c.name: c for c in ContainerType}


class ContainerID:
    """Root("name", type) or Normal(peer, counter, type).

    reference: loro-common/src/lib.rs:591.  Hashable + totally ordered so
    it can key host dictionaries and sort deterministically into columnar
    dictionaries for the device.
    """

    __slots__ = ("name", "peer", "counter", "ctype", "_hash")

    def __init__(
        self,
        ctype: ContainerType,
        name: Optional[str] = None,
        peer: Optional[PeerID] = None,
        counter: Optional[Counter] = None,
    ):
        self.ctype = ContainerType(ctype)
        self.name = name
        self.peer = peer
        self.counter = counter
        if (name is None) == (peer is None):
            raise ValueError("ContainerID is either Root(name) or Normal(peer,counter)")
        self._hash = hash((self.ctype, name, peer, counter))

    # -- constructors -------------------------------------------------
    @staticmethod
    def root(name: str, ctype: ContainerType) -> "ContainerID":
        return ContainerID(ctype, name=name)

    @staticmethod
    def normal(peer: PeerID, counter: Counter, ctype: ContainerType) -> "ContainerID":
        return ContainerID(ctype, peer=peer, counter=counter)

    # -- predicates ---------------------------------------------------
    @property
    def is_root(self) -> bool:
        return self.name is not None

    # -- protocol -----------------------------------------------------
    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, ContainerID)
            and self.ctype == other.ctype
            and self.name == other.name
            and self.peer == other.peer
            and self.counter == other.counter
        )

    def _key(self) -> Tuple:
        # roots sort before normals; deterministic across processes
        if self.is_root:
            return (0, self.name, int(self.ctype))
        return (1, self.peer, self.counter, int(self.ctype))

    def __lt__(self, other: "ContainerID") -> bool:
        return self._key() < other._key()

    def __repr__(self) -> str:
        if self.is_root:
            return f"cid:root-{self.name}:{self.ctype.name}"
        return f"cid:{self.counter}@{self.peer}:{self.ctype.name}"

    __str__ = __repr__

    @staticmethod
    def parse(s: str) -> "ContainerID":
        """Parse the `cid:` string form (mirrors reference's TryFrom<&str>)."""
        if not s.startswith("cid:"):
            raise ValueError(f"not a container id: {s!r}")
        body, _, tname = s[4:].rpartition(":")
        ctype = ContainerType.from_name(tname)
        if body.startswith("root-"):
            return ContainerID.root(body[5:], ctype)
        c, _, p = body.partition("@")
        return ContainerID.normal(int(p), int(c), ctype)


class TreeID(NamedTuple):
    """Node id in a movable tree (reference: loro-common/src/lib.rs:1172)."""

    peer: PeerID
    counter: Counter

    @property
    def id(self) -> ID:
        return ID(self.peer, self.counter)

    def __str__(self) -> str:
        return f"{self.counter}@{self.peer}"

    @staticmethod
    def parse(s: str) -> "TreeID":
        c, p = s.split("@")
        return TreeID(int(p), int(c))


IdOrRoot = Union[ID, None]
