"""LoroValue: the JSON-shaped value universe.

reference: crates/loro-common (LoroValue enum).  Host-side we use plain
Python values (None, bool, int, float, str, bytes, list, dict) plus
ContainerID for child-container references.  This module provides
validation, deep-equality helpers and canonical JSON conversion used by
tests and the JSON codec.
"""
from __future__ import annotations

import base64
from typing import Any, Dict, List, Union

from .ids import ContainerID

LoroValue = Union[None, bool, int, float, str, bytes, List["LoroValue"], Dict[str, "LoroValue"], ContainerID]


def validate_value(v: Any) -> Any:
    """Check v is within the LoroValue universe; returns v."""
    if v is None or isinstance(v, (bool, int, float, str, bytes, ContainerID)):
        return v
    if isinstance(v, (list, tuple)):
        for x in v:
            validate_value(x)
        return list(v)
    if isinstance(v, dict):
        for k, x in v.items():
            if not isinstance(k, str):
                raise TypeError(f"map keys must be str, got {type(k)}")
            validate_value(x)
        return v
    raise TypeError(f"not a LoroValue: {type(v)}")


def to_json(v: Any) -> Any:
    """Canonical JSON form: container refs and bytes are tagged objects so
    they round-trip unambiguously (plain strings/dicts pass through)."""
    if isinstance(v, ContainerID):
        return {"__cid__": str(v)}
    if isinstance(v, bytes):
        return {"__bytes__": base64.b64encode(v).decode()}
    if isinstance(v, list):
        return [to_json(x) for x in v]
    if isinstance(v, dict):
        return {k: to_json(x) for k, x in v.items()}
    return v


def from_json(v: Any) -> Any:
    if isinstance(v, dict):
        if set(v.keys()) == {"__cid__"}:
            return ContainerID.parse(v["__cid__"])
        if set(v.keys()) == {"__bytes__"}:
            return base64.b64decode(v["__bytes__"])
        return {k: from_json(x) for k, x in v.items()}
    if isinstance(v, list):
        return [from_json(x) for x in v]
    return v


def deep_eq(a: Any, b: Any) -> bool:
    """Deep equality with int/float care (1 == 1.0 but types kept loose,
    matching the reference's I64/Double distinction only where it matters)."""
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(deep_eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(deep_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b
