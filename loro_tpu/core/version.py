"""Version types: VersionVector, Frontiers, VersionRange.

reference: crates/loro-internal/src/version.rs (+ version/frontiers.rs).
A VersionVector maps peer -> next-expected counter (i.e. number of known
ops).  Frontiers are the DAG heads (minimal set of ids whose causal
closure equals a version).  Device-side a batch of VVs becomes a dense
`[n_docs, n_peers] i32` array via a peer dictionary (ops/columnar.py).
"""
from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from .ids import ID, Counter, IdSpan, PeerID


class VersionVector:
    """peer -> end counter (exclusive).  Ops (peer, 0..end) are included."""

    __slots__ = ("_m",)

    def __init__(self, m: Optional[Dict[PeerID, Counter]] = None):
        self._m: Dict[PeerID, Counter] = dict(m) if m else {}

    # -- access -------------------------------------------------------
    def get(self, peer: PeerID) -> Counter:
        return self._m.get(peer, 0)

    def includes(self, id: ID) -> bool:
        return id.counter < self._m.get(id.peer, 0)

    def includes_span(self, span: IdSpan) -> bool:
        return span.end <= self._m.get(span.peer, 0)

    def items(self) -> Iterable[Tuple[PeerID, Counter]]:
        return self._m.items()

    def peers(self) -> Iterable[PeerID]:
        return self._m.keys()

    def __len__(self) -> int:
        return len(self._m)

    def __iter__(self) -> Iterator[PeerID]:
        return iter(self._m)

    def total_ops(self) -> int:
        return sum(self._m.values())

    # -- mutation -----------------------------------------------------
    def set_end(self, peer: PeerID, end: Counter) -> None:
        if end <= 0:
            self._m.pop(peer, None)
        else:
            self._m[peer] = end

    def extend_to_include(self, span: IdSpan) -> None:
        if span.end > self._m.get(span.peer, 0):
            self._m[span.peer] = span.end

    def merge(self, other: "VersionVector") -> None:
        for p, c in other._m.items():
            if c > self._m.get(p, 0):
                self._m[p] = c

    # -- algebra ------------------------------------------------------
    def copy(self) -> "VersionVector":
        return VersionVector(self._m)

    def meet(self, other: "VersionVector") -> "VersionVector":
        """Greatest lower bound (pointwise min)."""
        out = {}
        for p, c in self._m.items():
            oc = other._m.get(p, 0)
            if min(c, oc) > 0:
                out[p] = min(c, oc)
        return VersionVector(out)

    def join(self, other: "VersionVector") -> "VersionVector":
        out = dict(self._m)
        for p, c in other._m.items():
            if c > out.get(p, 0):
                out[p] = c
        return VersionVector(out)

    def __le__(self, other: "VersionVector") -> bool:
        return all(c <= other._m.get(p, 0) for p, c in self._m.items())

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, VersionVector):
            return NotImplemented
        a = {p: c for p, c in self._m.items() if c > 0}
        b = {p: c for p, c in other._m.items() if c > 0}
        return a == b

    def __hash__(self):  # pragma: no cover - VVs are not dict keys normally
        return hash(tuple(sorted((p, c) for p, c in self._m.items() if c > 0)))

    def diff_spans(self, other: "VersionVector") -> List[IdSpan]:
        """Spans present in self but not in other (self \\ other)."""
        out = []
        for p, c in self._m.items():
            oc = other._m.get(p, 0)
            if c > oc:
                out.append(IdSpan(p, oc, c))
        return sorted(out)

    def sub_vv(self, other: "VersionVector") -> List[IdSpan]:
        return self.diff_spans(other)

    def __repr__(self) -> str:
        inner = ", ".join(f"{p}:{c}" for p, c in sorted(self._m.items()))
        return f"VV{{{inner}}}"

    def to_json(self) -> Dict[str, int]:
        return {str(p): c for p, c in sorted(self._m.items())}

    @staticmethod
    def from_json(d: Dict[str, int]) -> "VersionVector":
        return VersionVector({int(p): c for p, c in d.items()})

    def encode(self) -> bytes:
        """Compact binary form (reference: VersionVector::encode)."""
        return _encode_u64_varint_pairs(
            sorted((p, c) for p, c in self._m.items() if c > 0)
        )

    @staticmethod
    def decode(data: bytes) -> "VersionVector":
        """Raises ValueError on malformed/truncated input (wire API)."""
        return VersionVector(dict(_decode_u64_varint_pairs(data)))


def _encode_u64_varint_pairs(pairs) -> bytes:
    """Shared wire shape for VersionVector and Frontiers: varint count,
    then per entry u64-LE + varint."""
    import struct

    out = bytearray()
    pairs = list(pairs)
    _uvarint(out, len(pairs))
    for a, b in pairs:
        out += struct.pack("<Q", a)
        _uvarint(out, b)
    return bytes(out)


def _decode_u64_varint_pairs(data: bytes):
    """Inverse of _encode_u64_varint_pairs; raises ValueError on
    malformed/truncated input."""
    import struct

    try:
        pos = [0]
        n = _read_uvarint(data, pos)
        if n > len(data):
            raise ValueError("count exceeds payload")
        out = []
        for _ in range(n):
            (a,) = struct.unpack_from("<Q", data, pos[0])
            pos[0] += 8
            out.append((a, _read_uvarint(data, pos)))
        return out
    except (IndexError, struct.error) as e:
        raise ValueError(f"malformed pair blob: {e}") from e


def _uvarint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            return


def _read_uvarint(data: bytes, pos: List[int]) -> int:
    v = 0
    shift = 0
    while True:
        b = data[pos[0]]
        pos[0] += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow")


class Frontiers:
    """A minimal set of DAG head ids.  reference: version/frontiers.rs.

    Stored as a sorted tuple for hashability (checkout targets, fork
    points and undo stack entries key on frontiers).
    """

    __slots__ = ("_ids",)

    def __init__(self, ids: Iterable[ID] = ()):  # deduplicates + sorts
        self._ids: Tuple[ID, ...] = tuple(sorted(set(ids)))

    @staticmethod
    def from_id(id: ID) -> "Frontiers":
        return Frontiers((id,))

    def as_ids(self) -> Tuple[ID, ...]:
        return self._ids

    def is_empty(self) -> bool:
        return not self._ids

    def __len__(self) -> int:
        return len(self._ids)

    def __iter__(self) -> Iterator[ID]:
        return iter(self._ids)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Frontiers) and self._ids == other._ids

    def __hash__(self) -> int:
        return hash(self._ids)

    def __repr__(self) -> str:
        return f"Frontiers[{', '.join(map(str, self._ids))}]"

    def to_json(self) -> List[str]:
        return [str(i) for i in self._ids]

    @staticmethod
    def from_json(v: List[str]) -> "Frontiers":
        return Frontiers(ID.parse(s) for s in v)

    def encode(self) -> bytes:
        """Compact binary form: varint count + (u64 peer, varint ctr)."""
        return _encode_u64_varint_pairs((i.peer, i.counter) for i in self._ids)

    @staticmethod
    def decode(data: bytes) -> "Frontiers":
        """Raises ValueError on malformed input."""
        return Frontiers(ID(p, c) for p, c in _decode_u64_varint_pairs(data))


class VersionRange:
    """peer -> (start, end) counter ranges (reference: version.rs:33).

    Used for ImportStatus pending reporting."""

    __slots__ = ("_m",)

    def __init__(self, m: Optional[Dict[PeerID, Tuple[Counter, Counter]]] = None):
        self._m: Dict[PeerID, Tuple[Counter, Counter]] = dict(m) if m else {}

    def is_empty(self) -> bool:
        return not self._m

    def extend_to_include(self, span: IdSpan) -> None:
        if span.peer in self._m:
            s, e = self._m[span.peer]
            self._m[span.peer] = (min(s, span.start), max(e, span.end))
        else:
            self._m[span.peer] = (span.start, span.end)

    def items(self) -> Iterable[Tuple[PeerID, Tuple[Counter, Counter]]]:
        return self._m.items()

    def __eq__(self, other):
        return isinstance(other, VersionRange) and self._m == other._m

    def __repr__(self):
        inner = ", ".join(f"{p}:[{s},{e})" for p, (s, e) in sorted(self._m.items()))
        return f"VersionRange{{{inner}}}"
