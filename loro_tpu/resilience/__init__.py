"""loro_tpu.resilience: supervised device execution, fault injection,
and graceful host degradation for the fleet merge path.

Four pieces (docs/RESILIENCE.md has the full rules and rationale):

- ``supervisor``  — DeviceSupervisor: bounded in-flight launch budget
  with periodic fetch-drains, cooperative deadlines (checked BETWEEN
  launches, never signaling mid-compile/mid-transfer), bounded retry
  with exponential backoff for transient ``UNAVAILABLE`` errors, and
  typed DeviceFailure for everything terminal.
- ``probe``       — the staggered never-signaled backend-init probe
  ladder (``wait_for_backend``) + the cheap pre-upload
  ``tunnel_alive`` check.
- ``faultinject`` — env (``LORO_FAULT=...``) + programmatic fault
  hooks: backend-init hang/raise, launch exceptions, slow fetches,
  truncated codec bytes, per-doc poison payloads — every degradation
  path runs on the 8-device CPU mesh in CI.
- ``hostpath``    — the host ``models/`` mirror that degraded resident
  epochs and Fleet merges re-run on (byte-identical by the
  differential-fuzz contract).

All outcomes report through the ``obs`` registry (``resilience.*``,
``probe.*``, ``faultinject.*``) and ``DeviceSupervisor.report()``
feeds bench.py's ``resilience`` sidecar.
"""
from __future__ import annotations

from ..errors import (
    BackendUnavailable,
    DeadlineExceeded,
    DeviceFailure,
    ResilienceError,
)
from . import faultinject, hostpath, probe
from .probe import read_status, start_probe, tunnel_alive, wait_for_backend
from .supervisor import (
    DeviceSupervisor,
    RetryPolicy,
    default_transient,
    get_supervisor,
    set_supervisor,
)

__all__ = [
    "BackendUnavailable",
    "DeadlineExceeded",
    "DeviceFailure",
    "DeviceSupervisor",
    "ResilienceError",
    "RetryPolicy",
    "default_transient",
    "faultinject",
    "get_supervisor",
    "hostpath",
    "probe",
    "read_status",
    "set_supervisor",
    "start_probe",
    "tunnel_alive",
    "wait_for_backend",
]
