"""Host-engine mirror for graceful degradation of resident epochs.

When the DeviceSupervisor declares a device failure mid-epoch, the
ResidentServer re-runs the epoch on the host ``models/`` engine — a
per-doc ``LoroDoc`` replica set replayed from the server's round
journal.  The host engine is byte-identical to the device kernels by
the differential-fuzz contract (every kernel is fuzzed against the
host ``models/`` state), so degraded reads are exact, just slower.

The mirror exposes the SAME read-method names as the resident device
batches (``texts`` / ``richtexts`` / ``values`` / ``value_maps`` /
``root_value_maps`` / ``parent_maps`` / ``children_maps`` /
``value_lists``) so the server's read delegation is mechanical.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..core.ids import ContainerID, ContainerType


def normalize_updates(per_doc_updates: Sequence):
    """Bytes entries -> Change lists (what the mirror and the journal
    replay consume); Change lists pass through."""
    from ..codec.binary import decode_changes

    out = []
    for u in per_doc_updates:
        if isinstance(u, (bytes, bytearray)):
            out.append(decode_changes(bytes(u)))
        else:
            out.append(u)
    return out


class HostEngine:
    """Per-doc LoroDoc replica set driven by the same per-round update
    lists the device batch ingests."""

    def __init__(self, family: str, n_docs: int):
        from ..doc import LoroDoc

        self.family = family
        self.n_docs = n_docs
        # mirror peer ids live far above any realistic client peer so a
        # replica's own id never collides with replayed history
        self.docs = [LoroDoc(peer=(1 << 40) + i) for i in range(n_docs)]
        self.epoch = 0
        self._cid: Optional[ContainerID] = None
        # per-doc, first-seen-ordered container ids (the device batches
        # report map/counter values keyed by the cids IN that doc's
        # ops, so the mirror must scope them the same way)
        self._seen_cids: List[Dict[ContainerID, None]] = [
            {} for _ in range(n_docs)
        ]

    def apply(self, per_doc_updates: Sequence, cid=None) -> int:
        """Apply one sync round (None = no update for that doc)."""
        if cid is not None:
            self._cid = cid
        updates = normalize_updates(per_doc_updates)
        for di, changes in enumerate(updates):
            if not changes:
                continue
            for ch in changes:
                for op in ch.ops:
                    self._seen_cids[di].setdefault(op.container)
            self.docs[di]._import_changes(list(changes), origin="resilience")
        self.epoch += 1
        return self.epoch

    # -- read mirrors (same names as the device batches) ---------------
    def _handler(self, doc):
        if self._cid is None:
            raise ValueError(f"{self.family} host mirror has no container id yet")
        return doc.get_container(self._cid)

    def texts(self, use_solver: bool = False) -> List[str]:
        return [self._handler(d).to_string() for d in self.docs]

    def richtexts(self) -> List[list]:
        return [self._handler(d).get_richtext_value() for d in self.docs]

    def values(self, use_solver: bool = False) -> List[list]:
        return [self._handler(d).get_value() for d in self.docs]

    def value_lists(self) -> List[list]:
        return [self._handler(d).get_value() for d in self.docs]

    def _cids_of(self, di: int, ctype: ContainerType) -> List[ContainerID]:
        return [c for c in self._seen_cids[di] if c.ctype == ctype]

    def value_maps(self):
        if self.family == "counter":
            return [
                {c: float(d.get_container(c).get_value())
                 for c in self._cids_of(di, ContainerType.Counter)}
                for di, d in enumerate(self.docs)
            ]
        out = []
        for di, d in enumerate(self.docs):
            got: Dict = {}
            for c in self._cids_of(di, ContainerType.Map):
                for k, v in d.get_container(c).get_value().items():
                    got[(c, k)] = v
            out.append(got)
        return out

    def root_value_maps(self, name: str):
        return [d.get_map(name).get_value() for d in self.docs]

    def parent_maps(self) -> List[dict]:
        out = []
        for d in self.docs:
            tr = self._handler(d)
            out.append({x: tr.parent(x) for x in tr.nodes()})
        return out

    def children_maps(self) -> List[dict]:
        out = []
        for d in self.docs:
            tr = self._handler(d)
            kids = {}
            for x in [None] + tr.nodes():
                ch = tr.children(x)
                if ch:
                    kids[x] = ch
            out.append(kids)
        return out


def host_merge_changes(family: str, docs_changes: Sequence[Sequence], cid=None):
    """One-shot host fallback for the Fleet ``merge_*_changes`` APIs:
    replay each doc's change list into a fresh host replica and read
    the same result shape the device merge returns."""
    eng = HostEngine(family, len(docs_changes))
    eng.apply(list(docs_changes), cid)
    if family == "text":
        return eng.texts()
    if family == "richtext":
        return eng.richtexts()
    if family == "movable":
        return eng.value_lists()
    if family == "tree":
        return eng.parent_maps()
    if family == "tree_children":
        return eng.children_maps()
    if family == "counter":
        return eng.value_maps()
    raise ValueError(f"no host fallback for family {family!r}")
