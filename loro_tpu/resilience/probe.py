"""Backend-init probe API: the `.probe_stagger.sh` pattern, codified.

The TPU pool can refuse allocations for a whole session (rounds 4 and
5: every backend init hung 25-40 min in ``jax.devices()`` then raised
``UNAVAILABLE``).  It is a lottery, and the winning pattern is:

- a **fresh, detached, NEVER-signaled probe subprocess** every ~2 min
  (``start_probe``) — each writes a status JSON as it advances
  (``step``: spawned -> init -> done | error);
- a cooperative ``wait_for_backend(deadline)`` that polls the status
  file and keeps re-spawning stale probes until one reports ``done``
  or the deadline passes — it NEVER signals a probe (a SIGTERM/SIGKILL
  mid-backend-init can wedge the axon tunnel for the whole session);
- ``tunnel_alive()`` — the cheap pre-upload liveness check: tiny jit +
  host fetch in an abandonable subprocess with a hard wait cap.

Probes honor ``LORO_FAULT=backend_init:...`` (hang / raise) so the
whole ladder is testable on the CPU mesh without a TPU in sight, and
``LORO_PROBE_FAKE`` (``ok`` | ``hang:S`` | ``raise``) to skip backend
init entirely in unit tests.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Callable, Optional

from ..errors import BackendUnavailable
from ..obs import flight
from ..obs import metrics as obs
from .faultinject import register_site

register_site(
    "backend_init", "resilience.probe subprocess: hang or raise during "
    "backend init (the TPU-pool lottery)")

DEFAULT_STATUS = ".probe_device.json"
DEFAULT_STAGGER_S = 120.0

# The probe body. Runs in a fresh interpreter: writes status JSON at
# each step so the parent can distinguish "never started" from "hung in
# backend init" from "done".  Never signaled by anyone.
_PROBE_BODY = r"""
import json, os, sys, time
path = sys.argv[1]
def write(step, **kw):
    kw.update(step=step, pid=os.getpid(), t=time.time())
    tmp = path + ".%d.tmp" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(kw, f)
    os.replace(tmp, path)
write("spawned")
fake = os.environ.get("LORO_PROBE_FAKE", "")
try:
    if fake:
        write("init")
        if fake.startswith("hang"):
            s = float(fake.split(":", 1)[1]) if ":" in fake else 3600.0
            time.sleep(min(s, 3600.0))
            write("done", platform="fake")
        elif fake == "raise":
            raise RuntimeError("UNAVAILABLE: fake backend init error")
        else:
            write("done", platform="fake")
    else:
        try:
            from loro_tpu.resilience import faultinject as fi
            fi.check("backend_init")
        except ImportError:
            pass
        write("init")
        import jax, jax.numpy as jnp, numpy as np
        dev = jax.devices()[0]
        x = jax.jit(lambda v: v + 1)(jnp.zeros(8, jnp.int32))
        int(np.asarray(x)[0])
        write("done", platform=dev.platform,
              kind=str(getattr(dev, "device_kind", dev.platform)))
except BaseException as e:
    write("error", error="%s: %s" % (type(e).__name__, e))
    raise
"""


def start_probe(status_path: str = DEFAULT_STATUS,
                log_path: Optional[str] = None) -> subprocess.Popen:
    """Spawn one detached probe (own session — abandonable, never
    signaled).  Its stdout/stderr go to `log_path` (default: status
    path + ``.log``, appended so the ladder's history accumulates)."""
    obs.counter("probe.spawns_total").inc()
    log = open(log_path or (status_path + ".log"), "ab")
    try:
        env = dict(os.environ)
        env.setdefault("PYTHONPATH", os.pathsep.join(sys.path))
        return subprocess.Popen(
            [sys.executable, "-c", _PROBE_BODY, status_path],
            stdout=log, stderr=log, start_new_session=True, env=env,
        )
    finally:
        log.close()


def read_status(status_path: str = DEFAULT_STATUS) -> Optional[dict]:
    try:
        with open(status_path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def wait_for_backend(deadline_s: float,
                     status_path: str = DEFAULT_STATUS,
                     stagger_s: float = DEFAULT_STAGGER_S,
                     poll_s: float = 2.0,
                     clock: Callable[[], float] = time.monotonic,
                     sleep: Callable[[float], None] = time.sleep,
                     spawn: Callable[..., object] = start_probe,
                     raise_on_timeout: bool = False) -> dict:
    """Run the staggered probe ladder until a probe reports ``done`` or
    ``deadline_s`` elapses.  Returns the final status dict augmented
    with ``ok`` (bool), ``probes`` (spawn count) and ``waited_s``.

    Probes are never signaled: a hung probe is simply left behind and a
    fresh one is spawned every ``stagger_s``.  With
    ``raise_on_timeout`` the timeout becomes a typed
    BackendUnavailable instead of ``ok=False``."""
    t0 = clock()
    deadline = t0 + deadline_s
    try:
        # a stale step=done from a PREVIOUS session must not pass for a
        # live backend — only status written by this ladder's probes
        # counts
        os.unlink(status_path)
    except OSError:
        pass
    spawn(status_path)
    probes = 1
    last_spawn = t0
    while True:
        st = read_status(status_path)
        if st is not None and st.get("step") == "done":
            out = dict(st, ok=True, probes=probes, waited_s=clock() - t0)
            obs.gauge("probe.backend_up").set(1)
            flight.record("probe.done", probes=probes,
                          waited_s=round(out["waited_s"], 3),
                          platform=st.get("platform"))
            return out
        now = clock()
        if now >= deadline:
            break
        if now - last_spawn >= stagger_s:
            # the previous probe is stale (hung init or died): abandon
            # it unsignaled and start a fresh attempt — the lottery
            flight.record("probe.respawn", probes=probes,
                          last_step=(st or {}).get("step"))
            spawn(status_path)
            probes += 1
            last_spawn = now
        sleep(min(poll_s, max(deadline - now, 0.0)))
    st = read_status(status_path) or {}
    obs.gauge("probe.backend_up").set(0)
    out = dict(st, ok=False, probes=probes, waited_s=clock() - t0)
    # the ladder timing out IS the TPU-pool-lottery post-mortem case
    # that used to die with nothing: log it and (when armed) dump the
    # black box
    flight.record("probe.timeout", probes=probes,
                  last_step=st.get("step"),
                  waited_s=round(out["waited_s"], 3))
    flight.dump_on("probe_timeout")
    if raise_on_timeout:
        raise BackendUnavailable(
            "backend_init", probes,
            f"no probe reported done within {deadline_s:.0f}s "
            f"(last step: {st.get('step')!r})",
        )
    return out


def tunnel_alive(timeout_s: float = 75.0) -> bool:
    """Fast liveness probe: tiny jit + host fetch in a subprocess.  A
    wedged axon tunnel hangs on the FIRST device op, so a hard wait cap
    fails fast.  The child is NEVER signaled on timeout — even a tiny
    op can be mid-launch, and a signal mid-launch is what wedges
    tunnels in the first place; it is abandoned in its own session and
    exits on its own when (if) the op resolves."""
    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "x = jax.jit(lambda v: v + 1)(jnp.zeros(8, jnp.int32));"
        "print(int(np.asarray(x)[0]))"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    obs.counter("probe.tunnel_probes_total").inc()
    try:
        ok = proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        ok = False  # abandoned, not signaled
    obs.gauge("probe.tunnel_alive").set(1 if ok else 0)
    flight.record("probe.tunnel", alive=ok)
    if not ok:
        # a dead tunnel probe is the wedge signature — the black box
        # is the only record of what was in flight when it happened
        flight.dump_on("tunnel_wedge")
    return ok
