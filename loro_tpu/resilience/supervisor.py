"""DeviceSupervisor: every fleet/resident device call routes through it.

Codifies the tunnel-safety rules that previously lived as folklore in
CLAUDE.md and throwaway shell scripts (now docs/RESILIENCE.md):

- **bounded in-flight budget** — the async launch queue never grows
  past ``drain_every`` launches before a fetch-sync drains it (the
  SIGTERM post-mortem: a 900s watchdog killed a child with a 1280-deep
  queue and wedged the tunnel for the session);
- **cooperative deadlines** — checked BETWEEN launches only; a deadline
  expiry raises DeadlineExceeded at a launch boundary and NEVER signals
  a process mid-compile or mid-transfer;
- **bounded retry with exponential backoff** — transient
  ``UNAVAILABLE``-class errors retry up to ``max_retries`` with
  ``backoff_base * 2**attempt`` sleeps (capped); anything else — or an
  exhausted budget — becomes a typed DeviceFailure the caller can
  degrade on.  Launches that donate buffers pass ``retry=False``
  (a failed donated launch may have consumed its inputs);
- **pre-upload tunnel probe** — ``tunnel_alive()`` is the cheap
  never-signaled subprocess x+1 fetch; run it before big uploads.

Only device/runtime-layer errors (XlaRuntimeError, OSError, transient
``UNAVAILABLE``-marked errors, injected faults) are ever wrapped into
DeviceFailure.  Host-side errors — poison payloads (CodecDecodeError /
ValueError), bad change lists, config errors like "capacity exceeded"
— pass through untouched: they must reach the per-doc isolation logic
or the caller's eyes, not the degradation logic.

All outcomes feed the obs registry (``resilience.*`` metrics) and the
``report()`` dict that bench.py banks as the ``resilience`` sidecar.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..analysis.lockwitness import named_lock
from ..errors import DeadlineExceeded, DeviceFailure, LoroError
from ..obs import flight
from ..obs import metrics as obs
from . import faultinject

faultinject.register_site(
    "launch", "DeviceSupervisor.launch: raise before the device call "
    "(transient UNAVAILABLE retries; anything else -> DeviceFailure)")
faultinject.register_site(
    "fetch", "DeviceSupervisor.fetch/drain: slow or failing host fetch")

# substrings that mark an error transient (retry-worthy): the backend
# init / RPC errors the TPU pool throws when it is flaky but alive
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED",
                      "ABORTED", "connection reset", "temporarily")


def default_transient(exc: BaseException) -> bool:
    s = f"{type(exc).__name__}: {exc}"
    return any(m in s for m in _TRANSIENT_MARKERS)


def _is_device_error(exc: BaseException) -> bool:
    """Errors from the device/runtime layer — the only ones the
    supervisor may wrap into DeviceFailure.  Host-side errors (data
    errors, config errors like 'capacity exceeded ... pass
    auto_grow=True') pass through untouched so their guidance reaches
    the caller instead of being swallowed into silent degradation."""
    if isinstance(exc, (OSError, ConnectionError, SystemError)):
        return True
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        return isinstance(exc, XlaRuntimeError)
    except ImportError:
        return False


class RetryPolicy:
    """Bounded retry with exponential backoff (no jitter: deterministic
    under fake clocks)."""

    def __init__(self, max_retries: int = 3, backoff_base: float = 0.25,
                 backoff_max: float = 8.0,
                 retryable: Callable[[BaseException], bool] = default_transient):
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.retryable = retryable

    def backoff(self, attempt: int) -> float:
        """Sleep before retry `attempt` (0-based)."""
        return min(self.backoff_base * (2.0 ** attempt), self.backoff_max)


class DeviceSupervisor:
    """Supervised execution of device launches and fetches.

    ``clock``/``sleep`` are injectable (tests use fake clocks; tier-1
    never wall-sleeps).  A supervisor is cheap enough to leave on every
    path: one lock + a couple of counters per launch.
    """

    def __init__(self, drain_every: int = 8, retry: Optional[RetryPolicy] = None,
                 deadline_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.drain_every = max(1, int(drain_every))
        self.retry = retry if retry is not None else RetryPolicy()
        self.clock = clock
        self.sleep = sleep
        self._deadline = None if deadline_s is None else clock() + deadline_s
        self._lock = named_lock("supervisor.state")
        self._in_flight = 0
        # report counters (reset via reset_report)
        self._launches = 0
        self._retries = 0
        self._failures = 0
        self._degradations = 0
        self._deadline_aborts = 0
        self._drains = 0
        self._max_in_flight = 0

    # -- deadline ------------------------------------------------------
    def set_deadline(self, deadline_s: Optional[float]) -> None:
        """(Re)arm the cooperative deadline, `deadline_s` from now."""
        self._deadline = None if deadline_s is None else self.clock() + deadline_s

    def remaining(self) -> Optional[float]:
        return None if self._deadline is None else self._deadline - self.clock()

    def check_deadline(self, label: str = "") -> None:
        """Raise DeadlineExceeded if the budget is spent.  Called only
        BETWEEN launches — expiry never interrupts in-flight work."""
        r = self.remaining()
        if r is not None and r <= 0:
            with self._lock:
                self._deadline_aborts += 1
            obs.counter("resilience.deadline_aborts_total").inc(label=label or "-")
            raise DeadlineExceeded(
                f"cooperative deadline expired before launch {label!r} "
                f"(over by {-r:.1f}s); in-flight work was never signaled"
            )

    # -- launches ------------------------------------------------------
    def launch(self, thunk: Callable[[], object], label: str = "launch",
               retry: bool = True, drain: Optional[Callable[[], None]] = None):
        """Run one device launch (an async dispatch: jit call,
        device_put, donated scatter...).  Retries transient errors when
        ``retry`` (pure, non-donating thunks only), wraps terminal
        runtime errors into DeviceFailure, and fetch-drains the queue
        every ``drain_every`` launches via ``drain`` (or the next
        explicit ``fetch``/``drain`` call when None)."""
        self.check_deadline(label)
        attempts = 0
        while True:
            injected = True
            try:
                faultinject.check("launch", label=label)
                injected = False
                out = thunk()
                break
            except LoroError:
                raise
            except BaseException as e:  # noqa: BLE001 — classified below
                transient = self.retry.retryable(e)
                if not (injected or transient or _is_device_error(e)):
                    # host-side error (poison payload, bad change list,
                    # capacity config): not the device's fault — reach
                    # the isolation logic / the caller unchanged
                    raise
                attempts += 1
                if retry and transient and attempts <= self.retry.max_retries \
                        and (self.remaining() is None or self.remaining() > 0):
                    with self._lock:
                        self._retries += 1
                    obs.counter("resilience.retries_total").inc(label=label)
                    flight.record("sup.retry", label=label,
                                  attempt=attempts,
                                  error=f"{type(e).__name__}: {e}"[:160])
                    self.sleep(self.retry.backoff(attempts - 1))
                    continue
                with self._lock:
                    self._failures += 1
                obs.counter("resilience.launch_failures_total").inc(label=label)
                flight.record("sup.failure", label=label,
                              attempts=attempts,
                              error=f"{type(e).__name__}: {e}"[:160])
                raise DeviceFailure(
                    label, attempts, f"{type(e).__name__}: {e}"
                ) from e
        with self._lock:
            self._launches += 1
            self._in_flight += 1
            self._max_in_flight = max(self._max_in_flight, self._in_flight)
            depth = self._in_flight
            # NOT retained across calls: holding the caller's bound
            # drain method on the process-global supervisor would pin
            # the enclosing object (e.g. a whole resident batch) long
            # after the caller is gone
        obs.counter("resilience.launches_total").inc(label=label)
        obs.gauge("resilience.in_flight").set(depth)
        if depth >= self.drain_every:
            self.drain(drain if drain is not None else self._auto_drain(out))
        return out

    def _auto_drain(self, result) -> Callable[[], None]:
        """Default drain: fetch the smallest jax-array leaf of the
        launch result (the honest sync — block_until_ready lies under
        the axon tunnel)."""
        def _drain() -> None:
            import jax
            import numpy as np

            leaves = [x for x in jax.tree_util.tree_leaves(result)
                      if hasattr(x, "dtype")]
            if leaves:
                np.asarray(min(leaves, key=lambda a: getattr(a, "size", 1 << 62)))
        return _drain

    def guard(self, fn: Callable[[], object], label: str = "fetch"):
        """Run a device-touching host read (fetch / state export) and
        classify failures exactly like launch does — JAX dispatch is
        async, so a mid-merge device failure often surfaces at the SYNC
        point, not the launch; without this, sync-point errors would
        bypass every ``except DeviceFailure`` degradation handler.  No
        retry: the queue state behind a failed fetch is unknown."""
        injected = True
        try:
            faultinject.check("fetch", label=label)
            injected = False
            return fn()
        except LoroError:
            raise
        except BaseException as e:  # noqa: BLE001 — classified below
            if not (injected or self.retry.retryable(e) or _is_device_error(e)):
                raise
            with self._lock:
                self._failures += 1
            obs.counter("resilience.launch_failures_total").inc(label=label)
            raise DeviceFailure(label, 1, f"{type(e).__name__}: {e}") from e

    def drain(self, drain_fn: Optional[Callable[[], None]] = None) -> None:
        """Synchronize: run the drain fetch and zero the in-flight
        count (with no ``drain_fn`` it only resets the counters — the
        caller already synced some other way)."""
        fn = drain_fn
        if fn is not None:
            try:
                self.guard(fn, label="drain")
            except BaseException:
                # the queue state behind a failed drain is unknown, but
                # the depth counter must not keep climbing past the
                # budget while the caller degrades — reset it with the
                # failure in flight
                with self._lock:
                    self._in_flight = 0
                obs.gauge("resilience.in_flight").set(0)
                raise
        with self._lock:
            self._in_flight = 0
            self._drains += 1
        obs.counter("resilience.drains_total").inc()
        obs.gauge("resilience.in_flight").set(0)

    def fetch(self, value, label: str = "fetch"):
        """Supervised host fetch (np.asarray): the sync point of a
        merge.  Resets the in-flight count — a fetch drains the queue
        through it.  Device errors surfacing here become typed
        DeviceFailure (see guard)."""
        import numpy as np

        out = self.guard(lambda: np.asarray(value), label=label)
        with self._lock:
            self._in_flight = 0
        obs.gauge("resilience.in_flight").set(0)
        return out

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    @property
    def max_in_flight(self) -> int:
        with self._lock:
            return self._max_in_flight

    # -- degradation accounting ---------------------------------------
    def note_degradation(self, where: str) -> None:
        """Callers report a host-fallback degradation so the bench
        sidecar captures it.  The flight recorder logs the event and —
        when auto-dumping is armed (``LORO_FLIGHT_DIR``) — writes the
        black-box snapshot: the last N structured events BEFORE the
        degradation, which is exactly what the post-mortems never had
        (docs/OBSERVABILITY.md "Flight recorder")."""
        with self._lock:
            self._degradations += 1
        obs.counter("resilience.degradations_total").inc(where=where)
        flight.record("sup.degrade", where=where)
        flight.dump_on(f"degradation:{where}")

    # -- tunnel probe --------------------------------------------------
    def tunnel_alive(self, timeout_s: float = 75.0) -> bool:
        """Cheap pre-upload probe: tiny jit + fetch in a NEVER-signaled
        subprocess (see resilience.probe.tunnel_alive)."""
        from .probe import tunnel_alive

        return tunnel_alive(timeout_s)

    # -- reporting -----------------------------------------------------
    def report(self) -> dict:
        """Compact outcome dict for the bench ``resilience`` sidecar."""
        with self._lock:
            return {
                "launches": self._launches,
                "retries": self._retries,
                "failures": self._failures,
                "degradations": self._degradations,
                "deadline_aborts": self._deadline_aborts,
                "drains": self._drains,
                "max_in_flight": self._max_in_flight,
                "drain_every": self.drain_every,
            }

    def reset_report(self) -> None:
        with self._lock:
            self._launches = self._retries = self._failures = 0
            self._degradations = self._deadline_aborts = self._drains = 0
            self._max_in_flight = self._in_flight = 0


# -- process-default supervisor ----------------------------------------
_default: Optional[DeviceSupervisor] = None
_default_lock = threading.Lock()


def get_supervisor() -> DeviceSupervisor:
    global _default
    with _default_lock:
        if _default is None:
            _default = DeviceSupervisor()
        return _default


def set_supervisor(sup: Optional[DeviceSupervisor]) -> None:
    """Install a process-wide supervisor (None restores a fresh
    default).  bench.py installs one with the child deadline; tests
    install fake-clock instances."""
    global _default
    with _default_lock:
        _default = sup
